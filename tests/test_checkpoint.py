"""Distributed checkpoint (C45) tests: sharded save/restore roundtrip with a
mesh, CheckpointManager retention, TrainEpochRange auto-resume.
(reference analogues: dist_sharding_save.py, test_auto_checkpoint.py)."""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.checkpoint import (CheckpointManager,
                                               TrainEpochRange,
                                               load_checkpoint,
                                               save_checkpoint)
from paddle_tpu.distributed.engine import ParallelTrainer
from paddle_tpu.distributed.mesh import build_mesh


def test_sharded_save_restore_roundtrip(tmp_path):
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "model"))
    sh = NamedSharding(mesh, P("model", None))
    x = jax.device_put(jnp.arange(64, dtype=jnp.float32).reshape(8, 8), sh)
    state = {"w": x, "step": jnp.asarray(3)}
    save_checkpoint(str(tmp_path / "ck"), state)
    restored = load_checkpoint(str(tmp_path / "ck"), template=state)
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(x))
    assert restored["w"].sharding == sh          # mesh-keyed restore
    assert int(restored["step"]) == 3


def test_checkpoint_manager_retention(tmp_path):
    m = CheckpointManager(str(tmp_path / "mgr"), max_to_keep=2,
                          use_async=False)
    for s in range(4):
        m.save(s, {"v": jnp.asarray(float(s))})
    m.wait_until_finished()
    assert m.latest_step() == 3
    assert len(list(m.all_steps())) == 2          # retention policy
    out = m.restore()
    assert float(out["v"]) == 3.0
    m.close()


def test_trainer_checkpoint_resume(tmp_path):
    build_mesh({"data": 2, "model": 4})
    paddle.seed(0)
    net = nn.Linear(8, 8)
    opt = paddle.optimizer.Adam(1e-2, parameters=net.parameters())

    def loss_fn(out, y):
        return jnp.mean((out - y) ** 2)

    tr = ParallelTrainer(net, opt, loss_fn)
    rs = np.random.RandomState(0)
    x, y = rs.rand(8, 8).astype("f4"), rs.rand(8, 8).astype("f4")
    for _ in range(3):
        tr.train_step(x, y)
    tr.save_checkpoint(str(tmp_path / "trainer_ck"))
    w_saved = np.asarray(tr.state["params"]["weight"])

    # fresh trainer restores exactly
    paddle.seed(1)
    net2 = nn.Linear(8, 8)
    opt2 = paddle.optimizer.Adam(1e-2, parameters=net2.parameters())
    tr2 = ParallelTrainer(net2, opt2, loss_fn)
    tr2.load_checkpoint(str(tmp_path / "trainer_ck"))
    np.testing.assert_array_equal(
        np.asarray(tr2.state["params"]["weight"]), w_saved)
    # training continues from restored state
    loss_a = float(tr.train_step(x, y))
    loss_b = float(tr2.train_step(x, y))
    assert abs(loss_a - loss_b) < 1e-5


def test_train_epoch_range_resume(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_JOB_ID", "jtest")
    d = str(tmp_path / "auto")
    r1 = TrainEpochRange(5, "run", checkpoint_dir=d)
    seen = []
    for e in r1.get():
        seen.append(e)
        r1.save({"epoch": jnp.asarray(e)})
        if e == 2:
            break                       # simulate preemption
    assert seen == [0, 1, 2]

    r2 = TrainEpochRange(5, "run", checkpoint_dir=d)
    assert int(r2.restored_state["epoch"]) == 2
    assert list(r2.get()) == [3, 4]     # resumes after last saved epoch


def test_train_epoch_range_generator_autosave(tmp_path, monkeypatch):
    from paddle_tpu.distributed.checkpoint import train_epoch_range
    monkeypatch.setenv("PADDLE_JOB_ID", "jgen")
    d = str(tmp_path / "auto2")
    state = {"w": jnp.asarray(0.0)}
    for e in train_epoch_range(3, "g", get_state=lambda: state,
                               checkpoint_dir=d):
        state = {"w": jnp.asarray(float(e))}
    r = TrainEpochRange(3, "g", checkpoint_dir=d)
    assert float(r.restored_state["w"]) == 2.0   # auto-saved each epoch
