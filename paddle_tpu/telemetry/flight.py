"""paddle_tpu.telemetry.flight — always-on flight recorder.

A bounded per-process ring buffer of the most recent completed spans
(fed by ``telemetry.tracing`` on every span end — including spans whose
traces are later dropped by tail sampling) plus the registry's recent
metric marks.  When something anomalous happens the ring is dumped to
``flight_<reason>_<step>.json`` so the "what was the process doing in
the seconds before" question is answerable after the fact.

Dump triggers wired across the repo:

- hang watchdog fire       (resilience.runner → HangWatchdog.on_fire)
- divergence quarantine    (resilience.runner integrity verdict)
- drain                    (serving shutdown(drain=True), runner SIGTERM)
- shed burn-rate breach    (telemetry.slo rolling-window monitor)
- SIGUSR2                  (install_signal_handler; operator-initiated)

Dumps land in the configured output directory (``configure(out_dir)``,
set automatically by ``telemetry.scope(run_dir)``; overridable with
``PADDLE_TPU_FLIGHT_DIR``).  Without a destination, ``dump`` is a no-op
returning None — the ring itself always records.

Multi-host: each process dumps locally; ``merge_dumps`` combines per-host
dump files rank-0-side, tagging every metric series and span with
``process_index`` via the same ``telemetry.aggregate`` key-tagging used
for registry merges.  ``gather_via_coordinator``-style transport is not
needed for dumps — they are files already, so the FileCoordinator root
(or any shared directory) is the rendezvous.
"""
from __future__ import annotations

import json
import os
import signal
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = [
    "FlightRecorder", "get_recorder", "configure", "record", "dump",
    "spans_dumped", "install_signal_handler", "merge_dumps",
    "find_dumps", "reset",
]


def _registry():
    from paddle_tpu import telemetry
    return telemetry.get_registry()


class FlightRecorder:
    """Ring of recent span records + dump-to-JSON on demand."""

    def __init__(self, capacity: int = 2048, marks_tail: int = 4096):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._marks_tail = marks_tail
        self.out_dir: Optional[str] = None
        self.process_index: int = 0
        self.dumps: List[str] = []
        self._spans_dumped = 0

    # -- hot path ---------------------------------------------------------
    def record(self, span_rec: dict):
        self._ring.append(span_rec)   # deque.append is atomic

    # -- configuration ----------------------------------------------------
    def configure(self, out_dir: Optional[str], process_index: int = 0):
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
        self.out_dir = out_dir
        self.process_index = process_index

    def _resolve_dir(self) -> Optional[str]:
        if self.out_dir:
            return self.out_dir
        env = os.environ.get("PADDLE_TPU_FLIGHT_DIR")
        if env:
            os.makedirs(env, exist_ok=True)
            return env
        return None

    # -- dumping ----------------------------------------------------------
    def dump(self, reason: str, step: Optional[int] = None,
             extra: Optional[dict] = None) -> Optional[str]:
        """Write the ring + a registry snapshot; returns the path or None
        when no output directory is configured."""
        out_dir = self._resolve_dir()
        if out_dir is None:
            return None
        with self._lock:
            spans = list(self._ring)
            self._spans_dumped += len(spans)
        reg = _registry()
        marks = reg.marks()
        payload = {
            "reason": reason,
            "step": int(step) if step is not None else 0,
            "pid": os.getpid(),
            "process_index": self.process_index,
            "wall_time": time.time(),
            "perf_counter_ns": time.perf_counter_ns(),
            "spans": spans,
            "metrics": reg.to_dict(),
            # recent metric deltas: the tail of the registry's mark stream
            # (timestamped per-observation events, when marks_enabled)
            "marks": [list(m) for m in marks[-self._marks_tail:]],
        }
        if extra:
            payload["extra"] = extra
        base = f"flight_{reason}_{payload['step']}"
        path = os.path.join(out_dir, base + ".json")
        k = 0
        while os.path.exists(path):  # same reason+step twice / shared dir
            k += 1
            path = os.path.join(out_dir, f"{base}_{os.getpid()}_{k}.json")
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except OSError:
            return None
        with self._lock:
            self.dumps.append(path)
        reg.counter("flight_dumps_total").inc(reason=reason)
        return path

    def spans_dumped(self) -> int:
        with self._lock:
            return self._spans_dumped

    def ring_len(self) -> int:
        return len(self._ring)


_recorder = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return _recorder


def reset(capacity: int = 2048):
    """Fresh recorder (tests); drops configuration and dump history."""
    global _recorder
    _recorder = FlightRecorder(capacity=capacity)


def configure(out_dir: Optional[str], process_index: int = 0):
    _recorder.configure(out_dir, process_index=process_index)


def record(span_rec: dict):
    _recorder.record(span_rec)


def dump(reason: str, step: Optional[int] = None,
         extra: Optional[dict] = None) -> Optional[str]:
    return _recorder.dump(reason, step=step, extra=extra)


def spans_dumped() -> int:
    return _recorder.spans_dumped()


def install_signal_handler(signum=None):
    """Dump on SIGUSR2 (operator "what are you doing right now").  Only
    possible from the main thread; elsewhere a no-op returning False."""
    signum = signum if signum is not None else signal.SIGUSR2
    if threading.current_thread() is not threading.main_thread():
        return False
    prev = signal.getsignal(signum)

    def _handler(sig, frame):
        _recorder.dump("sigusr2")
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
            prev(sig, frame)

    try:
        signal.signal(signum, _handler)
    except (ValueError, OSError):
        return False
    return True


# ---------------------------------------------------------------------------
# rank-0 merge of per-host dumps

def find_dumps(root: str, reason: Optional[str] = None) -> List[str]:
    """All flight dump files under ``root`` (recursive), optionally
    filtered by reason."""
    out = []
    prefix = f"flight_{reason}_" if reason else "flight_"
    for dirpath, _dirs, files in os.walk(root):
        for fn in files:
            if fn.startswith(prefix) and fn.endswith(".json"):
                out.append(os.path.join(dirpath, fn))
    return sorted(out)


def merge_dumps(paths: List[str], out_path: Optional[str] = None) -> dict:
    """Merge per-host dump files rank-0-side.

    Metric series are merged through
    ``telemetry.aggregate.merge_process_dicts`` so every series key gains
    a ``process_index=N`` label (per-host values stay distinct); spans
    are concatenated with a ``process_index`` field.  ``process_index``
    comes from the dump payload (written by each host's recorder).
    """
    from . import aggregate
    snapshots: Dict[int, dict] = {}
    spans: List[dict] = []
    dumps_meta = []
    for p in paths:
        with open(p) as f:
            d = json.load(f)
        idx = int(d.get("process_index", len(snapshots)))
        while idx in snapshots:   # two dumps from one host: keep both spans,
            idx += 1000           # displace the duplicate metrics snapshot
        snapshots[idx] = d.get("metrics", {})
        for sp in d.get("spans", []):
            sp = dict(sp)
            sp["process_index"] = d.get("process_index", idx)
            spans.append(sp)
        dumps_meta.append({"path": p, "reason": d.get("reason"),
                           "step": d.get("step"),
                           "process_index": d.get("process_index", idx)})
    merged = {
        "dumps": dumps_meta,
        "metrics": aggregate.merge_process_dicts(snapshots),
        "spans": spans,
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(merged, f)
    return merged
