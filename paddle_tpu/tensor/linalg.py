"""Linear algebra (reference: python/paddle/tensor/linalg.py; cuBLAS/cuSOLVER
kernels in operators/math/ — on TPU these are XLA MXU matmuls / host-offloaded
decompositions)."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    if p == "fro":
        if axis is None:
            return jnp.sqrt(jnp.sum(jnp.square(x)))
        return jnp.linalg.norm(x, ord="fro" if isinstance(axis, (list, tuple)) else None,
                               axis=tuple(axis) if isinstance(axis, (list, tuple)) else axis,
                               keepdims=keepdim)
    if p == float("inf") or p == "inf":
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf") or p == "-inf":
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.sum(jnp.abs(x) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)


def dist(x, y, p=2, name=None):
    return norm(x - y, p=p)


def cross(x, y, axis=9, name=None):
    if axis == 9:  # paddle default: first axis of size 3
        axis = next(i for i, s in enumerate(x.shape) if s == 3)
    return jnp.cross(x, y, axis=axis)


def cholesky(x, upper=False, name=None):
    out = jnp.linalg.cholesky(x)
    return jnp.swapaxes(out, -1, -2).conj() if upper else out


def cholesky_solve(x, y, upper=False, name=None):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


def inverse(x, name=None):
    return jnp.linalg.inv(x)


# paddle.linalg.inv spelling (reference python/paddle/linalg.py)
inv = inverse


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


def det(x, name=None):
    return jnp.linalg.det(x)


def slogdet(x, name=None):
    sign, logabs = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logabs])


def svd(x, full_matrices=False, name=None):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


def qr(x, mode="reduced", name=None):
    return jnp.linalg.qr(x, mode=mode)


def eig(x, name=None):
    return jnp.linalg.eig(x)


def eigh(x, UPLO="L", name=None):
    return jnp.linalg.eigh(x, UPLO=UPLO)


def eigvals(x, name=None):
    return jnp.linalg.eigvals(x)


def eigvalsh(x, UPLO="L", name=None):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def matrix_power(x, n, name=None):
    return jnp.linalg.matrix_power(x, n)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return jnp.linalg.matrix_rank(x, rtol=tol)


def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


def solve(x, y, name=None):
    return jnp.linalg.solve(x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


def multi_dot(x, name=None):
    return jnp.linalg.multi_dot(x)


def histogram(input, bins=100, min=0, max=0, name=None):
    import numpy as np
    arr = np.asarray(input)
    if min == 0 and max == 0:
        min, max = float(arr.min()), float(arr.max())
    hist, _ = np.histogram(arr, bins=bins, range=(min, max))
    return jnp.asarray(hist)


def bincount(x, weights=None, minlength=0, name=None):
    return jnp.bincount(x, weights=weights, minlength=minlength)


def einsum(equation, *operands):
    return jnp.einsum(equation, *operands)
