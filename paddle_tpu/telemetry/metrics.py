"""Labeled metrics: Counter / Gauge / Histogram behind a Registry.

The reference's platform/monitor.h exposes flat int64 StatValue gauges
registered in a global map; this is the generalization the rest of the
framework instruments against: three metric kinds, each holding a family
of series keyed by a (sorted) label set, collected by the exporters in
``telemetry.export`` (Prometheus text, JSONL, chrome-trace counters).

Design constraints (ISSUE 3):
- recording is host-side and cheap: one dict lookup + one lock per op,
  no jax imports, safe to call at trace time;
- metrics always record once you hold the object — the *instrumentation
  sites* in engine/io/checkpoint gate on ``telemetry.enabled()`` so the
  disabled cost is a module-global read per step;
- when ``marks_enabled`` is set on the registry (done by
  ``telemetry.scope``), every update also appends a timestamped mark so
  the chrome-trace exporter can emit a counter track aligned with the
  profiler's host ranges (same ``time.perf_counter_ns`` timebase).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "DEFAULT_BUCKETS",
           "StreamingQuantile"]

# Wide enough to cover dataloader fetches (~us) through checkpoint saves
# (~minutes); seconds everywhere.
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

LabelKey = Tuple[Tuple[str, str], ...]


class StreamingQuantile:
    """Quantile over the most recent ``maxlen`` observations.

    The one shared streaming-percentile implementation (ISSUE 18):
    ``tracing.KeepPolicy``'s tail-latency threshold and the calibration
    drift summaries both use it instead of carrying their own reservoir
    + sort. A bounded deque keeps the newest samples; the sorted view is
    cached and recomputed at most every ``recompute_every`` appends, so
    a quantile read between recomputes can be up to that many samples
    stale — fine for thresholds and summaries, where staleness only
    shifts borderline decisions. Not thread-safe on its own; callers
    that share an instance across threads hold their own lock (the
    pattern every user here already follows).
    """

    __slots__ = ("_values", "_adds", "_sorted", "recompute_every")

    def __init__(self, maxlen: int = 512, recompute_every: int = 64):
        self._values = deque(maxlen=maxlen)
        self._adds = 0
        self._sorted: Optional[List[float]] = None
        self.recompute_every = max(1, int(recompute_every))

    def add(self, v: float):
        self._values.append(float(v))
        self._adds += 1
        if self._sorted is not None and \
                self._adds % self.recompute_every == 0:
            self._sorted = None

    def __len__(self) -> int:
        return len(self._values)

    def quantile(self, q: float) -> Optional[float]:
        """The ``q``-quantile of the reservoir (None when empty), with
        the same nearest-rank index both former ad-hoc copies used."""
        n = len(self._values)
        if n == 0:
            return None
        if self._sorted is None or len(self._sorted) != n:
            self._sorted = sorted(self._values)
        return self._sorted[min(n - 1, int(float(q) * n))]

    def median(self) -> Optional[float]:
        return self.quantile(0.5)


def _label_key(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "", registry: "Registry" = None):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._series: Dict[LabelKey, object] = {}
        self._registry = registry

    def _mark(self, key: LabelKey, value: float):
        reg = self._registry
        if reg is not None and reg.marks_enabled:
            reg._mark(self.name, key, value)

    def label_keys(self) -> List[LabelKey]:
        with self._lock:
            return list(self._series.keys())

    def reset(self):
        with self._lock:
            self._series.clear()


class Counter(_Metric):
    """Monotonic sum per label set."""

    kind = "counter"

    def inc(self, n: float = 1.0, **labels) -> float:
        key = _label_key(labels)
        with self._lock:
            v = self._series.get(key, 0.0) + n
            self._series[key] = v
        self._mark(key, v)
        return v

    def value(self, **labels) -> float:
        with self._lock:
            if labels:
                return float(self._series.get(_label_key(labels), 0.0))
            return float(sum(self._series.values()))

    def series(self) -> Dict[LabelKey, float]:
        with self._lock:
            return dict(self._series)


class Gauge(_Metric):
    """Last-set value per label set."""

    kind = "gauge"

    def set(self, v: float, **labels) -> float:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = float(v)
        self._mark(key, float(v))
        return float(v)

    def inc(self, n: float = 1.0, **labels) -> float:
        key = _label_key(labels)
        with self._lock:
            v = self._series.get(key, 0.0) + n
            self._series[key] = v
        self._mark(key, v)
        return v

    def dec(self, n: float = 1.0, **labels) -> float:
        return self.inc(-n, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            if labels:
                return float(self._series.get(_label_key(labels), 0.0))
            if not self._series:
                return 0.0
            if len(self._series) == 1:
                return float(next(iter(self._series.values())))
            return float(self._series.get((), 0.0))

    def series(self) -> Dict[LabelKey, float]:
        with self._lock:
            return dict(self._series)


class _HistSeries:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, nbuckets: int):
        self.counts = [0] * nbuckets  # per-bucket (non-cumulative) counts
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    """Bucketed distribution per label set (Prometheus-style le buckets)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "", registry: "Registry" = None,
                 buckets: Optional[Iterable[float]] = None):
        super().__init__(name, help, registry)
        self.buckets: Tuple[float, ...] = tuple(
            sorted(buckets if buckets is not None else DEFAULT_BUCKETS))

    def observe(self, v: float, **labels):
        v = float(v)
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _HistSeries(len(self.buckets))
            # first bucket whose upper bound holds v; past-the-end = +Inf
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    s.counts[i] += 1
                    break
            s.sum += v
            s.count += 1
        self._mark(key, v)

    def count(self, **labels) -> int:
        with self._lock:
            if labels:
                s = self._series.get(_label_key(labels))
                return s.count if s else 0
            return sum(s.count for s in self._series.values())

    def sum(self, **labels) -> float:
        with self._lock:
            if labels:
                s = self._series.get(_label_key(labels))
                return s.sum if s else 0.0
            return float(sum(s.sum for s in self._series.values()))

    def value(self, **labels) -> float:
        """Mean of observations (convenience for logs folding)."""
        c = self.count(**labels)
        return self.sum(**labels) / c if c else 0.0

    def series(self) -> Dict[LabelKey, _HistSeries]:
        with self._lock:
            return dict(self._series)


class Registry:
    """Name -> metric map plus the (optional) timestamped mark buffer."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        self.marks_enabled = False
        self._marks = deque(maxlen=65536)  # (t_ns, name, labelkey, value)

    def _get_or_create(self, cls, name: str, help: str, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, registry=self, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Iterable[float]] = None) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Histogram(
                    name, help, registry=self, buckets=buckets)
            elif not isinstance(m, Histogram):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested histogram")
            return m

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def _mark(self, name: str, key: LabelKey, value: float):
        self._marks.append((time.perf_counter_ns(), name, key, value))

    def marks(self) -> List[Tuple[int, str, LabelKey, float]]:
        return list(self._marks)

    def clear_marks(self):
        self._marks.clear()

    def reset(self):
        """Drop every metric and mark (tests / fresh runs)."""
        with self._lock:
            self._metrics.clear()
        self._marks.clear()

    def to_dict(self) -> Dict[str, dict]:
        """JSON-friendly snapshot used by the JSONL summary event."""
        out = {}
        for m in self.metrics():
            if isinstance(m, Histogram):
                series = {_fmt_key(k): {"count": s.count, "sum": s.sum}
                          for k, s in m.series().items()}
            else:
                series = {_fmt_key(k): v for k, v in m.series().items()}
            out[m.name] = {"type": m.kind, "help": m.help, "series": series}
        return out


def _fmt_key(key: LabelKey) -> str:
    return ",".join(f"{k}={v}" for k, v in key) if key else ""
