"""paddle_tpu.jit — staging + export (reference: python/paddle/jit/
to_static, fluid/dygraph/jit.py:515 save, :876 load; dy2static AST machinery
fluid/dygraph/dygraph_to_static/).

The reference rewrites Python ASTs into a static Program. Here staging is
jax.jit over the functionalized layer, preceded by the dy2static AST pass
(dy2static.py): Python if/while/for-range over traced values are rewritten
to lax.cond/while_loop, boolean ops become tensor-aware lazy converters,
and unsupported constructs raise source-located diagnostics. Export is
StableHLO via jax.export (replacing save_inference_model's serialized
ProgramDesc).
"""
from __future__ import annotations

import os
import pickle
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .functionalization import functional_call, state_of, trainable_mask  # noqa: F401


class InputSpec:
    """reference: python/paddle/static/input.py InputSpec."""

    def __init__(self, shape, dtype="float32", name=None):
        from ..framework import dtype as dtype_mod
        self.shape = tuple(-1 if s is None else s for s in shape)
        self.dtype = dtype_mod.convert_dtype_to_jax(dtype)
        self.name = name

    def to_shape_dtype(self, batch_size=1):
        shape = tuple(batch_size if s == -1 else s for s in self.shape)
        return jax.ShapeDtypeStruct(shape, self.dtype)


class TracedLayer:
    """A Layer staged through jax.jit: callable with the same signature, pure
    and compiled. Buffers (e.g. BN stats) are frozen at trace time in eval
    mode (matching the reference's inference export)."""

    def __init__(self, layer, input_spec=None, jit_kwargs=None):
        self.layer = layer
        self.input_spec = input_spec
        params, buffers = state_of(layer)
        self._params = params
        self._buffers = buffers

        def pure(params, buffers, *args, **kwargs):
            out, _ = functional_call(layer, params, buffers, *args, **kwargs)
            return out

        self._pure = pure
        self._jitted = jax.jit(pure, **(jit_kwargs or {}))

    def refresh_state(self):
        self._params, self._buffers = state_of(self.layer)

    def __call__(self, *args, **kwargs):
        return self._jitted(self._params, self._buffers, *args, **kwargs)

    @property
    def forward(self):
        return self.__call__


def to_static(layer_or_fn=None, input_spec=None, **jit_kwargs):
    """Decorator/wrapper: stage a Layer or function with jax.jit after the
    dy2static AST pass (reference: paddle.jit.to_static ->
    program_translator.py:232 StaticFunction; AST transformers in
    dygraph_to_static/ast_transformer.py). Python if/while/for-range over
    traced tensors become lax.cond/while_loop; see jit/dy2static.py."""

    def wrap(obj):
        from ..nn.layer import Layer
        if not ProgramTranslator.enable_to_static:
            return obj
        if isinstance(obj, Layer):
            import types

            from .dy2static import convert_function
            try:
                converted = convert_function(type(obj).forward)
                obj.forward = types.MethodType(converted, obj)
            except Exception as e:  # uncovered shape: stage the original
                import warnings
                warnings.warn(
                    f"dy2static: AST conversion of "
                    f"{type(obj).__name__}.forward failed ({e}); staging "
                    "the original forward (tensor-dependent Python control "
                    "flow will fail to trace)")
            return TracedLayer(obj, input_spec, jit_kwargs)
        from .dy2static import convert_function
        return jax.jit(convert_function(obj), **jit_kwargs)

    if layer_or_fn is None:
        return wrap
    return wrap(layer_or_fn)


def _example_args(layer, input_spec: Optional[Sequence[InputSpec]]):
    if input_spec is None:
        raise ValueError("jit.save requires input_spec for tracing")
    return tuple(jnp.zeros(s.to_shape_dtype(1).shape, s.to_shape_dtype(1).dtype)
                 if isinstance(s, InputSpec) else jnp.asarray(s)
                 for s in input_spec)


def poly_arg_specs(input_spec, args):
    """Export-time arg specs: dynamic dims (None/-1 in an InputSpec) become
    symbolic shapes so the loaded model accepts any size there (the
    reference's ProgramDesc keeps -1 dims natively; StableHLO needs shape
    polymorphism). Shared by jit.save and static.save_inference_model.

    Symbol naming: dynamic dim 0 shares one "batch" symbol across all
    unnamed specs (so forward() may combine two dynamic-batch inputs —
    export can prove the dims equal); other dynamic dims get per-spec
    symbols. A named InputSpec scopes all its symbols by its name, letting
    the user decouple batch dims that are genuinely independent.
    """
    from jax import export as jax_export

    poly_specs = []
    for i, s in enumerate(input_spec):
        if isinstance(s, InputSpec) and any(d == -1 for d in s.shape):
            tag = s.name if s.name else None
            dims = []
            for j, d in enumerate(s.shape):
                if d != -1:
                    dims.append("_")
                elif j == 0:
                    dims.append(f"{tag}_batch" if tag else "batch")
                else:
                    dims.append(f"{tag}_d{j}" if tag else f"d{i}_{j}")
            poly_specs.append("(" + ", ".join(dims) + ")")
        else:
            poly_specs.append(None)
    if any(p is not None for p in poly_specs):
        return jax_export.symbolic_args_specs(args, poly_specs)
    return args


def save(layer, path, input_spec=None, **configs):
    """Export a Layer as StableHLO + params (reference: fluid/dygraph/jit.py:515
    jit.save → __model__ + params; here: .stablehlo + .pdiparams pickle)."""
    from jax import export as jax_export

    layer.eval()
    params, buffers = state_of(layer)
    params, buffers = dict(params), dict(buffers)

    def pure(params, buffers, *args):
        out, _ = functional_call(layer, params, buffers, *args)
        return out

    args = _example_args(layer, input_spec)
    arg_specs = poly_arg_specs(input_spec, args)
    exported = jax_export.export(jax.jit(pure))(
        jax.tree_util.tree_map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params),
        jax.tree_util.tree_map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), buffers),
        *arg_specs)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".stablehlo", "wb") as f:
        f.write(exported.serialize())
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump({
            "params": {k: np.asarray(v) for k, v in params.items()},
            "buffers": {k: np.asarray(v) for k, v in buffers.items()},
        }, f)


class TranslatedLayer:
    """Loaded exported model (reference: fluid/dygraph/io.py:1082)."""

    def __init__(self, exported, params, buffers):
        self._exported = exported
        self._params = params
        self._buffers = buffers

    def __call__(self, *args):
        return self._exported.call(self._params, self._buffers, *args)

    forward = __call__

    def eval(self):
        return self

    def parameters(self):
        return list(self._params.values())


def load(path, **configs) -> TranslatedLayer:
    from jax import export as jax_export

    with open(path + ".stablehlo", "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(path + ".pdiparams", "rb") as f:
        blob = pickle.load(f)
    params = {k: jnp.asarray(v) for k, v in blob["params"].items()}
    buffers = {k: jnp.asarray(v) for k, v in blob["buffers"].items()}
    return TranslatedLayer(exported, params, buffers)


def not_to_static(fn):
    return fn


# -- dy2static compat surface (reference fluid/dygraph/dygraph_to_static) ----
_verbosity = 0
_code_level = 0


def set_verbosity(level: int = 0, also_to_stdout: bool = False):
    """Compat with reference jit.set_verbosity: there is no AST transpiler to
    log (jax.jit traces Python directly), so this only records the level."""
    global _verbosity
    _verbosity = int(level)


def set_code_level(level: int = 100, also_to_stdout: bool = False):
    """Compat with reference jit.set_code_level (transformed-code printing)."""
    global _code_level
    _code_level = int(level)


class ProgramTranslator:
    """Singleton compat shim (reference dygraph_to_static/program_translator
    .py:232). ``enable(False)`` disables staging: to_static returns the
    original callable unchanged."""

    _instance = None
    enable_to_static = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static: bool):
        ProgramTranslator.enable_to_static = bool(enable_to_static)
