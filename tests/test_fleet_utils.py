"""Recompute (C54) + GradientMerge (C55) tests.
(reference analogues: test_dygraph_recompute.py, gradient-merge tests)."""
import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.engine import ParallelTrainer
from paddle_tpu.distributed.fleet.utils import (checkpoint_policy,
                                                fused_allreduce_gradients,
                                                recompute)
from paddle_tpu.distributed.mesh import build_mesh


def test_recompute_same_values_and_grads():
    paddle.seed(0)
    block = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 8))
    x = jnp.asarray(np.random.RandomState(0).randn(4, 8), dtype=jnp.float32)

    y_plain = block(x)
    y_rc = recompute(block, x)
    np.testing.assert_allclose(np.asarray(y_rc), np.asarray(y_plain),
                               rtol=1e-6)

    def loss_plain(xx):
        return jnp.sum(block(xx) ** 2)

    def loss_rc(xx):
        return jnp.sum(recompute(block, xx) ** 2)

    g0 = jax.grad(loss_plain)(x)
    g1 = jax.grad(loss_rc)(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), rtol=1e-5)


def test_recompute_policy_names():
    assert checkpoint_policy("dots_saveable") is not None
    assert checkpoint_policy(None) is None
    import pytest
    with pytest.raises(ValueError, match="unknown checkpoint policy"):
        checkpoint_policy("bogus")


def test_recompute_dropout_deterministic_under_jit():
    """Randomness must match between saved fwd and recomputed fwd — free with
    functional PRNG (the reference needs explicit RNG state tracking)."""
    paddle.seed(0)
    drop = nn.Dropout(0.5)
    from paddle_tpu.jit.functionalization import functional_call

    def f(x, key):
        out, _ = functional_call(drop, {}, {}, x, rng=key)
        return jnp.sum(out * out)

    def f_rc(x, key):
        return jnp.sum(recompute(
            lambda xx: functional_call(drop, {}, {}, xx, rng=key)[0], x) ** 2)

    x = jnp.ones((64,))
    key = jax.random.PRNGKey(0)
    # grads agree → the recomputed forward used the same dropout mask
    g0 = jax.jit(jax.grad(lambda xx: f(xx, key) ** 0.5))(x)
    g1 = jax.jit(jax.grad(lambda xx: f_rc(xx, key) ** 0.5))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), rtol=1e-5)


def test_fused_allreduce_gradients_outside_spmd_noop():
    g = {"w": jnp.ones((2, 2))}
    out = fused_allreduce_gradients(g)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray(g["w"]))


def test_gradient_merge_matches_big_batch():
    build_mesh({"data": 2})

    def loss_fn(out, y):
        return jnp.mean((out - y) ** 2)

    rs = np.random.RandomState(0)
    x, y = rs.rand(8, 6).astype("f4"), rs.rand(8, 4).astype("f4")

    def make():
        paddle.seed(42)
        net = nn.Linear(6, 4)
        opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
        return net, opt

    net1, opt1 = make()
    t1 = ParallelTrainer(net1, opt1, loss_fn)
    l1 = float(t1.train_step(x, y))
    w1 = np.asarray(t1.state["params"]["weight"])

    net2, opt2 = make()
    t2 = ParallelTrainer(net2, opt2, loss_fn, accumulate_steps=4)
    l2 = float(t2.train_step(x, y))
    w2 = np.asarray(t2.state["params"]["weight"])

    assert abs(l1 - l2) < 1e-6
    np.testing.assert_allclose(w2, w1, rtol=1e-5, atol=1e-6)


class TestSavePersistables:
    def test_model_scope_and_ps_shard(self, tmp_path, monkeypatch):
        import os
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu import nn, static
        from paddle_tpu.distributed import fleet as fleet_mod

        f = fleet_mod.Fleet()
        net = nn.Linear(4, 2)
        out = f.save_persistables(dirname=str(tmp_path / "m"), model=net)
        st = paddle.load(os.path.join(out, "model.pdparams"))
        np.testing.assert_array_equal(np.asarray(st["weight"]),
                                      np.asarray(net.weight.value))
        # scope variant picks up static-program parameters
        prog = static.Program.trace(
            lambda x: static.nn.fc(x, 3), static.data("x", [2, 4]))
        static.Executor().run(prog, feed={"x": np.ones((2, 4), "f4")})
        out2 = f.save_persistables(dirname=str(tmp_path / "s"))
        assert len(paddle.load(os.path.join(out2, "scope.pdparams"))) > 0
        # hosted PS shard rides along
        srv = f.init_server(dim=4, optimizer="sgd", port=0)
        srv.table.pull(np.asarray([1, 2], np.int64))
        out3 = f.save_persistables(dirname=str(tmp_path / "p"), model=net)
        assert os.path.exists(os.path.join(out3, "sparse_shard.bin"))
        f.stop_server()

        import pytest as _pytest
        with _pytest.raises(ValueError, match="dirname"):
            f.save_persistables()


class TestFleetSave:
    def test_save_persistables_and_inference_paths(self, tmp_path):
        import os
        import numpy as np
        import jax.numpy as jnp
        import paddle_tpu as paddle
        from paddle_tpu import nn
        from paddle_tpu.distributed import fleet as fleet_mod
        from paddle_tpu.jit import InputSpec

        f = fleet_mod.Fleet()
        net = nn.Linear(4, 2)
        net.eval()
        # no feed/fetch -> persistables
        out = f.save(str(tmp_path / "pers"), model=net)
        assert os.path.exists(os.path.join(out, "model.pdparams"))
        # with input_spec -> StableHLO inference artifact, loadable
        path = f.save(str(tmp_path / "inf"), model=net,
                      input_spec=[InputSpec([1, 4])])
        loaded = paddle.jit.load(path)
        x = jnp.ones((1, 4))
        np.testing.assert_allclose(np.asarray(net(x)),
                                   np.asarray(loaded(x)), rtol=1e-5)
        import pytest as _pytest
        with _pytest.raises(ValueError, match="model"):
            f.save(str(tmp_path / "bad"), feed=["x"], fetch=["out"])
        # save_inference_model without input_spec: an empty spec would
        # export a 0-input graph — must be named, not silently exported
        with _pytest.raises(ValueError, match="input_spec"):
            f.save_inference_model(dirname=str(tmp_path / "bad2"),
                                   model=net)

    def test_init_server_port_uses_pserver_id(self, monkeypatch):
        """The server's slot in PADDLE_PSERVER_ENDPOINTS is indexed by
        PADDLE_PSERVER_ID (the server role's own index), not the trainer
        id (ADVICE r3: a trainer id that happens to be in range would
        silently bind another server's port)."""
        from paddle_tpu.distributed import fleet as fleet_mod

        f = fleet_mod.Fleet()
        monkeypatch.setenv("PADDLE_PSERVER_ENDPOINTS",
                           "127.0.0.1:0,127.0.0.1:1")
        monkeypatch.setenv("PADDLE_PSERVER_ID", "0")
        monkeypatch.setenv("PADDLE_TRAINER_ID", "1")  # would pick :1
        srv = f.init_server(dim=4, optimizer="sgd")
        try:
            # PSERVER_ID=0 selects endpoint :0 (ephemeral bind), proving
            # the trainer id was ignored; picking :1 would either bind
            # port 1 (EACCES) or error
            assert srv.endpoint.rsplit(":", 1)[1] not in ("1",)
        finally:
            f.stop_server()
            srv.stop()
