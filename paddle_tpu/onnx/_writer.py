"""Layer-graph -> ONNX ModelProto converter (wire-format, dependency-free).

The reference's paddle2onnx converts per-op from a traced Program; the TPU
framework's interchange format is StableHLO (jit.save), and this module adds
genuine ONNX emission for the feed-forward layer graphs that cover the model
zoo's CNN/MLP family (LeNet, VGG-style stacks, MLPs): Sequential-like
containers of Linear / Conv2D / pool / activation / norm / flatten /
dropout. Anything the walker cannot express raises NotImplementedError and
the caller falls back to StableHLO with a warning.

ONNX field numbers per onnx/onnx.proto; see _pb.py.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from . import _pb

# TensorProto.DataType
FLOAT, INT64 = 1, 7

# AttributeProto.AttributeType
ATTR_FLOAT, ATTR_INT, ATTR_INTS = 1, 2, 7


def _attr(name: str, value) -> bytes:
    body = _pb.f_str(1, name)
    if isinstance(value, float):
        body += _pb.tag(2, 5) + __import__("struct").pack("<f", value)
        body += _pb.f_varint(20, ATTR_FLOAT)
    elif isinstance(value, int):
        body += _pb.f_varint(3, value)
        body += _pb.f_varint(20, ATTR_INT)
    elif isinstance(value, (list, tuple)):
        for v in value:
            body += _pb.f_varint(8, int(v))
        body += _pb.f_varint(20, ATTR_INTS)
    else:
        raise TypeError(f"unsupported attr {name}={value!r}")
    return body


def _node(op_type: str, inputs: List[str], outputs: List[str],
          name: str = "", attrs: Optional[dict] = None) -> bytes:
    body = b"".join(_pb.f_str(1, i) for i in inputs)
    body += b"".join(_pb.f_str(2, o) for o in outputs)
    if name:
        body += _pb.f_str(3, name)
    body += _pb.f_str(4, op_type)
    for k, v in (attrs or {}).items():
        body += _pb.f_bytes(5, _attr(k, v))
    return body


# ONNX TensorProto.DataType enums for the exact-dtype policy: integer
# widths are preserved (an int32-ids model must load with int32 inputs —
# widening to i64 broke consumers), floats keep their width, and bf16 is
# exported as FLOAT (documented: every bf16 value is exactly
# representable in f32, and runtime BFLOAT16 kernel coverage is patchy).
_NP_TO_ONNX = {
    np.dtype(np.float32): 1, np.dtype(np.uint8): 2, np.dtype(np.int8): 3,
    np.dtype(np.uint16): 4, np.dtype(np.int16): 5, np.dtype(np.int32): 6,
    np.dtype(np.int64): 7, np.dtype(np.bool_): 9,
    np.dtype(np.float16): 10, np.dtype(np.float64): 11,
    np.dtype(np.uint32): 12, np.dtype(np.uint64): 13,
}


def _np_onnx_dtype(arr: np.ndarray):
    """(storage array, onnx enum) under the exact-dtype policy."""
    if str(arr.dtype) == "bfloat16":
        return arr.astype(np.float32), 1
    dt = _NP_TO_ONNX.get(arr.dtype)
    if dt is None:
        raise NotImplementedError(f"dtype {arr.dtype} in ONNX export")
    return arr, dt


def _tensor(name: str, arr: np.ndarray) -> bytes:
    arr, dt = _np_onnx_dtype(np.asarray(arr))
    body = b"".join(_pb.f_varint(1, int(d)) for d in arr.shape)
    body += _pb.f_varint(2, dt)
    body += _pb.f_str(8, name)
    data = np.ascontiguousarray(arr)
    if dt == 9:  # BOOL raw_data is one byte per element
        data = data.astype(np.uint8)
    body += _pb.f_bytes(9, data.tobytes())
    return body


def _value_info(name: str, shape, elem_type: int = FLOAT) -> bytes:
    dims = b""
    for d in shape:
        if d is None or (isinstance(d, int) and d < 0):
            dim = _pb.f_str(2, "N")  # dim_param (dynamic batch)
        else:
            dim = _pb.f_varint(1, int(d))
        dims += _pb.f_bytes(1, dim)
    tensor_type = _pb.f_varint(1, elem_type) + _pb.f_bytes(2, dims)
    type_proto = _pb.f_bytes(1, tensor_type)
    return _pb.f_str(1, name) + _pb.f_bytes(2, type_proto)


def _model(graph: bytes, opset_version: int) -> bytes:
    opset = _pb.f_str(1, "") + _pb.f_varint(2, opset_version)
    return (_pb.f_varint(1, 8)                      # ir_version = 8
            + _pb.f_str(2, "paddle_tpu")            # producer_name
            + _pb.f_bytes(7, graph)
            + _pb.f_bytes(8, opset))


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _pads4(padding):
    """paddle padding -> ONNX pads [t, l, b, r]."""
    if isinstance(padding, int):
        return [padding] * 4
    p = list(padding)
    if len(p) == 2:                     # (ph, pw)
        return [p[0], p[1], p[0], p[1]]
    if len(p) == 4:                     # (t, b, l, r) paddle order
        return [p[0], p[2], p[1], p[3]]
    raise NotImplementedError(f"padding {padding!r}")


class _GraphBuilder:
    def __init__(self):
        self.nodes: List[bytes] = []
        self.initializers: List[bytes] = []
        self.counter = 0

    def fresh(self, hint: str) -> str:
        self.counter += 1
        return f"{hint}_{self.counter}"

    def add_init(self, hint: str, arr) -> str:
        name = self.fresh(hint)
        self.initializers.append(_tensor(name, np.asarray(arr)))
        return name

    def add_node(self, op_type, inputs, outputs, attrs=None):
        self.nodes.append(_node(op_type, inputs, outputs,
                                name=self.fresh(op_type.lower()),
                                attrs=attrs))


# Non-container zoo models whose forward is verified to be a plain
# sequential composition of their registered sublayers (+auto-flatten
# before Linear). Models with skip connections (ResNet, MobileNetV2) must
# NOT be added here — a child-walk would silently drop the residual adds.
_SEQUENTIAL_SAFE = {"LeNet"}


def _flatten_layers(layer):
    """Yield the execution-ordered leaf layers of Sequential-style models."""
    from ..nn.layers.container import LayerList, Sequential
    if isinstance(layer, (Sequential, LayerList)):
        for sub in layer:
            yield from _flatten_layers(sub)
        return
    subs = list(layer.children()) if hasattr(layer, "children") else []
    if not subs:
        yield layer
        return
    if type(layer).__name__ in _SEQUENTIAL_SAFE:
        for sub in subs:
            yield from _flatten_layers(sub)
        return
    raise NotImplementedError(
        f"layer {type(layer).__name__} has sublayers with a custom forward; "
        "only Sequential-style compositions are convertible")


_SIMPLE_ACTS = {
    "ReLU": "Relu", "Sigmoid": "Sigmoid", "Tanh": "Tanh", "ELU": "Elu",
    "Softplus": "Softplus", "Softsign": "Softsign", "SELU": "Selu",
    "Identity": "Identity",
}


def _np(v):
    return np.asarray(getattr(v, "value", v))


def _convert_layer(g: _GraphBuilder, layer, cur: str) -> str:
    """Append nodes for `layer`, consuming tensor `cur`; return output name."""
    cls = type(layer).__name__
    if cls in _SIMPLE_ACTS:
        kwargs = dict(getattr(layer, "_kwargs", {}) or {})
        attrs = None
        if cls == "ELU" and set(kwargs) <= {"alpha"}:
            attrs = {"alpha": float(kwargs.get("alpha", 1.0))}
            kwargs.pop("alpha", None)
        if kwargs:
            # e.g. GELU(approximate=True), Softplus(beta=...): the bare ONNX
            # node would silently compute a different function
            raise NotImplementedError(
                f"{cls} with kwargs {sorted(kwargs)} has no exact ONNX "
                "equivalent")
        out = g.fresh("act")
        g.add_node(_SIMPLE_ACTS[cls], [cur], [out], attrs)
        return out
    if cls == "LeakyReLU":
        out = g.fresh("leaky")
        g.add_node("LeakyRelu", [cur], [out],
                   {"alpha": float(layer.negative_slope)})
        return out
    if cls == "GELU":
        if getattr(layer, "_kwargs", {}).get("approximate"):
            raise NotImplementedError(
                "GELU(approximate=True) (tanh form) has no exact ONNX "
                "expansion here; only the erf form is emitted")
        # opset<20 has no Gelu: x * 0.5 * (1 + erf(x/sqrt(2)))
        s = g.add_init("gelu_scale", np.float32(1.0 / np.sqrt(2.0)))
        half = g.add_init("gelu_half", np.float32(0.5))
        one = g.add_init("gelu_one", np.float32(1.0))
        t1, t2, t3, t4, out = (g.fresh("gelu") for _ in range(5))
        g.add_node("Mul", [cur, s], [t1])
        g.add_node("Erf", [t1], [t2])
        g.add_node("Add", [t2, one], [t3])
        g.add_node("Mul", [t3, half], [t4])
        g.add_node("Mul", [cur, t4], [out])
        return out
    if cls == "Softmax":
        out = g.fresh("softmax")
        g.add_node("Softmax", [cur], [out],
                   {"axis": int(getattr(layer, "axis", -1))})
        return out
    if cls in ("Dropout", "Dropout2D", "Dropout3D", "AlphaDropout"):
        return cur  # inference export: dropout is identity
    if cls == "Flatten":
        if layer.start_axis != 1 or layer.stop_axis not in (-1, 3):
            raise NotImplementedError("Flatten with non-default axes")
        out = g.fresh("flat")
        g.add_node("Flatten", [cur], [out], {"axis": 1})
        return out
    if cls == "Linear":
        w = g.add_init("weight", _np(layer.weight))
        ins = [cur, w]
        if layer.bias is not None:
            ins.append(g.add_init("bias", _np(layer.bias)))
        out = g.fresh("gemm")
        g.add_node("Gemm", ins, [out], {"alpha": 1.0, "beta": 1.0,
                                        "transA": 0, "transB": 0})
        return out
    if cls == "Conv2D":
        if layer.data_format != "NCHW":
            raise NotImplementedError("ONNX Conv requires NCHW")
        w = g.add_init("conv_w", _np(layer.weight))
        ins = [cur, w]
        if layer.bias is not None:
            ins.append(g.add_init("conv_b", _np(layer.bias)))
        out = g.fresh("conv")
        g.add_node("Conv", ins, [out], {
            "kernel_shape": list(layer.kernel_size),
            "strides": list(_pair(layer.stride)),
            "pads": _pads4(layer.padding),
            "dilations": list(_pair(layer.dilation)),
            "group": int(layer.groups)})
        return out
    if cls in ("MaxPool2D", "AvgPool2D"):
        if layer._kw.get("ceil_mode"):
            raise NotImplementedError(f"{cls} with ceil_mode=True")
        if layer._kw.get("data_format", "NCHW") != "NCHW":
            raise NotImplementedError("ONNX pooling requires NCHW")
        out = g.fresh("pool")
        k = _pair(layer.kernel_size)
        s = _pair(layer.stride if layer.stride is not None
                  else layer.kernel_size)
        attrs = {"kernel_shape": list(k), "strides": list(s),
                 "pads": _pads4(layer.padding)}
        if cls == "AvgPool2D":
            attrs["count_include_pad"] = 0 if layer._kw.get(
                "exclusive", True) else 1
        g.add_node("MaxPool" if cls == "MaxPool2D" else "AveragePool",
                   [cur], [out], attrs)
        return out
    if cls == "AdaptiveAvgPool2D":
        if tuple(np.atleast_1d(layer.output_size)) not in ((1,), (1, 1)):
            raise NotImplementedError("AdaptiveAvgPool2D only to (1,1)")
        out = g.fresh("gap")
        g.add_node("GlobalAveragePool", [cur], [out])
        return out
    if cls in ("BatchNorm2D", "BatchNorm1D", "BatchNorm"):
        n = layer.num_features
        scale = g.add_init("bn_scale", _np(layer.weight)
                           if layer.weight is not None else np.ones(n, "f"))
        bias = g.add_init("bn_bias", _np(layer.bias)
                          if layer.bias is not None else np.zeros(n, "f"))
        mean = g.add_init("bn_mean", _np(layer._mean))
        var = g.add_init("bn_var", _np(layer._variance))
        out = g.fresh("bn")
        g.add_node("BatchNormalization", [cur, scale, bias, mean, var],
                   [out], {"epsilon": float(layer.epsilon)})
        return out
    raise NotImplementedError(f"no ONNX converter for layer {cls}")


def _out_shape(layer, in_shape):
    """Output shape via abstract evaluation (batch kept dynamic if it was)."""
    import jax
    import jax.numpy as jnp
    concrete = [1 if (d is None or d < 0) else int(d) for d in in_shape]
    try:
        out = jax.eval_shape(
            lambda x: layer(x), jnp.zeros(concrete, jnp.float32))
        shape = list(out.shape)
        if in_shape and (in_shape[0] is None or in_shape[0] < 0):
            shape[0] = None
        return shape
    except Exception:
        return [None]


def export_layer_to_onnx(layer, path: str, input_spec=None,
                         opset_version: int = 13) -> str:
    """Convert a Sequential-style Layer into an ONNX file at `path`."""
    if input_spec is None:
        raise NotImplementedError("onnx export requires input_spec")
    spec = input_spec[0] if isinstance(input_spec, (list, tuple)) else input_spec
    shape = list(getattr(spec, "shape", spec))
    g = _GraphBuilder()
    cur = "input"
    rank = len(shape)
    # Auto-inserting Flatten before Linear is only known-correct for the
    # whitelisted zoo models (their forward really flattens there). A plain
    # Sequential applying Linear to a rank>2 tensor maps over the LAST dim
    # (F.linear), which Gemm-after-Flatten would NOT compute — refuse and
    # fall back rather than emit a different function.
    allow_autoflatten = type(layer).__name__ in _SEQUENTIAL_SAFE
    for leaf in _flatten_layers(layer):
        if type(leaf).__name__ == "Linear" and rank > 2:
            if not allow_autoflatten:
                raise NotImplementedError(
                    "Linear on a rank>2 tensor (last-dim matmul) has no "
                    "Gemm equivalent without an explicit Flatten layer")
            flat = g.fresh("autoflat")
            g.add_node("Flatten", [cur], [flat], {"axis": 1})
            cur, rank = flat, 2
        cur = _convert_layer(g, leaf, cur)
        if type(leaf).__name__ == "Flatten":
            rank = 2
    out_name = g.fresh("output")
    g.add_node("Identity", [cur], [out_name])
    graph = b"".join(_pb.f_bytes(1, n) for n in g.nodes)
    graph += _pb.f_str(2, "paddle_tpu_graph")
    graph += b"".join(_pb.f_bytes(5, t) for t in g.initializers)
    graph += _pb.f_bytes(11, _value_info("input", shape))
    graph += _pb.f_bytes(12, _value_info(out_name, _out_shape(layer, shape)))
    model = _model(graph, opset_version)
    with open(path, "wb") as f:
        f.write(model)
    return path
