"""Launcher (C57) tests: env wiring, watch loop, elastic restart.
(reference analogues: test_launch_coverage.py, elastic unit tests)."""
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return str(p)


def _run_launch(args, cwd):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # workers must not inherit pytest's jax platform state
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch"] + args,
        capture_output=True, text=True, env=env, cwd=cwd, timeout=120)


def test_launch_sets_cluster_env(tmp_path):
    script = _write(tmp_path, "worker.py", """
        import os, sys
        rank = int(os.environ["PADDLE_TRAINER_ID"])
        n = int(os.environ["PADDLE_TRAINERS_NUM"])
        eps = os.environ["PADDLE_TRAINER_ENDPOINTS"].split(",")
        assert len(eps) == n == 2
        assert os.environ["PADDLE_CURRENT_ENDPOINT"] == eps[rank]
        assert os.environ["JAX_PROCESS_ID"] == str(rank)
        assert os.environ["JAX_NUM_PROCESSES"] == "2"
        print(f"rank {rank} ok")
    """)
    r = _run_launch(["--nproc_per_node", "2", "--log_dir",
                     str(tmp_path / "logs"), script], cwd=str(tmp_path))
    assert r.returncode == 0, r.stderr
    logs = sorted(os.listdir(tmp_path / "logs"))
    assert logs == ["workerlog.0", "workerlog.1"]
    assert "rank 0 ok" in (tmp_path / "logs" / "workerlog.0").read_text()


def test_launch_fail_fast(tmp_path):
    script = _write(tmp_path, "bad.py", """
        import os, sys, time
        if os.environ["PADDLE_TRAINER_ID"] == "1":
            sys.exit(3)
        time.sleep(30)   # rank 0 hangs; supervisor must kill it
    """)
    r = _run_launch(["--nproc_per_node", "2", script], cwd=str(tmp_path))
    assert r.returncode == 3
    assert "rank 1 exited with 3" in r.stderr


def test_launch_elastic_restart(tmp_path):
    # rank 0 fails on the first incarnation, succeeds after relaunch
    script = _write(tmp_path, "flaky.py", """
        import os, sys
        flag = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "restarted.flag")
        if os.environ["PADDLE_TRAINER_ID"] == "0" and not os.path.exists(flag):
            open(flag, "w").close()
            sys.exit(7)
        print("survived", os.environ["PADDLE_TRAINER_ID"])
    """)
    r = _run_launch(["--nproc_per_node", "2", "--max_restarts", "2", script],
                    cwd=str(tmp_path))
    assert r.returncode == 0, r.stderr
    assert "elastic restart 1/2" in r.stderr
