// Graph table for graph-learning workloads — the capability of the
// reference's distributed/table/common_graph_table.cc (sharded adjacency
// store + uniform neighbor sampling + node feature rows; NOT a port: fresh
// unordered_map adjacency with per-shard locks, xorshift sampling, C ABI
// for ctypes). Multi-host sharding happens above by node-key hash routing,
// exactly like the sparse table (distributed/ps/service.py).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <mutex>
#include <random>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

constexpr int kShards = 64;

struct Node {
  std::vector<int64_t> neighbors;
  std::vector<float> weights;   // empty = unweighted
  std::vector<float> feature;   // empty = no feature
};

struct GShard {
  std::unordered_map<int64_t, Node> nodes;
  std::mutex mu;
};

class GraphTable {
 public:
  explicit GraphTable(int feat_dim, uint64_t seed)
      : feat_dim_(feat_dim), seed_(seed) {}

  static int ShardOf(int64_t key) {
    uint64_t h = static_cast<uint64_t>(key);
    h ^= h >> 33;
    h *= 0xff51afd7ed558ccdULL;
    h ^= h >> 33;
    return static_cast<int>(h % kShards);
  }

  void AddEdges(const int64_t* src, const int64_t* dst, const float* w,
                int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
      GShard& s = shards_[ShardOf(src[i])];
      std::lock_guard<std::mutex> lk(s.mu);
      Node& node = s.nodes[src[i]];
      node.neighbors.push_back(dst[i]);
      // weights stay index-aligned with neighbors across mixed
      // weighted/unweighted AddEdges calls: unweighted inserts get 1.0
      node.weights.push_back(w ? w[i] : 1.0f);
    }
  }

  void SetFeature(const int64_t* keys, const float* feats, int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
      GShard& s = shards_[ShardOf(keys[i])];
      std::lock_guard<std::mutex> lk(s.mu);
      Node& node = s.nodes[keys[i]];
      node.feature.assign(feats + i * feat_dim_,
                          feats + (i + 1) * feat_dim_);
    }
  }

  void GetFeature(const int64_t* keys, float* out, int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
      GShard& s = shards_[ShardOf(keys[i])];
      std::lock_guard<std::mutex> lk(s.mu);
      auto it = s.nodes.find(keys[i]);
      if (it != s.nodes.end() &&
          static_cast<int>(it->second.feature.size()) == feat_dim_) {
        std::memcpy(out + i * feat_dim_, it->second.feature.data(),
                    sizeof(float) * feat_dim_);
      } else {
        std::memset(out + i * feat_dim_, 0, sizeof(float) * feat_dim_);
      }
    }
  }

  int64_t Degree(int64_t key) {
    GShard& s = shards_[ShardOf(key)];
    std::lock_guard<std::mutex> lk(s.mu);
    auto it = s.nodes.find(key);
    return it == s.nodes.end()
               ? 0
               : static_cast<int64_t>(it->second.neighbors.size());
  }

  // Uniform sample (with replacement if degree < k, reference
  // random_sample_neighboors semantics return actual count): out gets k
  // slots per key, missing filled with -1; counts[i] = actual neighbors
  // written.
  void SampleNeighbors(const int64_t* keys, int64_t n, int k, uint64_t seed,
                       int64_t* out, int64_t* counts, int weighted) {
    for (int64_t i = 0; i < n; ++i) {
      GShard& s = shards_[ShardOf(keys[i])];
      std::lock_guard<std::mutex> lk(s.mu);
      auto it = s.nodes.find(keys[i]);
      int64_t* dst = out + i * k;
      if (it == s.nodes.end() || it->second.neighbors.empty()) {
        for (int j = 0; j < k; ++j) dst[j] = -1;
        counts[i] = 0;
        continue;
      }
      const auto& nb = it->second.neighbors;
      int64_t deg = static_cast<int64_t>(nb.size());
      std::mt19937_64 rng(seed_ ^ seed ^
                          (static_cast<uint64_t>(keys[i]) * 0x9e3779b9ULL));
      if (weighted && deg > k) {
        // Efraimidis-Spirakis weighted sampling without replacement:
        // key_j = u_j^(1/w_j); take the k largest keys.
        const auto& wt = it->second.weights;
        std::uniform_real_distribution<double> uni(
            std::numeric_limits<double>::min(), 1.0);
        std::vector<std::pair<double, int64_t>> es(deg);
        for (int64_t j = 0; j < deg; ++j) {
          double w = (j < static_cast<int64_t>(wt.size()) && wt[j] > 0.0f)
                         ? static_cast<double>(wt[j])
                         : 1e-12;
          es[j] = {std::pow(uni(rng), 1.0 / w), nb[j]};
        }
        std::nth_element(es.begin(), es.begin() + k, es.end(),
                         [](const auto& a, const auto& b) {
                           return a.first > b.first;
                         });
        for (int j = 0; j < k; ++j) dst[j] = es[j].second;
        counts[i] = k;
        continue;
      }
      if (deg <= k) {
        // all neighbors (shuffled), pad with -1
        std::vector<int64_t> perm(nb);
        for (int64_t j = deg - 1; j > 0; --j) {
          std::swap(perm[j], perm[rng() % (j + 1)]);
        }
        for (int64_t j = 0; j < k; ++j) dst[j] = j < deg ? perm[j] : -1;
        counts[i] = deg;
      } else {
        // Floyd's sampling without replacement
        std::unordered_map<int64_t, int64_t> repl;
        for (int64_t j = 0; j < k; ++j) {
          int64_t r = static_cast<int64_t>(rng() % (deg - j)) + j;
          int64_t vj = repl.count(j) ? repl[j] : j;
          int64_t vr = repl.count(r) ? repl[r] : r;
          dst[j] = nb[vr];
          repl[r] = vj;
        }
        counts[i] = k;
      }
    }
  }

  int64_t NumNodes() {
    int64_t n = 0;
    for (auto& s : shards_) {
      std::lock_guard<std::mutex> lk(s.mu);
      n += static_cast<int64_t>(s.nodes.size());
    }
    return n;
  }

 private:
  int feat_dim_;
  uint64_t seed_;
  GShard shards_[kShards];
};

}  // namespace

extern "C" {

void* ps_graph_create(int feat_dim, uint64_t seed) {
  return new GraphTable(feat_dim, seed);
}

void ps_graph_destroy(void* g) { delete static_cast<GraphTable*>(g); }

void ps_graph_add_edges(void* g, const int64_t* src, const int64_t* dst,
                        const float* w, int64_t n) {
  static_cast<GraphTable*>(g)->AddEdges(src, dst, w, n);
}

void ps_graph_set_feature(void* g, const int64_t* keys, const float* feats,
                          int64_t n) {
  static_cast<GraphTable*>(g)->SetFeature(keys, feats, n);
}

void ps_graph_get_feature(void* g, const int64_t* keys, float* out,
                          int64_t n) {
  static_cast<GraphTable*>(g)->GetFeature(keys, out, n);
}

int64_t ps_graph_degree(void* g, int64_t key) {
  return static_cast<GraphTable*>(g)->Degree(key);
}

void ps_graph_sample_neighbors(void* g, const int64_t* keys, int64_t n,
                               int k, uint64_t seed, int64_t* out,
                               int64_t* counts, int weighted) {
  static_cast<GraphTable*>(g)->SampleNeighbors(keys, n, k, seed, out,
                                               counts, weighted);
}

int64_t ps_graph_num_nodes(void* g) {
  return static_cast<GraphTable*>(g)->NumNodes();
}

}  // extern "C"
