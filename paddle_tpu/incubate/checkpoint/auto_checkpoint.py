"""Auto-checkpoint for transparent resume after preemption (reference:
incubate/checkpoint/auto_checkpoint.py — TrainEpochRange:265 snapshots
exe/program state keyed by job id each epoch, train_epoch_range:598
generator skips already-completed epochs on restart; storage via
fleet/utils/fs.py HDFSClient).

TPU-native: state is whatever pytree the caller registers (trainer state,
model state_dict, …) saved with the sharded orbax-style checkpointer
(distributed/checkpoint.py CheckpointManager); the job id comes from
PADDLE_JOB_ID / PADDLE_RUNNING_ENV like the reference's AutoCheckpointChecker.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Optional

_CHECKER = None


class AutoCheckpointChecker:
    """reference auto_checkpoint.py:71 — env-driven config gate."""

    def __init__(self):
        self.job_id = os.environ.get("PADDLE_JOB_ID", "")
        self.hdfs_home = os.environ.get("PADDLE_EDL_HDFS_HOME",
                                        os.environ.get(
                                            "PADDLE_AUTO_CHECKPOINT_DIR", ""))
        self.save_checkpoint_inter = int(
            os.environ.get("PADDLE_EDL_SAVE_CHECKPOINT_INTER", "900"))

    @property
    def valid(self) -> bool:
        return bool(self.job_id) and bool(self.hdfs_home)

    def checkpoint_dir(self) -> str:
        return os.path.join(self.hdfs_home, self.job_id)


def _checker() -> AutoCheckpointChecker:
    global _CHECKER
    if _CHECKER is None:
        _CHECKER = AutoCheckpointChecker()
    return _CHECKER


class TrainEpochRange:
    """reference auto_checkpoint.py:265. Iterate epochs; on entry restores
    the newest snapshot and resumes after its epoch; saves every
    ``save_checkpoint_inter`` seconds (and on the final epoch).
    Storage is distributed.checkpoint.CheckpointManager (shared with the
    manual distributed.checkpoint.TrainEpochRange variant).

    The caller registers state via ``add_state(get_fn, set_fn)`` — get_fn
    returns the pytree to snapshot, set_fn restores it.
    """

    def __init__(self, max_epoch_num: int, name: str,
                 checkpoint_inter: Optional[int] = None,
                 checkpoint_dir: Optional[str] = None):
        import time
        self.name = name
        self.max_epoch_num = max_epoch_num
        c = _checker()
        self._dir = checkpoint_dir or (
            os.path.join(c.checkpoint_dir(), name) if c.valid else None)
        self._inter = (checkpoint_inter if checkpoint_inter is not None
                       else c.save_checkpoint_inter)
        self._get: Optional[Callable[[], Any]] = None
        self._set: Optional[Callable[[Any], None]] = None
        self._mgr = None
        self._last_save = time.time()
        self.restored_from: Optional[int] = None
        if self._dir:
            from ...distributed.checkpoint import CheckpointManager
            self._mgr = CheckpointManager(self._dir, max_to_keep=2)

    def add_state(self, get_fn: Callable[[], Any],
                  set_fn: Callable[[Any], None]):
        self._get, self._set = get_fn, set_fn
        return self

    def _restore(self) -> int:
        if self._mgr is None or self._set is None:
            return 0
        step = self._mgr.latest_step()
        if step is None:
            return 0
        template = self._get() if self._get else None
        self._set(self._mgr.restore(step, template=template))
        self.restored_from = step
        return step + 1

    def _save(self, epoch: int, force: bool = False):
        import time
        if self._mgr is None or self._get is None:
            return
        now = time.time()
        if force or (now - self._last_save) >= self._inter:
            self._mgr.save(epoch, self._get())
            self._mgr.wait_until_finished()
            self._last_save = now

    def get(self):
        """Generator over remaining epochs (reference :398 get)."""
        start = self._restore()
        for epoch in range(start, self.max_epoch_num):
            yield epoch
            self._save(epoch, force=(epoch == self.max_epoch_num - 1))

    def __iter__(self):
        return self.get()


def train_epoch_range(max_epoch_num: int, save_checkpoint_inter=None,
                      name: str = "auto_checkpoint",
                      checkpoint_dir: Optional[str] = None) -> TrainEpochRange:
    """reference auto_checkpoint.py:598."""
    return TrainEpochRange(max_epoch_num, name,
                           checkpoint_inter=save_checkpoint_inter,
                           checkpoint_dir=checkpoint_dir)
