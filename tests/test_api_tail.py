"""API-tail parity batch: Bilinear/set_global_initializer, incubate
LookAhead/ModelAverage/softmax_mask_fuse_upper_triangle, folder datasets,
device queries. (reference analogues: test_initializer.py,
test_lookahead.py, test_modelaverage.py, test_softmax_mask_fuse_op.py,
test_datasets.py.)"""
import os

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn


class TestInitializers:
    def test_bilinear_kernel_values(self):
        init = nn.initializer.Bilinear()
        w = np.asarray(init((1, 1, 4, 4), jnp.float32))[0, 0]
        # separable triangle filter for factor-2 upsampling
        expect_1d = np.asarray([0.25, 0.75, 0.75, 0.25])
        np.testing.assert_allclose(w, np.outer(expect_1d, expect_1d),
                                   rtol=1e-6)
        with pytest.raises(ValueError):
            init((4, 4), jnp.float32)

    def test_set_global_initializer(self):
        nn.initializer.set_global_initializer(
            nn.initializer.Constant(3.0), nn.initializer.Constant(-1.0))
        try:
            lin = nn.Linear(4, 2)
            np.testing.assert_allclose(np.asarray(lin.weight.value),
                                       np.full((4, 2), 3.0))
            np.testing.assert_allclose(np.asarray(lin.bias.value),
                                       np.full((2,), -1.0))
        finally:
            nn.initializer.set_global_initializer(None, None)
        lin2 = nn.Linear(4, 2)
        assert not np.allclose(np.asarray(lin2.weight.value), 3.0)
        with pytest.raises(TypeError):
            nn.initializer.set_global_initializer("xavier")


class TestLookAhead:
    def test_slow_weights_sync_every_k(self):
        from paddle_tpu.incubate import LookAhead
        paddle.seed(0)
        lin = nn.Linear(2, 1)
        inner = paddle.optimizer.SGD(1.0, parameters=lin.parameters())
        opt = LookAhead(inner, alpha=0.5, k=3)
        params = {"w": jnp.asarray([4.0])}
        state = opt.init_state(params)
        g = {"w": jnp.asarray([1.0])}
        # steps 1,2: fast falls by 1 each; slow stays 4
        for expect_fast in (3.0, 2.0):
            params, state = opt.apply_gradients(params, dict(g), state,
                                                lr=1.0)
            assert float(params["w"][0]) == pytest.approx(expect_fast)
        # step 3: fast would be 1; sync: slow = 4 + .5*(1-4) = 2.5 = fast
        params, state = opt.apply_gradients(params, dict(g), state, lr=1.0)
        assert float(params["w"][0]) == pytest.approx(2.5)
        assert float(state["slow"]["w"][0]) == pytest.approx(2.5)

    def test_validation(self):
        from paddle_tpu.incubate import LookAhead
        inner = paddle.optimizer.SGD(0.1, parameters=[])
        with pytest.raises(ValueError):
            LookAhead(inner, alpha=1.5)
        with pytest.raises(ValueError):
            LookAhead(inner, k=0)

    def test_trains_in_parallel_trainer(self):
        from paddle_tpu.distributed.engine import ParallelTrainer
        from paddle_tpu.distributed.mesh import build_mesh
        from paddle_tpu.incubate import LookAhead
        build_mesh({"data": 1})
        paddle.seed(1)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
        opt = LookAhead(paddle.optimizer.SGD(
            0.1, parameters=net.parameters()), alpha=0.8, k=5)
        tr = ParallelTrainer(net, opt,
                             lambda o, y: jnp.mean((o - y) ** 2))
        rs = np.random.RandomState(0)
        x = rs.randn(16, 8).astype("f4")
        y = x.sum(1, keepdims=True).astype("f4")
        losses = [float(tr.train_step(x, y)) for _ in range(12)]
        assert losses[-1] < losses[0]


class TestModelAverage:
    def test_window_average_and_apply_restore(self):
        from paddle_tpu.incubate import ModelAverage
        paddle.seed(0)
        lin = nn.Linear(1, 1, bias_attr=False)
        ma = ModelAverage(0.5, parameters=lin.parameters(),
                          min_average_window=2, max_average_window=4)
        seen = []
        for v in (1.0, 2.0, 3.0, 4.0):
            lin.weight.value = jnp.full((1, 1), v)
            seen.append(v)
            ma.step()
        live = float(np.asarray(lin.weight.value)[0, 0])
        with ma.apply():
            avg = float(np.asarray(lin.weight.value)[0, 0])
            # all 4 values still in the (sum_1+sum_2) window
            assert avg == pytest.approx(np.mean(seen))
        assert float(np.asarray(lin.weight.value)[0, 0]) == \
            pytest.approx(live)  # restored

    def test_apply_without_restore(self):
        from paddle_tpu.incubate import ModelAverage
        lin = nn.Linear(1, 1, bias_attr=False)
        ma = ModelAverage(1.0, parameters=lin.parameters(),
                          min_average_window=100)
        lin.weight.value = jnp.full((1, 1), 10.0)
        ma.step()
        with ma.apply(need_restore=False):
            pass
        assert float(np.asarray(lin.weight.value)[0, 0]) == \
            pytest.approx(10.0)
        ma.restore()


class TestSoftmaxMaskFuse:
    def test_matches_masked_softmax(self):
        from paddle_tpu.incubate import softmax_mask_fuse_upper_triangle
        rs = np.random.RandomState(0)
        x = rs.randn(2, 3, 5, 5).astype("f4")
        out = np.asarray(softmax_mask_fuse_upper_triangle(x))
        mask = np.tril(np.ones((5, 5), bool))
        ref = np.where(mask, x, -1e30)
        ref = np.exp(ref - ref.max(-1, keepdims=True))
        ref = ref / ref.sum(-1, keepdims=True)
        np.testing.assert_allclose(out, np.where(mask, ref, 0.0),
                                   rtol=1e-5, atol=1e-7)
        assert (out[..., 0, 1:] == 0).all()
        np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)


class TestFolderDatasets:
    def _make_tree(self, root):
        from PIL import Image
        for cls, color in (("cat", (255, 0, 0)), ("dog", (0, 255, 0))):
            d = os.path.join(root, cls)
            os.makedirs(d)
            for i in range(3):
                Image.new("RGB", (8, 8), color).save(
                    os.path.join(d, f"{i}.png"))

    def test_dataset_folder(self, tmp_path):
        from paddle_tpu.vision.datasets import DatasetFolder
        self._make_tree(str(tmp_path))
        ds = DatasetFolder(str(tmp_path))
        assert ds.classes == ["cat", "dog"]
        assert len(ds) == 6
        img, label = ds[0]
        assert label == 0
        assert np.asarray(img).shape == (8, 8, 3)
        labels = sorted(t for _, t in ds.samples)
        assert labels == [0, 0, 0, 1, 1, 1]

    def test_image_folder_flat(self, tmp_path):
        from paddle_tpu.vision.datasets import ImageFolder
        self._make_tree(str(tmp_path))
        ds = ImageFolder(str(tmp_path))
        assert len(ds) == 6
        (img,) = ds[0]
        assert np.asarray(img).shape == (8, 8, 3)

    def test_flowers_voc_gate_without_files(self):
        from paddle_tpu.vision.datasets import VOC2012, Flowers
        with pytest.raises(FileNotFoundError, match="egress"):
            Flowers()
        with pytest.raises(FileNotFoundError, match="egress"):
            VOC2012()


def test_is_compiled_with_rocm():
    assert paddle.device.is_compiled_with_rocm() is False
    assert paddle.is_compiled_with_rocm() is False


class TestStaticAmp:
    def test_surface_and_decorate(self):
        from paddle_tpu import static
        for n in ("decorate", "CustomOpLists", "AutoMixedPrecisionLists",
                  "fp16_guard", "cast_model_to_fp16",
                  "cast_parameters_to_fp16", "bf16"):
            assert hasattr(static.amp, n), n
        lin = nn.Linear(4, 2)
        opt = static.amp.decorate(
            paddle.optimizer.SGD(0.1, parameters=lin.parameters()),
            init_loss_scaling=128.0)
        assert opt.get_loss_scaling() == 128.0
        # delegation to the wrapped optimizer
        assert opt.get_lr() == pytest.approx(0.1)

    def test_cast_model_and_guard(self):
        from paddle_tpu import static
        lin = nn.Linear(4, 2)
        static.amp.cast_model_to_fp16(lin)
        assert str(lin.weight.value.dtype) == "bfloat16"
        with static.amp.fp16_guard():
            from paddle_tpu.amp import amp_state
            assert amp_state().enabled
        with pytest.raises(TypeError):
            static.amp.cast_model_to_fp16(object())

    def test_loss_scaling_engages(self):
        from paddle_tpu import static
        lin = nn.Linear(1, 1, bias_attr=False)
        opt = static.amp.decorate(
            paddle.optimizer.SGD(1.0, parameters=lin.parameters()),
            init_loss_scaling=4.0)
        # backward() scales the loss by the live scale
        assert float(opt.backward(jnp.asarray(1.0))) == pytest.approx(4.0)
        # functional path: scaled grads are unscaled before the update
        params = {"w": jnp.asarray([2.0])}
        state = opt._optimizer.init_state(params)
        scaled_g = {"w": jnp.asarray([4.0])}      # true grad 1.0, scale 4
        new_p, state = opt.apply_gradients(params, scaled_g, state, lr=1.0)
        assert float(new_p["w"][0]) == pytest.approx(1.0)   # 2 - 1*1
        # non-finite grads: parameters and optimizer state keep old values
        inf_g = {"w": jnp.asarray([jnp.inf])}
        new_p2, state2 = opt.apply_gradients(new_p, inf_g, state, lr=1.0)
        assert float(new_p2["w"][0]) == pytest.approx(1.0)
        assert int(state2["step"]) == int(state["step"])
