"""Static sharding propagation: predict implicit resharding collectives.

The SPMD partitioner silently inserts all-gathers / all-to-alls /
all-reduces wherever operand NamedSharding layouts conflict or an output
layout is unreachable from its operands. The analysis layer was blind to
them: the cost model priced only the EXPLICIT collectives inside
shard_map regions. This pass closes that gap from the jaxpr alone,
before any compile: seed the top-level invars with the program's real
``PartitionSpec``s, run per-primitive transfer rules over the canonical
walker's traversal (elementwise / dot_general / reshape / transpose /
reduce / scan / while / cond / pjit / shard_map / sharding_constraint),
and at every equation where specs disagree record a :class:`ReshardSite`
with the collective kind, payload bytes, ring-model wire bytes and
modeled time over ``mesh.axis_links`` (ici vs dcn).

Spec domain (:class:`ASpec`): per-dimension tuples of mesh axis names
(empty = replicated on that dim) plus a ``partial`` axis set — the
GSPMD "partial-sum pending all-reduce" state a sharded contraction
produces. Mesh axes of size 1 are dropped at entry, so a single-device
mesh trivially propagates to zero sites.

Collective kinds:
- ``all-gather``  — sharded axes dropped (sharded -> replicated);
- ``all-to-all``  — an axis moved between dimensions;
- ``all-reduce``  — a partial-sum resolved to full values (XLA may
  lower it as reduce-scatter when the target is sharded; either way it
  is one collective op in the compiled HLO, which is what
  :meth:`ShardingInfo.predicted_collectives` counts).

Replicated -> sharded is a local slice and free.

Consumers: the four ``implicit-resharding`` rule family members in
:mod:`.rules`, ``cost.overlap_summary(reshard_sites=...)`` (the PR 8
list scheduler prices hidden resharding on the wire streams), the
``tools/lint_program.py --dump-sharding`` table, and
:func:`resharding_table` — the planner-ready API
``distributed/auto.py`` scores candidate layouts with.

This is a MODEL of the partitioner, not the partitioner: transfer rules
follow GSPMD's cheapest-legal-choice conventions (slice the replicated
operand of a half-sharded contraction instead of gathering the sharded
one; carry partial sums through linear ops) and are validated against
actually-compiled SPMD HLO collective counts in
tests/test_sharding_analysis.py.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .cost import aval_bytes
from .walker import source_summary, subjaxprs, unwrap

__all__ = [
    "ASpec", "ReshardSite", "ShardingInfo", "propagate",
    "resharding_table", "spec_str",
]

# ring-algorithm wire multiple per participating rank (see cost._COLL_RING)
_RING = {"all-gather": 1.0, "all-to-all": 1.0, "all-reduce": 2.0}

_FALLBACK_BW = {"ici": 9.0e10, "dcn": 6.25e9}

# partial sums survive these unary ops unchanged (linear, shape-only, or
# uniform rescale); add/sub carry only when every operand agrees (below)
_PARTIAL_SAFE = frozenset({
    "mul", "div", "neg", "convert_element_type", "reduce_precision",
    "copy", "stop_gradient", "transpose", "reshape", "broadcast_in_dim",
    "squeeze", "expand_dims", "reduce_sum", "slice", "gather",
    "dynamic_slice", "concatenate", "pad", "rev",
})

_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_prod", "reduce_max", "reduce_min",
    "reduce_and", "reduce_or",
})

# data movers whose output keeps an input dim's axes only where the dim
# size is unchanged (a partial slice of a sharded dim reshards; modeled
# as a silent drop — usually a cheap halo, not a full collective)
_SIZE_GATED = frozenset({"slice", "dynamic_slice", "rev", "pad"})

_OPAQUE = frozenset({
    "gather", "scatter", "scatter-add", "scatter_add", "scatter_mul",
    "scatter_min", "scatter_max", "sort", "top_k", "iota",
    "rng_bit_generator", "random_seed", "random_bits", "random_wrap",
    "random_unwrap", "pallas_call", "threefry2x32",
})


@dataclass(frozen=True)
class ASpec:
    """Array sharding: per-dim mesh-axis tuples + partial-sum axes."""
    dims: Tuple[Tuple[str, ...], ...] = ()
    partial: frozenset = frozenset()
    constrained: bool = False  # produced by an explicit sharding_constraint

    @property
    def replicated(self) -> bool:
        return not self.partial and all(not d for d in self.dims)

    def axis_map(self) -> Dict[str, int]:
        return {ax: d for d, axes in enumerate(self.dims) for ax in axes}


def _repl(ndim: int) -> ASpec:
    return ASpec(((),) * ndim)


def spec_str(a: ASpec) -> str:
    parts = []
    for axes in a.dims:
        if not axes:
            parts.append("None")
        elif len(axes) == 1:
            parts.append(repr(axes[0]))
        else:
            parts.append("(" + ",".join(repr(x) for x in axes) + ")")
    s = "P(" + ", ".join(parts) + ")"
    if a.partial:
        s += "+sum{" + ",".join(sorted(a.partial)) + "}"
    return s


def _rank(v) -> int:
    return len(getattr(getattr(v, "aval", None), "shape", ()) or ())


def from_pspec(spec, ndim: int, sizes: Dict[str, int]) -> ASpec:
    """Normalize a PartitionSpec / NamedSharding / ASpec / None to an
    ASpec of the given rank, dropping mesh axes of size <= 1."""
    if isinstance(spec, ASpec):
        dims = tuple(spec.dims[:ndim]) + ((),) * max(0, ndim - len(spec.dims))
        return ASpec(dims, spec.partial, spec.constrained)
    if spec is not None and hasattr(spec, "spec"):   # NamedSharding
        spec = spec.spec
    dims: List[Tuple[str, ...]] = []
    entries = tuple(spec) if spec is not None else ()
    seen = set()
    for d in range(ndim):
        e = entries[d] if d < len(entries) else None
        if e is None:
            dims.append(())
            continue
        if isinstance(e, str):
            e = (e,)
        try:
            axes = tuple(ax for ax in e
                         if isinstance(ax, str) and sizes.get(ax, 1) > 1
                         and ax not in seen)
        except TypeError:      # UNCONSTRAINED and friends
            axes = ()
        seen.update(axes)
        dims.append(axes)
    return ASpec(tuple(dims))


@dataclass(frozen=True)
class ReshardSite:
    """One predicted implicit collective the partitioner will insert."""
    kind: str                    # "all-gather" | "all-to-all" | "all-reduce"
    axes: Tuple[str, ...]        # mesh axes crossed
    bytes: float                 # global payload bytes
    wire_bytes: float            # ring-model per-rank wire bytes
    time_s: float                # wire_bytes / link bandwidth, one firing
    link: str                    # "ici" | "dcn"
    trips: float                 # enclosing static trip-count product
    path: Tuple[str, ...]
    eqn_index: int
    primitive: str
    operand: int                 # resharded invar index; -1 = the output
    detail: str
    source: Optional[str]
    in_loop: bool
    from_constraint: bool        # the dropped spec came from an explicit
                                 # sharding_constraint
    anchors: Tuple = ()          # ((path, index), ...) outer->inner eqn
                                 # chain, for overlap-model attachment

    def to_dict(self) -> dict:
        return {"kind": self.kind, "axes": list(self.axes),
                "bytes": self.bytes, "wire_bytes": self.wire_bytes,
                "time_s": self.time_s, "link": self.link,
                "trips": self.trips, "path": "/".join(self.path) or "<top>",
                "eqn_index": self.eqn_index, "primitive": self.primitive,
                "operand": self.operand, "detail": self.detail,
                "source": self.source, "in_loop": self.in_loop,
                "from_constraint": self.from_constraint}


@dataclass
class ShardingInfo:
    """Result of one propagation run."""
    sites: List[ReshardSite]
    out_specs: List[ASpec]
    table: List[dict]
    dropped_constraints: List[ReshardSite]

    def predicted_collectives(self) -> int:
        """Number of implicit collective OPS the compiled HLO text will
        contain (loop-body sites count once — HLO has one op per site)."""
        return len(self.sites)

    def total_time(self) -> float:
        """Modeled wall seconds of all predicted resharding, per step."""
        return sum(s.time_s * s.trips for s in self.sites)

    def total_wire_bytes(self) -> float:
        return sum(s.wire_bytes * s.trips for s in self.sites)

    def to_dict(self) -> dict:
        return {"n_sites": len(self.sites),
                "total_time_s": self.total_time(),
                "total_wire_bytes": self.total_wire_bytes(),
                "sites": [s.to_dict() for s in self.sites],
                "table": self.table}


class _SiteCtx:
    """Where-am-I context threaded through the recursion."""
    __slots__ = ("path", "index", "eqn", "trips", "in_loop", "stack")

    def __init__(self, path, index, eqn, trips, in_loop, stack):
        self.path, self.index, self.eqn = path, index, eqn
        self.trips, self.in_loop, self.stack = trips, in_loop, stack


class _Propagator:
    def __init__(self, mesh, while_trips: float, collect_table: bool):
        self.mesh = mesh
        shape = dict(getattr(mesh, "shape", {}) or {})
        self.sizes = {ax: int(n) for ax, n in shape.items() if int(n) > 1}
        try:
            from ..distributed.mesh import (axis_links, link_bandwidth,
                                            link_latency)
            self.links = axis_links(mesh) if mesh is not None else {}
            self._bw = link_bandwidth
            self._lat = link_latency
        except Exception:
            self.links = {}
            self._bw = lambda link: _FALLBACK_BW.get(link, _FALLBACK_BW["ici"])
            self._lat = lambda link: 0.0
        self.while_trips = max(float(while_trips), 1.0)
        self.collect_table = collect_table
        self.sites: List[ReshardSite] = []
        self.table: List[dict] = []
        self.dropped_constraints: List[ReshardSite] = []

    # -- site plumbing ------------------------------------------------------

    def _group(self, axes) -> int:
        n = 1
        for ax in axes:
            n *= self.sizes.get(ax, 1)
        return n

    def _site(self, kind, axes, payload, sctx: _SiteCtx, operand, detail,
              record, from_constraint=False):
        axes = tuple(sorted(set(axes)))
        n = self._group(axes)
        if not axes or n <= 1 or not record:
            return
        link = "dcn" if any(self.links.get(ax) == "dcn" for ax in axes) \
            else "ici"
        wire = _RING[kind] * (n - 1) / n * float(payload)
        site = ReshardSite(
            kind=kind, axes=axes, bytes=float(payload), wire_bytes=wire,
            time_s=wire / max(self._bw(link), 1.0) + self._lat(link),
            link=link,
            trips=sctx.trips, path=sctx.path, eqn_index=sctx.index,
            primitive=sctx.eqn.primitive.name if sctx.eqn is not None
            else "", operand=operand, detail=detail,
            source=source_summary(sctx.eqn) if sctx.eqn is not None
            else None, in_loop=sctx.in_loop,
            from_constraint=from_constraint,
            anchors=sctx.stack + ((sctx.path, sctx.index),))
        self.sites.append(site)
        if from_constraint:
            self.dropped_constraints.append(site)

    def _classify(self, src: ASpec, dst_dims, aval, sctx, operand, detail,
                  record):
        """Emit sites for resharding ``src`` to ``dst_dims`` and return
        the achieved spec (= dst for the moved/dropped axes; gaining axes
        is a free local slice)."""
        src_map = src.axis_map()
        dst_map = {ax: d for d, axes in enumerate(dst_dims) for ax in axes}
        moved = [ax for ax, d in src_map.items()
                 if ax in dst_map and dst_map[ax] != d]
        dropped = [ax for ax, d in src_map.items() if ax not in dst_map]
        payload = aval_bytes(aval)
        if moved:
            self._site("all-to-all", moved, payload, sctx, operand,
                       detail + f" (axis moved between dims: {moved})",
                       record)
        if dropped:
            self._site("all-gather", dropped, payload, sctx, operand,
                       detail + f" (sharded axes dropped: {dropped})",
                       record, from_constraint=src.constrained)
        return ASpec(tuple(tuple(a) for a in dst_dims))

    def _resolve_partial(self, a: ASpec, aval, sctx, operand, detail,
                         record) -> ASpec:
        if not a.partial:
            return a
        self._site("all-reduce", tuple(a.partial), aval_bytes(aval), sctx,
                   operand, detail + " (partial sum materialized)", record)
        return ASpec(a.dims, frozenset(), a.constrained)

    # -- scope traversal ----------------------------------------------------

    def run(self, raw, consts, in_specs, out_specs):
        env: Dict[int, ASpec] = {}
        in_specs = list(in_specs or ())
        for i, v in enumerate(raw.invars):
            spec = in_specs[i] if i < len(in_specs) else None
            env[id(v)] = from_pspec(spec, _rank(v), self.sizes)
        for cv in raw.constvars:
            env[id(cv)] = _repl(_rank(cv))
        outs = self._scope(raw, env, (), 1.0, (), False, True)
        # top-level boundary: partial sums must materialize somewhere;
        # sharded outputs stay sharded unless the caller pinned out_specs
        end = _SiteCtx((), len(raw.eqns), raw.eqns[-1] if raw.eqns else None,
                       1.0, False, ())
        final = []
        for k, (v, a) in enumerate(zip(raw.outvars, outs)):
            a = self._resolve_partial(a, getattr(v, "aval", None), end, -1,
                                      f"output #{k}", True)
            if out_specs is not None and k < len(out_specs):
                want = from_pspec(out_specs[k], _rank(v), self.sizes)
                if want.dims != a.dims:
                    a = self._classify(a, want.dims, getattr(v, "aval", None),
                                       end, -1, f"output #{k} pinned to "
                                       f"{spec_str(want)}", True)
            final.append(a)
        return final

    def _read(self, env, atom) -> ASpec:
        if hasattr(atom, "val"):         # Literal
            return _repl(_rank(atom))
        return env.get(id(atom), _repl(_rank(atom)))

    def _scope(self, raw, env, path, trips, stack, in_loop, record):
        for i, eqn in enumerate(raw.eqns):
            sctx = _SiteCtx(path, i, eqn, trips, in_loop, stack)
            n0 = len(self.sites)
            outs = self._eqn(eqn, env, sctx, record)
            for v, a in zip(eqn.outvars, outs):
                env[id(v)] = a
            if self.collect_table and record:
                self.table.append({
                    "path": "/".join(path) or "<top>", "eqn_index": i,
                    "primitive": eqn.primitive.name,
                    "in": [spec_str(self._read(env, a)) for a in eqn.invars],
                    "out": [spec_str(a) for a in outs],
                    "conflicts": len(self.sites) - n0})
        return [self._read(env, v) for v in raw.outvars]

    # -- per-primitive transfer rules ---------------------------------------

    def _eqn(self, eqn, env, sctx, record) -> List[ASpec]:
        name = eqn.primitive.name
        ins = [self._read(env, a) for a in eqn.invars]
        try:
            if name == "sharding_constraint":
                return self._t_constraint(eqn, ins, sctx, record)
            if name == "dot_general":
                return self._t_dot(eqn, ins, sctx, record)
            if name in _REDUCE_PRIMS:
                return self._t_reduce(eqn, ins)
            if name in ("argmax", "argmin", "reduce_argmax", "reduce_argmin"):
                return self._t_arg_reduce(eqn, ins, sctx, record)
            if name == "transpose":
                p = eqn.params["permutation"]
                a = ins[0]
                return [ASpec(tuple(a.dims[int(d)] for d in p), a.partial,
                              a.constrained)]
            if name == "reshape":
                return self._t_reshape(eqn, ins, sctx, record)
            if name == "broadcast_in_dim":
                return self._t_broadcast(eqn, ins)
            if name == "squeeze":
                dims = set(int(d) for d in eqn.params.get("dimensions", ()))
                a = ins[0]
                return [ASpec(tuple(ax for d, ax in enumerate(a.dims)
                                    if d not in dims), a.partial,
                              a.constrained)]
            if name == "expand_dims":
                dims = sorted(int(d) for d in
                              eqn.params.get("dimensions", ()))
                a = ins[0]
                out = list(a.dims)
                for d in dims:
                    out.insert(d, ())
                return [ASpec(tuple(out), a.partial, a.constrained)]
            if name == "concatenate":
                return self._t_concat(eqn, ins, sctx, record)
            if name == "dynamic_update_slice":
                return [ASpec(ins[0].dims, ins[0].partial)]
            if name in _SIZE_GATED:
                return self._t_size_gated(eqn, ins)
            if name == "scan":
                return self._t_scan(eqn, ins, sctx, record)
            if name == "while":
                return self._t_while(eqn, ins, sctx, record)
            if name == "cond":
                return self._t_cond(eqn, ins, sctx, record)
            if name == "shard_map":
                return self._t_shard_map(eqn, ins, sctx, record)
            if name in _OPAQUE:
                # conservative: replicated dims, partial carried when the
                # op is a linear selection (gather/scatter), else dropped
                partial = ins[0].partial if ins and name in _PARTIAL_SAFE \
                    else frozenset()
                return [ASpec(((),) * _rank(v), partial)
                        for v in eqn.outvars]
            subs = list(subjaxprs(eqn))
            if len(subs) == 1 and subs[0].kind == "call":
                return self._t_call(eqn, subs[0], ins, sctx, record)
            if subs:   # unknown higher-order: opaque
                return [_repl(_rank(v)) for v in eqn.outvars]
            return self._t_default(eqn, ins, sctx, record)
        except Exception:
            # a transfer rule must never sink an analysis run
            return [_repl(_rank(v)) for v in eqn.outvars]

    def _t_default(self, eqn, ins, sctx, record) -> List[ASpec]:
        """Generic elementwise merge: same-rank operands must agree; the
        largest operand's layout wins and the others reshard to it."""
        out_r = _rank(eqn.outvars[0])
        cands = [(i, a) for i, (a, v) in enumerate(zip(ins, eqn.invars))
                 if _rank(v) == out_r and out_r > 0]
        # partial handling: identical partials on every participating
        # operand carry (grad accumulation adds partials); a lone partial
        # carries through linear/uniform ops; anything else materializes
        partials = [a.partial for _, a in cands if a.partial]
        name = eqn.primitive.name
        if partials and not (
                len(set(partials)) == 1
                and (len(partials) == len(cands) or name in _PARTIAL_SAFE
                     or len(cands) == 1)):
            for k, (i, a) in enumerate(cands):
                if a.partial:
                    cands[k] = (i, self._resolve_partial(
                        a, eqn.invars[i].aval, sctx, i,
                        f"operand #{i} of {name}", record))
            partials = []
        if not cands:
            partial = frozenset().union(*[a.partial for a in ins]) \
                if ins else frozenset()
            return [ASpec(((),) * _rank(v),
                          partial if _rank(v) == 0 else frozenset())
                    for v in eqn.outvars]
        # GSPMD-style union merge: start from the most-sharded operand
        # (ties: largest) and absorb unconflicted axes from the others.
        # A replicated or subset-sharded operand slices for free; only a
        # genuine per-dim disagreement reshards (to the merged layout).
        dom_i, dom = max(cands, key=lambda t: (
            sum(1 for d in t[1].dims if d),
            aval_bytes(eqn.invars[t[0]].aval)))
        merged = list(dom.dims)
        used = {ax for axes in merged for ax in axes}
        for i, a in cands:
            if i == dom_i:
                continue
            for d, axes in enumerate(a.dims):
                if axes and not merged[d] and not (set(axes) & used):
                    merged[d] = axes
                    used.update(axes)
        merged = tuple(merged)
        for i, a in cands:
            if all(not axes or axes == merged[d][:len(axes)]
                   for d, axes in enumerate(a.dims)):
                continue   # slicing down to the merged layout is local
            self._classify(a, merged, eqn.invars[i].aval, sctx, i,
                           f"operand #{i} of {name} laid out "
                           f"{spec_str(a)} vs {spec_str(ASpec(merged))}",
                           record)
        partial = partials[0] if partials else frozenset()
        return [ASpec(merged, partial) if _rank(v) == out_r
                else _repl(_rank(v)) for v in eqn.outvars]

    def _t_constraint(self, eqn, ins, sctx, record) -> List[ASpec]:
        a = ins[0]
        sh = eqn.params.get("sharding")
        spec = getattr(sh, "spec", sh)
        want = from_pspec(spec, _rank(eqn.outvars[0]), self.sizes)
        a = self._resolve_partial(a, eqn.invars[0].aval, sctx, 0,
                                  "sharding_constraint input", record)
        if a.dims != want.dims:
            self._classify(a, want.dims, eqn.invars[0].aval, sctx, 0,
                           f"sharding_constraint {spec_str(a)} -> "
                           f"{spec_str(want)}", record)
        return [ASpec(want.dims, frozenset(), True)]

    def _t_dot(self, eqn, ins, sctx, record) -> List[ASpec]:
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        la, ra = ins[0], ins[1]
        # partial operands: a bilinear op cannot carry both; resolve
        la = self._resolve_partial(la, eqn.invars[0].aval, sctx, 0,
                                   "dot_general lhs", record)
        ra = self._resolve_partial(ra, eqn.invars[1].aval, sctx, 1,
                                   "dot_general rhs", record)
        l_con = set(ax for d in lc for ax in la.dims[int(d)])
        r_con = set(ax for d in rc for ax in ra.dims[int(d)])
        shared = l_con & r_con
        only_l, only_r = l_con - r_con, r_con - l_con
        partial = set(shared)
        if only_l and only_r:
            # contracting dims sharded over DIFFERENT axes: one operand
            # must reshard; gather the smaller one, keep the bigger's
            lb_b = aval_bytes(eqn.invars[0].aval)
            rb_b = aval_bytes(eqn.invars[1].aval)
            if lb_b <= rb_b:
                self._site("all-gather", tuple(only_l), lb_b, sctx, 0,
                           "dot_general contracting dims sharded over "
                           f"conflicting axes {sorted(only_l)} vs "
                           f"{sorted(only_r)}", record)
                partial |= only_r
            else:
                self._site("all-gather", tuple(only_r), rb_b, sctx, 1,
                           "dot_general contracting dims sharded over "
                           f"conflicting axes {sorted(only_r)} vs "
                           f"{sorted(only_l)}", record)
                partial |= only_l
        else:
            # one-sided contraction sharding: slicing the replicated
            # operand is free; the product is a partial sum
            partial |= only_l | only_r
        # batch dims: must agree; the bigger operand wins
        out_dims: List[Tuple[str, ...]] = []
        used = set(partial)
        for ld, rd in zip(lb, rb):
            lax, rax = la.dims[int(ld)], ra.dims[int(rd)]
            if lax != rax:
                big_is_l = aval_bytes(eqn.invars[0].aval) >= \
                    aval_bytes(eqn.invars[1].aval)
                win = lax if big_is_l else rax
                lose_i = 1 if big_is_l else 0
                lose = ra if big_is_l else la
                self._classify(
                    lose, [win if d == int(rd if big_is_l else ld) else
                           lose.dims[d] for d in range(len(lose.dims))],
                    eqn.invars[lose_i].aval, sctx, lose_i,
                    "dot_general batch dim layout conflict", record)
            else:
                win = lax
            win = tuple(ax for ax in win if ax not in used)
            used.update(win)
            out_dims.append(win)
        for d in range(len(la.dims)):
            if d in set(int(x) for x in lc) or d in set(int(x) for x in lb):
                continue
            axes = tuple(ax for ax in la.dims[d] if ax not in used)
            used.update(axes)
            out_dims.append(axes)
        for d in range(len(ra.dims)):
            if d in set(int(x) for x in rc) or d in set(int(x) for x in rb):
                continue
            axes = tuple(ax for ax in ra.dims[d] if ax not in used)
            used.update(axes)
            out_dims.append(axes)
        return [ASpec(tuple(out_dims), frozenset(partial))]

    def _t_reduce(self, eqn, ins) -> List[ASpec]:
        a = ins[0]
        axes = set(int(d) for d in eqn.params.get("axes", ()))
        partial = set(a.partial)
        out_dims = []
        for d, ax in enumerate(a.dims):
            if d in axes:
                partial.update(ax)
            else:
                out_dims.append(ax)
        return [ASpec(tuple(out_dims), frozenset(partial))
                for _ in eqn.outvars]

    def _t_arg_reduce(self, eqn, ins, sctx, record) -> List[ASpec]:
        a = self._resolve_partial(ins[0], eqn.invars[0].aval, sctx, 0,
                                  "arg-reduction input", record)
        axes = set(int(d) for d in eqn.params.get("axes", ()))
        gathered = [ax for d in axes for ax in a.dims[d]]
        if gathered:
            self._site("all-gather", gathered,
                       aval_bytes(eqn.invars[0].aval), sctx, 0,
                       "arg-reduction over a sharded dim needs the full "
                       "dim materialized", record)
        out_dims = tuple(ax for d, ax in enumerate(a.dims) if d not in axes)
        return [ASpec(out_dims) for _ in eqn.outvars]

    def _t_reshape(self, eqn, ins, sctx, record) -> List[ASpec]:
        a = ins[0]
        in_shape = tuple(int(s) for s in eqn.invars[0].aval.shape)
        out_shape = tuple(int(s) for s in eqn.outvars[0].aval.shape)
        out_dims: List[Tuple[str, ...]] = [() for _ in out_shape]
        dropped: List[str] = []
        # greedy factor grouping: advance both cursors until the running
        # products match; within a group only the MAJOR-most input dim's
        # axes can survive, onto the major-most output dim (divisibility
        # permitting) — everything else reshards
        i = j = 0
        while i < len(in_shape) or j < len(out_shape):
            gi, gj = [i], [j] if j < len(out_shape) else []
            pi = in_shape[i] if i < len(in_shape) else 1
            pj = out_shape[j] if j < len(out_shape) else 1
            while pi != pj:
                if pi < pj and i + 1 < len(in_shape):
                    i += 1
                    gi.append(i)
                    pi *= in_shape[i]
                elif pj < pi and j + 1 < len(out_shape):
                    j += 1
                    gj.append(j)
                    pj *= out_shape[j]
                else:
                    break
            group_in = [d for d in gi if d < len(in_shape)]
            group_out = [d for d in gj if d < len(out_shape)]
            major_axes = a.dims[group_in[0]] if group_in else ()
            minor = [ax for d in group_in[1:] for ax in a.dims[d]]
            if group_out and major_axes:
                n = self._group(major_axes)
                if n > 0 and out_shape[group_out[0]] % max(n, 1) == 0:
                    out_dims[group_out[0]] = major_axes
                else:
                    dropped.extend(major_axes)
            elif major_axes:
                dropped.extend(major_axes)
            dropped.extend(minor)
            i += 1
            j = (group_out[-1] + 1) if group_out else j + 1
        if dropped:
            self._site("all-gather", dropped, aval_bytes(eqn.invars[0].aval),
                       sctx, 0,
                       f"reshape {list(in_shape)} -> {list(out_shape)} "
                       f"cannot keep axes {sorted(set(dropped))}", record,
                       from_constraint=a.constrained)
        return [ASpec(tuple(out_dims), a.partial)]

    def _t_broadcast(self, eqn, ins) -> List[ASpec]:
        a = ins[0]
        bdims = tuple(int(d) for d in eqn.params["broadcast_dimensions"])
        in_shape = tuple(int(s) for s in eqn.invars[0].aval.shape)
        out_shape = tuple(int(s) for s in eqn.outvars[0].aval.shape)
        out_dims: List[Tuple[str, ...]] = [() for _ in out_shape]
        for src, dst in enumerate(bdims):
            if in_shape[src] == out_shape[dst]:
                out_dims[dst] = a.dims[src]
        return [ASpec(tuple(out_dims), a.partial)]

    def _t_concat(self, eqn, ins, sctx, record) -> List[ASpec]:
        cd = int(eqn.params["dimension"])
        dom_i = max(range(len(ins)),
                    key=lambda i: aval_bytes(eqn.invars[i].aval))
        dom = ins[dom_i]
        out_dims = tuple(() if d == cd else ax
                         for d, ax in enumerate(dom.dims))
        for i, a in enumerate(ins):
            want = tuple(() if d == cd else out_dims[d]
                         for d in range(len(a.dims)))
            if a.dims != want and not a.replicated:
                self._classify(a, want, eqn.invars[i].aval, sctx, i,
                               f"concatenate operand #{i} layout conflict",
                               record)
        return [ASpec(out_dims,
                      frozenset().union(*[a.partial for a in ins]))]

    def _t_size_gated(self, eqn, ins) -> List[ASpec]:
        a = ins[0]
        in_shape = tuple(int(s) for s in eqn.invars[0].aval.shape)
        out_shape = tuple(int(s) for s in eqn.outvars[0].aval.shape)
        out_dims = tuple(
            a.dims[d] if d < len(in_shape) and in_shape[d] == out_shape[d]
            else () for d in range(len(out_shape)))
        return [ASpec(out_dims, a.partial)]

    # -- structured control flow --------------------------------------------

    def _t_call(self, eqn, sub, ins, sctx, record) -> List[ASpec]:
        inner, consts = unwrap(sub.jaxpr)
        env: Dict[int, ASpec] = {}
        outer_in = list(ins)
        inner_in = list(inner.invars)
        if len(outer_in) > len(inner_in):   # call consts ride first
            outer_in = outer_in[len(outer_in) - len(inner_in):]
        if len(outer_in) == len(inner_in):
            for iv, a in zip(inner_in, outer_in):
                env[id(iv)] = ASpec(
                    tuple(a.dims[:_rank(iv)])
                    + ((),) * max(0, _rank(iv) - len(a.dims)),
                    a.partial, a.constrained)
        for cv in inner.constvars:
            env[id(cv)] = _repl(_rank(cv))
        outs = self._scope(inner, env, sctx.path + (sub.label,), sctx.trips,
                           sctx.stack + ((sctx.path, sctx.index),),
                           sctx.in_loop, record)
        return outs[:len(eqn.outvars)] + [
            _repl(_rank(v)) for v in eqn.outvars[len(outs):]]

    def _loop_fixpoint(self, eqn, body_raw, body_label, const_specs,
                       carry_specs, extra_specs, trips, sctx, record):
        """Shared scan/while carry fixpoint: iterate the body abstractly,
        meeting carry specs toward replicated until stable, then run one
        recording pass. Returns (carry_specs, body_out_specs)."""
        def body_once(carries, rec):
            env: Dict[int, ASpec] = {}
            seq = list(const_specs) + list(carries) + list(extra_specs)
            for iv, a in zip(body_raw.invars, seq):
                env[id(iv)] = a
            for cv in body_raw.constvars:
                env[id(cv)] = _repl(_rank(cv))
            return self._scope(
                body_raw, env, sctx.path + (body_label,),
                sctx.trips * trips,
                sctx.stack + ((sctx.path, sctx.index),), True, rec)

        n_carry = len(carry_specs)
        for _ in range(4):
            outs = body_once(carry_specs, False)
            new = []
            changed = False
            for a, b in zip(carry_specs, outs[:n_carry]):
                met_dims = tuple(
                    da if da == db else ()
                    for da, db in zip(a.dims, b.dims))
                met = ASpec(met_dims)
                if met.dims != a.dims:
                    changed = True
                new.append(met)
            carry_specs = new
            if not changed:
                break
        outs = body_once(carry_specs, record)
        # carry boundary: partial sums and layout mismatches reshard on
        # EVERY iteration — this is what resharding-in-scan-body prices
        bctx = _SiteCtx(sctx.path, sctx.index, eqn, sctx.trips * trips,
                        True, sctx.stack)
        fixed = []
        for k, (a, b) in enumerate(zip(carry_specs, outs[:n_carry])):
            cv = body_raw.outvars[k]
            b = self._resolve_partial(b, getattr(cv, "aval", None), bctx, -1,
                                      f"loop carry #{k}", record)
            if b.dims != a.dims:
                b = self._classify(b, a.dims, getattr(cv, "aval", None),
                                   bctx, -1, f"loop carry #{k} layout "
                                   "changes across iterations", record)
            fixed.append(ASpec(a.dims))
        return fixed, outs

    def _t_scan(self, eqn, ins, sctx, record) -> List[ASpec]:
        body, _ = unwrap(eqn.params["jaxpr"])
        nc = int(eqn.params.get("num_consts", 0))
        nk = int(eqn.params.get("num_carry", 0))
        trips = float(eqn.params.get("length", 1))
        const_specs = ins[:nc]
        carry_specs = list(ins[nc:nc + nk])
        xs_specs = []
        for a in ins[nc + nk:]:
            xs_specs.append(ASpec(tuple(a.dims[1:])))  # scanned dim peeled
        carry_specs, outs = self._loop_fixpoint(
            eqn, body, "scan", const_specs, carry_specs, xs_specs, trips,
            sctx, record)
        result = list(carry_specs)
        bctx = _SiteCtx(sctx.path, sctx.index, eqn, sctx.trips * trips,
                        True, sctx.stack)
        for k, a in enumerate(outs[len(carry_specs):]):
            ov = body.outvars[len(carry_specs) + k]
            a = self._resolve_partial(a, getattr(ov, "aval", None), bctx, -1,
                                      f"scan stacked output #{k}", record)
            result.append(ASpec(((),) + a.dims))  # new leading (time) dim
        return result[:len(eqn.outvars)] + [
            _repl(_rank(v)) for v in eqn.outvars[len(result):]]

    def _t_while(self, eqn, ins, sctx, record) -> List[ASpec]:
        cn = int(eqn.params.get("cond_nconsts", 0))
        bn = int(eqn.params.get("body_nconsts", 0))
        body, _ = unwrap(eqn.params["body_jaxpr"])
        const_specs = ins[cn:cn + bn]
        carry_specs = list(ins[cn + bn:])
        carry_specs, _ = self._loop_fixpoint(
            eqn, body, "while[body]", const_specs, carry_specs, (),
            self.while_trips, sctx, record)
        return carry_specs[:len(eqn.outvars)] + [
            _repl(_rank(v)) for v in eqn.outvars[len(carry_specs):]]

    def _t_cond(self, eqn, ins, sctx, record) -> List[ASpec]:
        operands = ins[1:]
        merged = None
        for bi, br in enumerate(eqn.params.get("branches", ())):
            inner, _ = unwrap(br)
            env: Dict[int, ASpec] = {}
            for iv, a in zip(inner.invars, operands):
                env[id(iv)] = a
            for cv in inner.constvars:
                env[id(cv)] = _repl(_rank(cv))
            outs = self._scope(inner, env, sctx.path + (f"cond[{bi}]",),
                               sctx.trips,
                               sctx.stack + ((sctx.path, sctx.index),),
                               sctx.in_loop, record)
            if merged is None:
                merged = outs
            else:
                merged = [ASpec(tuple(da if da == db else ()
                                      for da, db in zip(a.dims, b.dims)),
                                a.partial | b.partial)
                          for a, b in zip(merged, outs)]
        merged = merged or []
        bctx = _SiteCtx(sctx.path, sctx.index, eqn, sctx.trips,
                        sctx.in_loop, sctx.stack)
        final = []
        for k, a in enumerate(merged[:len(eqn.outvars)]):
            final.append(self._resolve_partial(
                a, getattr(eqn.outvars[k], "aval", None), bctx, -1,
                f"cond output #{k}", record))
        return final + [_repl(_rank(v))
                        for v in eqn.outvars[len(final):]]

    def _t_shard_map(self, eqn, ins, sctx, record) -> List[ASpec]:
        """Manual region: check the entry boundary against in_names
        (explicit collectives inside are already priced by the overlap
        model; the interior is NOT walked — its avals are per-shard)."""
        in_names = eqn.params.get("in_names", ())
        out_names = eqn.params.get("out_names", ())

        def names_to_spec(names, ndim):
            dims = [() for _ in range(ndim)]
            for d, axes in (names or {}).items():
                if int(d) < ndim:
                    dims[int(d)] = tuple(
                        ax for ax in axes if self.sizes.get(ax, 1) > 1)
            return ASpec(tuple(dims))

        for i, (a, names) in enumerate(zip(ins, in_names)):
            want = names_to_spec(names, _rank(eqn.invars[i]))
            a = self._resolve_partial(a, eqn.invars[i].aval, sctx, i,
                                      f"shard_map operand #{i}", record)
            if a.dims != want.dims:
                self._classify(a, want.dims, eqn.invars[i].aval, sctx, i,
                               f"shard_map expects {spec_str(want)} but "
                               f"operand arrives {spec_str(a)}", record)
        return [names_to_spec(names, _rank(v))
                for v, names in zip(eqn.outvars, out_names)]


def propagate(closed, mesh, in_specs, *, out_specs=None,
              while_trips: float = 1.0,
              collect_table: bool = False) -> ShardingInfo:
    """Run the sharding-propagation pass over ``closed``.

    ``in_specs``: one PartitionSpec / NamedSharding / ASpec / None per
    flat top-level invar (missing entries read as replicated).
    ``out_specs``: optional pinned output layouts (a jitted function's
    ``out_shardings``); partial sums at outputs always materialize.
    Returns a :class:`ShardingInfo` with every predicted implicit
    collective, the per-equation spec table (``collect_table=True``)
    and any constraints erased by reshapes.
    """
    raw, consts = unwrap(closed)
    prop = _Propagator(mesh, while_trips, collect_table)
    outs = prop.run(raw, consts, in_specs, out_specs)
    return ShardingInfo(sites=prop.sites, out_specs=outs, table=prop.table,
                        dropped_constraints=prop.dropped_constraints)


def resharding_table(closed, mesh, in_specs, *, out_specs=None,
                     while_trips: float = 1.0) -> List[dict]:
    """Planner-ready flat table of predicted implicit resharding: one
    dict per site (kind, axes, bytes, wire_bytes, time_s, link, trips,
    path, eqn_index, primitive, source). ``distributed/auto.py`` scores
    candidate layouts by summing ``time_s * trips`` over this table."""
    info = propagate(closed, mesh, in_specs, out_specs=out_specs,
                     while_trips=while_trips)
    return [s.to_dict() for s in info.sites]
