"""Auto-parallel planning: propose mesh degrees from a memory model.

Beyond the reference (v2.1 has no auto-parallel): mechanizes the
"How to Scale Your Model" recipe — pick a mesh, check the per-device
memory arithmetic, prefer the cheapest collectives. The planner searches
(data, sharding, model, pipe) factorizations of the device count and
returns the first layout whose estimated per-device bytes fit HBM,
ordered by communication cost (DP < ZeRO < TP < PP — reshard over the
fastest axes first; TP pays per-layer collectives, PP pays bubble).

Estimates use the standard transformer accounting:
  params/device    = P * b_param / (tp * pp * zshard)
  grads/device     = P * b_param / (tp * pp * zshard_g)
  opt state/device = P * 8 bytes (adam m+v fp32) / (tp * pp * zshard_o)
  activations      ~ L/pp * B * S * H * c_act * b_act / tp   (remat ÷ ~L)

This is a PLANNER, not a profiler: numbers are first-order sizing to pick
a starting layout; profile and iterate for the last 20%.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = ["MemoryEstimate", "Plan", "plan", "resharding_cost"]

_ADAM_BYTES = 8          # m + v, fp32 each
_ACT_COEFF = 18          # bytes-ish per (B,S,H) element across a block's
                         # live set with flash attention (no S^2 term)


@dataclass
class MemoryEstimate:
    params: float
    grads: float
    opt_state: float
    activations: float

    @property
    def total(self) -> float:
        return self.params + self.grads + self.opt_state + self.activations


@dataclass
class Plan:
    degrees: Dict[str, int]
    per_device: MemoryEstimate
    hbm_bytes: float
    rationale: List[str] = field(default_factory=list)

    @property
    def fits(self) -> bool:
        return self.per_device.total <= self.hbm_bytes

    def build_mesh(self):
        from .mesh import build_mesh
        return build_mesh({k: v for k, v in self.degrees.items() if v > 1}
                          or {"data": 1})


def _factorizations(n: int):
    """All (data, sharding, model, pipe) with product n, model/pipe powers
    of 2 (TP wants the MXU-friendly head splits)."""
    out = []
    def divs(x):
        return [d for d in range(1, x + 1) if x % d == 0]
    for pipe in divs(n):
        for model in divs(n // pipe):
            if model & (model - 1):      # non-power-of-2 TP: skip
                continue
            rest = n // (pipe * model)
            for shard in divs(rest):
                out.append({"data": rest // shard, "sharding": shard,
                            "model": model, "pipe": pipe})
    return out


def _estimate(n_params: float, deg: Dict[str, int], *, layers, hidden,
              seq_len, batch_per_device, param_bytes, zero_stage,
              remat) -> MemoryEstimate:
    tp, pp, z = deg["model"], deg["pipe"], deg["sharding"]
    shard_p = z if zero_stage >= 3 else 1
    shard_g = z if zero_stage >= 2 else 1
    shard_o = z if zero_stage >= 1 else 1
    mp = tp * pp
    params = n_params * param_bytes / (mp * shard_p)
    grads = n_params * param_bytes / (mp * shard_g)
    opt = n_params * _ADAM_BYTES / (mp * shard_o)
    act = (layers / pp) * batch_per_device * seq_len * hidden \
        * _ACT_COEFF / tp
    if remat:
        act = act / max(1.0, layers / pp) + \
            batch_per_device * seq_len * hidden * _ACT_COEFF / tp
    return MemoryEstimate(params, grads, opt, act)


def _comm_cost(deg: Dict[str, int]) -> tuple:
    """Sort key: prefer fewer model/pipe degrees (TP = per-layer
    collectives, PP = bubble + schedule complexity), then less ZeRO
    resharding, then more plain DP."""
    return (deg["pipe"], deg["model"], deg["sharding"], -deg["data"])


def plan(n_params: float, n_devices: int, *, layers: int = 24,
         hidden: int = 2048, seq_len: int = 2048,
         batch_per_device: int = 8, hbm_bytes: float = 16e9,
         param_bytes: int = 2, zero_stage: int = 1,
         remat: Optional[bool] = None, max_model: int = 8,
         headroom: float = 0.9) -> Plan:
    """Propose mesh degrees for training an n_params transformer on
    n_devices chips. Returns the cheapest-communication Plan that fits
    ``headroom * hbm_bytes``; raises ValueError if nothing fits (with the
    closest layout's numbers in the message)."""
    if n_devices < 1:
        raise ValueError("n_devices must be >= 1")
    budget = headroom * hbm_bytes
    candidates = []
    for deg in _factorizations(n_devices):
        if deg["model"] > max_model or deg["model"] > max(1, hidden // 128):
            continue
        if deg["pipe"] > max(1, layers):
            continue
        for use_remat in ((remat,) if remat is not None else (False, True)):
            est = _estimate(n_params, deg, layers=layers, hidden=hidden,
                            seq_len=seq_len,
                            batch_per_device=batch_per_device,
                            param_bytes=param_bytes,
                            zero_stage=zero_stage, remat=use_remat)
            candidates.append((deg, use_remat, est))
    fitting = [(d, r, e) for d, r, e in candidates if e.total <= budget]
    if not fitting:
        best = min(candidates, key=lambda t: t[2].total)
        raise ValueError(
            f"no layout fits: closest is {best[0]} "
            f"(remat={best[1]}) at {best[2].total / 1e9:.1f} GB/device vs "
            f"budget {budget / 1e9:.1f} GB — add devices, raise "
            f"zero_stage, or shrink the per-device batch")
    deg, use_remat, est = min(
        fitting, key=lambda t: (_comm_cost(t[0]), t[1]))
    why = [
        f"{n_devices} devices -> data={deg['data']} sharding="
        f"{deg['sharding']} model={deg['model']} pipe={deg['pipe']}",
        f"per-device: params {est.params/1e9:.2f} GB + grads "
        f"{est.grads/1e9:.2f} GB + opt {est.opt_state/1e9:.2f} GB + act "
        f"{est.activations/1e9:.2f} GB = {est.total/1e9:.2f} GB "
        f"(budget {budget/1e9:.1f} GB)",
        f"zero_stage={zero_stage}, remat={use_remat}",
    ]
    if deg["model"] > 1:
        why.append("TP engaged: params exceed what DP+ZeRO fits alone")
    if deg["pipe"] > 1:
        why.append("PP engaged: per-layer state exceeds TP ceiling")
    p = Plan(degrees=deg, per_device=est, hbm_bytes=hbm_bytes,
             rationale=why)
    p.remat = use_remat
    return p


def resharding_cost(closed, mesh, in_specs, *, while_trips: float = 1.0
                    ) -> dict:
    """Score one candidate layout by its predicted implicit-resharding
    traffic: run the static sharding-propagation pass
    (analysis/sharding.py) over ``closed`` seeded with ``in_specs`` and
    fold the per-site table into planner-ready totals. Returns
    ``{"n_sites", "time_s", "wire_bytes", "dcn_bytes", "sites"}`` —
    lower ``time_s`` (and especially ``dcn_bytes``) means the layout
    needs fewer silent partitioner collectives, the second-order term
    the memory model above cannot see."""
    from ..analysis.sharding import resharding_table
    rows = resharding_table(closed, mesh, in_specs,
                            while_trips=while_trips)
    return {
        "n_sites": len(rows),
        "time_s": sum(r["time_s"] * max(r["trips"], 1.0) for r in rows),
        "wire_bytes": sum(r["wire_bytes"] * max(r["trips"], 1.0)
                          for r in rows),
        "dcn_bytes": sum(r["bytes"] for r in rows if r["link"] == "dcn"),
        "sites": rows,
    }
