"""PTB language-model dataset (reference:
python/paddle/text/datasets/imikolov.py:31 — simple-examples tarball,
min-freq word dict, NGRAM windows or SEQ mode with <s>/<e> markers).
"""
from __future__ import annotations

import collections
import tarfile

import numpy as np

from ...io.dataset import Dataset
from ...utils.download import DATA_HOME, get_path_from_url

URL = "https://dataset.bj.bcebos.com/imikolov%2Fsimple-examples.tar.gz"
MD5 = "30177ea32e27c525793142b6bf2c8e2d"


class Imikolov(Dataset):
    """data_type='NGRAM': samples are window_size-grams (tuple of arrays);
    data_type='SEQ': samples are (src_seq, trg_seq) shifted by one."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=True):
        assert data_type.upper() in ("NGRAM", "SEQ"), data_type
        assert mode.lower() in ("train", "test"), mode
        self.data_type = data_type.upper()
        self.window_size = window_size
        self.mode = mode.lower()
        self.min_word_freq = min_word_freq
        if data_file is None:
            assert download, "data_file not set and download disabled"
            data_file = get_path_from_url(URL, DATA_HOME + "/imikolov",
                                          decompress=False)
        self.data_file = data_file
        self.word_idx = self._build_dict()
        self.data = self._load()

    def _member(self, tf, suffix):
        for m in tf:
            if m.name.endswith(suffix):
                return m
        raise IOError(f"{suffix} not found in {self.data_file}")

    def _lines(self, suffix):
        with tarfile.open(self.data_file) as tf:
            f = tf.extractfile(self._member(tf, suffix))
            for line in f:
                yield line.decode("utf-8", "ignore").strip().split()

    def _build_dict(self):
        freq = collections.Counter()
        for words in self._lines("ptb.train.txt"):
            freq.update(words)
        freq.pop("<unk>", None)
        kept = [(w, c) for w, c in freq.items() if c >= self.min_word_freq]
        kept.sort(key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load(self):
        suffix = f"ptb.{self.mode}.txt"
        unk = self.word_idx["<unk>"]
        data = []
        for words in self._lines(suffix):
            if self.data_type == "NGRAM":
                assert self.window_size > 0, "window_size must be set >0"
                ids = [self.word_idx.get(w, unk)
                       for w in ["<s>"] * (self.window_size - 1) + words
                       + ["<e>"]]
                # markers outside the dict map to unk, matching reference
                for i in range(self.window_size, len(ids) + 1):
                    data.append(tuple(ids[i - self.window_size:i]))
            else:
                ids = [self.word_idx.get(w, unk) for w in words]
                src = [self.word_idx.get("<s>", unk)] + ids
                trg = ids + [self.word_idx.get("<e>", unk)]
                data.append((src, trg))
        return data

    def __getitem__(self, idx):
        return tuple(np.array(x) for x in self.data[idx])

    def __len__(self):
        return len(self.data)
