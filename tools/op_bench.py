"""Standalone op micro-benchmark harness.

Reference: paddle/fluid/operators/benchmark/op_tester.cc (C64 in SURVEY.md §2)
— runs a single op from a config N times and reports latency. TPU
translation: jit-compile the op once, time steady-state iterations with a
device sync per batch, report op name / shapes / mean latency / achieved
GB/s + GFLOP/s where derivable.

Usage:
    python tools/op_bench.py                      # built-in suite
    python tools/op_bench.py matmul --m 1024 --n 1024 --k 1024 --dtype bf16
"""
from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _sync(x):
    import jax
    leaves = jax.tree_util.tree_leaves(x)
    if leaves:
        np.asarray(leaves[0])  # host fetch = reliable sync (see bench.py)


def time_op(fn, args, iters=50, warmup=5):
    import jax
    jfn = jax.jit(fn)
    for _ in range(warmup):
        out = jfn(*args)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jfn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / iters


def bench_case(name, fn, args, flops=None, bytes_moved=None, iters=50):
    dt = time_op(fn, args, iters=iters)
    rec = {"op": name, "mean_us": round(dt * 1e6, 2)}
    if flops:
        rec["gflops"] = round(flops / dt / 1e9, 1)
    if bytes_moved:
        rec["gbps"] = round(bytes_moved / dt / 1e9, 1)
    print(json.dumps(rec))
    return rec


def default_suite(dtype="bfloat16", iters=50):
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import nn

    rng = np.random.RandomState(0)
    dt = jnp.dtype(dtype)
    results = []

    m = k = n = 2048
    a = jnp.asarray(rng.randn(m, k), dt)
    b = jnp.asarray(rng.randn(k, n), dt)
    results.append(bench_case(
        f"matmul_{m}x{k}x{n}_{dtype}", jnp.matmul, (a, b),
        flops=2 * m * k * n, bytes_moved=(m * k + k * n + m * n) * dt.itemsize,
        iters=iters))

    x = jnp.asarray(rng.randn(8, 3, 224, 224), dt)
    w = jnp.asarray(rng.randn(64, 3, 7, 7), dt)
    results.append(bench_case(
        "conv2d_resnet_stem", lambda x, w: nn.functional.conv2d(
            x, w, stride=2, padding=3), (x, w), iters=iters))

    h = jnp.asarray(rng.randn(8, 1024, 1024), dt)
    wln = jnp.ones((1024,), dt)
    bln = jnp.zeros((1024,), dt)
    results.append(bench_case(
        "layer_norm_8x1024x1024",
        lambda h, w, b: nn.functional.layer_norm(h, (1024,), w, b),
        (h, wln, bln), bytes_moved=2 * h.size * dt.itemsize, iters=iters))

    q = jnp.asarray(rng.randn(4, 1024, 8, 64), dt)
    results.append(bench_case(
        "flash_attention_s1024",
        lambda q: nn.functional.scaled_dot_product_attention(
            q, q, q, is_causal=True, training=False), (q,),
        # causal: only the lower triangle is computed -> half the dense count
        flops=4 * 4 * 8 * 1024 * 1024 * 64 // 2, iters=iters))

    e = jnp.asarray(rng.randn(50304, 768), dt)
    ids = jnp.asarray(rng.randint(0, 50304, (8, 1024)), jnp.int32)
    results.append(bench_case(
        "embedding_50k", lambda e, i: jnp.take(e, i, axis=0), (e, ids),
        bytes_moved=8 * 1024 * 768 * dt.itemsize, iters=iters))

    sm_x = jnp.asarray(rng.randn(8192, 50304), dt)
    results.append(bench_case(
        "softmax_8192x50304", lambda x: paddle.nn.functional.softmax(x, -1),
        (sm_x,), bytes_moved=2 * sm_x.size * dt.itemsize, iters=iters))
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("op", nargs="?", help="matmul | suite (default)")
    ap.add_argument("--m", type=int, default=1024)
    ap.add_argument("--n", type=int, default=1024)
    ap.add_argument("--k", type=int, default=1024)
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["float32", "bfloat16", "float16"])
    args = ap.parse_args()
    if args.op in (None, "suite"):
        default_suite(args.dtype, iters=args.iters)
        return
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    dt = jnp.dtype(args.dtype)
    if args.op == "matmul":
        a = jnp.asarray(rng.randn(args.m, args.k), dt)
        b = jnp.asarray(rng.randn(args.k, args.n), dt)
        bench_case(f"matmul_{args.m}x{args.k}x{args.n}_{args.dtype}",
                   jnp.matmul, (a, b), flops=2 * args.m * args.k * args.n,
                   iters=args.iters)
    else:
        raise SystemExit(f"unknown op {args.op!r} (use: matmul | suite)")


if __name__ == "__main__":
    main()
