"""Compressed gradient exchange: bucketed block-scaled int8 collectives with
error feedback (EQuARX, arXiv:2506.17615; reference analogue: the bucketed
NCCL Reducer in imperative/reducer.cc + DGC's residual accumulation in
fluid DGCMomentumOptimizer).

The reference frameworks's data-parallel hot path coalesces many small
per-tensor gradients into a few large flat buckets before the collective
(reducer.cc). This module is that layer for the TPU/XLA port, plus an
EQuARX-style two-phase quantized all-reduce:

  phase 0   per-block abs-max, pmax'd over the axis so every rank quantizes
            with the SAME scale (makes the reduction a pure integer sum);
  phase 1   int8 quantize -> reduce-scatter. The reduce-scatter is
            decomposed as all_to_all of the int8 chunks + a LOCAL int32
            accumulation: the wire dtype stays int8 (1 byte/elem) while the
            sum is exact in int32 (n * 127 never wraps) — the
            "psum_scatter of int32-accumulated shards" shape, done so XLA
            never moves 4-byte words for 1-byte payloads;
  phase 2   each rank dequantizes its reduced chunk, re-quantizes it with a
            fresh local per-block scale, and all_gathers int8 + scales.

Error feedback: the local phase-1 quantization error (x - deq(q(x))) is
returned to the caller and added to the NEXT step's gradient before
quantizing — the DGC local-accumulation idiom (optimizer/optimizer.py
DGCMomentum slot "v"): compression error is carried forward, not lost.

The int4 policy halves the wire again: two values packed per byte,
per-64-element blocks (4-bit steps are coarse, so blocks shrink to keep
the shared scale local), and scales crossing the wire as bf16 (a 4-bit
payload does not deserve 4-byte scales — and halving scale traffic is
what keeps the per-64 blocks above the 7x bytes win vs fp32). The local
accumulation of n quantized values lives in int16 while n * 7 < 2**15
and auto-widens to int32 above that (``int4_accum_dtype``).

``policy`` may also be a per-axis mapping ({axis: policy}): on
multi-slice topologies the ICI hops are fast enough that quantize
overhead loses, so ``grad_sync="int8"/"int4"`` should gate to the DCN
(cross-slice) axes only — the mesh-axis -> link-type map lives in
distributed/mesh.py. Lossless axis groups exchange FIRST (the cheap
ICI pre-reduction conditions the quantizer's input), quantized groups
after.

Everything here is plain traced jax: called inside a shard_map region the
collectives lower to XLA ICI/DCN ops and the latency-hiding scheduler
overlaps the per-bucket exchanges with backward compute (the bucket-size
knob exists exactly to give the scheduler multiple chunks to pipeline).
"""
from __future__ import annotations

import math
from typing import Mapping, Optional, Union

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "GRAD_SYNC_POLICIES", "QUANTIZED_POLICIES", "DEFAULT_BLOCK",
    "DEFAULT_INT4_BLOCK", "DEFAULT_BUCKET_BYTES", "INT16_SAFE_RANKS",
    "resolve_block", "int4_accum_dtype",
    "quantize_int8_blocks", "dequantize_int8_blocks",
    "quantize_int4_blocks", "dequantize_int4_blocks",
    "pack_int4", "unpack_int4",
    "compressed_tree_mean", "compressed_psum_scatter", "init_residuals",
    "normalize_axis_policies", "wire_bytes_per_rank", "tree_wire_bytes",
    "residual_norm",
]

GRAD_SYNC_POLICIES = ("fp32", "bf16", "int8", "int4")
QUANTIZED_POLICIES = ("int8", "int4")
DEFAULT_BLOCK = 256          # int8 quantization block
DEFAULT_INT4_BLOCK = 64      # int4: 4-bit steps are coarse -> smaller blocks
DEFAULT_BUCKET_BYTES = 4 << 20  # 4 MiB of fp32 per collective chunk

# int16 can hold a sum of n int4-range (|q| <= 7) values while n*7 fits:
INT16_SAFE_RANKS = (2 ** 15 - 1) // 7   # 4681


def resolve_block(policy: str, block: Optional[int]) -> int:
    """Per-policy default quantization block (block=None)."""
    if block is not None:
        return int(block)
    return DEFAULT_INT4_BLOCK if policy == "int4" else DEFAULT_BLOCK


def int4_accum_dtype(n: int):
    """Accumulation dtype for a sum of ``n`` int4-range values: int16
    while n*7 < 2**15, auto-widened to int32 above (and asserted sane —
    2**31/7 ranks is not a real machine)."""
    assert n * 7 < 2 ** 31, f"int4 accumulation over n={n} ranks overflows int32"
    return jnp.int16 if n <= INT16_SAFE_RANKS else jnp.int32


# --------------------------------------------------------------------------
# block quantization
# --------------------------------------------------------------------------

def quantize_int8_blocks(x, block: int = DEFAULT_BLOCK, scale=None):
    """Per-block symmetric int8 quantization of a flat fp32 array.

    ``x.size`` must be a multiple of ``block``. Returns ``(q, scale)`` with
    ``q`` int8 of x's shape and ``scale`` fp32 of shape (x.size // block,).
    When ``scale`` is given it is used as-is (the shared-scale path)."""
    xb = x.reshape(-1, block)
    if scale is None:
        amax = jnp.max(jnp.abs(xb), axis=1)
        scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(xb / scale[:, None]), -127, 127).astype(jnp.int8)
    return q.reshape(x.shape), scale


def dequantize_int8_blocks(q, scale, block: int = DEFAULT_BLOCK):
    xb = q.astype(jnp.float32).reshape(-1, block) * scale[:, None]
    return xb.reshape(q.shape)


def quantize_int4_blocks(x, block: int = DEFAULT_INT4_BLOCK, scale=None):
    """Per-block symmetric int4 quantization: values in [-7, 7], carried
    in an int8 array (``pack_int4`` packs two per byte for the wire).
    Returns ``(q, scale)`` like :func:`quantize_int8_blocks`."""
    xb = x.reshape(-1, block)
    if scale is None:
        amax = jnp.max(jnp.abs(xb), axis=1)
        scale = jnp.where(amax > 0, amax / 7.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(xb / scale[:, None]), -7, 7).astype(jnp.int8)
    return q.reshape(x.shape), scale


def dequantize_int4_blocks(q, scale, block: int = DEFAULT_INT4_BLOCK):
    """Dequantize int4-range values (any integer dtype — the accumulation
    path hands int16/int32 sums straight in)."""
    xb = q.astype(jnp.float32).reshape(-1, block) * scale[:, None]
    return xb.reshape(q.shape)


def pack_int4(q):
    """Pack a flat even-length int8 array of int4-range values two per
    byte (uint8): element 2i rides the low nibble, 2i+1 the high one."""
    pairs = q.reshape(-1, 2).astype(jnp.uint8)
    return (pairs[:, 0] & 0x0F) | ((pairs[:, 1] & 0x0F) << 4)


def unpack_int4(p):
    """Invert :func:`pack_int4`: uint8 bytes -> flat int8 values (sign-
    extended from the nibbles)."""
    lo = (p & 0x0F).astype(jnp.int8)
    hi = ((p >> 4) & 0x0F).astype(jnp.int8)
    lo = jnp.where(lo >= 8, lo - 16, lo)
    hi = jnp.where(hi >= 8, hi - 16, hi)
    return jnp.stack([lo, hi], axis=-1).reshape(-1)


# --------------------------------------------------------------------------
# axis helpers
# --------------------------------------------------------------------------

def _axis_tuple(axis):
    return axis if isinstance(axis, tuple) else (axis,)


def _axes_bound(axis) -> bool:
    for ax in _axis_tuple(axis):
        try:
            lax.axis_index(ax)
        except Exception:
            return False
    return True


def _axis_size(axis) -> int:
    # psum of a python scalar is evaluated statically at trace time
    return int(lax.psum(1, axis))


# --------------------------------------------------------------------------
# the two-phase int8 all-reduce over one flat bucket
# --------------------------------------------------------------------------

def _int8_bucket_sum(flat, axis, n: int, block: int):
    """All-reduce-SUM of one flat fp32 bucket (size % (n*block) == 0).

    Returns (reduced_sum, local_recon) where local_recon is the dequantized
    value of THIS rank's contribution — the caller forms the error-feedback
    residual as ``flat - local_recon``."""
    # phase 0: shared per-block scale (tiny fp32 collective, size/block)
    _, local_scale = quantize_int8_blocks(flat, block)
    amax = local_scale * 127.0
    scale = jnp.maximum(lax.pmax(amax, axis), 1e-30) / 127.0
    q, _ = quantize_int8_blocks(flat, block, scale=scale)
    recon = dequantize_int8_blocks(q, scale, block)
    if n == 1:
        return recon, recon
    c = flat.size // n
    # phase 1: decomposed reduce-scatter — int8 on the wire, int32 accum.
    # all_to_all row j of rank r -> rank j; received row j = rank j's
    # quantized version of MY chunk (same shared scale), so the sum is a
    # pure integer accumulation.
    recv = lax.all_to_all(q.reshape(n, c), axis, split_axis=0,
                          concat_axis=0, tiled=False)
    acc = jnp.sum(recv.astype(jnp.int32), axis=0)              # (c,) exact
    idx = lax.axis_index(axis)
    my_scales = lax.dynamic_slice_in_dim(scale, idx * (c // block),
                                         c // block, axis=0)
    red = dequantize_int8_blocks(acc, my_scales, block)         # (c,) fp32
    # phase 2: re-quantize the reduced chunk with a fresh LOCAL scale
    # (each rank owns a distinct chunk) and all_gather int8 + scales
    q2, s2 = quantize_int8_blocks(red, block)
    full_q = lax.all_gather(q2, axis, axis=0, tiled=True)
    full_s = lax.all_gather(s2, axis, axis=0, tiled=True)
    return dequantize_int8_blocks(full_q, full_s, block), recon


def _int4_bucket_sum(flat, axis, n: int, block: int):
    """All-reduce-SUM of one flat fp32 bucket through the packed int4
    exchange (size % (n*block) == 0, block even). Same two-phase shape as
    :func:`_int8_bucket_sum`, except: values pack two per byte for every
    wire move, scales cross as bf16 (half the scale traffic — what keeps
    per-64 blocks above 7x vs fp32), and the local accumulation dtype
    widens from int16 to int32 once n * 7 leaves the int16 range."""
    # phase 0: shared per-block scale; the amax all-reduce rides bf16
    _, local_scale = quantize_int4_blocks(flat, block)
    amax = lax.pmax((local_scale * 7.0).astype(jnp.bfloat16), axis)
    scale = jnp.maximum(amax.astype(jnp.float32), 1e-30) / 7.0
    q, _ = quantize_int4_blocks(flat, block, scale=scale)
    recon = dequantize_int4_blocks(q, scale, block)
    if n == 1:
        return recon, recon
    c = flat.size // n
    # phase 1: decomposed reduce-scatter — nibble-packed uint8 on the
    # wire, int16 (int32 past INT16_SAFE_RANKS ranks) local accumulation
    packed = pack_int4(q).reshape(n, c // 2)
    recv = lax.all_to_all(packed, axis, split_axis=0, concat_axis=0,
                          tiled=False)
    vals = unpack_int4(recv.reshape(-1)).reshape(n, c)
    acc = jnp.sum(vals.astype(int4_accum_dtype(n)), axis=0)       # exact
    idx = lax.axis_index(axis)
    my_scales = lax.dynamic_slice_in_dim(scale, idx * (c // block),
                                         c // block, axis=0)
    red = dequantize_int4_blocks(acc, my_scales, block)           # (c,)
    # phase 2: fresh local scale, rounded to its bf16 wire format BEFORE
    # quantizing so q2 * gathered-scale is self-consistent
    _, s2 = quantize_int4_blocks(red, block)
    s2 = s2.astype(jnp.bfloat16)
    q2, _ = quantize_int4_blocks(red, block, scale=s2.astype(jnp.float32))
    full_q = lax.all_gather(pack_int4(q2), axis, axis=0, tiled=True)
    full_s = lax.all_gather(s2, axis, axis=0, tiled=True)
    out = dequantize_int4_blocks(unpack_int4(full_q),
                                 full_s.astype(jnp.float32), block)
    return out, recon


def _bucket_mean(flat, groups, sizes, blocks):
    """Mean of one flat fp32 bucket over every (axes, policy) group,
    exchanged sequentially (lossless groups first — see
    ``normalize_axis_policies``). Returns ``(mean, err)`` where err is
    this rank's total quantization error (None when no group quantizes):
    the error-feedback residual the caller carries to the next step."""
    x, err = flat, None
    for (axes, pol), n, blk in zip(groups, sizes, blocks):
        if pol in QUANTIZED_POLICIES:
            fn = _int8_bucket_sum if pol == "int8" else _int4_bucket_sum
            s, recon = fn(x, axes, n, blk)
            e = x - recon
            err = e if err is None else err + e
            x = s / n
        elif n > 1:
            if pol == "bf16":
                x = lax.pmean(x.astype(jnp.bfloat16), axes).astype(x.dtype)
            else:
                x = lax.pmean(x, axes)
    return x, err


def normalize_axis_policies(axis, policy):
    """Resolve ``policy`` — one name for all axes, or a per-axis mapping
    ({axis: policy}, unlisted axes exact) — into ordered exchange groups
    ``[(axes_tuple, policy)]``. Lossless groups come first: the cheap
    exact pre-reduction (ICI hops under DCN gating) runs before the
    quantizer sees the data, so the compressed group quantizes the
    already-averaged gradient."""
    axes = _axis_tuple(axis)
    if isinstance(policy, str):
        per = {ax: policy for ax in axes}
    else:
        per = {ax: policy.get(ax, "fp32") for ax in axes}
    for ax, p in per.items():
        if p not in GRAD_SYNC_POLICIES:
            raise ValueError(f"grad_sync policy {p!r} for axis {ax!r} "
                             f"not in {GRAD_SYNC_POLICIES}")
    groups = []
    for p in GRAD_SYNC_POLICIES:    # fp32, bf16, int8, int4: lossless first
        g = tuple(ax for ax in axes if per[ax] == p)
        if g:
            groups.append((g, p))
    return groups


# --------------------------------------------------------------------------
# pytree flatten / bucket / exchange / unflatten
# --------------------------------------------------------------------------

def _dtype_groups(leaves):
    """Group leaf indices by dtype, preserving first-appearance order, so
    bf16 grads and fp32 grads ride separate flat segments."""
    groups = {}
    for i, v in enumerate(leaves):
        groups.setdefault(jnp.asarray(v).dtype, []).append(i)
    return groups


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def bucket_sizes(total: int, bucket_numel: int, align: int):
    """Split ``total`` (already a multiple of ``align``) into bucket sizes,
    each a multiple of ``align``; all but the last are ``bucket_numel``."""
    bucket_numel = max(_round_up(bucket_numel, align), align)
    sizes = []
    done = 0
    while done < total:
        s = min(bucket_numel, total - done)
        sizes.append(s)
        done += s
    return sizes


def compressed_tree_mean(tree, axis,
                         policy: Union[str, Mapping] = "int8",
                         block: Optional[int] = None,
                         bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                         residuals=None):
    """Mean-reduce a gradient pytree over ``axis`` through the bucketed
    compressed exchange.

    ``policy`` is one name for every axis, or a per-axis mapping
    ({axis: policy}, unlisted axes fp32) — the DCN-gating path: quantized
    groups ride only the axes the caller marked, lossless groups
    pre-reduce first. ``block=None`` picks the per-policy default (256
    for int8, 64 for int4).

    Returns ``(mean_tree, new_residuals)``. ``residuals`` is the
    error-feedback state (same treedef, fp32 leaves) consumed whenever
    any group quantizes (int8/int4): the effective gradient is
    ``g + residual`` and the new residual is the part the quantizers
    dropped. For fp32/bf16 it is passed through untouched. Outside a
    traced region (axis unbound) this is identity — the single-card fast
    path, matching collective.py conventions.
    """
    groups = normalize_axis_policies(axis, policy)   # also validates
    if not _axes_bound(axis):
        return tree, residuals
    sizes = [_axis_size(axes) for axes, _ in groups]
    blocks = [resolve_block(p, block) for _, p in groups]
    align = 1
    for (_, p), n_g, blk in zip(groups, sizes, blocks):
        if p in QUANTIZED_POLICIES:
            if p == "int4" and blk % 2:
                raise ValueError(f"int4 block must be even, got {blk}")
            align = math.lcm(align, n_g * blk)
    quantized = any(p in QUANTIZED_POLICIES for _, p in groups)

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    res_leaves = (jax.tree_util.tree_flatten(residuals)[0]
                  if residuals is not None else None)
    use_ef = quantized and res_leaves is not None
    out_leaves = [None] * len(leaves)
    new_res = list(res_leaves) if res_leaves is not None else None

    for dtype, idxs in _dtype_groups(leaves).items():
        if not jnp.issubdtype(dtype, jnp.floating):
            # non-float leaves (counters etc.) never quantize
            for i in idxs:
                out_leaves[i] = lax.pmean(leaves[i], axis)
            continue
        parts = [leaves[i].reshape(-1).astype(jnp.float32) for i in idxs]
        if use_ef:
            parts = [p + new_res[i].reshape(-1) for p, i in zip(parts, idxs)]
        flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        total = _round_up(flat.size, align)
        if total != flat.size:
            flat = jnp.concatenate(
                [flat, jnp.zeros(total - flat.size, jnp.float32)])
        means, errs = [], []
        off = 0
        for s in bucket_sizes(total, max(bucket_bytes // 4, align), align):
            m, e = _bucket_mean(flat[off:off + s], groups, sizes, blocks)
            means.append(m)
            errs.append(e)
            off += s
        mean = means[0] if len(means) == 1 else jnp.concatenate(means)
        if use_ef:
            err = errs[0] if len(errs) == 1 else jnp.concatenate(errs)
        off = 0
        for i in idxs:
            sz = leaves[i].size
            out_leaves[i] = mean[off:off + sz].reshape(
                leaves[i].shape).astype(dtype)
            if use_ef:
                new_res[i] = err[off:off + sz].reshape(leaves[i].shape)
            off += sz

    out = jax.tree_util.tree_unflatten(treedef, out_leaves)
    res_out = (jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(residuals), new_res)
        if res_leaves is not None else residuals)
    return out, res_out


def compressed_psum_scatter(x, axis, scatter_dim: int = 0,
                            policy: str = "int8",
                            block: Optional[int] = None,
                            residual=None):
    """Block-quantized reduce-scatter SUM over ``axis`` — phase 1 of the
    two-phase exchange with NO gather: the wire-compressed drop-in for
    ``lax.psum_scatter(x, axis, scatter_dimension=scatter_dim,
    tiled=True)`` on the engine's ZeRO-2/3 sharded-grad leaves (each rank
    keeps only its own chunk, so gathering back would waste the win).

    Returns the SUM like psum_scatter; callers divide by the axis size
    themselves. ``residual`` opts into error feedback: when given (an
    fp32 array of x's shape), the effective input is ``x + residual``
    and the call returns ``(out, new_residual)`` where new_residual is
    this rank's full-tensor quantization error — the sharded-leaf
    counterpart of :func:`compressed_tree_mean`'s residual threading, so
    ZeRO-2/3 leaves get the same convergence treatment as replicated
    ones. With ``residual=None`` the call is stateless and returns just
    the scattered sum (the original contract). Lossless policies fall
    back to the plain (bf16-cast for "bf16") psum_scatter, passing any
    residual through untouched.
    """
    if policy not in GRAD_SYNC_POLICIES:
        raise ValueError(f"grad_sync policy {policy!r} not in "
                         f"{GRAD_SYNC_POLICIES}")
    if policy not in QUANTIZED_POLICIES:
        if policy == "bf16" and x.dtype == jnp.float32:
            out = lax.psum_scatter(
                x.astype(jnp.bfloat16), axis,
                scatter_dimension=scatter_dim, tiled=True).astype(x.dtype)
        else:
            out = lax.psum_scatter(x, axis, scatter_dimension=scatter_dim,
                                   tiled=True)
        return out if residual is None else (out, residual)
    n = _axis_size(axis)
    blk = resolve_block(policy, block)
    if policy == "int4":
        if blk % 2:
            raise ValueError(f"int4 block must be even, got {blk}")
        quant, dequant, levels = (quantize_int4_blocks,
                                  dequantize_int4_blocks, 7.0)
    else:
        quant, dequant, levels = (quantize_int8_blocks,
                                  dequantize_int8_blocks, 127.0)
    xm = jnp.moveaxis(x, scatter_dim, 0)
    if residual is not None:
        xm = xm.astype(jnp.float32) + jnp.moveaxis(
            residual, scatter_dim, 0).astype(jnp.float32)
    d0 = xm.shape[0]
    if d0 % n:
        raise ValueError(f"scatter dim size {d0} not divisible by axis "
                         f"size {n}")
    chunk_shape = (d0 // n,) + xm.shape[1:]
    m = math.prod(chunk_shape)
    m_pad = _round_up(max(m, 1), blk)
    rows = xm.astype(jnp.float32).reshape(n, m)
    if m_pad != m:
        rows = jnp.concatenate(
            [rows, jnp.zeros((n, m_pad - m), jnp.float32)], axis=1)
    flat = rows.reshape(-1)
    # shared per-block scale so the reduction is a pure integer sum;
    # int4's scale traffic rides bf16 like the all-reduce path
    _, local_scale = quant(flat, blk)
    amax = local_scale * levels
    if policy == "int4":
        amax = lax.pmax(amax.astype(jnp.bfloat16), axis).astype(jnp.float32)
    else:
        amax = lax.pmax(amax, axis)
    scale = jnp.maximum(amax, 1e-30) / levels
    q, _ = quant(flat, blk, scale=scale)
    if n > 1:
        if policy == "int4":
            packed = pack_int4(q).reshape(n, m_pad // 2)
            recv = lax.all_to_all(packed, axis, split_axis=0,
                                  concat_axis=0, tiled=False)
            vals = unpack_int4(recv.reshape(-1)).reshape(n, m_pad)
            acc = jnp.sum(vals.astype(int4_accum_dtype(n)), axis=0)
        else:
            recv = lax.all_to_all(q.reshape(n, m_pad), axis, split_axis=0,
                                  concat_axis=0, tiled=False)
            acc = jnp.sum(recv.astype(jnp.int32), axis=0)
        idx = lax.axis_index(axis)
        nsc = m_pad // blk
        my_scales = lax.dynamic_slice_in_dim(scale, idx * nsc, nsc, axis=0)
    else:
        acc, my_scales = q, scale
    red = dequant(acc, my_scales, blk)
    out = red[:m].reshape(chunk_shape)
    out = jnp.moveaxis(out, 0, scatter_dim).astype(x.dtype)
    if residual is None:
        return out
    # error feedback: this rank's full-tensor quantization error — what the
    # shared-scale quantizer dropped from (x + residual) — carries forward
    recon = dequant(q, scale, blk).reshape(n, m_pad)
    err = (rows - recon)[:, :m].reshape((d0,) + chunk_shape[1:])
    return out, jnp.moveaxis(err, 0, scatter_dim)


def init_residuals(tree):
    """Zero error-feedback state for a gradient pytree (fp32 leaves)."""
    return jax.tree_util.tree_map(
        lambda v: jnp.zeros(jnp.shape(v), jnp.float32), tree)


# --------------------------------------------------------------------------
# wire accounting (the bench's bytes-on-wire model)
# --------------------------------------------------------------------------

def wire_bytes_per_rank(numel: int, n: int, policy: str,
                        block: Optional[int] = None,
                        dtype_bytes: int = 4) -> float:
    """Bytes each rank moves for one mean over ``numel`` elements, ring
    algorithms: all-reduce = 2(n-1)/n payloads, reduce-scatter/all-gather =
    (n-1)/n each. The quantized figures count both phases plus every scale
    exchange (the pmax all-reduce of per-block scales and the phase-2
    gathered scales); int4 moves half a byte per value and 2-byte bf16
    scales."""
    if n <= 1:
        return 0.0
    ring = (n - 1) / n
    nscales = numel / resolve_block(policy, block)
    if policy == "fp32":
        return 2 * ring * numel * dtype_bytes
    if policy == "bf16":
        return 2 * ring * numel * 2
    if policy == "int8":
        return (2 * ring * nscales * 4        # phase 0: scale pmax
                + ring * numel * 1            # phase 1: int8 all_to_all
                + ring * (numel * 1 + nscales * 4))  # phase 2: all_gather
    if policy == "int4":
        return (2 * ring * nscales * 2        # phase 0: bf16 scale pmax
                + ring * numel * 0.5          # phase 1: packed all_to_all
                + ring * (numel * 0.5 + nscales * 2))  # phase 2: all_gather
    raise ValueError(f"unknown policy {policy!r}")


def tree_wire_bytes(tree, n: int, policy: str,
                    block: Optional[int] = None) -> float:
    """Logical bytes ONE ``compressed_tree_mean`` over ``n`` ranks moves
    per rank for this pytree — the telemetry counterpart of
    ``wire_bytes_per_rank``, applying the exchange's actual grouping:
    float leaves coalesce per dtype group into an fp32 flat padded to
    ``n*block``; non-float leaves go through a per-leaf pmean."""
    if n <= 1:
        return 0.0
    blk = resolve_block(policy, block)
    leaves = jax.tree_util.tree_leaves(tree)
    align = n * blk
    total = 0.0
    for dtype, idxs in _dtype_groups(leaves).items():
        sizes = [int(jnp.asarray(leaves[i]).size) for i in idxs]
        if not jnp.issubdtype(dtype, jnp.floating):
            itemsize = jnp.dtype(dtype).itemsize
            total += sum(2 * (n - 1) / n * s * itemsize for s in sizes)
            continue
        padded = _round_up(sum(sizes), align)
        total += wire_bytes_per_rank(padded, n, policy, blk)
    return total


_RESIDUAL_NORM_FN = None


def residual_norm(tree) -> float:
    """Host-side L2 norm of the error-feedback residual state — the
    telemetry hook watching whether int8 quantization error stays bounded
    (it should hover, not grow, once error feedback converges). Blocks on
    the device reduction; call off the hot path / when telemetry is on."""
    global _RESIDUAL_NORM_FN
    if _RESIDUAL_NORM_FN is None:
        def _norm(t):
            leaves = jax.tree_util.tree_leaves(t)
            sq = sum(jnp.sum(jnp.square(v.astype(jnp.float32)))
                     for v in leaves)
            return jnp.sqrt(sq)
        _RESIDUAL_NORM_FN = jax.jit(_norm)
    return float(_RESIDUAL_NORM_FN(tree))
