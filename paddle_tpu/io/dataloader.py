"""DataLoader (reference: fluid/reader.py:146 DataLoader,
fluid/dataloader/dataloader_iter.py:97 single-process, :248 multi-process).

TPU-native design: worker *processes* (fork) pull index batches from a queue
and push collated numpy batches back (the reference's shared-mem LoDTensor
path is replaced by pickled numpy over pipes — fine for host→TPU feed since
the transfer is overlapped by a device-prefetch depth of 2, which is what
operators/reader/buffered_reader.cc achieves with CUDA streams).
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import queue as queue_mod
import threading
import time
import traceback

import numpy as np

from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, np.ndarray):
        return np.stack(batch, axis=0)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    arr = np.asarray(batch)
    return arr


class WorkerInfo:
    """Info visible inside a DataLoader worker (reference
    fluid/dataloader/worker.py WorkerInfo: id/num_workers/dataset)."""

    def __init__(self, id, num_workers, dataset, seed=None):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


_worker_info = None


def get_worker_info():
    """Return the current WorkerInfo inside a worker process, else None
    (reference python/paddle/io get_worker_info — used by IterableDataset
    shards)."""
    return _worker_info


def _worker_loop(dataset, index_queue, data_queue, collate_fn, worker_id,
                 worker_init_fn, num_workers=0):
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset)
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    while True:
        item = index_queue.get()
        if item is None:
            break
        seq, indices = item
        try:
            samples = [dataset[i] for i in indices]
            data_queue.put((seq, collate_fn(samples), None))
        except Exception:
            data_queue.put((seq, None, traceback.format_exc()))


class _MultiProcessIter:
    def __init__(self, loader):
        self.loader = loader
        self.batches = list(iter(loader.batch_sampler))
        ctx = mp.get_context("fork")
        self.index_queue = ctx.Queue()
        self.data_queue = ctx.Queue()
        self.workers = []
        for wid in range(loader.num_workers):
            w = ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, self.index_queue, self.data_queue,
                      loader.collate_fn, wid, loader.worker_init_fn,
                      loader.num_workers),
                daemon=True)
            w.start()
            self.workers.append(w)
        # backpressure: keep at most num_workers * prefetch_factor batches in
        # flight (the buffered_reader.cc double-buffer bound, host-side)
        self.window = max(loader.num_workers * loader.prefetch_factor, 1)
        self.dispatched = 0
        for _ in range(min(self.window, len(self.batches))):
            self._dispatch_next()
        self.reorder = {}
        self.next_seq = 0

    def _dispatch_next(self):
        if self.dispatched < len(self.batches):
            self.index_queue.put((self.dispatched,
                                  self.batches[self.dispatched]))
            self.dispatched += 1

    def __iter__(self):
        return self

    def __next__(self):
        if self.next_seq >= len(self.batches):
            self._shutdown(graceful=True)
            raise StopIteration
        while self.next_seq not in self.reorder:
            seq, data, err = self.data_queue.get()
            if err is not None:
                self._shutdown()
                raise RuntimeError(f"DataLoader worker failed:\n{err}")
            self.reorder[seq] = data
        data = self.reorder.pop(self.next_seq)
        self.next_seq += 1
        self._dispatch_next()
        return self.loader._to_output(data)

    def _shutdown(self, graceful=False):
        if graceful:
            for _ in self.workers:
                self.index_queue.put(None)
        for w in self.workers:
            if w.is_alive():
                if graceful:
                    w.join(timeout=1.0)
                if w.is_alive():
                    w.terminate()
        self.workers = []

    def __del__(self):
        self._shutdown()


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.worker_init_fn = worker_init_fn
        self.prefetch_factor = prefetch_factor
        self.use_buffer_reader = use_buffer_reader
        self._is_iterable_ds = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif not self._is_iterable_ds:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)
            self.batch_size = batch_size
        else:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last

    def _to_output(self, data):
        return data

    def _iter_iterable(self):
        it = iter(self.dataset)
        while True:
            batch = list(itertools.islice(it, self.batch_size))
            if not batch:
                return
            if len(batch) < self.batch_size and getattr(self, "drop_last", False):
                return
            yield self._to_output(self.collate_fn(batch))

    def _iter_single(self):
        from ..resilience import faults
        from ..resilience.retry import call_with_retry
        step = 0
        for indices in self.batch_sampler:

            def _fetch():
                # transient source failures (remote fs hiccups, injected
                # data_fetch faults) are retried here, not surfaced to the
                # training loop
                faults.maybe_raise("data_fetch", step=step,
                                   site="dataloader_fetch",
                                   msg="injected data_fetch in dataloader")
                return [self.dataset[i] for i in indices]

            samples = call_with_retry(_fetch, site="dataloader_fetch",
                                      tries=3, base_delay=0.01)
            step += 1
            yield self._to_output(self.collate_fn(samples))

    def __iter__(self):
        if self._is_iterable_ds:
            base = self._iter_iterable()
        elif self.num_workers > 0:
            base = _MultiProcessIter(self)
        else:
            base = self._iter_single()
        if self.use_buffer_reader:
            it = _PrefetchIter(base, depth=self.prefetch_factor)
        else:
            it = iter(base)
        from .. import telemetry
        if telemetry.enabled():
            return _TimedIter(it)
        return it

    def __len__(self):
        if self.batch_sampler is not None:
            return len(self.batch_sampler)
        raise TypeError("length of IterableDataset loader is unknown")


class _TimedIter:
    """Telemetry wrapper: time each batch fetch. With prefetch in front,
    near-zero fetch times mean the pipeline keeps up; fetch times
    approaching step time are the input-starvation signature (compare the
    dataloader_fetch_seconds histogram against step_time_seconds)."""

    def __init__(self, it):
        self._it = iter(it)

    def __iter__(self):
        return self

    def __next__(self):
        from .. import telemetry
        t0 = time.perf_counter()
        item = next(self._it)
        telemetry.histogram(
            "dataloader_fetch_seconds",
            "wall time blocked fetching one batch").observe(
                time.perf_counter() - t0)
        telemetry.counter(
            "dataloader_batches_total", "batches served").inc()
        return item


class _PrefetchIter:
    """Background-thread prefetch (the host-side analogue of
    operators/reader/buffered_reader.cc double buffering)."""

    def __init__(self, source, depth=2):
        self.q = queue_mod.Queue(maxsize=depth)
        self.done = object()
        self.exc = None

        def run():
            try:
                for item in source:
                    self.q.put(item)
            except BaseException as e:  # noqa: BLE001
                self.exc = e
            finally:
                self.q.put(self.done)

        self.thread = threading.Thread(target=run, name="data-prefetch",
                                       daemon=True)
        self.thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        item = self.q.get()
        if item is self.done:
            if self.exc is not None:
                raise self.exc
            raise StopIteration
        return item
