"""Overload-robust inference serving runtime (ISSUE 10).

The reference platform serves Paddle models through a C++ server
(paddle_serving / fleet serving) whose core loop is: bounded admission,
dynamic batching, a worker pool per model replica, and health-driven
failover. This module is that loop over the repo's ``Predictor`` /
``jit.load`` path, built robustness-first — overload, stragglers, and
replica death degrade gracefully instead of cascading:

- **Admission control + deadlines** — ``submit`` places a request in a
  bounded queue. A request carries an absolute deadline; it is rejected
  at admission when the queue is full (``queue_full``) or when the
  queue's *modeled* wait (EWMA service rate over recent batches) already
  exceeds the deadline (``deadline_infeasible``). A request whose
  deadline passes while it waits is dropped, never executed
  (``deadline_expired_in_queue``). Every shed cause is counted in
  ``serving_requests_shed_total{cause=...}``.

- **Continuous batching** — a batcher thread coalesces compatible
  requests (same per-row input signature) into padded batches whose row
  counts are bucketed to powers of two (``ops.pallas.tuner.shape_bucket``
  semantics), so every batch hits one of a small closed set of compiled
  programs. ``serving_recompiles_total`` counts first-seen
  (signature, bucket) pairs — it must stop growing after warmup.

- **Replica health + failover** — batches round-robin over healthy
  replicas. Each dispatch arms a per-call deadline (the
  ``integrity.HangWatchdog`` semantics: a timer that fires once if the
  call does not finish in time); on fire the replica is marked
  unhealthy, its worker is respawned (the wedged thread is abandoned —
  a stuck device call cannot be interrupted), its in-flight requests
  are requeued to the survivors at the front of the queue, and the
  replica re-enters through jittered-backoff probation
  (``resilience.retry`` backoff math, strikes lengthen the sentence).
  ``serving_io`` (transient IOError) and ``replica_stall`` (wedged
  call) faults from ``resilience.faults`` make both paths
  deterministically testable.

- **Graceful drain** — ``shutdown(drain=True)`` (or the installed
  SIGTERM handler) stops admitting (``draining`` shed cause) while
  accepted work runs to completion.

Accounting invariant: every submitted request terminates in exactly one
of ``completed`` / ``shed`` / ``expired`` / ``failed`` — nothing is
silently lost, including requests in flight on a replica that dies.

Typical use::

    server = InferenceServer.from_config(config, replicas=2)
    with server:
        req = server.submit([x], deadline_s=0.2)
        out = req.result(timeout=1.0)   # raises RequestShed / DeadlineExpired
"""
from __future__ import annotations

import collections
import queue as _queue_mod
import signal
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..ops.pallas.tuner import shape_bucket
from ..resilience import faults
from ..resilience.retry import _backoff
from ..telemetry import tracing as _tracing

__all__ = [
    "InferenceServer", "ServingConfig", "Request",
    "RequestShed", "DeadlineExpired", "ServingError",
    "SHED_CAUSES", "predictor_executor",
    "DecodeServer", "GenerationRequest",
]

# terminal request states (the accounting universe)
PENDING = "pending"
COMPLETED = "completed"
SHED = "shed"
EXPIRED = "expired"
FAILED = "failed"

SHED_CAUSES = ("queue_full", "deadline_infeasible",
               "deadline_expired_in_queue", "draining")


class RequestShed(RuntimeError):
    """The request was rejected by admission control / drain."""

    def __init__(self, cause: str):
        super().__init__(f"request shed: {cause}")
        self.cause = cause


class DeadlineExpired(RuntimeError):
    """The request's deadline passed before it could execute."""


class ServingError(RuntimeError):
    """The request failed terminally (executor error past recovery)."""


class Request:
    """One inference request: a list of arrays sharing a leading row
    dim, an optional absolute deadline, and a future-style result."""

    _ids = iter(range(1, 2 ** 62))
    _ids_lock = threading.Lock()

    def __init__(self, inputs: Sequence[np.ndarray],
                 deadline_s: Optional[float] = None,
                 tokens: Optional[int] = None):
        self.inputs = [np.ascontiguousarray(x) for x in inputs]
        if not self.inputs:
            raise ValueError("a request needs at least one input array")
        for x in self.inputs:
            if x.ndim < 1:
                raise ValueError("request inputs must have a leading "
                                 "batch (row) dimension")
        rows = self.inputs[0].shape[0]
        if any(x.shape[0] != rows for x in self.inputs):
            raise ValueError("all request inputs must share the leading "
                             "row dimension")
        with Request._ids_lock:
            self.id = next(Request._ids)
        self.rows = int(rows)
        self.tokens = int(tokens) if tokens is not None else self.rows
        self.arrival = time.monotonic()
        self.deadline = (None if deadline_s is None
                         else self.arrival + float(deadline_s))
        self.state = PENDING
        self.attempts = 0  # dispatches that ended in a failover requeue
        self.cause: Optional[str] = None
        self.outputs: Optional[List[np.ndarray]] = None
        self.error: Optional[BaseException] = None
        self.t_dispatch: Optional[float] = None  # first dispatch only
        self.t_done: Optional[float] = None
        # admission model's predicted wait (x admission_safety) at submit
        # time; paired with the measured wait at first dispatch by
        # telemetry.calibration ("serving_queue_wait")
        self.t_predicted_wait: Optional[float] = None
        # invoked exactly once, after the request reaches ANY terminal
        # state (resource owners — e.g. the KV cache — hook cleanup here
        # so every seal path releases, not just the happy one)
        self.on_terminal: Optional[Callable[["Request"], None]] = None
        self._done = threading.Event()
        self._lock = threading.Lock()
        # tail-sampled tracing (telemetry.tracing): populated only when
        # tracing is enabled — the disabled hot path allocates no spans
        self._trace = None
        self._span_wait = None      # admission -> first dispatch
        self._attempt_span = None   # current dispatch attempt

    def signature(self):
        """Batch-compatibility key: per-row shape + dtype of each input."""
        return tuple((x.shape[1:], x.dtype.str) for x in self.inputs)

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now if now is not None else time.monotonic())
                > self.deadline)

    def _seal(self, state: str, outputs=None, error=None,
              cause=None) -> bool:
        """Move to a terminal state exactly once (first sealer wins —
        the requeue path and a late result from a wedged replica race)."""
        with self._lock:
            if self.state != PENDING:
                return False
            self.state = state
            self.outputs = outputs
            self.error = error
            self.cause = cause
            self.t_done = time.monotonic()
        if self._trace is not None:
            self._close_trace(state)
        cb = self.on_terminal
        if cb is not None:
            try:
                cb(self)
            except Exception as e:  # noqa: BLE001 - cleanup must not unseal
                import warnings
                warnings.warn(f"request {self.id} on_terminal hook "
                              f"failed: {e!r}", stacklevel=2)
        self._done.set()
        return True

    def _close_trace(self, outcome: str):
        """Run the tail-sampling keep/drop decision for this request's
        trace; any span the seal raced still open is ended with the
        outcome so the trace tree is complete at close."""
        tr = self._trace
        for sp in (self._span_wait, self._attempt_span):
            if sp is not None and not sp._ended:
                sp.end(outcome)
        rel = None if self.deadline is None else self.deadline - self.arrival
        tr.close(outcome, deadline_s=rel, failover=self.attempts > 0,
                 attempts=self.attempts, cause=self.cause)

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> List[np.ndarray]:
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.id} still pending")
        if self.state == COMPLETED:
            return self.outputs
        if self.state == SHED:
            raise RequestShed(self.cause)
        if self.state == EXPIRED:
            raise DeadlineExpired(
                f"request {self.id} expired ({self.cause})")
        raise ServingError(
            f"request {self.id} failed: {self.error!r}") from self.error

    @property
    def latency(self) -> Optional[float]:
        return (None if self.t_done is None
                else self.t_done - self.arrival)


class ServingConfig:
    """Knobs for :class:`InferenceServer` (defaults sized for tests /
    CPU smoke; production raises queue depth and call timeout)."""

    def __init__(self, max_queue: int = 256, max_batch: int = 8,
                 batch_wait_s: float = 0.002,
                 call_timeout_s: float = 2.0,
                 admission_safety: float = 1.0,
                 probation_base_s: float = 0.05,
                 probation_factor: float = 2.0,
                 probation_max_s: float = 2.0,
                 probation_jitter: float = 0.5,
                 rate_ewma: float = 0.3,
                 default_deadline_s: Optional[float] = None,
                 max_attempts: int = 6,
                 seed: int = 0):
        if max_queue < 1 or max_batch < 1:
            raise ValueError("max_queue and max_batch must be >= 1")
        self.max_queue = int(max_queue)
        self.max_batch = int(max_batch)
        self.batch_wait_s = float(batch_wait_s)
        self.call_timeout_s = float(call_timeout_s)
        self.admission_safety = float(admission_safety)
        self.probation_base_s = float(probation_base_s)
        self.probation_factor = float(probation_factor)
        self.probation_max_s = float(probation_max_s)
        self.probation_jitter = float(probation_jitter)
        self.rate_ewma = float(rate_ewma)
        self.default_deadline_s = default_deadline_s
        self.max_attempts = int(max_attempts)
        self.seed = int(seed)


class _BatchJob:
    """One padded batch in flight on a replica. ``try_finish`` /
    ``try_cancel`` are mutually exclusive: whichever side wins decides
    whether the results are published or the requests requeued."""

    def __init__(self, requests: List[Request], arrays: List[np.ndarray],
                 bucket: int, rows: int, seq: int):
        self.requests = requests
        self.arrays = arrays
        self.bucket = bucket
        self.rows = rows
        self.seq = seq
        self.timer: Optional[threading.Timer] = None
        self._lock = threading.Lock()
        self._done = False
        self._cancelled = False

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    def try_finish(self) -> bool:
        with self._lock:
            if self._cancelled or self._done:
                return False
            self._done = True
            return True

    def try_cancel(self) -> bool:
        with self._lock:
            if self._done or self._cancelled:
                return False
            self._cancelled = True
            return True


class _Replica:
    """One executor: a callable over padded input arrays, a work queue,
    and a worker thread. Health is probation-based: a strike benches
    the replica for a jittered-backoff interval, then it is optimistically
    re-admitted (a still-wedged replica strikes again, longer)."""

    def __init__(self, idx: int, fn: Callable, server: "InferenceServer"):
        self.idx = idx
        self.fn = fn
        self.server = server
        self.healthy = True
        self.strikes = 0
        self.probation_until = 0.0
        self.generation = 0
        self.lock = threading.Lock()
        self.queue: _queue_mod.Queue = _queue_mod.Queue()
        self.thread: Optional[threading.Thread] = None

    def start(self):
        self._spawn(self.generation, self.queue)

    def _spawn(self, gen: int, q: _queue_mod.Queue):
        self.thread = threading.Thread(
            target=self.server._worker_loop, args=(self, gen, q),
            name=f"serving-replica-{self.idx}-g{gen}", daemon=True)
        self.thread.start()

    def mark_unhealthy(self, reason: str, respawn: bool = False):
        abandoned = None
        with self.lock:
            self.strikes += 1
            self.healthy = False
            delay = _backoff(self.strikes, self.server.cfg.probation_base_s,
                             self.server.cfg.probation_factor,
                             self.server.cfg.probation_max_s,
                             self.server.cfg.probation_jitter,
                             site=f"replica{self.idx}",
                             seed=self.server.cfg.seed)
            self.probation_until = time.monotonic() + delay
            if respawn:
                # the old worker is wedged inside the executor; abandon
                # it together with its queue so a fresh thread serves
                # the replica when probation ends
                abandoned = self.queue
                self.generation += 1
                self.queue = _queue_mod.Queue()
                self._spawn(self.generation, self.queue)
        if abandoned is not None:
            abandoned.put(None)  # stop the old worker if it ever returns
            self.server._abandon_queue(abandoned)
        self.server._count("serving_replica_unhealthy_total", reason=reason)
        self.server._set_healthy_gauge()

    def pending(self) -> int:
        return self.queue.qsize()

    def maybe_readmit(self, now: float) -> bool:
        with self.lock:
            if not self.healthy and now >= self.probation_until:
                self.healthy = True
                self.server._set_healthy_gauge()
            return self.healthy


class InferenceServer:
    """The serving runtime: admission queue -> continuous batcher ->
    replica dispatch. ``model_fns`` is one callable per replica taking
    the padded input arrays and returning the output arrays (leading
    dim = batch); use :meth:`from_config` to build them from the
    ``Predictor`` path."""

    def __init__(self, model_fns, replicas: Optional[int] = None,
                 config: Optional[ServingConfig] = None):
        if callable(model_fns):
            model_fns = [model_fns] * (replicas or 1)
        model_fns = list(model_fns)
        if not model_fns:
            raise ValueError("need at least one replica")
        self.cfg = config or ServingConfig()
        self.replicas = [_Replica(i, fn, self) for i, fn in
                         enumerate(model_fns)]
        self._cv = threading.Condition()
        self._deque: collections.deque = collections.deque()
        self._inflight: set = set()
        self._inflight_rows = 0
        self._seen_shapes: set = set()
        # fleet/executor-cache hook: called with (sig, bucket) whenever a
        # first-seen shape pays a compile, so a persistent cache can
        # record it and pre-warm future replicas (see executor_cache.py)
        self.shape_observer: Optional[Callable[[str, int], None]] = None
        self._seq = 0
        self._rr = 0
        self._ewma_rows_per_s: Optional[float] = None
        self._ewma_batch_s: Optional[float] = None
        # EWMA cold-start (ISSUE 18): seed the service rate from the
        # calibration DB when one was fitted, so the very first admission
        # decisions price wait with a measured rate instead of modeling
        # zero wait until the first batch completes. _rate_source tracks
        # where the current rate came from ({ewma|calibrated|default})
        # and is surfaced by stats() as modeled_wait_source.
        self._rate_source = "default"
        try:
            from ..telemetry import calibration as _calibration
            seeded = _calibration.serving_rates()
        except Exception:  # pragma: no cover - admission must not crash
            seeded = None
        if seeded is not None:
            self._ewma_rows_per_s, self._ewma_batch_s = seeded
            self._rate_source = "calibrated"
        self._draining = False
        self._stopped = False
        self._started = False
        self._batcher: Optional[threading.Thread] = None
        self._prev_sigterm = None
        # server-owned accounting (mirrored to telemetry when enabled)
        self._clock = threading.Lock()
        self.counts: Dict[str, int] = collections.defaultdict(int)
        self.shed_causes: Dict[str, int] = collections.defaultdict(int)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "InferenceServer":
        if self._started:
            return self
        self._started = True
        for r in self.replicas:
            r.start()
        self._batcher = threading.Thread(
            target=self._batcher_loop, name="serving-batcher", daemon=True)
        self._batcher.start()
        self._set_healthy_gauge()
        return self

    def warm_start(self, shape_pairs) -> int:
        """Pre-seed the seen-shape set with ``(sig, bucket)`` pairs whose
        executables are already compiled (primed from the persistent
        executor cache), so serving them does NOT count as a recompile.
        Returns the number of newly seeded pairs."""
        added = 0
        for sig, bucket in shape_pairs:
            pair = (sig, int(bucket))
            if pair not in self._seen_shapes:
                self._seen_shapes.add(pair)
                added += 1
        return added

    def __enter__(self) -> "InferenceServer":
        return self.start()

    def __exit__(self, *exc):
        self.shutdown(drain=not any(exc))

    def shutdown(self, drain: bool = True, timeout: float = 30.0):
        """Stop the server. With ``drain`` accepted work finishes first
        while new admissions are shed with cause ``draining``."""
        first = not self._draining and not self._stopped
        self._draining = True
        if drain and first:
            # flight-recorder snapshot of the last seconds before drain
            # (no-op unless a dump directory is configured)
            from ..telemetry import flight as _flight
            _flight.dump("drain")
        if drain:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                with self._cv:
                    idle = not self._deque and not self._inflight
                if idle:
                    break
                time.sleep(0.005)
        self._stopped = True
        with self._cv:
            self._cv.notify_all()
        for r in self.replicas:
            r.queue.put(None)  # poison
        if self._batcher is not None:
            self._batcher.join(timeout=2.0)
        for r in self.replicas:
            if r.thread is not None:
                r.thread.join(timeout=0.5)
        # anything still queued can no longer run
        with self._cv:
            leftovers = list(self._deque)
            self._deque.clear()
        for req in leftovers:
            self._terminal(req, SHED, cause="draining")

    def install_sigterm_drain(self):
        """SIGTERM -> graceful drain (finish accepted work, reject new
        admissions), chaining any previous handler."""
        self._prev_sigterm = signal.getsignal(signal.SIGTERM)

        def _handler(signum, frame):
            self._draining = True
            threading.Thread(target=self.shutdown, name="serving-drain",
                             kwargs={"drain": True}, daemon=True).start()
            prev = self._prev_sigterm
            if callable(prev) and prev not in (signal.SIG_IGN,
                                               signal.SIG_DFL):
                prev(signum, frame)

        signal.signal(signal.SIGTERM, _handler)

    @property
    def draining(self) -> bool:
        return self._draining

    # -- admission -----------------------------------------------------------

    def submit(self, inputs: Sequence[np.ndarray],
               deadline_s: Optional[float] = None,
               tokens: Optional[int] = None) -> Request:
        """Admit a request (or shed it — the returned request is then
        already terminal with the shed cause recorded)."""
        if deadline_s is None:
            deadline_s = self.cfg.default_deadline_s
        req = Request(inputs, deadline_s=deadline_s, tokens=tokens)
        if _tracing.enabled():
            req._trace = _tracing.start_trace(
                "serving_request", req_id=req.id, rows=req.rows)
            req._span_wait = req._trace.span("admission_wait")
        self._count_only("submitted")
        if self._draining or self._stopped:
            self._terminal(req, SHED, cause="draining")
            return req
        with self._cv:
            wait = self._modeled_wait_locked(req.rows) \
                * self.cfg.admission_safety
            if len(self._deque) >= self.cfg.max_queue:
                cause = "queue_full"
            elif req.deadline is not None and \
                    wait + req.arrival > req.deadline:
                cause = "deadline_infeasible"
            else:
                req.t_predicted_wait = wait
                self._deque.append(req)
                self._gauge("serving_queue_depth", len(self._deque))
                self._cv.notify_all()
                return req
        self._terminal(req, SHED, cause=cause)
        return req

    def _modeled_wait_locked(self, rows: int) -> float:
        """Expected wait for a request of ``rows`` arriving now: queued +
        in-flight rows over the EWMA service rate, plus one batch
        latency. The EWMA is a PER-REPLICA rate (one batch over its own
        execute time), so the drain rate scales with the healthy replica
        count — admission tightens by itself while a replica sits in
        probation. Cold start (no completed batch yet) uses the
        calibration-DB seeded rate when one was fitted (see __init__ /
        ``modeled_wait_source``), else models zero wait — admission
        cannot reject what it cannot estimate."""
        if self._ewma_rows_per_s is None or self._ewma_rows_per_s <= 0:
            return 0.0
        healthy = max(1, sum(1 for r in self.replicas if r.healthy))
        ahead = sum(r.rows for r in self._deque) + self._inflight_rows
        return (ahead + rows) / (self._ewma_rows_per_s * healthy) \
            + (self._ewma_batch_s or 0.0)

    def modeled_wait(self, rows: int = 1) -> float:
        with self._cv:
            return self._modeled_wait_locked(rows)

    # -- batcher -------------------------------------------------------------

    def _bucket(self, rows: int) -> int:
        return min(shape_bucket(rows, floor=1), self.cfg.max_batch)

    def _batcher_loop(self):
        while True:
            batch = self._form_batch()
            if batch is None:
                return
            if batch:
                self._dispatch(batch)

    def _pop_expired_locked(self, now: float) -> List[Request]:
        expired = [r for r in self._deque if r.expired(now)]
        if expired:
            for r in expired:
                self._deque.remove(r)
        return expired

    def _form_batch(self) -> Optional[List[Request]]:
        """Pull a head-of-line-compatible group from the queue (waiting
        up to ``batch_wait_s`` to coalesce more rows), dropping expired
        requests. Returns None when the server stops."""
        expired: List[Request] = []
        try:
            with self._cv:
                while not self._deque and not self._stopped:
                    self._cv.wait(0.05)
                if self._stopped:
                    return None
                now = time.monotonic()
                expired.extend(self._pop_expired_locked(now))
                if not self._deque:
                    return []
                first = self._deque[0]
                sig = first.signature()
                batch = [first]
                rows = first.rows
                deadline = now + self.cfg.batch_wait_s
                while rows < self.cfg.max_batch:
                    # take every queued compatible request that fits
                    for r in list(self._deque):
                        if r is first or r in batch:
                            continue
                        if (r.signature() == sig
                                and self._fits(batch, rows, r)):
                            batch.append(r)
                            rows += r.rows
                    remaining = deadline - time.monotonic()
                    if rows >= self.cfg.max_batch or remaining <= 0 \
                            or self._stopped:
                        break
                    self._cv.wait(remaining)
                for r in batch:
                    self._deque.remove(r)
                self._gauge("serving_queue_depth", len(self._deque))
        finally:
            for r in expired:
                self._terminal(r, EXPIRED, cause="deadline_expired_in_queue")
        return batch

    def _dispatch(self, batch: List[Request]):
        now = time.monotonic()
        live = [r for r in batch if not r.expired(now) and not r.done()]
        for r in batch:
            if r not in live:
                self._terminal(r, EXPIRED,
                               cause="deadline_expired_in_queue")
        if not live:
            return
        rows = sum(r.rows for r in live)
        bucket = self._bucket(rows)
        arrays = self._pad_concat(live, bucket)
        sig = live[0].signature()
        if (sig, bucket) not in self._seen_shapes:
            self._seen_shapes.add((sig, bucket))
            self._count("serving_recompiles_total")
            obs = self.shape_observer
            if obs is not None:
                try:
                    obs(sig, bucket)
                except Exception:
                    pass  # cache bookkeeping must never fail a batch
        replica = self._pick_replica(live)
        if replica is None:
            return  # everyone expired while no replica was healthy
        with self._cv:
            self._seq += 1
            job = _BatchJob(live, arrays, bucket, rows, self._seq)
            self._inflight.add(job)
            self._inflight_rows += rows
        for r in live:
            if r.t_dispatch is None:
                r.t_dispatch = time.monotonic()
                self._observe("serving_queue_wait_seconds",
                              r.t_dispatch - r.arrival)
                if r.t_predicted_wait:
                    # admission's modeled wait vs the wait that actually
                    # happened (calibration records regardless of the
                    # telemetry gate — server-owned accounting)
                    from ..telemetry import calibration as _calibration
                    _calibration.record("serving_queue_wait",
                                        r.t_predicted_wait,
                                        r.t_dispatch - r.arrival)
            sp = r._span_wait
            if sp is not None and not sp._ended:
                sp.end("ok")
            if r._trace is not None and not r._trace.closed:
                # one span per dispatch attempt — failovers and decode
                # re-entry steps each get their own
                r._attempt_span = r._trace.span(
                    "execute", attempt=r.attempts, replica=replica.idx,
                    generation=replica.generation, batch_seq=job.seq,
                    bucket=bucket, rows=r.rows, phase=self._phase_of(r))
        self._count("serving_batches_total")
        self._gauge("serving_batch_occupancy", rows / float(bucket))
        replica.queue.put(job)

    def _pick_replica(self, live: List[Request]) -> Optional[_Replica]:
        """Round-robin over healthy replicas; unhealthy ones are probed
        out of probation. Blocks while none is available (requests keep
        their deadlines and are shed here if they all expire)."""
        while not self._stopped:
            now = time.monotonic()
            n = len(self.replicas)
            for k in range(n):
                r = self.replicas[(self._rr + k) % n]
                # the pending cap is backpressure: excess work waits in
                # the admission queue (where deadlines apply), not in a
                # replica queue where a slow replica would strand it
                if r.maybe_readmit(now) and r.pending() < 2:
                    self._rr = (self._rr + k + 1) % n
                    return r
            still = [r for r in live if not r.expired(now)]
            if not still:
                for r in live:
                    self._terminal(r, EXPIRED,
                                   cause="deadline_expired_in_queue")
                return None
            # while blocked here the batcher is not forming batches, so
            # reap deadline-expired queue entries in place — an expired
            # request must terminate promptly, not wait for capacity
            with self._cv:
                reap = self._pop_expired_locked(now)
            for r in reap:
                self._terminal(r, EXPIRED, cause="deadline_expired_in_queue")
            time.sleep(0.005)
        return None

    def _fits(self, batch: List[Request], rows: int, r: Request) -> bool:
        """May ``r`` join the forming batch? Base packs by summed rows;
        subclasses add their own capacity axes (token budget + row cap)."""
        return rows + r.rows <= self.cfg.max_batch

    def _phase_of(self, r: Request) -> str:
        """Trace-span phase label for a dispatch of ``r``."""
        return "infer"

    def _pad_concat(self, batch: List[Request], bucket: int) -> List[np.ndarray]:
        n_inputs = len(batch[0].inputs)
        arrays = []
        for i in range(n_inputs):
            cat = np.concatenate([r.inputs[i] for r in batch], axis=0)
            pad = bucket - cat.shape[0]
            if pad > 0:
                # repeat the last row: stays in-domain for token inputs
                cat = np.concatenate(
                    [cat, np.repeat(cat[-1:], pad, axis=0)], axis=0)
            arrays.append(cat)
        return arrays

    # -- replica execution ---------------------------------------------------

    def _worker_loop(self, replica: _Replica, gen: int,
                     q: _queue_mod.Queue):
        while not self._stopped:
            try:
                job = q.get(timeout=0.1)
            except _queue_mod.Empty:
                continue
            if job is None:
                return
            with replica.lock:
                stale = replica.generation != gen
            if stale:
                # abandoned generation: this worker raced the respawn's
                # queue drain and won the get() — requeue, never drop
                if job.try_cancel():
                    if job.timer is not None:
                        job.timer.cancel()
                    self._finish_inflight(job)
                    self._requeue(job.requests)
                continue
            self._execute_on(replica, job)

    def _execute_on(self, replica: _Replica, job: _BatchJob):
        # the per-call deadline measures EXECUTION, not queue time — it
        # is armed here, when the worker picks the job up, so a busy
        # (healthy) replica with queued work is never mistaken for a
        # wedged one
        job.timer = threading.Timer(self.cfg.call_timeout_s,
                                    self._on_call_timeout, (replica, job))
        job.timer.daemon = True
        job.timer.start()
        t0 = time.monotonic()
        try:
            spec = faults.fire_spec("replica_stall", step=job.seq,
                                    site="serving_execute")
            if spec is not None:
                # simulated wedged device call: block until the per-call
                # deadline cancels the job (or the server stops)
                while not (job.cancelled or self._stopped):
                    time.sleep(0.005)
                return
            faults.maybe_raise("serving_io", step=job.seq,
                               site="serving_execute")
            outs = replica.fn(job.arrays)
        except Exception as e:  # noqa: BLE001 - any executor error fails over
            self._on_execute_error(replica, job, e)
            return
        self._on_batch_done(replica, job, outs, time.monotonic() - t0)

    def _on_batch_done(self, replica: _Replica, job: _BatchJob,
                       outs, dt: float):
        if not job.try_finish():
            return  # per-call deadline already fired; requests requeued
        if job.timer is not None:
            job.timer.cancel()
        self._finish_inflight(job)
        outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
        outs = [np.asarray(o) for o in outs]
        self._observe("serving_execute_seconds", dt)
        # service-rate EWMA feeds the admission wait model
        a = self.cfg.rate_ewma
        rate = job.rows / max(dt, 1e-9)
        with self._cv:
            self._ewma_rows_per_s = rate if self._ewma_rows_per_s is None \
                else a * rate + (1 - a) * self._ewma_rows_per_s
            self._ewma_batch_s = dt if self._ewma_batch_s is None \
                else a * dt + (1 - a) * self._ewma_batch_s
            # a calibrated seed decays into the live EWMA from batch 1
            self._rate_source = "ewma"
        off = 0
        for r in job.requests:
            sl = [o[off:off + r.rows] for o in outs]
            off += r.rows
            sp = r._attempt_span
            if sp is not None and not sp._ended:
                sp.end("ok")
            if r._seal(COMPLETED, outputs=sl):
                self._count_outcome(COMPLETED)
                self._count("serving_tokens_total", n=r.tokens)
                self._observe("serving_e2e_seconds", r.t_done - r.arrival)

    def _on_execute_error(self, replica: _Replica, job: _BatchJob,
                          err: BaseException):
        if not job.try_cancel():
            return
        if job.timer is not None:
            job.timer.cancel()
        self._finish_inflight(job)
        self._count("serving_execute_errors_total",
                    error=type(err).__name__)
        self._count("serving_replica_failover_total")
        self._count_only("failovers")
        replica.mark_unhealthy("io_error")
        self._requeue(job.requests)

    def _on_call_timeout(self, replica: _Replica, job: _BatchJob):
        if not job.try_cancel():
            return
        self._finish_inflight(job)
        self._count("serving_replica_failover_total")
        self._count_only("failovers")
        # the wedged thread cannot be interrupted — bench the replica
        # and serve it with a fresh worker after probation
        replica.mark_unhealthy("stall", respawn=True)
        self._requeue(job.requests)

    def _abandon_queue(self, q: _queue_mod.Queue):
        """Requeue every job still sitting in a dead replica's queue —
        nothing in an abandoned queue may be silently lost."""
        while True:
            try:
                job = q.get_nowait()
            except _queue_mod.Empty:
                return
            if job is None:
                continue
            if job.try_cancel():
                if job.timer is not None:
                    job.timer.cancel()
                self._finish_inflight(job)
                self._requeue(job.requests)

    def _finish_inflight(self, job: _BatchJob):
        with self._cv:
            if job in self._inflight:
                self._inflight.discard(job)
                self._inflight_rows -= job.rows

    def _requeue(self, requests: List[Request]):
        """Return a failed batch's requests to the FRONT of the queue
        (they were already admitted — no re-admission checks), shedding
        the ones whose deadline has meanwhile passed."""
        now = time.monotonic()
        back: List[Request] = []
        for r in requests:
            sp = r._attempt_span
            if sp is not None and not sp._ended:
                sp.end("failover")
            if r.done():
                continue
            if r.expired(now):
                self._terminal(r, EXPIRED,
                               cause="deadline_expired_in_queue")
                continue
            r.attempts += 1
            if r.attempts >= self.cfg.max_attempts:
                # a deadline-less request must still terminate: cap the
                # failover bounces so a poisoned batch cannot circulate
                if r._seal(FAILED, error=ServingError(
                        f"request {r.id} failed after {r.attempts} "
                        f"dispatch attempts")):
                    self._count_outcome(FAILED)
                continue
            back.append(r)
        if not back:
            return
        self._count("serving_requeued_requests_total", n=len(back))
        self._count_only("requeues", n=len(back))
        with self._cv:
            for r in reversed(back):
                self._deque.appendleft(r)
            self._gauge("serving_queue_depth", len(self._deque))
            self._cv.notify_all()

    # -- accounting / telemetry ---------------------------------------------

    def _terminal(self, req: Request, state: str, cause: str):
        if not req._seal(state, cause=cause):
            return
        self._count_outcome(state)
        self._count("serving_requests_shed_total", cause=cause)
        with self._clock:
            self.shed_causes[cause] += 1
        # burn-rate watch: a shed spike is exactly when the rolling-window
        # SLO monitor should look (no-op unless one is installed)
        from ..telemetry import slo as _slo
        _slo.maybe_poll()

    def _count_outcome(self, outcome: str):
        with self._clock:
            self.counts[outcome] += 1
        from .. import telemetry
        if telemetry.enabled():
            telemetry.counter(
                "serving_requests_total",
                "serving requests by terminal outcome").inc(outcome=outcome)

    def _count_only(self, key: str, n: int = 1):
        with self._clock:
            self.counts[key] += n

    def _count(self, name: str, n: float = 1, **labels):
        if name in ("serving_recompiles_total", "serving_batches_total",
                    "serving_tokens_total"):
            with self._clock:
                self.counts[name.replace("serving_", "")
                            .replace("_total", "")] += int(n)
        from .. import telemetry
        if telemetry.enabled():
            telemetry.counter(name, "").inc(n, **labels)

    def _gauge(self, name: str, v: float):
        from .. import telemetry
        if telemetry.enabled():
            telemetry.gauge(name, "").set(v)

    def _observe(self, name: str, v: float):
        from .. import telemetry
        if telemetry.enabled():
            telemetry.histogram(name, "").observe(v)

    def _set_healthy_gauge(self):
        self._gauge("serving_replicas_healthy",
                    sum(1 for r in self.replicas if r.healthy))

    def stats(self) -> Dict[str, object]:
        """Snapshot of the server-owned accounting (independent of the
        telemetry registry so tests and the bench need no scope)."""
        with self._clock:
            counts = dict(self.counts)
            causes = dict(self.shed_causes)
        with self._cv:
            depth = len(self._deque)
            inflight = len(self._inflight)
        return {
            "submitted": counts.get("submitted", 0),
            "completed": counts.get(COMPLETED, 0),
            "shed": counts.get(SHED, 0),
            "expired": counts.get(EXPIRED, 0),
            "failed": counts.get(FAILED, 0),
            "shed_causes": causes,
            "failovers": counts.get("failovers", 0),
            "requeues": counts.get("requeues", 0),
            "batches": counts.get("batches", 0),
            "recompiles": counts.get("recompiles", 0),
            "tokens": counts.get("tokens", 0),
            "queue_depth": depth,
            "inflight_batches": inflight,
            "replicas_healthy": sum(1 for r in self.replicas if r.healthy),
            # where the admission wait model's service rate came from:
            # "ewma" once a batch completed, "calibrated" while running
            # on the calibration-DB seed, "default" cold (models 0 wait)
            "modeled_wait_source": self._rate_source,
        }

    def accounted(self) -> bool:
        """The zero-silent-loss invariant: every submitted request is in
        a terminal bucket."""
        s = self.stats()
        return s["submitted"] == (s["completed"] + s["shed"]
                                  + s["expired"] + s["failed"])

    # -- constructors --------------------------------------------------------

    @classmethod
    def from_config(cls, config, replicas: int = 1,
                    serving: Optional[ServingConfig] = None
                    ) -> "InferenceServer":
        """Build a server over ``replicas`` Predictors for an inference
        ``Config`` (the pool shares one loaded layer via the per-prefix
        load cache)."""
        from . import PredictorPool
        pool = PredictorPool(config, replicas)
        fns = [predictor_executor(pool.retrieve(i))
               for i in range(replicas)]
        return cls(fns, config=serving)


def predictor_executor(pred) -> Callable:
    """Adapt a ``Predictor`` to the server's executor signature."""

    def fn(arrays: List[np.ndarray]) -> List[np.ndarray]:
        return pred.run(list(arrays))

    return fn


# ===========================================================================
# decode-native serving (ISSUE 11): autoregressive generation over the
# paged KV cache, scheduled through the same batcher/admission machinery
# ===========================================================================

class GenerationRequest(Request):
    """One autoregressive generation: prompt in, ``max_new`` greedy
    tokens out (``result()`` -> ``[np.int32 generated tokens]``).

    The SAME object rides the queue for every step of its life — prefill
    chunks, then one-token decode steps — re-entering at the FRONT after
    each completed step so in-flight sequences outrank new admissions.
    ``rows`` is reinterpreted as the tokens the request wants to compute
    in its NEXT step (prefill chunk size, or 1 for decode), which makes
    the base scheduler's row arithmetic — packing, buckets, in-flight
    accounting, the EWMA service rate, the modeled-wait admission model —
    token-denominated without touching it."""

    def __init__(self, prompt_tokens, max_new_tokens: int,
                 deadline_s: Optional[float] = None,
                 eos_token: Optional[int] = None):
        prompt = [int(t) for t in np.asarray(prompt_tokens).reshape(-1)]
        if not prompt:
            raise ValueError("generation needs a non-empty prompt")
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        super().__init__([np.asarray(prompt, np.int32).reshape(1, -1)],
                         deadline_s=deadline_s,
                         tokens=len(prompt) + int(max_new_tokens))
        self.prompt = prompt
        self.max_new = int(max_new_tokens)
        self.eos_token = None if eos_token is None else int(eos_token)
        self.generated: List[int] = []
        self.seq = None                 # kv_cache.CacheSeq (set at admission)
        self.chunk: List[int] = []      # tokens of the NEXT step

    def signature(self):
        # every generation is batch-compatible with every other: the
        # decode executor consumes the flattened varlen layout
        return ("__generate__",)


class DecodeServer(InferenceServer):
    """Decode-native serving: mixed prefill/decode continuous batching
    over a :class:`~paddle_tpu.inference.kv_cache.PagedKVCache`.

    ``step_fns`` are per-replica executors with the decode contract —
    ``fn([tokens, row_id, positions, valid, tables, ctx_lens, last_idx])
    -> [next_tokens (T,), k_new (L, T, H, D), v_new (L, T, H, D)]``
    where ``next_tokens[t]`` is the greedy next token AT flattened slot
    ``t`` — a plain step consumes its chunk's last slot, a
    speculative-verify chunk consumes every slot at once (see
    ``inference.decode_model.make_step_fn``); ``T`` is the token-budget
    bucket, ``R = min(T, max_batch_rows)`` the row bucket, so the
    compiled-shape set stays closed. The executor only COMPUTES; the
    cache is written here, after ``try_finish`` — a cancelled or wedged
    call can never corrupt cache state, and a requeued step re-runs
    idempotently (greedy decode is deterministic).

    Admission folds cache pressure into the modeled wait: pages the
    prompt + generation will need beyond the free + evictable supply add
    ``pages * page_size / rate`` of wait, so tight caches surface as
    ``deadline_infeasible`` shedding, not mid-decode OOM. Prefix pages
    matched at admission are pinned (ref-counted) until the request
    reaches a terminal state — ``Request.on_terminal`` releases them on
    EVERY seal path, including drain and failover exhaustion.

    ``cfg.max_batch`` is the per-dispatch TOKEN budget (decode steps
    cost 1, prefill chunks up to ``prefill_chunk``)."""

    def __init__(self, step_fns, cache, replicas: Optional[int] = None,
                 config: Optional[ServingConfig] = None,
                 prefill_chunk: int = 32,
                 max_pages_per_seq: Optional[int] = None,
                 max_batch_rows: Optional[int] = None):
        super().__init__(step_fns, replicas=replicas, config=config)
        self.cache = cache
        self.prefill_chunk = max(1, min(int(prefill_chunk),
                                        self.cfg.max_batch))
        self.max_batch_rows = max(1, int(max_batch_rows
                                         or self.cfg.max_batch))
        self.max_pages_per_seq = int(max_pages_per_seq or cache.num_pages)

    # -- admission -----------------------------------------------------------

    def submit(self, *a, **kw):
        raise TypeError("DecodeServer serves generations: use "
                        "submit_generate(prompt_tokens, max_new_tokens)")

    def submit_generate(self, prompt_tokens, max_new_tokens: int,
                        deadline_s: Optional[float] = None,
                        eos_token: Optional[int] = None
                        ) -> GenerationRequest:
        """Admit a generation (or shed it: the returned request is then
        already terminal with the cause recorded). ``eos_token`` seals
        the request early when greedy decode emits it."""
        if deadline_s is None:
            deadline_s = self.cfg.default_deadline_s
        req = GenerationRequest(prompt_tokens, max_new_tokens,
                                deadline_s=deadline_s,
                                eos_token=eos_token)
        if _tracing.enabled():
            req._trace = _tracing.start_trace(
                "serving_request", req_id=req.id, kind="generate",
                prompt_tokens=len(req.prompt), max_new=req.max_new)
            req._span_wait = req._trace.span("admission_wait")
        self._count_only("submitted")
        if self._draining or self._stopped:
            self._terminal(req, SHED, cause="draining")
            return req
        total = len(req.prompt) + req.max_new
        if self.cache.pages_needed(total) > self.max_pages_per_seq:
            raise ValueError(
                f"generation spans {self.cache.pages_needed(total)} pages "
                f"> max_pages_per_seq={self.max_pages_per_seq}")
        # ambient span: the cache reports prefix hits / evictions into
        # the admission_wait span without signature changes
        with _tracing.use_span(req._span_wait), self._cv:
            if len(self._deque) >= self.cfg.max_queue:
                cause = "queue_full"
            else:
                # prefix pages the prompt already shares don't need
                # allocating; everything else must fit the pool
                matched, _ = self.cache.match_prefix(req.prompt[:-1])
                needed = self.cache.pages_needed(total) \
                    - matched // self.cache.page_size
                wait = self._decode_wait_locked(req, needed)
                if needed > self.cache.num_pages:
                    cause = "deadline_infeasible"  # can never fit
                elif req.deadline is not None and \
                        wait * self.cfg.admission_safety + req.arrival \
                        > req.deadline:
                    cause = "deadline_infeasible"
                else:
                    req.t_predicted_wait = wait * self.cfg.admission_safety
                    req.seq = self.cache.create(req.prompt[:-1])
                    req.on_terminal = self._release_request
                    self._assign_chunk(req)
                    self._deque.append(req)
                    self._gauge("serving_queue_depth", len(self._deque))
                    self._cv.notify_all()
                    return req
        self._terminal(req, SHED, cause=cause)
        return req

    def _decode_wait_locked(self, req: GenerationRequest,
                            needed_pages: int) -> float:
        """Base modeled wait (token-denominated) plus the cache-pressure
        term: pages short of the free + evictable supply each cost a
        page worth of tokens at the EWMA service rate — eviction keeps
        up with decode, so shortfall is time, not failure."""
        first_chunk = min(self.prefill_chunk,
                          max(1, len(req.prompt) - 1))
        wait = self._modeled_wait_locked(first_chunk)
        if self._ewma_rows_per_s and needed_pages > 0:
            short = needed_pages - (self.cache.free_pages()
                                    + self.cache.evictable_pages())
            if short > 0:
                healthy = max(1, sum(1 for r in self.replicas if r.healthy))
                wait += (short * self.cache.page_size
                         / (self._ewma_rows_per_s * healthy))
        return wait

    def _release_request(self, req: Request):
        # a speculative draft fork may still be pinned if the request
        # seals mid-verify (drain, failover exhaustion, deadline)
        fork = getattr(req, "draft_fork", None)
        if fork is not None:
            self.cache.release(fork)
            req.draft_fork = None
        if getattr(req, "seq", None) is not None:
            self.cache.release(req.seq)

    def _assign_chunk(self, req: GenerationRequest):
        """Point the request at its next step's tokens. Prefill walks
        the prompt from the cache frontier (``seq.length`` — prefix hits
        land past them for free); decode feeds back the last generated
        token. ``rows`` tracks the chunk's token cost for the packer."""
        done = req.seq.length
        if done < len(req.prompt):
            req.chunk = req.prompt[done:done + self.prefill_chunk]
        else:
            req.chunk = [req.generated[-1]]
        req.rows = len(req.chunk)

    # -- batching ------------------------------------------------------------

    def _fits(self, batch: List[Request], rows: int, r: Request) -> bool:
        # token budget AND a row cap (the executor's R dimension)
        return (len(batch) < self.max_batch_rows
                and rows + r.rows <= self.cfg.max_batch)

    def _phase_of(self, r: Request) -> str:
        return ("decode" if r.seq is not None
                and r.seq.length >= len(r.prompt) else "prefill")

    def _pad_concat(self, batch: List[Request],
                    bucket: int) -> List[np.ndarray]:
        """Flattened varlen layout: every request's chunk tokens
        concatenated on one axis of width ``bucket`` (the token bucket),
        plus per-row block tables / context lengths. The row dimension is
        ``min(bucket, max_batch_rows)`` — deterministic in the token
        bucket, so it adds no recompile axis."""
        t_b = bucket
        r_b = min(bucket, self.max_batch_rows)
        tokens = np.zeros(t_b, np.int32)
        row_id = np.zeros(t_b, np.int32)
        positions = np.zeros(t_b, np.int32)
        valid = np.zeros(t_b, np.int32)
        tables = np.zeros((r_b, self.max_pages_per_seq), np.int32)
        ctx_lens = np.zeros(r_b, np.int32)
        last_idx = np.zeros(r_b, np.int32)
        off = 0
        for i, r in enumerate(batch):
            n = len(r.chunk)
            tokens[off:off + n] = r.chunk
            row_id[off:off + n] = i
            positions[off:off + n] = np.arange(
                r.seq.length, r.seq.length + n, dtype=np.int32)
            valid[off:off + n] = 1
            tables[i] = self.cache.block_table(r.seq,
                                               self.max_pages_per_seq)
            ctx_lens[i] = r.seq.length
            last_idx[i] = off + n - 1
            off += n
        return [tokens, row_id, positions, valid, tables, ctx_lens,
                last_idx]

    # -- completion ----------------------------------------------------------

    def _on_batch_done(self, replica: _Replica, job: _BatchJob,
                       outs, dt: float):
        if not job.try_finish():
            return  # per-call deadline fired; the step re-runs elsewhere
        if job.timer is not None:
            job.timer.cancel()
        self._finish_inflight(job)
        outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
        next_tokens, k_new, v_new = [np.asarray(o) for o in outs]
        self._observe("serving_execute_seconds", dt)
        a = self.cfg.rate_ewma
        rate = job.rows / max(dt, 1e-9)
        with self._cv:
            self._ewma_rows_per_s = rate if self._ewma_rows_per_s is None \
                else a * rate + (1 - a) * self._ewma_rows_per_s
            self._ewma_batch_s = dt if self._ewma_batch_s is None \
                else a * dt + (1 - a) * self._ewma_batch_s
            # a calibrated seed decays into the live EWMA from batch 1
            self._rate_source = "ewma"
        # cache writes + sequence advance happen HERE (post-try_finish):
        # a cancelled job never touched the cache, so its requests re-run
        # the identical step on a survivor
        back: List[Request] = []
        off = 0
        for i, r in enumerate(job.requests):
            n = len(r.chunk)
            sp = r._attempt_span
            try:
                # ambient span: cache append/evict events land on this
                # step's execute span
                with _tracing.use_span(sp):
                    self._advance(r, next_tokens[off:off + n],
                                  k_new[:, off:off + n],
                                  v_new[:, off:off + n], back)
            except Exception as e:  # noqa: BLE001 - CacheOOM et al.
                if r._seal(FAILED, error=e if isinstance(e, ServingError)
                           else ServingError(
                               f"request {r.id} step failed: {e!r}")):
                    self._count_outcome(FAILED)
            if sp is not None and not sp._ended:
                sp.end("ok", tokens=n)
            off += n
        if back:
            with self._cv:
                for r in reversed(back):
                    self._deque.appendleft(r)
                self._gauge("serving_queue_depth", len(self._deque))
                self._cv.notify_all()

    def _commit_chunk(self, r: GenerationRequest, nxt: np.ndarray,
                      k_chunk: np.ndarray, v_chunk: np.ndarray):
        """Write the chunk's K/V and consume its sampled token(s).
        ``nxt`` is the per-slot next-token slice for this chunk; the
        plain path samples from the last slot only. Speculative serving
        overrides this to accept a draft run from the full slice."""
        self.cache.append(r.seq, r.chunk, k_chunk, v_chunk)
        if r.seq.length >= len(r.prompt):
            # the step's last token was prompt-final or a decode token:
            # its logits sample the next generated token
            r.generated.append(int(nxt[-1]))
            self._count_only("decode_tokens")
            self._count("decode_tokens_total")

    def _advance(self, r: GenerationRequest, nxt: np.ndarray,
                 k_chunk: np.ndarray, v_chunk: np.ndarray,
                 back: List[Request]):
        """Commit one completed step (``_commit_chunk``), then
        complete / expire / re-enqueue."""
        if r.done():
            return  # sealed while in flight (e.g. drain-expire race)
        self._commit_chunk(r, nxt, k_chunk, v_chunk)
        eos_hit = (r.eos_token is not None and r.generated
                   and r.generated[-1] == r.eos_token)
        if len(r.generated) >= r.max_new or eos_hit:
            if r._seal(COMPLETED,
                       outputs=[np.asarray(r.generated, np.int32)]):
                self._count_outcome(COMPLETED)
                self._count("serving_tokens_total", n=r.tokens)
                self._observe("serving_e2e_seconds",
                              r.t_done - r.arrival)
            return
        if r.expired():
            self._terminal(r, EXPIRED, cause="deadline_expired_in_queue")
            return
        self._assign_chunk(r)
        back.append(r)

    def stats(self) -> Dict[str, object]:
        s = super().stats()
        with self._clock:
            s["decode_tokens"] = self.counts.get("decode_tokens", 0)
        s["kv_cache"] = self.cache.stats()
        return s
