"""Pipeline layer description (reference:
fleet/meta_parallel/parallel_layers/pp_layers.py — LayerDesc, SharedLayerDesc:62,
SegmentLayers:23 uniform/param-count partition, PipelineLayer:76).

TPU-native: PipelineLayer partitions a LayerDesc list into pp_degree stages.
The reference materializes only the local stage's layers per rank
(pp_layers.py:76); the SPMD equivalent here is the *stacked-stage* trick:
contiguous runs of structurally identical layers (the transformer body)
whose members distribute evenly over the stages are stored as ONE set of
parameters with a leading member dim, sharded over the "pipe" mesh axis
(P("pipe", ...)). Each device physically holds only its own stage's slice —
per-device parameter and optimizer-slot memory for those layers is 1/pp,
matching the reference's per-rank materialization. Layers that cannot stack
(embedding on the first stage, norm+head on the last) stay replicated over
the pipe axis; the engine reduces their gradients with a psum over "pipe"
so the replication is genuine.

SharedLayerDesc (tied embeddings) keeps ONE owner copy of the shared
parameters — replicated over the pipe axis — and the non-owner occurrence
applies ``forward_func`` against the owner's weight. The pipe-axis grad
psum accumulates both stages' contributions, which is the TPU form of the
reference's allreduce over the shared-comm group (pp_layers.py:62).
"""
from __future__ import annotations

from typing import Callable, List, Optional

import jax.numpy as jnp
import numpy as np

from jax.sharding import PartitionSpec as P

from ....nn.layer import Layer, Parameter


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_cls, Layer):
            raise TypeError(f"{layer_cls} must be a Layer subclass")

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.inputs, **self.kwargs)

    def signature(self):
        """Structural identity: two descs with equal signatures build
        structurally identical layers (stackable)."""
        return (self.layer_cls, self.inputs, tuple(sorted(self.kwargs.items())))

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """Tied layers across stages (reference: pp_layers.py:62 — e.g. embedding
    weights shared with the LM head). The first occurrence builds and owns
    the parameters; later occurrences apply ``forward_func(x, owner_weight)``
    (or the owner layer itself). Owner params stay replicated over the pipe
    axis and the engine's pipe-axis grad psum sums the contributions from
    every stage that uses them."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr

    def signature(self):
        return ("shared", self.layer_name)


class SegmentLayers:
    """Partition N layer descs into `num_parts` stages (reference:
    pp_layers.py:23): uniform or layer-type-count weighted."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.layers_desc = layers_desc
        self.num_parts = num_parts
        self.method = method
        assert len(layers_desc) >= num_parts, \
            f"{len(layers_desc)} layers < {num_parts} stages"

    def do_segment(self) -> List[int]:
        if self.method == "uniform":
            return self.uniform(len(self.layers_desc), self.num_parts)
        if self.method.startswith("layer:"):
            # segment so each stage has equal count of the named layer type
            name = self.method.split(":", 1)[1]
            marks = [i for i, d in enumerate(self.layers_desc)
                     if d.layer_cls.__name__ == name]
            per = len(marks) // self.num_parts
            assert per > 0
            bounds = [0]
            for p in range(1, self.num_parts):
                bounds.append(marks[p * per])
            bounds.append(len(self.layers_desc))
            return bounds
        raise ValueError(f"unknown segment method {self.method}")

    @staticmethod
    def uniform(num_items, num_parts) -> List[int]:
        return [int(np.round(i * num_items / num_parts))
                for i in range(num_parts + 1)]


def _escape(name: str) -> str:
    return name.replace(".", "__")


class _StackedStage(Layer):
    """N structurally identical member layers stored as stacked parameters.

    Parameter ``p`` of the member template becomes one stacked array of
    shape ``(N, *p.shape)`` with pspec ``P("pipe", *p.pspec)`` — the leading
    member dim is sharded over the pipe mesh axis, so each device stores
    only its own stage's contiguous chunk of members. Member TP specs
    (e.g. P(None, "model")) are preserved in the trailing dims.
    """

    def __init__(self, members: List[Layer]):
        super().__init__()
        self.size = len(members)
        # the template is intentionally NOT registered as a sublayer: its
        # per-member parameters are replaced by the stacks below, and it is
        # only used as the functional skeleton for apply
        object.__setattr__(self, "_template", members[0])
        self.param_names = [n for n, _ in members[0].named_parameters()]
        self.buffer_names = [n for n, _ in members[0].named_buffers()]
        for name, p0 in members[0].named_parameters():
            vals = [dict(m.named_parameters())[name].value for m in members]
            sp = Parameter(jnp.stack(vals), trainable=p0.trainable)
            member_spec = tuple(p0.pspec) if p0.pspec is not None else \
                (None,) * (vals[0].ndim)
            sp.pspec = P("pipe", *member_spec)
            self.add_parameter(_escape(name), sp)
        # stacked buffers shard over pipe like the params (the engine reads
        # buffer_pspecs; without it the P() default would hand the scan a
        # full-length buffer stack against k-length param slices)
        self.buffer_pspecs = {}
        for name in self.buffer_names:
            vals = [dict(m.named_buffers())[name] for m in members]
            self.register_buffer(_escape(name), jnp.stack(vals))
            self.buffer_pspecs[_escape(name)] = P(
                "pipe", *((None,) * vals[0].ndim))

    # -- functional application -------------------------------------------
    def member_state(self, j, params=None, buffers=None):
        """(params, buffers) of member j, un-escaped for the template.
        `params`/`buffers` default to this module's own stacked values but
        may be the (possibly traced, possibly local-sliced) stacks extracted
        from an engine state dict."""
        if params is None:
            params = {n: self._parameters[n].value
                      for n in self._parameters}
        if buffers is None:
            buffers = dict(self._buffers)
        pj = {n: params[_escape(n)][j] for n in self.param_names}
        bj = {n: buffers[_escape(n)][j] for n in self.buffer_names}
        return pj, bj

    def apply_member(self, j, x, params=None, buffers=None, rng=None):
        from ....jit.functionalization import functional_call
        pj, bj = self.member_state(j, params, buffers)
        out, _ = functional_call(self._template, pj, bj, x, rng=rng)
        return out

    def forward(self, x):
        """Apply all members sequentially (single-device dense semantics)."""
        for j in range(self.size):
            x = self.apply_member(j, x)
        return x


class PipelineLayer(Layer):
    """Holds the full desc list + the stage plan.

    Storage (see module docstring): stackable runs -> ``stack{g}``
    (_StackedStage, pipe-sharded); everything else -> ``mod{i}`` replicated
    over pipe; SharedLayerDesc non-owner occurrences hold no params.
    ``self.plan[i]`` describes desc i:
      ("layer", i)            — apply mod{i}
      ("stacked", gid, m)     — apply member m (global index) of stack{gid}
      ("shared", owner_i, fw, attr) — apply fw(x, owner weight) / owner
    """

    def __init__(self, layers: List[LayerDesc], num_stages: int,
                 loss_fn: Optional[Callable] = None, seg_method="uniform",
                 topology=None, **kwargs):
        super().__init__()
        self.descs = layers
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.segment = SegmentLayers(layers, num_stages, seg_method).do_segment()
        owners = {}
        built: List[Optional[Layer]] = []
        plan = []
        for i, d in enumerate(layers):
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in owners:
                    built.append(None)
                    plan.append(("shared", owners[d.layer_name],
                                 d.forward_func, d.shared_weight_attr))
                    continue
                owners[d.layer_name] = i
            built.append(d.build_layer())
            plan.append(("layer", i))
        self.shared_keys = set(owners)
        # stackable groups: contiguous identical plain descs whose members
        # distribute evenly (k per stage) over ALL stages
        self.groups = []           # [(a, b, k)]
        for a, b in self._identical_runs(layers, plan):
            counts = [max(0, min(b, self.segment[s + 1]) -
                          max(a, self.segment[s]))
                      for s in range(num_stages)]
            if min(counts) >= 1 and len(set(counts)) == 1 \
                    and sum(counts) == b - a:
                gid = len(self.groups)
                self.groups.append((a, b, counts[0]))
                stack = _StackedStage(built[a:b])
                self.add_sublayer(f"stack{gid}", stack)
                for i in range(a, b):
                    plan[i] = ("stacked", gid, i - a)
        for i, ent in enumerate(plan):
            if ent[0] == "layer":
                self.add_sublayer(f"mod{i}", built[i])
        self.plan = plan

    @staticmethod
    def _identical_runs(layers, plan):
        """Maximal contiguous runs (a, b) of >1 identical plain LayerDescs."""
        runs, a = [], 0
        n = len(layers)
        while a < n:
            b = a + 1
            if plan[a][0] == "layer" and \
                    not isinstance(layers[a], SharedLayerDesc):
                sig = layers[a].signature()
                while b < n and plan[b][0] == "layer" and \
                        not isinstance(layers[b], SharedLayerDesc) and \
                        layers[b].signature() == sig:
                    b += 1
            if b - a > 1:
                runs.append((a, b))
            a = b
        return runs

    def named_buffer_pspecs(self):
        """Full-name -> PartitionSpec for buffers that must not default to
        replicated (the pipe-stacked stage buffers)."""
        out = {}
        for gid in range(len(self.groups)):
            stack = getattr(self, f"stack{gid}")
            for esc, spec in stack.buffer_pspecs.items():
                out[f"stack{gid}.{esc}"] = spec
        return out

    # -- stage structure ----------------------------------------------------
    def stage_items(self, stage_id: int):
        lo, hi = self.segment[stage_id], self.segment[stage_id + 1]
        return [(i, self.plan[i]) for i in range(lo, hi)]

    def uniform_split(self):
        """Decompose the plan as (pre_items, stack_gid, post_items) when
        the pipeline has the canonical transformer shape: stage-0-only
        prologue (embedding...), ONE stacked group spanning every stage
        evenly, last-stage-only epilogue (norm/head...).

        This is the shape the collective-safe uniform schedules need:
        every device executes the SAME pre/stack/post program each tick
        (heterogeneous parts masked by stage id), so collectives inside
        layers (ring attention over "sep", TP psums) are issued uniformly
        — collectives under a per-device lax.switch branch are undefined
        behavior in SPMD (half the devices join one op instance, half
        another: deadlock or silent data corruption). Returns None when
        the plan does not decompose (the switch-based fallback schedules
        then apply, which are only safe for collective-free stages).
        """
        if len(self.groups) != 1:
            return None
        a, b, _ = self.groups[0]
        if a > self.segment[1] or b < self.segment[self.num_stages - 1]:
            return None  # prologue/epilogue spill into middle stages
        pre = [(i, self.plan[i]) for i in range(a)]
        post = [(i, self.plan[i]) for i in range(b, len(self.plan))]
        return pre, 0, post

    def owner_weight_key(self, owner_i: int, attr: str) -> str:
        """Flat param-dict key of a shared owner's weight."""
        return f"mod{owner_i}.{attr}"

    def _apply_item(self, i, ent, x):
        """Eager/dense application of one plan item (own parameter values)."""
        kind = ent[0]
        if kind == "layer":
            return getattr(self, f"mod{i}")(x)
        if kind == "stacked":
            _, gid, m = ent
            return getattr(self, f"stack{gid}").apply_member(m, x)
        _, owner_i, fw, attr = ent
        owner = getattr(self, f"mod{owner_i}")
        if fw is not None:
            w = owner
            for part in attr.split("."):
                w = getattr(w, part)
            return fw(x, getattr(w, "value", w))
        return owner(x)

    def forward(self, x):
        """Non-pipelined reference forward (single-device semantics)."""
        for i, ent in enumerate(self.plan):
            x = self._apply_item(i, ent, x)
        return x
