"""Sharded embedding lookup with alltoall id-exchange.

Capability target: the reference's HeterPS inter-device embedding comm
(`framework/fleet/heter_ps/heter_comm.h:50` — push/pull of sparse rows
between GPU-resident table shards) and `c_embedding`'s row-sharded
lookup. TPU-native shape: the table lives row-sharded over a mesh axis
(each device owns ``rows/n`` consecutive rows in HBM); a lookup of
arbitrary global row ids exchanges the IDS to their owning shard with
``lax.all_to_all``, gathers locally, and alltoalls the rows back —
moving O(ids * dim) over ICI instead of the O(ids * dim * n_shards)
a masked-gather + psum (VocabParallelEmbedding-style) pays.

Everything is static-shaped for XLA: ids are bucketed per destination
shard into fixed-capacity buckets (``bucket_cap``). Ids that overflow a
bucket (pathological skew) are resolved by a masked-gather + psum
fallback — correctness never depends on the cap, only performance.

Must be called inside ``shard_map`` with the table's mesh axis mapped;
the custom_vjp routes row-gradients back to the owning shard through
the transposed alltoall (scatter-add on the owner).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["alltoall_lookup"]


def _exchange(local_rows, ids, axis, bucket_cap, rows_per_shard):
    """Forward exchange. Returns (out (N, dim), residuals for bwd)."""
    n = lax.psum(1, axis)
    n_ids = ids.shape[0]
    dim = local_rows.shape[-1]
    cap = int(bucket_cap)

    valid = ids >= 0
    owner = jnp.clip(jnp.where(valid, ids, 0) // rows_per_shard, 0, n - 1)
    owner = jnp.where(valid, owner, n)  # invalid ids -> no bucket

    # stable sort by owner; position of each id within its owner group
    order = jnp.argsort(owner, stable=True)
    owner_s = owner[order]
    ids_s = ids[order]
    group_start = jnp.searchsorted(owner_s, jnp.arange(n + 1))
    pos_in_group = jnp.arange(n_ids) - group_start[jnp.clip(owner_s, 0, n)]
    in_bucket = (pos_in_group < cap) & (owner_s < n)

    # send buffers: per-destination buckets of ids (+ original positions
    # kept locally so returned rows scatter back without a round trip)
    # sentinel lanes are routed to OOB row n and DROPPED — writing them
    # to any in-bounds slot could clobber a real bucketed id
    dst_r = jnp.where(in_bucket, owner_s, n)
    dst_c = jnp.where(in_bucket, pos_in_group, 0)
    send_ids = jnp.full((n, cap), -1, ids.dtype)
    send_ids = send_ids.at[dst_r, dst_c].set(ids_s, mode="drop")
    home_pos = jnp.full((n, cap), n_ids, jnp.int32)
    home_pos = home_pos.at[dst_r, dst_c].set(order.astype(jnp.int32),
                                             mode="drop")

    # ship id buckets to their owners; row j of recv = the bucket device
    # j sent to THIS shard
    recv_ids = lax.all_to_all(send_ids, axis, 0, 0)
    my_lo = lax.axis_index(axis) * rows_per_shard
    local_idx = jnp.clip(recv_ids - my_lo, 0, local_rows.shape[0] - 1)
    hit = recv_ids >= 0
    rows = jnp.where(hit[..., None],
                     local_rows[local_idx], 0.0)          # (n, cap, dim)
    # rows ride back along the same lanes
    back = lax.all_to_all(rows, axis, 0, 0)               # (n, cap, dim)

    out = jnp.zeros((n_ids + 1, dim), local_rows.dtype)
    out = out.at[home_pos.reshape(-1)].set(
        back.reshape(-1, dim), mode="drop")[:n_ids]

    # overflow fallback (pathological bucket skew): all_gather every
    # shard's overflow ids, owners contribute rows, psum_scatter returns
    # each shard exactly its own slice — exact for per-shard ids, costs
    # one (n, N, dim) exchange only in traffic, not in correctness
    ovf = jnp.zeros((n_ids,), jnp.bool_).at[
        jnp.where(in_bucket, n_ids, order)].set(True, mode="drop")
    ovf = ovf & (ids >= 0)
    ovf_ids = jnp.where(ovf, ids, -1)
    all_ovf = lax.all_gather(ovf_ids, axis)               # (n, N)
    o_mine = (all_ovf >= my_lo) & (all_ovf < my_lo + rows_per_shard)
    o_idx = jnp.clip(jnp.where(o_mine, all_ovf, 0) - my_lo, 0,
                     local_rows.shape[0] - 1)
    contrib = jnp.where(o_mine[..., None],
                        local_rows[o_idx], 0.0)           # (n, N, dim)
    o_rows = lax.psum_scatter(contrib, axis,
                              scatter_dimension=0)        # (N, dim)
    out = jnp.where(ovf[:, None], o_rows, out)
    return out, (home_pos, ovf, o_mine, o_idx, local_idx, hit)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def alltoall_lookup(local_rows, ids, axis: str, bucket_cap: int,
                    rows_per_shard: int):
    """Gather rows[ids] from a row-sharded table inside shard_map.

    local_rows: (rows_per_shard, dim) this shard's slice of the table.
    ids: (N,) THIS shard's global row indices (-1 = padding -> zero
    row) — per-shard ids, i.e. the shard's slice of the batch, NOT a
    replicated id list (for replicated ids over a model axis use
    VocabParallelEmbedding's masked-gather + psum instead). Returns
    (N, dim) rows for this shard's ids.
    """
    out, _ = _exchange(local_rows, ids, axis, bucket_cap, rows_per_shard)
    return out


def _fwd(local_rows, ids, axis, bucket_cap, rows_per_shard):
    out, res = _exchange(local_rows, ids, axis, bucket_cap,
                         rows_per_shard)
    return out, (res, local_rows.shape)


def _bwd(axis, bucket_cap, rows_per_shard, saved, g):
    (home_pos, ovf, o_mine, o_idx, local_idx, hit), shape = saved
    dim = g.shape[-1]

    # grads ride the transposed route: pack per-owner buckets from the
    # ORIGINAL positions, alltoall to owners, scatter-add into the shard
    gpad = jnp.concatenate([g, jnp.zeros((1, dim), g.dtype)], 0)
    send_g = gpad[jnp.clip(home_pos, 0, g.shape[0])]      # (n, cap, dim)
    send_g = jnp.where((home_pos < g.shape[0])[..., None], send_g, 0.0)
    recv_g = lax.all_to_all(send_g, axis, 0, 0)           # (n, cap, dim)
    d_local = jnp.zeros(shape, g.dtype)
    d_local = d_local.at[local_idx].add(
        jnp.where(hit[..., None], recv_g, 0.0))

    # overflow transpose: all_gather every shard's overflow cotangents,
    # owner scatter-adds the entries it owns
    g_ovf = jnp.where(ovf[:, None], g, 0.0)
    all_g = lax.all_gather(g_ovf, axis)                   # (n, N, dim)
    d_local = d_local.at[o_idx.reshape(-1)].add(
        jnp.where(o_mine.reshape(-1)[:, None],
                  all_g.reshape(-1, dim), 0.0))
    return d_local, None


alltoall_lookup.defvjp(_fwd, _bwd)
