"""Functionalization bridge: imperative Layer ⇄ pure JAX function.

This is the TPU-native replacement for the reference's entire
dygraph-to-static machinery (reference:
python/paddle/fluid/dygraph/dygraph_to_static/program_translator.py:232) and
for the static Program/Executor stack: instead of AST-rewriting Python into a
ProgramDesc, we temporarily swap traced values into the Layer's Parameter
boxes and buffers, call the unchanged Python ``forward``, and read the
mutated buffers back out. The result is a pure function
``(params, buffers, inputs) -> (outputs, new_buffers)`` that jax.jit / pjit
can stage, shard, and compile.
"""
from __future__ import annotations

import contextlib
from collections import OrderedDict
from typing import Any, Dict, Tuple

import jax

from ..framework.random import rng_guard


def state_of(layer) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Extract (trainable+frozen params, buffers) as flat name->array dicts."""
    params = OrderedDict((n, p.value) for n, p in layer.named_parameters())
    buffers = OrderedDict(layer.named_buffers())
    return params, buffers


def trainable_mask(layer) -> Dict[str, bool]:
    return OrderedDict((n, p.trainable) for n, p in layer.named_parameters())


@contextlib.contextmanager
def _swapped_state(layer, params, buffers):
    """Swap `params`/`buffers` values into the layer; restore on exit.

    Yields accessor callables to read the possibly-mutated buffer values
    before restoration.
    """
    param_boxes = OrderedDict(layer.named_parameters())
    buf_owners = {}
    for lp, sub in layer.named_sublayers(include_self=True):
        for name in sub._buffers:
            full = lp + ("." if lp else "") + name
            buf_owners[full] = (sub, name)

    saved_params = {n: b.value for n, b in param_boxes.items()}
    saved_bufs = {n: owner._buffers[name] for n, (owner, name) in buf_owners.items()}
    try:
        for n, v in (params or {}).items():
            if n in param_boxes:
                param_boxes[n].value = v
        for n, v in (buffers or {}).items():
            if n in buf_owners:
                owner, name = buf_owners[n]
                owner._buffers[name] = v

        def read_buffers():
            return OrderedDict(
                (n, buf_owners[n][0]._buffers[buf_owners[n][1]])
                for n in (buffers if buffers is not None else buf_owners))

        yield read_buffers
    finally:
        for n, v in saved_params.items():
            param_boxes[n].value = v
        for n, (owner, name) in buf_owners.items():
            owner._buffers[name] = saved_bufs[n]


def functional_call(layer, params, buffers, *args, rng=None, **kwargs):
    """Run ``layer(*args, **kwargs)`` as a pure function of (params, buffers).

    Returns ``(outputs, new_buffers)``. ``rng`` (a jax PRNG key) scopes all
    implicit randomness (dropout etc.) so the call is deterministic under jit.
    """
    with _swapped_state(layer, params, buffers) as read_buffers:
        if rng is not None:
            with rng_guard(rng):
                out = layer(*args, **kwargs)
        else:
            out = layer(*args, **kwargs)
        new_buffers = read_buffers()
    return out, new_buffers


def value_and_grad_fn(layer, loss_fn, has_aux: bool = False):
    """Build a pure ``(params, buffers, rng, *batch) -> ((loss, aux_buffers), grads)``.

    ``loss_fn(outputs_of_layer_call)`` is the user loss; the layer call is
    ``layer(*batch)``. The reference analogue is append_backward on a Program
    (python/paddle/fluid/backward.py:1377) — here it is just jax.grad over the
    functionalized call.
    """

    def pure_loss(params, buffers, rng, *batch):
        out, new_buffers = functional_call(layer, params, buffers, *batch, rng=rng)
        loss = loss_fn(out)
        if has_aux:
            loss, aux = loss
            return loss, (new_buffers, aux)
        return loss, (new_buffers, None)

    return jax.value_and_grad(pure_loss, has_aux=True)
