"""Auto-parallel planning: search the parallelism space on the cost model.

Beyond the reference (v2.1 has no auto-parallel): mechanizes the
"How to Scale Your Model" recipe — pick a mesh, check the per-device
memory arithmetic, price the collectives — and then goes one step
further than the recipe: :func:`plan_search` searches the FULL config
space (mesh degree factorizations over data / sharding / pipe / model /
sep, per-axis ``grad_sync`` compression policy, exchange bucket count,
remat, microbatch count) and ranks candidates by **predicted end-to-end
step time** under the calibrated cost model, not by memory alone.

Two-tier search:

1. *analytic tier* — every enumerated candidate is pruned by cheap
   static bounds (axis caps, divisibility) and the memory model (HBM
   fit), then scored with a closed-form step-time model: compute at
   ``telemetry.peak_flops_per_sec()``, gradient-exchange wire seconds
   from ``compressed.wire_bytes_per_rank`` over the calibrated
   ``mesh.link_bandwidth`` / ``link_latency`` constants, TP / sep /
   pipeline collective terms, the gpipe bubble, and the
   backward-overlap hiding the bucketed exchange buys. No staging, so
   thousands of candidates cost milliseconds.
2. *staged tier* — the analytic top-k are staged for real (the caller
   provides a ``builder(plan) -> (trainer, inputs, labels)``) and
   re-scored exactly: ``cost.overlap_plan`` + ``cost.replay_overlap``
   makespan over the candidate's actual staged step, including the
   sharding pass's predicted implicit resharding
   (:func:`resharding_cost` sites on the wire streams). Staged scores
   replace analytic ones for those candidates and the final ranking
   puts exactly-scored plans first.

:func:`plan` keeps the original memory-first behavior (cheapest-
communication layout that fits) for callers that only want a starting
layout; ``plan_search`` is the planner.

Estimates use the standard transformer accounting:
  params/device    = P * b_param / (tp * pp * zshard)
  grads/device     = P * b_param / (tp * pp * zshard_g)
  opt state/device = P * 8 bytes (adam m+v fp32) / (tp * pp * zshard_o)
  activations      ~ L/pp * B * S * H * c_act * b_act / (tp * sep)
                     (remat ÷ ~L/pp; B = per-device per-microbatch)

This is a PLANNER, not a profiler: numbers rank layouts to pick a
starting config; ``tools/bench_plan.py`` closes the loop by recording
the planner's predicted step time against the measured one
(``calibration_drift_ratio{key=planner_step_time}``).
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["MemoryEstimate", "Plan", "TimeBreakdown", "plan",
           "plan_search", "score_plan", "resharding_cost",
           "GRAD_SYNC_POLICIES"]

_ADAM_BYTES = 8          # m + v, fp32 each
_ACT_COEFF = 18          # bytes-ish per (B,S,H) element across a block's
                         # live set with flash attention (no S^2 term).
                         # FALLBACK ONLY: when a candidate is staged, the
                         # activation term defers to analysis/cost.py
                         # peak-live-bytes over the real step jaxpr (see
                         # MemoryEstimate.source).

# grad_sync wire policies in preference order (ties break toward the
# earlier, simpler policy): exact fp32, bf16 halved, EQuARX-style int8
# (~4x fewer bytes), nibble-packed int4 (~7x).
GRAD_SYNC_POLICIES = ("fp32", "bf16", "int8", "int4")


@dataclass
class MemoryEstimate:
    params: float
    grads: float
    opt_state: float
    activations: float
    #: which model produced the ACTIVATION term: ``"act-coefficient"``
    #: is the hand-rolled ``_ACT_COEFF * B*S*H`` sizing (no jaxpr
    #: available — the pre-staging fallback); ``"peak-live-bytes/chip"``
    #: means the candidate was staged and ``analysis.cost
    #: .peak_live_bytes`` over its real step jaxpr (divided across
    #: chips) replaced the coefficient estimate.
    source: str = "act-coefficient"

    @property
    def total(self) -> float:
        return self.params + self.grads + self.opt_state + self.activations


@dataclass
class TimeBreakdown:
    """Per-candidate predicted step-time rationale (seconds).

    ``total`` is stored, not derived: the analytic tier sums its terms;
    the staged tier uses the overlap model's makespan (where the bubble
    lives inside ``compute`` and the reshard share of the stall is
    inside ``exposed_collective``)."""
    total: float
    compute: float
    bubble: float
    exposed_collective: float
    reshard: float
    collective: float = 0.0      # total collective seconds incl. hidden
    tier: str = "analytic"       # "analytic" | "staged"

    def to_dict(self) -> dict:
        return {"total_s": self.total, "compute_s": self.compute,
                "bubble_s": self.bubble,
                "exposed_collective_s": self.exposed_collective,
                "reshard_s": self.reshard,
                "collective_s": self.collective, "tier": self.tier}


@dataclass
class Plan:
    degrees: Dict[str, int]
    per_device: MemoryEstimate
    hbm_bytes: float
    rationale: List[str] = field(default_factory=list)
    remat: bool = False
    grad_sync: str = "fp32"
    grad_sync_dcn_only: bool = False
    grad_sync_buckets: int = 1
    micro_batches: int = 1
    zero_stage: int = 1
    predicted: Optional[TimeBreakdown] = None

    @property
    def fits(self) -> bool:
        return self.per_device.total <= self.hbm_bytes

    def build_mesh(self):
        from .mesh import build_mesh
        return build_mesh({k: v for k, v in self.degrees.items() if v > 1}
                          or {"data": 1})

    def apply(self, *, mesh=None, build_mesh: bool = False) -> dict:
        """A ready ``ParallelTrainer`` kwargs dict for this plan.

        Microbatch mapping: with a pipe degree the count is the pipeline
        ``micro_batches``; without one a searched microbatch count > 1
        becomes ``accumulate_steps`` (GradientMerge — same per-device
        activation footprint win, no pipeline schedule). Pass
        ``build_mesh=True`` to build (and install) the plan's mesh and
        include it, or ``mesh=`` to use an existing one."""
        pp = self.degrees.get("pipe", 1)
        kw = {
            "micro_batches": self.micro_batches if pp > 1 else 1,
            "accumulate_steps": (1 if pp > 1
                                 else max(1, self.micro_batches)),
            "remat": self.remat,
            "zero_stage": self.zero_stage,
            "grad_sync": self.grad_sync,
            "grad_sync_buckets": self.grad_sync_buckets,
            "grad_sync_dcn_only": self.grad_sync_dcn_only,
        }
        if build_mesh:
            kw["mesh"] = self.build_mesh()
        elif mesh is not None:
            kw["mesh"] = mesh
        return kw

    def to_dict(self) -> dict:
        """JSON-stable summary (bench_plan.py / determinism tests)."""
        return {
            "degrees": {k: self.degrees[k]
                        for k in sorted(self.degrees)},
            "remat": self.remat, "grad_sync": self.grad_sync,
            "grad_sync_dcn_only": self.grad_sync_dcn_only,
            "grad_sync_buckets": self.grad_sync_buckets,
            "micro_batches": self.micro_batches,
            "zero_stage": self.zero_stage,
            "memory": {"params": self.per_device.params,
                       "grads": self.per_device.grads,
                       "opt_state": self.per_device.opt_state,
                       "activations": self.per_device.activations,
                       "total": self.per_device.total,
                       "source": self.per_device.source},
            "predicted": (self.predicted.to_dict()
                          if self.predicted else None),
            "rationale": list(self.rationale),
        }


def _factorizations(n: int):
    """All (data, sharding, model, pipe, sep) with product n; model and
    sep powers of 2 (TP wants the MXU-friendly head splits, sep the
    even ring splits). Deterministic enumeration order — the planner's
    candidate list must be reproducible across processes."""
    out = []
    def divs(x):
        return [d for d in range(1, x + 1) if x % d == 0]
    for pipe in divs(n):
        for model in divs(n // pipe):
            if model & (model - 1):      # non-power-of-2 TP: skip
                continue
            for sep in divs(n // (pipe * model)):
                if sep & (sep - 1):      # non-power-of-2 sep: skip
                    continue
                rest = n // (pipe * model * sep)
                for shard in divs(rest):
                    out.append({"data": rest // shard, "sharding": shard,
                                "model": model, "pipe": pipe, "sep": sep})
    return out


def _estimate(n_params: float, deg: Dict[str, int], *, layers, hidden,
              seq_len, batch_per_device, param_bytes, zero_stage,
              remat) -> MemoryEstimate:
    tp, pp, z = deg["model"], deg["pipe"], deg["sharding"]
    sep = deg.get("sep", 1)
    shard_p = z if zero_stage >= 3 else 1
    shard_g = z if zero_stage >= 2 else 1
    shard_o = z if zero_stage >= 1 else 1
    mp = tp * pp
    params = n_params * param_bytes / (mp * shard_p)
    grads = n_params * param_bytes / (mp * shard_g)
    opt = n_params * _ADAM_BYTES / (mp * shard_o)
    act = (layers / pp) * batch_per_device * seq_len * hidden \
        * _ACT_COEFF / (tp * sep)
    if remat:
        act = act / max(1.0, layers / pp) + \
            batch_per_device * seq_len * hidden * _ACT_COEFF / (tp * sep)
    return MemoryEstimate(params, grads, opt, act)


def _comm_cost(deg: Dict[str, int]) -> tuple:
    """Sort key: prefer fewer model/pipe/sep degrees (TP and sep pay
    per-layer collectives, PP pays bubble + schedule complexity), then
    less ZeRO resharding, then more plain DP."""
    return (deg["pipe"], deg["model"], deg.get("sep", 1),
            deg["sharding"], -deg["data"])


def plan(n_params: float, n_devices: int, *, layers: int = 24,
         hidden: int = 2048, seq_len: int = 2048,
         batch_per_device: int = 8, hbm_bytes: float = 16e9,
         param_bytes: int = 2, zero_stage: int = 1,
         remat: Optional[bool] = None, max_model: int = 8,
         headroom: float = 0.9) -> Plan:
    """Propose mesh degrees for training an n_params transformer on
    n_devices chips. Searches (data, sharding, model, pipe, sep)
    factorizations and returns the cheapest-communication Plan that fits
    ``headroom * hbm_bytes``; raises ValueError if nothing fits (with
    the closest layout's numbers in the message). Memory-first: for a
    predicted-step-TIME ranking over the same space (plus grad_sync
    policy / buckets / microbatches), use :func:`plan_search`."""
    if n_devices < 1:
        raise ValueError("n_devices must be >= 1")
    budget = headroom * hbm_bytes
    candidates = []
    for deg in _factorizations(n_devices):
        if deg["model"] > max_model or deg["model"] > max(1, hidden // 128):
            continue
        if deg["pipe"] > max(1, layers):
            continue
        if seq_len % deg["sep"]:
            continue
        for use_remat in ((remat,) if remat is not None else (False, True)):
            est = _estimate(n_params, deg, layers=layers, hidden=hidden,
                            seq_len=seq_len,
                            batch_per_device=batch_per_device,
                            param_bytes=param_bytes,
                            zero_stage=zero_stage, remat=use_remat)
            candidates.append((deg, use_remat, est))
    fitting = [(d, r, e) for d, r, e in candidates if e.total <= budget]
    if not fitting:
        best = min(candidates, key=lambda t: t[2].total)
        raise ValueError(
            f"no layout fits: closest is {best[0]} "
            f"(remat={best[1]}) at {best[2].total / 1e9:.1f} GB/device vs "
            f"budget {budget / 1e9:.1f} GB — add devices, raise "
            f"zero_stage, or shrink the per-device batch")
    deg, use_remat, est = min(
        fitting, key=lambda t: (_comm_cost(t[0]), t[1]))
    why = [
        f"{n_devices} devices -> data={deg['data']} sharding="
        f"{deg['sharding']} model={deg['model']} pipe={deg['pipe']} "
        f"sep={deg['sep']}",
        f"per-device: params {est.params/1e9:.2f} GB + grads "
        f"{est.grads/1e9:.2f} GB + opt {est.opt_state/1e9:.2f} GB + act "
        f"{est.activations/1e9:.2f} GB = {est.total/1e9:.2f} GB "
        f"(budget {budget/1e9:.1f} GB, act via {est.source})",
        f"zero_stage={zero_stage}, remat={use_remat}",
    ]
    if deg["model"] > 1:
        why.append("TP engaged: params exceed what DP+ZeRO fits alone")
    if deg["pipe"] > 1:
        why.append("PP engaged: per-layer state exceeds TP ceiling")
    if deg["sep"] > 1:
        why.append("sep engaged: context parallelism splits the "
                   "sequence-sized activation term")
    p = Plan(degrees=deg, per_device=est, hbm_bytes=hbm_bytes,
             rationale=why, zero_stage=zero_stage)
    p.remat = use_remat
    return p


# ---------------------------------------------------------------------------
# predicted-step-time search (the planner)
# ---------------------------------------------------------------------------

def _axis_link(links: Dict[str, str], axis: str) -> str:
    return links.get(axis, "ici")


def _predict_time(n_params: float, deg: Dict[str, int], *, layers, hidden,
                  seq_len, global_batch, param_bytes, policy, dcn_only,
                  buckets, remat, micro, zero_stage, links, peak_flops,
                  bw: Callable[[str], float],
                  lat: Callable[[str], float]) -> TimeBreakdown:
    """Closed-form per-step time for one candidate (the analytic tier).

    First-order transformer accounting at a FIXED global batch so every
    candidate prices the same optimization work: compute = 6*P*tokens /
    (chips * peak) (+1/3 re-forward under remat), gpipe bubble
    (pp-1)/M, ring-model wire seconds for the DP/ZeRO gradient exchange
    (``compressed.wire_bytes_per_rank`` — compression priced as TIME on
    the axis's calibrated link), per-layer TP and sep (ring-attention
    K/V) collectives, pipeline boundary p2p, and the ZeRO-3 parameter
    all-gather. K>=2 exchange buckets hide wire time under the
    remaining backward compute (engine's per-bucket custom_vjp hooks);
    K=1 is fully exposed after the backward."""
    from . import compressed
    d, z = deg["data"], deg["sharding"]
    tp, pp, sep = deg["model"], deg["pipe"], deg.get("sep", 1)
    n = d * z * tp * pp * sep
    tokens = float(global_batch) * seq_len
    flops = 6.0 * n_params * tokens
    if remat:
        flops *= 4.0 / 3.0           # re-forward during backward
    compute = flops / (n * peak_flops)
    bubble = compute * (pp - 1) / max(1, micro) if pp > 1 else 0.0

    # gradient exchange over the data (all-reduce) and sharding
    # (reduce-scatter + all-gather) axes; dcn_only gates compression to
    # DCN-linked axes (ICI hops stay fp32), mirroring the engine knob.
    numel_local = n_params / (tp * pp)
    exch = 0.0
    for axis, g in (("data", d), ("sharding", z)):
        if g <= 1:
            continue
        link = _axis_link(links, axis)
        pol = policy if (not dcn_only or link == "dcn") else "fp32"
        wire = compressed.wire_bytes_per_rank(int(numel_local), g, pol)
        exch += wire / bw(link) + buckets * lat(link)
    bwd = compute * 2.0 / 3.0
    hidden_t = 0.0 if buckets <= 1 else min(exch,
                                            bwd * (buckets - 1) / buckets)
    exch_exposed = exch - hidden_t

    # per-layer activation collectives. Activations are bf16 (2 bytes);
    # per-device activation elements at the full local batch:
    act_elems = (global_batch / (d * z)) * (seq_len / sep) * hidden
    act_bytes = act_elems * 2.0
    tp_t = sep_t = p2p_t = 0.0
    if tp > 1:
        link = _axis_link(links, "model")
        wire = 4.0 * (layers / pp) * act_bytes * 2.0 * (tp - 1) / tp
        tp_t = wire / bw(link) + 4.0 * (layers / pp) * lat(link)
    if sep > 1:
        link = _axis_link(links, "sep")
        wire = 2.0 * (layers / pp) * act_bytes * (sep - 1)
        sep_t = wire / bw(link) + (sep - 1) * (layers / pp) * lat(link)
    if pp > 1:
        link = _axis_link(links, "pipe")
        wire = 2.0 * (pp - 1) * act_bytes
        p2p_t = wire / bw(link) + 2.0 * (pp - 1) * max(1, micro) \
            * lat(link)
    z3_t = 0.0
    if zero_stage >= 3 and z > 1:
        link = _axis_link(links, "sharding")
        wire = 2.0 * n_params * param_bytes / (tp * pp) * (z - 1) / z
        z3_t = wire / bw(link) + 2.0 * lat(link)

    exposed = exch_exposed + tp_t + sep_t + p2p_t + z3_t
    coll = exch + tp_t + sep_t + p2p_t + z3_t
    total = compute + bubble + exposed
    return TimeBreakdown(total=total, compute=compute, bubble=bubble,
                         exposed_collective=exposed, reshard=0.0,
                         collective=coll, tier="analytic")


def _policy_rank(policy: str) -> int:
    try:
        return GRAD_SYNC_POLICIES.index(policy)
    except ValueError:
        return len(GRAD_SYNC_POLICIES)


def _tiebreak(p: Plan) -> tuple:
    """Deterministic total order below the predicted time: simplest
    config first (fewer exotic degrees, exact policy, fewer buckets /
    microbatches, no remat)."""
    return (_comm_cost(p.degrees), _policy_rank(p.grad_sync),
            p.grad_sync_dcn_only, p.grad_sync_buckets, p.micro_batches,
            p.remat)


def score_plan(p: Plan, n_params: float, *, layers, hidden, seq_len,
               global_batch, param_bytes=2, peak_flops=None) -> Plan:
    """Analytically (re-)price one plan in place — the scorer
    plan_search uses, exposed so baselines (all-DP, ``plan()``'s
    memory-first pick) can be priced with the SAME calibrated model the
    acceptance comparison needs."""
    from . import mesh as _mesh
    if peak_flops is None:
        from .. import telemetry as _telemetry
        peak_flops = _telemetry.peak_flops_per_sec()
    links = _mesh.axis_links(None)
    p.predicted = _predict_time(
        n_params, p.degrees, layers=layers, hidden=hidden,
        seq_len=seq_len, global_batch=global_batch,
        param_bytes=param_bytes, policy=p.grad_sync,
        dcn_only=p.grad_sync_dcn_only, buckets=p.grad_sync_buckets,
        remat=p.remat, micro=p.micro_batches, zero_stage=p.zero_stage,
        links=links, peak_flops=max(float(peak_flops), 1.0),
        bw=_mesh.link_bandwidth, lat=_mesh.link_latency)
    return p


def _stage_score(p: Plan, builder: Callable, peak_flops) -> Plan:
    """Exact tier: stage the candidate's real trainer step and score it
    with the overlap list scheduler + the sharding pass's implicit
    collectives, all priced by the calibrated constants. Also refines
    the memory estimate: the activation term defers to
    ``cost.peak_live_bytes`` over the staged jaxpr (per chip) instead
    of the ``_ACT_COEFF`` coefficient."""
    from ..analysis import cost as _cost
    from ..analysis.sharding import propagate
    trainer, inputs, labels = builder(p)
    closed = trainer.staged_jaxpr(inputs, labels)
    in_specs = None
    try:
        in_specs = trainer.staged_in_specs(inputs, labels)
        if len(in_specs) != len(closed.jaxpr.invars):
            in_specs = None
    except Exception:
        in_specs = None
    sites = []
    if in_specs is not None:
        try:
            sites = propagate(closed, trainer.mesh, in_specs).sites
        except Exception:
            sites = []
    oplan = _cost.overlap_plan(closed, trainer.mesh, reshard_sites=sites)
    s = _cost.replay_overlap(oplan, peak_flops=peak_flops)
    p.predicted = TimeBreakdown(
        total=s["makespan"], compute=s["compute_time"], bubble=0.0,
        exposed_collective=s["stalled_time"], reshard=s["reshard_time"],
        collective=s["collective_time"], tier="staged")
    n = 1
    for v in p.degrees.values():
        n *= v
    peak_live = _cost.peak_live_bytes(closed) / max(1, n)
    m = p.per_device
    act = max(0.0, peak_live - (m.params + m.grads + m.opt_state))
    p.per_device = MemoryEstimate(m.params, m.grads, m.opt_state, act,
                                  source="peak-live-bytes/chip")
    p.rationale.append(
        f"staged: makespan {s['makespan']:.3e}s = compute "
        f"{s['compute_time']:.3e}s + exposed collective "
        f"{s['stalled_time']:.3e}s (reshard {s['reshard_time']:.3e}s of "
        f"{s['n_reshard']} implicit sites; "
        f"{s['n_collectives']} collectives)")
    return p


def plan_search(n_params: float, n_devices: int, *, layers: int = 24,
                hidden: int = 2048, seq_len: int = 2048,
                global_batch: Optional[int] = None,
                batch_per_device: int = 8, hbm_bytes: float = 16e9,
                param_bytes: int = 2, zero_stage: int = 1,
                max_model: int = 8, max_pipe: Optional[int] = None,
                max_sep: int = 4, headroom: float = 0.9,
                policies: Sequence[str] = GRAD_SYNC_POLICIES,
                dcn_only_choices: Sequence[bool] = (False, True),
                buckets_choices: Sequence[int] = (1, 2, 4),
                micro_choices: Sequence[int] = (1, 2, 4),
                remat: Optional[bool] = None, top_k: int = 8,
                stage_top_k: int = 0,
                builder: Optional[Callable] = None,
                peak_flops: Optional[float] = None) -> List[Plan]:
    """Search the parallelism space and return plans ranked by predicted
    end-to-end step time (fastest first).

    The candidate space is the cross product of mesh degree
    factorizations of ``n_devices`` over (data, sharding, model, pipe,
    sep), grad-sync policy x ``grad_sync_dcn_only``, exchange bucket
    count, remat, and microbatch count — pruned by static bounds and
    the HBM memory model BEFORE any staging, scored analytically, and
    (optionally) the top ``stage_top_k`` survivors re-scored exactly
    from their staged step via ``builder(plan) -> (trainer, inputs,
    labels)``. Enumeration and every sort are deterministic: the same
    spec + chip count + calibration DB yields the same ranked list in
    any process.

    ``global_batch`` fixes the per-step optimization work across
    candidates (defaults to ``batch_per_device * n_devices`` — the
    all-DP reading of the :func:`plan` sizing). Each plan carries its
    ``predicted`` :class:`TimeBreakdown` and human-readable rationale;
    ``Plan.apply()`` turns the winner into ParallelTrainer kwargs.
    Raises ValueError when no candidate fits HBM (same contract as
    :func:`plan`)."""
    from .. import telemetry as _telemetry
    from . import mesh as _mesh
    t0 = time.perf_counter()
    if n_devices < 1:
        raise ValueError("n_devices must be >= 1")
    if global_batch is None:
        global_batch = batch_per_device * n_devices
    if peak_flops is None:
        peak_flops = _telemetry.peak_flops_per_sec()
    peak_flops = max(float(peak_flops), 1.0)
    links = _mesh.axis_links(None)
    has_dcn = "dcn" in set(links.values())
    budget = headroom * hbm_bytes

    n_enum = n_pruned_bounds = n_pruned_memory = 0
    candidates: List[Plan] = []
    best_overweight = None   # closest-to-fitting, for the error message
    for deg in _factorizations(n_devices):
        n_enum += 1
        d, z = deg["data"], deg["sharding"]
        tp, pp, sep = deg["model"], deg["pipe"], deg["sep"]
        # tier-0 static bounds: cheap, before any estimate
        if tp > max_model or tp > max(1, hidden // 128) \
                or pp > max(1, layers) \
                or (max_pipe is not None and pp > max_pipe) \
                or sep > max_sep or seq_len % sep \
                or global_batch % (d * z) \
                or global_batch < d * z:
            n_pruned_bounds += 1
            continue
        local_batch = global_batch // (d * z)
        for micro in micro_choices:
            if local_batch % micro:
                continue
            if pp > 1 and micro < pp:
                continue      # bubble-dominated; never worth staging
            for use_remat in ((remat,) if remat is not None
                              else (False, True)):
                est = _estimate(
                    n_params, deg, layers=layers, hidden=hidden,
                    seq_len=seq_len,
                    batch_per_device=local_batch / micro,
                    param_bytes=param_bytes, zero_stage=zero_stage,
                    remat=use_remat)
                if est.total > budget:
                    n_pruned_memory += 1
                    if best_overweight is None or \
                            est.total < best_overweight[2].total:
                        best_overweight = (deg, use_remat, est)
                    continue
                # wire-policy knobs only matter when a gradient
                # exchange exists; collapse the degenerate rows so the
                # ranked list has no duplicate-config aliases
                has_exchange = d > 1 or z > 1
                pols = policies if has_exchange else policies[:1]
                for pol in pols:
                    dcn_choices = ((False,) if pol == "fp32"
                                   or not has_exchange or not has_dcn
                                   else dcn_only_choices)
                    for dcn_only in dcn_choices:
                        bks = (buckets_choices if has_exchange
                               else buckets_choices[:1])
                        for k in bks:
                            candidates.append(Plan(
                                degrees=dict(deg), per_device=est,
                                hbm_bytes=hbm_bytes, remat=bool(use_remat),
                                grad_sync=pol,
                                grad_sync_dcn_only=bool(dcn_only),
                                grad_sync_buckets=int(k),
                                micro_batches=int(micro),
                                zero_stage=zero_stage))
    if not candidates:
        if best_overweight is not None:
            deg, use_remat, est = best_overweight
            raise ValueError(
                f"no layout fits: closest is {deg} (remat={use_remat}) "
                f"at {est.total / 1e9:.1f} GB/device vs budget "
                f"{budget / 1e9:.1f} GB — add devices, raise zero_stage, "
                f"or shrink the per-device batch")
        raise ValueError("no layout fits: no candidate passed the "
                         "static bounds — relax max_model/max_pipe/"
                         "max_sep or fix the batch divisibility")

    for p in candidates:
        score_plan(p, n_params, layers=layers, hidden=hidden,
                   seq_len=seq_len, global_batch=global_batch,
                   param_bytes=param_bytes, peak_flops=peak_flops)
    candidates.sort(key=lambda p: (p.predicted.total, _tiebreak(p)))
    ranked = candidates[:max(1, top_k)]

    n_staged = 0
    if builder is not None and stage_top_k > 0:
        staged, rest = [], []
        for i, p in enumerate(ranked):
            if i < stage_top_k:
                try:
                    staged.append(_stage_score(p, builder, peak_flops))
                    n_staged += 1
                    continue
                except Exception as e:   # candidate fails to stage:
                    p.rationale.append(   # drop to analytic, keep rank
                        f"staging failed ({type(e).__name__}: {e}); "
                        "analytic score kept")
            rest.append(p)
        staged.sort(key=lambda p: (p.predicted.total, _tiebreak(p)))
        ranked = staged + rest     # exactly-scored plans outrank the
        #                            analytic tail (tiers don't share a
        #                            scale: staged makespans price the
        #                            whole staged program)

    for rank, p in enumerate(ranked):
        b = p.predicted
        deg = p.degrees
        p.rationale[:0] = [
            f"#{rank + 1}: data={deg['data']} sharding={deg['sharding']} "
            f"model={deg['model']} pipe={deg['pipe']} sep={deg['sep']} "
            f"grad_sync={p.grad_sync}"
            + (" (dcn-only)" if p.grad_sync_dcn_only else "")
            + f" buckets={p.grad_sync_buckets} micro={p.micro_batches} "
            f"remat={p.remat}",
            f"predicted {b.total:.3e}s/step [{b.tier}] = compute "
            f"{b.compute:.3e}s + bubble {b.bubble:.3e}s + exposed "
            f"collective {b.exposed_collective:.3e}s + reshard "
            f"{b.reshard:.3e}s",
            f"per-device {p.per_device.total / 1e9:.2f} GB "
            f"(act via {p.per_device.source}, budget "
            f"{budget / 1e9:.1f} GB)",
        ]

    if _telemetry.enabled():
        c = _telemetry.counter(
            "planner_candidates_total",
            "plan_search candidates per processing tier")
        c.inc(n_enum, tier="enumerated")
        c.inc(n_pruned_bounds, tier="pruned_bounds")
        c.inc(n_pruned_memory, tier="pruned_memory")
        c.inc(len(candidates), tier="scored_analytic")
        c.inc(n_staged, tier="scored_staged")
        _telemetry.histogram(
            "planner_search_ms",
            "plan_search wall time (enumeration + pruning + scoring)"
        ).observe((time.perf_counter() - t0) * 1e3)
    return ranked


def resharding_cost(closed, mesh, in_specs, *, while_trips: float = 1.0
                    ) -> dict:
    """Score one candidate layout by its predicted implicit-resharding
    traffic: run the static sharding-propagation pass
    (analysis/sharding.py) over ``closed`` seeded with ``in_specs`` and
    fold the per-site table into planner-ready totals. Returns
    ``{"n_sites", "time_s", "wire_bytes", "dcn_bytes", "sites"}`` —
    lower ``time_s`` (and especially ``dcn_bytes``) means the layout
    needs fewer silent partitioner collectives, the second-order term
    the memory model above cannot see."""
    from ..analysis.sharding import resharding_table
    rows = resharding_table(closed, mesh, in_specs,
                            while_trips=while_trips)
    return {
        "n_sites": len(rows),
        "time_s": sum(r["time_s"] * max(r["trips"], 1.0) for r in rows),
        "wire_bytes": sum(r["wire_bytes"] * max(r["trips"], 1.0)
                          for r in rows),
        "dcn_bytes": sum(r["bytes"] for r in rows if r["link"] == "dcn"),
        "sites": rows,
    }
