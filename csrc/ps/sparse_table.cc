// Host-side sharded sparse embedding table — the TPU-native equivalent of
// the reference's brpc parameter-server sparse tables
// (reference behavior modeled: distributed/table/common_sparse_table.cc —
// sharded key->row storage with per-row optimizer state, pull auto-creates
// rows; framework/fleet/heter_ps/hashtable.h — hash-table embedding store;
// NOT a port: this is a fresh std::unordered_map + std::thread design with a
// C ABI for ctypes. The RPC transport lives in ps_service.cc (TCP frames);
// multi-host sharding is done above by key-hash routing
// (distributed/ps/service.py DistributedSparseTable), each server owning
// one hash shard of the key space.
//
// Concurrency: keys hash to NUM_SHARDS sub-maps, each with its own mutex.
// Batched pull/push fan out over worker threads; within one batch a shard
// is only touched by the thread owning (shard % nthreads), so duplicate
// keys serialize. The per-shard mutex is still taken for every row
// operation because *independent* calls may overlap (JAX may dispatch the
// pure_callback pull and the io_callback push on different host threads,
// and ctypes releases the GIL): find+create+update happen under the lock so
// a concurrent pool resize can never invalidate a row pointer in use.
//
// Optimizers run on the host, one row at a time, matching the PS model
// where the server applies updates (SGD / Adagrad / Adam).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kShards = 64;

enum Optimizer : int { kSGD = 0, kAdagrad = 1, kAdam = 2 };

struct Shard {
  std::unordered_map<int64_t, uint64_t> index;  // key -> row offset
  std::unordered_map<int64_t, uint64_t> touch;  // key -> last access tick
  std::unordered_map<int64_t, uint64_t> cold;   // key -> spill-file offset
  std::vector<float> pool;                      // rows, stride = row_width
  std::mutex mu;
};

class SparseTable {
 public:
  SparseTable(int dim, int opt, uint64_t seed, float init_range,
              float beta1, float beta2, float eps)
      : dim_(dim), opt_(opt), seed_(seed), init_range_(init_range),
        beta1_(beta1), beta2_(beta2), eps_(eps), step_(0) {
    switch (opt_) {
      case kSGD: slots_ = 0; break;
      case kAdagrad: slots_ = 1; break;
      case kAdam: slots_ = 2; break;
      default: slots_ = 0; opt_ = kSGD;
    }
    row_width_ = dim_ * (1 + slots_);
  }

  int dim() const { return dim_; }

  ~SparseTable() {
    if (spill_f_) std::fclose(spill_f_);
  }

  int64_t size() {
    int64_t n = 0;
    for (auto& s : shards_) {
      std::lock_guard<std::mutex> lk(s.mu);
      n += static_cast<int64_t>(s.index.size() + s.cold.size());
    }
    return n;
  }

  int64_t hot_rows() {
    int64_t n = 0;
    for (auto& s : shards_) {
      std::lock_guard<std::mutex> lk(s.mu);
      n += static_cast<int64_t>(s.index.size());
    }
    return n;
  }

  // SSD tier (reference table/ssd_sparse_table.cc, RocksDB-backed cold
  // store): evict least-recently-touched rows beyond `max_hot` to a spill
  // file; promoted back transparently by FindOrCreate. Each call rewrites
  // the spill file (compaction of promoted-away rows).
  bool Spill(const char* path, int64_t max_hot) {
    std::vector<std::unique_lock<std::mutex>> locks;
    locks.reserve(kShards);
    for (auto& s : shards_) locks.emplace_back(s.mu);
    std::lock_guard<std::mutex> flk(spill_mu_);

    // rank hot rows by recency; keys beyond max_hot get evicted
    std::vector<std::pair<uint64_t, int64_t>> hot;  // (touch, key)
    for (auto& s : shards_) {
      for (const auto& kv : s.index) {
        auto t = s.touch.find(kv.first);
        hot.emplace_back(t == s.touch.end() ? 0 : t->second, kv.first);
      }
    }
    int64_t n_evict =
        std::max<int64_t>(0, static_cast<int64_t>(hot.size()) - max_hot);
    std::unordered_map<int64_t, bool> evict;
    if (n_evict > 0) {
      std::nth_element(hot.begin(), hot.begin() + n_evict, hot.end());
      for (int64_t i = 0; i < n_evict; ++i) evict[hot[i].second] = true;
    }

    // two-phase: build the whole new file AND the new shard states first;
    // only COMMIT (swap shard state + file handle) after rename succeeds,
    // so any mid-write failure leaves the table untouched on the old file
    std::string tmp = std::string(path) + ".tmp";
    FILE* nf = std::fopen(tmp.c_str(), "wb+");
    if (!nf) return false;
    struct NewShard {
      std::unordered_map<int64_t, uint64_t> index;
      std::unordered_map<int64_t, uint64_t> cold;
      std::vector<float> pool;
    };
    std::vector<NewShard> staged(kShards);
    bool ok = true;
    std::vector<float> row(row_width_);
    for (int si = 0; si < kShards && ok; ++si) {
      Shard& s = shards_[si];
      NewShard& ns = staged[si];
      // surviving cold rows: copy from the old file (compaction)
      for (const auto& kv : s.cold) {
        if (!spill_f_) { ok = false; break; }
        std::fseek(spill_f_, static_cast<long>(kv.second), SEEK_SET);
        if (std::fread(row.data(), sizeof(float), row_width_, spill_f_) !=
            static_cast<size_t>(row_width_)) { ok = false; break; }
        ns.cold[kv.first] = static_cast<uint64_t>(std::ftell(nf));
        if (std::fwrite(row.data(), sizeof(float), row_width_, nf) !=
            static_cast<size_t>(row_width_)) { ok = false; break; }
      }
      if (!ok) break;
      for (const auto& kv : s.index) {
        const float* src = s.pool.data() + kv.second;
        if (evict.count(kv.first)) {
          ns.cold[kv.first] = static_cast<uint64_t>(std::ftell(nf));
          if (std::fwrite(src, sizeof(float), row_width_, nf) !=
              static_cast<size_t>(row_width_)) { ok = false; break; }
        } else {
          uint64_t off = ns.pool.size();
          ns.pool.resize(off + row_width_);
          std::memcpy(ns.pool.data() + off, src,
                      sizeof(float) * row_width_);
          ns.index[kv.first] = off;
        }
      }
    }
    if (!ok || std::fflush(nf) != 0 ||
        std::rename(tmp.c_str(), path) != 0) {
      std::fclose(nf);
      std::remove(tmp.c_str());
      return false;  // table state untouched, old spill file still valid
    }
    // commit
    for (int si = 0; si < kShards; ++si) {
      Shard& s = shards_[si];
      for (const auto& kv : staged[si].cold)
        if (s.index.count(kv.first)) s.touch.erase(kv.first);
      s.index = std::move(staged[si].index);
      s.pool = std::move(staged[si].pool);
      s.cold = std::move(staged[si].cold);
    }
    if (spill_f_) std::fclose(spill_f_);
    spill_f_ = nf;  // nf's descriptor follows the renamed file
    spill_path_ = path;
    return true;
  }

  // Lookup rows for keys[0..n); missing keys are initialized (uniform in
  // [-init_range, init_range], deterministic in (seed, key)) when
  // create_missing, else zero-filled.
  void Pull(const int64_t* keys, int64_t n, float* out, bool create_missing) {
    RunSharded(n, [&](int shard_lo, int tid, int nthreads) {
      for (int64_t i = 0; i < n; ++i) {
        int s = ShardOf(keys[i]);
        if (s % nthreads != tid) continue;
        float* dst = out + i * dim_;
        std::lock_guard<std::mutex> lk(shards_[s].mu);
        const float* row = FindOrCreate(keys[i], create_missing);
        if (row) {
          std::memcpy(dst, row, sizeof(float) * dim_);
        } else {
          std::memset(dst, 0, sizeof(float) * dim_);
        }
      }
    });
  }

  // Apply grads[0..n*dim) to rows of keys (creating them if absent).
  void Push(const int64_t* keys, int64_t n, const float* grads, float lr) {
    int64_t t = ++step_;
    // bias correction uses the table-global step (PS-style, one logical
    // optimizer step per push batch)
    float bc1 = 1.0f - std::pow(beta1_, static_cast<float>(t));
    float bc2 = 1.0f - std::pow(beta2_, static_cast<float>(t));
    RunSharded(n, [&](int shard_lo, int tid, int nthreads) {
      for (int64_t i = 0; i < n; ++i) {
        int s = ShardOf(keys[i]);
        if (s % nthreads != tid) continue;
        std::lock_guard<std::mutex> lk(shards_[s].mu);
        float* row = const_cast<float*>(FindOrCreate(keys[i], true));
        const float* g = grads + i * dim_;
        switch (opt_) {
          case kSGD:
            for (int d = 0; d < dim_; ++d) row[d] -= lr * g[d];
            break;
          case kAdagrad: {
            float* g2 = row + dim_;
            for (int d = 0; d < dim_; ++d) {
              g2[d] += g[d] * g[d];
              row[d] -= lr * g[d] / (std::sqrt(g2[d]) + eps_);
            }
            break;
          }
          case kAdam: {
            float* m = row + dim_;
            float* v = row + 2 * dim_;
            for (int d = 0; d < dim_; ++d) {
              m[d] = beta1_ * m[d] + (1.0f - beta1_) * g[d];
              v[d] = beta2_ * v[d] + (1.0f - beta2_) * g[d] * g[d];
              float mh = m[d] / bc1;
              float vh = v[d] / bc2;
              row[d] -= lr * mh / (std::sqrt(vh) + eps_);
            }
            break;
          }
        }
      }
    });
  }

  int row_width() const { return row_width_; }

  // Tier-exchange API (the HeterPS hot/cold handoff,
  // framework/fleet/heter_ps/heter_comm.h capability): read/write FULL
  // rows — value followed by the optimizer slot columns — so a device-
  // resident hot tier can take over a row (promote) and hand it back
  // (flush) without losing optimizer state.
  void ExportRows(const int64_t* keys, int64_t n, float* out,
                  bool create_missing) {
    RunSharded(n, [&](int, int tid, int nthreads) {
      for (int64_t i = 0; i < n; ++i) {
        int s = ShardOf(keys[i]);
        if (s % nthreads != tid) continue;
        float* dst = out + i * row_width_;
        std::lock_guard<std::mutex> lk(shards_[s].mu);
        const float* row = FindOrCreate(keys[i], create_missing);
        if (row) {
          std::memcpy(dst, row, sizeof(float) * row_width_);
        } else {
          std::memset(dst, 0, sizeof(float) * row_width_);
        }
      }
    });
  }

  void ImportRows(const int64_t* keys, int64_t n, const float* data) {
    RunSharded(n, [&](int, int tid, int nthreads) {
      for (int64_t i = 0; i < n; ++i) {
        int s = ShardOf(keys[i]);
        if (s % nthreads != tid) continue;
        std::lock_guard<std::mutex> lk(shards_[s].mu);
        float* row = const_cast<float*>(FindOrCreate(keys[i], true));
        std::memcpy(row, data + i * row_width_,
                    sizeof(float) * row_width_);
      }
    });
  }

  // Binary format: header(dim, opt, slots, step, nrows) then per row:
  // key + row_width floats.
  bool Save(const char* path) {
    // Exclusive snapshot: every shard lock is held for the whole write so a
    // concurrent Push (e.g. an async io_callback still landing) cannot add
    // rows after the header count is taken. Written to a temp file and
    // renamed so a crash mid-save never clobbers the previous checkpoint.
    std::vector<std::unique_lock<std::mutex>> locks;
    locks.reserve(kShards);
    for (auto& s : shards_) locks.emplace_back(s.mu);
    std::lock_guard<std::mutex> flk(spill_mu_);
    std::string tmp = std::string(path) + ".tmp";
    FILE* f = std::fopen(tmp.c_str(), "wb");
    if (!f) return false;
    int64_t nrows = 0;
    for (auto& s : shards_)
      nrows += static_cast<int64_t>(s.index.size() + s.cold.size());
    int64_t header[5] = {dim_, opt_, slots_, step_.load(), nrows};
    bool ok = std::fwrite(header, sizeof(int64_t), 5, f) == 5;
    std::vector<float> crow(row_width_);
    for (auto& s : shards_) {
      if (!ok) break;
      for (const auto& kv : s.index) {
        if (std::fwrite(&kv.first, sizeof(int64_t), 1, f) != 1 ||
            std::fwrite(s.pool.data() + kv.second, sizeof(float), row_width_,
                        f) != static_cast<size_t>(row_width_)) {
          ok = false;
          break;
        }
      }
      // spilled (cold) rows are part of the checkpoint too
      for (const auto& kv : s.cold) {
        if (!ok) break;
        if (!spill_f_) { ok = false; break; }
        std::fseek(spill_f_, static_cast<long>(kv.second), SEEK_SET);
        if (std::fread(crow.data(), sizeof(float), row_width_, spill_f_) !=
                static_cast<size_t>(row_width_) ||
            std::fwrite(&kv.first, sizeof(int64_t), 1, f) != 1 ||
            std::fwrite(crow.data(), sizeof(float), row_width_, f) !=
                static_cast<size_t>(row_width_)) {
          ok = false;
        }
      }
    }
    ok = (std::fclose(f) == 0) && ok;
    if (!ok) {
      std::remove(tmp.c_str());
      return false;
    }
    return std::rename(tmp.c_str(), path) == 0;
  }

  bool Load(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return false;
    int64_t header[5];
    if (std::fread(header, sizeof(int64_t), 5, f) != 5 ||
        header[0] != dim_ || header[1] != opt_) {
      std::fclose(f);
      return false;
    }
    // Stage the whole file first; the live table is only touched after the
    // file parses completely, so a truncated/corrupt checkpoint leaves the
    // existing contents intact.
    struct Staged {
      std::unordered_map<int64_t, uint64_t> index;
      std::vector<float> pool;
    };
    std::vector<Staged> staged(kShards);
    std::vector<float> row(row_width_);
    for (int64_t i = 0; i < header[4]; ++i) {
      int64_t key;
      if (std::fread(&key, sizeof(int64_t), 1, f) != 1 ||
          std::fread(row.data(), sizeof(float), row_width_, f) !=
              static_cast<size_t>(row_width_)) {
        std::fclose(f);
        return false;
      }
      Staged& st = staged[ShardOf(key)];
      uint64_t off = st.pool.size();
      st.pool.resize(off + row_width_);
      st.index[key] = off;
      std::memcpy(st.pool.data() + off, row.data(),
                  sizeof(float) * row_width_);
    }
    std::fclose(f);
    // a checkpoint fully replaces table contents (rows auto-created by a
    // warm-up pull before load must not survive and merge with it);
    // everything loads hot — the cold tier restarts empty
    for (int s = 0; s < kShards; ++s) {
      std::lock_guard<std::mutex> lk(shards_[s].mu);
      shards_[s].index = std::move(staged[s].index);
      shards_[s].pool = std::move(staged[s].pool);
      shards_[s].cold.clear();
      shards_[s].touch.clear();
    }
    step_ = header[3];
    return true;
  }

 private:
  static int ShardOf(int64_t key) {
    uint64_t h = static_cast<uint64_t>(key);
    h ^= h >> 33;
    h *= 0xff51afd7ed558ccdULL;
    h ^= h >> 33;
    return static_cast<int>(h % kShards);
  }

  uint64_t AllocRow(Shard& s) {
    uint64_t off = s.pool.size();
    s.pool.resize(off + row_width_, 0.0f);
    return off;
  }

  // caller must hold the shard's mutex
  const float* FindOrCreate(int64_t key, bool create) {
    Shard& s = shards_[ShardOf(key)];
    auto it = s.index.find(key);
    if (it != s.index.end()) {
      s.touch[key] = ++tick_;  // only EXISTING/created rows get a touch
      return s.pool.data() + it->second;
    }
    // SSD tier (reference table/ssd_sparse_table.cc): cold rows live in
    // the spill file and are transparently promoted back on access
    auto cit = s.cold.find(key);
    if (cit != s.cold.end()) {
      s.touch[key] = ++tick_;
      uint64_t off = AllocRow(s);
      {
        std::lock_guard<std::mutex> flk(spill_mu_);
        std::fseek(spill_f_, static_cast<long>(cit->second), SEEK_SET);
        if (std::fread(s.pool.data() + off, sizeof(float), row_width_,
                       spill_f_) != static_cast<size_t>(row_width_)) {
          std::memset(s.pool.data() + off, 0, sizeof(float) * row_width_);
        }
      }
      s.index[key] = off;
      s.cold.erase(cit);
      return s.pool.data() + off;
    }
    if (!create) return nullptr;
    s.touch[key] = ++tick_;
    uint64_t off = AllocRow(s);
    s.index[key] = off;
    float* row = s.pool.data() + off;
    if (init_range_ > 0.0f) {
      std::mt19937_64 rng(seed_ ^ static_cast<uint64_t>(key) * 0x9e3779b9ULL);
      std::uniform_real_distribution<float> dist(-init_range_, init_range_);
      for (int d = 0; d < dim_; ++d) row[d] = dist(rng);
    }
    return row;
  }

  template <typename Fn>
  void RunSharded(int64_t n, Fn fn) {
    int nthreads = static_cast<int>(
        std::min<int64_t>(std::max<int64_t>(n / 1024, 1), 8));
    if (nthreads <= 1) {
      fn(0, 0, 1);
      return;
    }
    std::vector<std::thread> workers;
    workers.reserve(nthreads);
    for (int t = 0; t < nthreads; ++t) {
      workers.emplace_back([&, t]() { fn(0, t, nthreads); });
    }
    for (auto& w : workers) w.join();
  }

  int dim_, opt_, slots_, row_width_;
  uint64_t seed_;
  float init_range_, beta1_, beta2_, eps_;
  std::atomic<int64_t> step_;
  std::atomic<uint64_t> tick_{0};
  FILE* spill_f_ = nullptr;
  std::string spill_path_;
  std::mutex spill_mu_;
  Shard shards_[kShards];
};

// Dense table: one contiguous parameter block with host optimizer — the
// analogue of distributed/table/common_dense_table.cc.
class DenseTable {
 public:
  DenseTable(int64_t size, int opt, float beta1, float beta2, float eps)
      : opt_(opt), beta1_(beta1), beta2_(beta2), eps_(eps), step_(0),
        data_(size, 0.0f) {
    if (opt_ == kAdagrad) slot1_.assign(size, 0.0f);
    if (opt_ == kAdam) {
      slot1_.assign(size, 0.0f);
      slot2_.assign(size, 0.0f);
    }
  }

  int64_t size() const { return static_cast<int64_t>(data_.size()); }

  void Set(const float* src) {
    std::memcpy(data_.data(), src, sizeof(float) * data_.size());
  }

  void Pull(float* out) {
    std::memcpy(out, data_.data(), sizeof(float) * data_.size());
  }

  void Push(const float* g, float lr) {
    int64_t t = ++step_;
    float bc1 = 1.0f - std::pow(beta1_, static_cast<float>(t));
    float bc2 = 1.0f - std::pow(beta2_, static_cast<float>(t));
    int64_t n = size();
    switch (opt_) {
      case kSGD:
        for (int64_t i = 0; i < n; ++i) data_[i] -= lr * g[i];
        break;
      case kAdagrad:
        for (int64_t i = 0; i < n; ++i) {
          slot1_[i] += g[i] * g[i];
          data_[i] -= lr * g[i] / (std::sqrt(slot1_[i]) + eps_);
        }
        break;
      case kAdam:
        for (int64_t i = 0; i < n; ++i) {
          slot1_[i] = beta1_ * slot1_[i] + (1.0f - beta1_) * g[i];
          slot2_[i] = beta2_ * slot2_[i] + (1.0f - beta2_) * g[i] * g[i];
          data_[i] -= lr * (slot1_[i] / bc1) /
                      (std::sqrt(slot2_[i] / bc2) + eps_);
        }
        break;
    }
  }

 private:
  int opt_;
  float beta1_, beta2_, eps_;
  std::atomic<int64_t> step_;
  std::vector<float> data_, slot1_, slot2_;
};

}  // namespace

extern "C" {

void* ps_sparse_create(int dim, int optimizer, uint64_t seed,
                       float init_range, float beta1, float beta2,
                       float eps) {
  return new SparseTable(dim, optimizer, seed, init_range, beta1, beta2, eps);
}

void ps_sparse_destroy(void* t) { delete static_cast<SparseTable*>(t); }

int64_t ps_sparse_size(void* t) {
  return static_cast<SparseTable*>(t)->size();
}

void ps_sparse_pull(void* t, const int64_t* keys, int64_t n, float* out,
                    int create_missing) {
  static_cast<SparseTable*>(t)->Pull(keys, n, out, create_missing != 0);
}

void ps_sparse_push(void* t, const int64_t* keys, int64_t n,
                    const float* grads, float lr) {
  static_cast<SparseTable*>(t)->Push(keys, n, grads, lr);
}

int ps_sparse_row_width(void* t) {
  return static_cast<SparseTable*>(t)->row_width();
}

void ps_sparse_export_rows(void* t, const int64_t* keys, int64_t n,
                           float* out, int create_missing) {
  static_cast<SparseTable*>(t)->ExportRows(keys, n, out,
                                           create_missing != 0);
}

void ps_sparse_import_rows(void* t, const int64_t* keys, int64_t n,
                           const float* data) {
  static_cast<SparseTable*>(t)->ImportRows(keys, n, data);
}

int ps_sparse_save(void* t, const char* path) {
  return static_cast<SparseTable*>(t)->Save(path) ? 1 : 0;
}

int ps_sparse_spill(void* t, const char* path, int64_t max_hot) {
  return static_cast<SparseTable*>(t)->Spill(path, max_hot) ? 1 : 0;
}

int64_t ps_sparse_hot_rows(void* t) {
  return static_cast<SparseTable*>(t)->hot_rows();
}

int ps_sparse_load(void* t, const char* path) {
  return static_cast<SparseTable*>(t)->Load(path) ? 1 : 0;
}

void* ps_dense_create(int64_t size, int optimizer, float beta1, float beta2,
                      float eps) {
  return new DenseTable(size, optimizer, beta1, beta2, eps);
}

void ps_dense_destroy(void* t) { delete static_cast<DenseTable*>(t); }

int64_t ps_dense_size(void* t) { return static_cast<DenseTable*>(t)->size(); }

void ps_dense_set(void* t, const float* src) {
  static_cast<DenseTable*>(t)->Set(src);
}

void ps_dense_pull(void* t, float* out) {
  static_cast<DenseTable*>(t)->Pull(out);
}

void ps_dense_push(void* t, const float* g, float lr) {
  static_cast<DenseTable*>(t)->Push(g, lr);
}

}  // extern "C"
