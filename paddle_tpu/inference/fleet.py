"""paddle_tpu.inference.fleet — self-healing serving fleet (ISSUE 19).

The reference platform is as much a fleet manager as a trainer: serving
survives membership churn, scales with load, and rolls new model
versions live. This module closes that loop over the pieces the repo
already has — the PR 10/11 ``InferenceServer`` / ``DecodeServer``, the
PR 7 ``FileCoordinator`` shared-FS membership protocol, the PR 13
checkpoint committer's ``latest_valid_step()`` anchors, the EQuARX
weight quantizer, and the ``telemetry/slo.py`` burn-rate rules (now
action-bearing):

- **Fleet = N members**, each one whole server instance built by a
  :class:`ModelGeneration` factory. ``submit`` routes to the member
  with the lowest modeled wait, so admission control stays per-member
  (a member in trouble sheds only its own queue). Membership is
  advertised through heartbeated member files under the
  ``FileCoordinator`` root — two fleets sharing the root see each
  other's members, the same shared-FS protocol the elastic trainer
  uses — and stale members are reaped on poll.

- **SLO-driven autoscaling** — ``poll_once`` (or the background control
  thread) scales up on modeled wait or queue depth; a burn-rate rule
  upgraded with ``rule.on_alert(fleet.scale_up_action())`` scales up
  the moment shedding crosses the SLO threshold. Scale direction is
  counted in ``fleet_scale_events_total{direction}``; live size is the
  ``fleet_replicas`` gauge. New members prime their compiled-executor
  set from the persistent ``executor_cache`` manifest, so scale-up does
  not pay ``serving_recompiles_total`` cold starts.

- **Zero-downtime hot-swap with automatic rollback** — a poller watches
  ``CheckpointManager.latest_valid_step()``; a newly committed step is
  published (quantized via ``inference.quant`` by the generation
  factory) and **canaried**: a shadow member takes a copy of a fraction
  of live traffic (results discarded, so user traffic is never served
  by an unvetted model), and its completion rate, failure burn, output
  sanity, and latency are compared against the incumbent members that
  served the primary copies. A failing canary is rolled back — the
  fleet stays on the incumbent generation, whose layer-cache entry was
  pinned (``inference.pin_layer``) so the overwritten artifact on disk
  cannot poison a rebuild — and the step is remembered as rejected. A
  passing canary is promoted by rolling members one at a time through
  drain → rebuild-at-new-generation → rejoin, preserving the
  ``accounted()`` zero-silent-loss invariant fleet-wide (every server
  instance ever spawned stays in the accounting universe).

Typical use::

    gen0 = predictor_generation(0, prefix, quant=("int8", None))
    fleet = ServingFleet(gen0, config=FleetConfig(min_members=2),
                         membership_root=coord.root,
                         watch_fn=manager.latest_valid_step,
                         publish_fn=publish)
    with fleet:
        req = fleet.submit([x], deadline_s=0.2)
"""
from __future__ import annotations

import itertools
import os
import json
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .serving import (COMPLETED, FAILED, InferenceServer, ServingConfig,
                      predictor_executor)

__all__ = ["FleetConfig", "ModelGeneration", "ServingFleet",
           "predictor_generation"]


def _default_sanity(outputs) -> bool:
    """A served result must at least be finite — the cheapest possible
    model-quality gate, and exactly what a bit-rotted / NaN-poisoned
    checkpoint fails."""
    try:
        for o in outputs or []:
            a = np.asarray(o)
            if a.dtype.kind == "f" and not np.isfinite(a).all():
                return False
        return True
    except Exception:
        return False


class ModelGeneration:
    """One deployable model version: an id (checkpoint step), a factory
    for fresh server instances, and the hooks a rollout needs — priming
    (executor cache), layer-cache pin/release, and the canary's output
    sanity check."""

    def __init__(self, gen_id: int, make_server: Callable[[], object],
                 prime: Optional[Callable[[object], int]] = None,
                 pin: Optional[Callable[[], None]] = None,
                 release: Optional[Callable[[], None]] = None,
                 sanity_fn: Optional[Callable] = None,
                 meta: Optional[dict] = None):
        self.gen_id = int(gen_id)
        self._make_server = make_server
        self._prime = prime
        self._pin = pin
        self._release = release
        self.sanity_fn = sanity_fn or _default_sanity
        self.meta = dict(meta or {})
        self._pinned = False

    def build(self) -> object:
        """A fresh UNSTARTED server for this generation; primed (compiled
        executors + warm_start) when a prime hook was provided."""
        server = self._make_server()
        if self._prime is not None:
            try:
                self._prime(server)
            except Exception:
                pass  # priming is an optimization, never a build failure
        return server

    def pin(self):
        if self._pin is not None and not self._pinned:
            self._pin()
            self._pinned = True

    def release(self):
        if self._release is not None and self._pinned:
            self._release()
            self._pinned = False


def predictor_generation(gen_id: int, prefix: str, quant=None,
                         replicas: int = 1,
                         serving: Optional[ServingConfig] = None,
                         executor_cache=None,
                         sanity_fn: Optional[Callable] = None,
                         executor_wrap: Optional[Callable] = None
                         ) -> ModelGeneration:
    """Build a :class:`ModelGeneration` over the Predictor path for the
    artifact currently at ``prefix``. The layer-cache key is captured
    NOW and pinned for the generation's lifetime, so a later hot-swap
    overwriting the files cannot change what this generation serves —
    the rollback guarantee (ISSUE 19 satellite)."""
    from . import (Config, Predictor, layer_cache_key, pin_layer,
                   unpin_layer)
    key = layer_cache_key(prefix, quant)

    def make_server():
        cfg = Config(prefix)
        if quant is not None:
            cfg.enable_weight_quantize(*quant)
        preds = [Predictor(cfg, layer_key=key) for _ in range(replicas)]
        fns = [predictor_executor(p) for p in preds]
        if executor_wrap is not None:
            # e.g. a fixed service pad making capacity machine-independent
            fns = [executor_wrap(fn) for fn in fns]
        server = InferenceServer(fns, config=serving)
        if executor_cache is not None:
            from . import executor_cache as ec
            akey = ec.artifact_key(prefix, quant)
            ec.prime(server, akey, executor_cache)
            ec.attach(server, akey, executor_cache)
        return server

    gen = ModelGeneration(gen_id, make_server,
                          pin=lambda: pin_layer(key),
                          release=lambda: unpin_layer(key),
                          sanity_fn=sanity_fn,
                          meta={"prefix": prefix, "quant": quant,
                                "layer_key": key})
    gen.pin()
    return gen


class FleetConfig:
    """Knobs for :class:`ServingFleet` (defaults sized for tests/CPU)."""

    def __init__(self,
                 min_members: int = 1,
                 max_members: int = 4,
                 scale_up_wait_s: float = 0.5,
                 scale_up_queue_depth: int = 32,
                 scale_down_idle_s: float = 10.0,
                 cooldown_s: float = 2.0,
                 poll_interval_s: float = 0.25,
                 member_stale_after_s: float = 10.0,
                 canary_shadow_fraction: float = 0.2,
                 canary_min_shadow: int = 8,
                 canary_timeout_s: float = 30.0,
                 canary_min_completion_frac: float = 0.5,
                 canary_max_failure_frac: float = 0.2,
                 canary_latency_factor: float = 3.0,
                 canary_latency_slack_s: float = 0.05,
                 drain_timeout_s: float = 30.0,
                 seed: int = 0):
        if min_members < 1 or max_members < min_members:
            raise ValueError("need 1 <= min_members <= max_members")
        self.min_members = int(min_members)
        self.max_members = int(max_members)
        self.scale_up_wait_s = float(scale_up_wait_s)
        self.scale_up_queue_depth = int(scale_up_queue_depth)
        self.scale_down_idle_s = float(scale_down_idle_s)
        self.cooldown_s = float(cooldown_s)
        self.poll_interval_s = float(poll_interval_s)
        self.member_stale_after_s = float(member_stale_after_s)
        self.canary_shadow_fraction = float(canary_shadow_fraction)
        self.canary_min_shadow = int(canary_min_shadow)
        self.canary_timeout_s = float(canary_timeout_s)
        self.canary_min_completion_frac = float(canary_min_completion_frac)
        self.canary_max_failure_frac = float(canary_max_failure_frac)
        self.canary_latency_factor = float(canary_latency_factor)
        self.canary_latency_slack_s = float(canary_latency_slack_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.seed = int(seed)


class _Member:
    """One fleet member: a whole server instance at some generation."""

    _ids = itertools.count(0)

    def __init__(self, server, generation: ModelGeneration):
        self.idx = next(_Member._ids)
        self.server = server
        self.generation = generation
        self.active = False          # taking fleet traffic
        self.name = f"m{self.idx}"


class _Canary:
    """Bookkeeping for an in-flight canary rollout: the shadow member
    plus the (primary, shadow) request pairs used for the verdict."""

    def __init__(self, member: _Member, generation: ModelGeneration):
        self.member = member
        self.generation = generation
        self.pairs: List[tuple] = []   # (primary Request, shadow Request)
        self.lock = threading.Lock()


class ServingFleet:
    """N serving members + autoscaler + hot-swap canary controller.

    ``generation`` is the initial :class:`ModelGeneration`; ``watch_fn``
    (e.g. ``CheckpointManager.latest_valid_step``) and ``publish_fn``
    (step -> ModelGeneration, typically quantizing via
    ``inference.quant``) enable the hot-swap poller. All control actions
    run through :meth:`poll_once` — call it directly for deterministic
    tests, or ``start(control=True)`` for the background thread.
    """

    def __init__(self, generation: ModelGeneration,
                 config: Optional[FleetConfig] = None,
                 membership_root: Optional[str] = None,
                 fleet_id: str = "serving",
                 host: Optional[str] = None,
                 watch_fn: Optional[Callable[[], Optional[int]]] = None,
                 publish_fn: Optional[Callable[[int], ModelGeneration]]
                 = None):
        self.cfg = config or FleetConfig()
        self.generation = generation
        self.fleet_id = fleet_id
        self.host = host or f"pid-{os.getpid()}"
        self._watch_fn = watch_fn
        self._publish_fn = publish_fn
        self._members: List[_Member] = []
        self._all_servers: List[object] = []   # every server ever, for
        #                                        fleet-wide accounted()
        self._canary: Optional[_Canary] = None
        self._rejected_steps: set = set()
        self._lock = threading.RLock()
        self._rng = random.Random(self.cfg.seed)
        self._started = False
        self._stopped = False
        self._draining = False
        self._last_scale = 0.0
        self._idle_since: Optional[float] = None
        self._control: Optional[threading.Thread] = None
        self._prev_sigterm = None
        self._shutdowns = 0
        self.last_canary_checks: Optional[dict] = None
        # fleet-owned control-plane accounting (mirrors telemetry)
        self.counts: Dict[str, int] = {
            "scale_up": 0, "scale_down": 0, "promoted": 0,
            "rolled_back": 0, "canary_checks": 0, "hot_swap_polls": 0}
        self._members_dir = None
        if membership_root is not None:
            self._members_dir = os.path.join(membership_root, "members",
                                             fleet_id)
            os.makedirs(self._members_dir, exist_ok=True)

    # -- lifecycle -----------------------------------------------------------

    def start(self, control: bool = False) -> "ServingFleet":
        if self._started:
            return self
        self._started = True
        with self._lock:
            while len(self._members) < self.cfg.min_members:
                self._add_member_locked(reason="bootstrap", count=False)
        self._heartbeat()
        self._set_replica_gauge()
        if control:
            self._control = threading.Thread(
                target=self._control_loop, name="fleet-control", daemon=True)
            self._control.start()
        return self

    def __enter__(self) -> "ServingFleet":
        return self.start()

    def __exit__(self, *exc):
        self.shutdown(drain=not any(exc))

    def shutdown(self, drain: bool = True):
        """Drain every member (and any canary) exactly once; the fleet
        admits nothing afterwards."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            self._draining = True
            members = list(self._members)
            canary = self._canary
            self._shutdowns += 1
        for m in members:
            m.active = False
            m.server.shutdown(drain=drain,
                              timeout=self.cfg.drain_timeout_s)
        if canary is not None:
            canary.member.server.shutdown(
                drain=drain, timeout=self.cfg.drain_timeout_s)
        if self._control is not None:
            self._control.join(timeout=2.0)
        self._remove_member_files()
        self._set_replica_gauge()

    def install_sigterm_drain(self):
        """SIGTERM -> one graceful fleet-wide drain (every member exactly
        once), chaining any previous handler — the fleet analogue of
        ``InferenceServer.install_sigterm_drain``."""
        import signal
        self._prev_sigterm = signal.getsignal(signal.SIGTERM)

        def _handler(signum, frame):
            self._draining = True
            threading.Thread(target=self.shutdown, name="fleet-drain",
                             kwargs={"drain": True}, daemon=True).start()
            prev = self._prev_sigterm
            if callable(prev) and prev not in (signal.SIG_IGN,
                                               signal.SIG_DFL):
                prev(signum, frame)

        signal.signal(signal.SIGTERM, _handler)

    @property
    def draining(self) -> bool:
        return self._draining

    # -- membership ----------------------------------------------------------

    def _member_file(self, member: _Member) -> Optional[str]:
        if self._members_dir is None:
            return None
        return os.path.join(self._members_dir,
                            f"{self.host}-{member.name}.json")

    def _heartbeat(self):
        """Advertise every active member under the coordinator root
        (atomic replace, the FileCoordinator write discipline)."""
        if self._members_dir is None:
            return
        with self._lock:
            members = [m for m in self._members if m.active]
        for m in members:
            path = self._member_file(m)
            tmp = path + ".tmp"
            try:
                with open(tmp, "w") as f:
                    json.dump({"host": self.host, "member": m.name,
                               "generation": m.generation.gen_id,
                               "t": time.time()}, f)
                os.replace(tmp, path)
            except OSError:
                pass   # shared FS hiccup; next heartbeat retries

    def _remove_member_files(self):
        if self._members_dir is None:
            return
        for m in self._members:
            path = self._member_file(m)
            try:
                os.remove(path)
            except OSError:
                pass

    def live_members(self) -> List[dict]:
        """Cluster-wide membership view: every non-stale member file
        under the root (includes members other fleets/hosts advertise)."""
        if self._members_dir is None:
            with self._lock:
                return [{"host": self.host, "member": m.name,
                         "generation": m.generation.gen_id}
                        for m in self._members if m.active]
        out = []
        now = time.time()
        try:
            names = os.listdir(self._members_dir)
        except OSError:
            return out
        for fn in names:
            if not fn.endswith(".json"):
                continue
            full = os.path.join(self._members_dir, fn)
            try:
                mtime = os.path.getmtime(full)
                with open(full) as f:
                    payload = json.load(f)
            except (OSError, ValueError):
                continue   # mid-replace: next poll sees it
            if now - mtime > self.cfg.member_stale_after_s:
                continue
            out.append(payload)
        return out

    def reap_stale_members(self) -> int:
        """Remove member files whose heartbeat went stale (a fleet that
        died without shutdown); returns the number reaped."""
        if self._members_dir is None:
            return 0
        reaped = 0
        now = time.time()
        try:
            names = os.listdir(self._members_dir)
        except OSError:
            return 0
        for fn in names:
            if not fn.endswith(".json"):
                continue
            full = os.path.join(self._members_dir, fn)
            try:
                if now - os.path.getmtime(full) \
                        > self.cfg.member_stale_after_s:
                    os.remove(full)
                    reaped += 1
            except OSError:
                continue
        return reaped

    def _add_member_locked(self, reason: str, count: bool = True,
                           generation: Optional[ModelGeneration] = None
                           ) -> _Member:
        gen = generation or self.generation
        member = _Member(gen.build(), gen)
        member.server.start()
        self._members.append(member)
        self._all_servers.append(member.server)
        member.active = True
        if count:
            self.counts["scale_up"] += 1
            self._count("fleet_scale_events_total", direction="up",
                        reason=reason)
        self._last_scale = time.monotonic()
        return member

    def _retire_member(self, member: _Member, direction: str, reason: str):
        member.active = False
        path = self._member_file(member)
        if path is not None:
            try:
                os.remove(path)
            except OSError:
                pass
        member.server.shutdown(drain=True,
                               timeout=self.cfg.drain_timeout_s)
        with self._lock:
            if member in self._members:
                self._members.remove(member)
            if direction == "down":
                self.counts["scale_down"] += 1
                self._count("fleet_scale_events_total", direction="down",
                            reason=reason)
            self._last_scale = time.monotonic()
        self._set_replica_gauge()

    # -- traffic -------------------------------------------------------------

    def _pick_member(self, include_inactive: bool = False) -> _Member:
        with self._lock:
            members = [m for m in self._members if m.active]
            if not members and include_inactive:
                # draining/stopped: any member will shed the admission
                # with cause "draining", which keeps accounting closed
                members = list(self._members)
        if not members:
            raise RuntimeError("fleet has no active members")
        return min(members, key=lambda m: m.server.modeled_wait())

    def _maybe_shadow(self, kind: str, args: tuple, kwargs: dict,
                      primary):
        """Mirror a fraction of live traffic onto the canary member;
        shadow results are never returned to callers, only judged."""
        with self._lock:
            canary = self._canary
            if canary is None or self._stopped:
                return
            if self._rng.random() >= self.cfg.canary_shadow_fraction:
                return
        try:
            if kind == "generate":
                shadow = canary.member.server.submit_generate(
                    *args, **kwargs)
            else:
                shadow = canary.member.server.submit(*args, **kwargs)
        except Exception:
            return   # a canary that cannot even admit fails the verdict
        #              via its completion fraction, not the caller
        with canary.lock:
            canary.pairs.append((primary, shadow))

    def submit(self, inputs: Sequence[np.ndarray],
               deadline_s: Optional[float] = None,
               tokens: Optional[int] = None):
        """Admit one request to the least-loaded member (and possibly a
        shadow copy to the canary)."""
        if self._draining or self._stopped:
            member = self._pick_member(include_inactive=True)
            return member.server.submit(inputs, deadline_s=deadline_s,
                                        tokens=tokens)
        member = self._pick_member()
        req = member.server.submit(inputs, deadline_s=deadline_s,
                                   tokens=tokens)
        self._maybe_shadow(
            "infer",
            ([np.copy(x) for x in inputs],),
            {"deadline_s": deadline_s, "tokens": tokens}, req)
        return req

    def submit_generate(self, prompt_tokens, max_new_tokens: int,
                        deadline_s: Optional[float] = None,
                        eos_token: Optional[int] = None):
        """Decode-fleet admission (members must be DecodeServers)."""
        member = self._pick_member()
        req = member.server.submit_generate(
            prompt_tokens, max_new_tokens, deadline_s=deadline_s,
            eos_token=eos_token)
        if not (self._draining or self._stopped):
            self._maybe_shadow(
                "generate", (list(prompt_tokens), max_new_tokens),
                {"deadline_s": deadline_s, "eos_token": eos_token}, req)
        return req

    def modeled_wait(self, rows: int = 1) -> float:
        with self._lock:
            members = [m for m in self._members if m.active]
        if not members:
            return float("inf")
        return min(m.server.modeled_wait(rows) for m in members)

    # -- autoscaling ---------------------------------------------------------

    def scale_up_action(self) -> Callable:
        """An ``SloRule.on_alert`` action: burn-rate breach -> scale up.

        ::

            rule.on_alert(fleet.scale_up_action())
        """

        def _action(rule, burn):
            self.request_scale_up(reason=f"slo_{rule.name}")

        return _action

    def request_scale_up(self, reason: str = "manual") -> bool:
        """Add a member now (SLO actions and operators call this); false
        when at max_members or stopped. Deliberately ignores the
        cooldown — an SLO breach IS the arbiter."""
        with self._lock:
            if self._stopped or self._draining:
                return False
            if sum(1 for m in self._members if m.active) \
                    >= self.cfg.max_members:
                return False
            self._add_member_locked(reason=reason)
        self._heartbeat()
        self._set_replica_gauge()
        return True

    def _autoscale(self, now: float):
        with self._lock:
            members = [m for m in self._members if m.active]
            n = len(members)
            if not members or self._stopped or self._draining:
                return
            in_cooldown = now - self._last_scale < self.cfg.cooldown_s
            depth = sum(m.server.stats()["queue_depth"] for m in members)
            wait = min(m.server.modeled_wait() for m in members)
            busy = depth > 0 or wait > 0.001
            if busy:
                self._idle_since = None
            elif self._idle_since is None:
                self._idle_since = now
            if in_cooldown:
                return
            if n < self.cfg.max_members and (
                    wait > self.cfg.scale_up_wait_s
                    or depth > self.cfg.scale_up_queue_depth):
                reason = ("modeled_wait" if wait > self.cfg.scale_up_wait_s
                          else "queue_depth")
                self._add_member_locked(reason=reason)
                self._set_replica_gauge()
                return
            idle_long = (self._idle_since is not None and
                         now - self._idle_since
                         >= self.cfg.scale_down_idle_s)
            victim = None
            if n > self.cfg.min_members and idle_long:
                victim = members[-1]
        if victim is not None:
            self._retire_member(victim, direction="down", reason="idle")

    # -- hot swap ------------------------------------------------------------

    def _maybe_hot_swap(self):
        if self._watch_fn is None or self._publish_fn is None:
            return
        if self._canary is not None or self._stopped or self._draining:
            return
        self.counts["hot_swap_polls"] += 1
        try:
            step = self._watch_fn()
        except Exception:
            return
        if step is None:
            return
        step = int(step)
        if step <= self.generation.gen_id or step in self._rejected_steps:
            return
        try:
            gen = self._publish_fn(step)
        except Exception:
            # a checkpoint that cannot even be published is rejected the
            # same way a failing canary is — don't retry it every poll
            self._rejected_steps.add(step)
            self._count("hot_swap_total", outcome="rolled_back")
            self.counts["rolled_back"] += 1
            return
        self.hot_swap(gen)

    def hot_swap(self, generation: ModelGeneration) -> bool:
        """Canary ``generation`` against live traffic; promote it to
        every member on pass, roll it back on fail. Returns promotion."""
        generation.pin()
        self.generation.pin()   # the rollback target must stay loadable
        canary_member = _Member(generation.build(), generation)
        canary_member.server.start()
        with self._lock:
            self._all_servers.append(canary_member.server)
            self._canary = _Canary(canary_member, generation)
        healthy, checks = self._canary_verdict(self._canary)
        self.counts["canary_checks"] += 1
        self._count("canary_health_checks_total",
                    outcome="pass" if healthy else "fail")
        with self._lock:
            canary = self._canary
            self._canary = None   # stop shadowing before the rollout
        if not healthy:
            self._rejected_steps.add(generation.gen_id)
            canary.member.server.shutdown(
                drain=True, timeout=self.cfg.drain_timeout_s)
            generation.release()
            self.counts["rolled_back"] += 1
            self._count("hot_swap_total", outcome="rolled_back")
            self.last_canary_checks = checks
            return False
        # promote: the canary already serves the new generation — adopt
        # it as a member, then roll the incumbents one at a time,
        # rebuilding each at the new generation so capacity is preserved
        # through the rollout (the adopted canary covers the first)
        old_gen = self.generation
        with self._lock:
            self.generation = generation
            incumbents = [m for m in self._members
                          if m.active and m.generation is old_gen]
            target = len([m for m in self._members if m.active])
            canary.member.active = True
            self._members.append(canary.member)
        for m in incumbents:
            # one-at-a-time: drain this member out of rotation while the
            # rest of the fleet (including the adopted canary) serves
            self._retire_member(m, direction="roll", reason="hot_swap")
            with self._lock:
                if len([x for x in self._members if x.active]) < target:
                    self._add_member_locked(reason="hot_swap", count=False,
                                            generation=generation)
        old_gen.release()
        self.counts["promoted"] += 1
        self._count("hot_swap_total", outcome="promoted")
        self._heartbeat()
        self._set_replica_gauge()
        self.last_canary_checks = checks
        return True

    def _canary_verdict(self, canary: _Canary):
        """Judge the canary on its shadow traffic: enough samples,
        completion fraction, failure burn, output sanity, and latency
        against the incumbent primaries of the SAME requests."""
        cfg = self.cfg
        deadline = time.monotonic() + cfg.canary_timeout_s
        while time.monotonic() < deadline:
            with canary.lock:
                done = sum(1 for _, s in canary.pairs if s.done())
            if done >= cfg.canary_min_shadow:
                break
            if self._stopped:
                break
            time.sleep(0.01)
        with canary.lock:
            pairs = list(canary.pairs)
        done = [(p, s) for p, s in pairs if s.done()]
        completed = [(p, s) for p, s in done if s.state == COMPLETED]
        failed = [s for _, s in done if s.state == FAILED]
        sanity_fn = canary.generation.sanity_fn
        insane = sum(1 for _, s in completed
                     if not sanity_fn(s.outputs))
        checks = {
            "enough_shadow": len(done) >= cfg.canary_min_shadow,
            "completion": (len(done) > 0 and
                           len(completed) >= cfg.canary_min_completion_frac
                           * len(done)),
            "failure_burn": len(failed) <= cfg.canary_max_failure_frac
            * max(1, len(done)),
            "sanity": insane == 0,
        }
        # latency: the primaries of the shadowed pairs are the incumbent
        # baseline for the very same traffic
        base = [p.latency for p, _ in completed
                if p.done() and p.state == COMPLETED
                and p.latency is not None]
        shad = [s.latency for _, s in completed if s.latency is not None]
        if base and shad:
            checks["latency"] = (
                float(np.median(shad)) <= cfg.canary_latency_factor
                * float(np.median(base)) + cfg.canary_latency_slack_s)
        else:
            checks["latency"] = True
        checks["shadow_count"] = len(done)
        checks["insane_outputs"] = insane
        healthy = all(v for k, v in checks.items()
                      if isinstance(v, bool))
        return healthy, checks

    # -- control loop --------------------------------------------------------

    def poll_once(self, now: Optional[float] = None):
        """One control-plane tick: heartbeat + reap + autoscale + the
        hot-swap poller. Deterministic entry point for tests/tools."""
        now = time.monotonic() if now is None else now
        self._heartbeat()
        self.reap_stale_members()
        self._autoscale(now)
        self._maybe_hot_swap()
        self._set_replica_gauge()

    def _control_loop(self):
        while not self._stopped:
            try:
                self.poll_once()
            except Exception:
                pass   # the control plane must never kill serving
            time.sleep(self.cfg.poll_interval_s)

    # -- accounting / telemetry ----------------------------------------------

    def stats(self) -> Dict[str, object]:
        """Fleet-wide aggregate over every server EVER owned (retired
        generations and rolled-back canaries included), plus control-
        plane counters."""
        with self._lock:
            servers = list(self._all_servers)
            members = [m for m in self._members if m.active]
            counts = dict(self.counts)
            gen_id = self.generation.gen_id
        agg = {k: 0 for k in ("submitted", "completed", "shed", "expired",
                              "failed", "failovers", "requeues", "batches",
                              "recompiles", "queue_depth")}
        shed_causes: Dict[str, int] = {}
        for s in servers:
            st = s.stats()
            for k in agg:
                agg[k] += int(st.get(k, 0))
            for cause, n in st.get("shed_causes", {}).items():
                shed_causes[cause] = shed_causes.get(cause, 0) + int(n)
        agg.update({
            "shed_causes": shed_causes,
            "members": len(members),
            "servers_ever": len(servers),
            "generation": gen_id,
            "member_generations": sorted(m.generation.gen_id
                                         for m in members),
            "scale_ups": counts["scale_up"],
            "scale_downs": counts["scale_down"],
            "promoted": counts["promoted"],
            "rolled_back": counts["rolled_back"],
            "canary_checks": counts["canary_checks"],
        })
        return agg

    def accounted(self) -> bool:
        """Zero silent loss, fleet-wide: every request ever submitted to
        ANY server this fleet spawned — members, rolled generations,
        rolled-back canaries, shadow copies — is in a terminal bucket."""
        with self._lock:
            servers = list(self._all_servers)
        return all(s.accounted() for s in servers)

    def _set_replica_gauge(self):
        self._gauge("fleet_replicas", len(self.live_members()))

    def _count(self, name: str, n: float = 1, **labels):
        from .. import telemetry
        if telemetry.enabled():
            telemetry.counter(name, "").inc(n, **labels)

    def _gauge(self, name: str, v: float):
        from .. import telemetry
        if telemetry.enabled():
            telemetry.gauge(name, "").set(v)
