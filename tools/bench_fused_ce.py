"""A/B the chunked LM-head CE (ops/chunked_ce.py) against the dense
logits path at the bench GPT config, on a real chip.

Run: python tools/bench_fused_ce.py [chunk ...]
Prints tok/s for the dense path and each chunk size; if a chunk wins,
switch bench_gpt's loss to GPTForPretraining.fused_head_loss.
Set SMOKE=1 for a tiny CPU-sized config (plumbing check only).
(Only a host scalar fetch is a trustworthy sync through the device
tunnel — see bench.py `_timed_steps`.)
"""
import os
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.jit.functionalization import functional_call, state_of
    from paddle_tpu.text.models import GPTForPretraining

    smoke = os.environ.get("SMOKE") == "1"
    if smoke:
        cfg = dict(vocab_size=512, hidden_size=64, num_layers=2,
                   num_heads=4, max_position_embeddings=64)
        batch, seq = 2, 32
        chunks = [int(a) for a in sys.argv[1:]] or [128]
        iters, warmup = 3, 2
    else:
        cfg = dict(vocab_size=50304, hidden_size=768, num_layers=12,
                   num_heads=12, max_position_embeddings=1024)
        batch, seq = 8, 1024
        chunks = [int(a) for a in sys.argv[1:]] or [4192, 8384, 16768]
        iters, warmup = 12, 8

    paddle.seed(0)
    build_mesh({"data": 1})
    model = GPTForPretraining(tensor_parallel=False, attn_dropout=0.0,
                              hidden_dropout=0.0, **cfg)
    if not smoke:
        model.bfloat16()
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg["vocab_size"], (batch, seq)),
                      jnp.int32)
    lbl = jnp.asarray(rng.randint(0, cfg["vocab_size"], (batch, seq)),
                      jnp.int32)

    class FusedLoss(nn.Layer):
        def __init__(self, model, chunk):
            super().__init__()
            self.model = model
            self._chunk = chunk

        def forward(self, ids, lbl):
            return self.model.fused_head_loss(ids, lbl, chunk=self._chunk)

    def timed(step, params):
        p = params
        for _ in range(warmup):
            l, p = step(p)
        float(l)
        t0 = time.perf_counter()
        for _ in range(iters):
            l, p = step(p)
        float(l)
        return batch * seq * iters / (time.perf_counter() - t0)

    params, buffers = state_of(model)

    @jax.jit
    def dense_step(p):
        def lf(p):
            out, _ = functional_call(model, p, buffers, ids)
            return nn.functional.cross_entropy(out, lbl)
        l, g = jax.value_and_grad(lf)(p)
        return l, jax.tree.map(lambda a, b: a - 1e-4 * b, p, g)

    print(f"dense logits path : {timed(dense_step, params):,.0f} tok/s")

    for chunk in chunks:
        wrapper = FusedLoss(model, chunk)
        wp, wb = state_of(wrapper)

        @jax.jit
        def fused_step(p, wb=wb):
            def lf(p):
                out, _ = functional_call(wrapper, p, wb, ids, lbl)
                return out
            l, g = jax.value_and_grad(lf)(p)
            return l, jax.tree.map(lambda a, b: a - 1e-4 * b, p, g)

        print(f"chunked CE {chunk:6d}: {timed(fused_step, wp):,.0f} tok/s")


if __name__ == "__main__":
    main()
