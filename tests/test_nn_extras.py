"""Tests for nn additions: diag_embed/gather_tree/temporal_shift,
dice_loss/hsigmoid_loss (+HSigmoidLoss layer), BeamSearchDecoder +
dynamic_decode, Adadelta optimizer, jit/io/utils compat shims.

Reference surfaces: python/paddle/nn/functional/extension.py, loss.py,
python/paddle/nn/decode.py, python/paddle/optimizer/adadelta.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import jax.numpy as jnp


def test_diag_embed_values_and_offset():
    x = paddle.to_tensor(np.array([[1.0, 2.0, 3.0]], dtype="float32"))
    out = np.asarray(nn.functional.diag_embed(x))
    assert out.shape == (1, 3, 3)
    np.testing.assert_allclose(np.diag(out[0]), [1, 2, 3])
    off = np.asarray(nn.functional.diag_embed(x, offset=1))
    assert off.shape == (1, 4, 4)
    np.testing.assert_allclose(np.diag(off[0], k=1), [1, 2, 3])
    neg = np.asarray(nn.functional.diag_embed(x, offset=-1))
    np.testing.assert_allclose(np.diag(neg[0], k=-1), [1, 2, 3])


def test_diag_embed_dim_placement():
    x = paddle.ones([2, 3])
    out = nn.functional.diag_embed(x, dim1=0, dim2=2)
    assert out.shape == (3, 2, 3)


def test_temporal_shift():
    # 2 videos x 2 segments, 4 channels
    x = np.arange(2 * 2 * 4 * 1 * 1, dtype="float32").reshape(4, 4, 1, 1)
    out = np.asarray(nn.functional.temporal_shift(x, seg_num=2,
                                                  shift_ratio=0.25))
    assert out.shape == (4, 4, 1, 1)
    x5 = x.reshape(2, 2, 4, 1, 1)
    # channel 0 shifted left: t=0 gets t=1's value, t=1 gets 0
    assert out.reshape(2, 2, 4)[0, 0, 0] == x5[0, 1, 0, 0, 0]
    assert out.reshape(2, 2, 4)[0, 1, 0] == 0.0
    # channel 1 shifted right: t=1 gets t=0's value, t=0 gets 0
    assert out.reshape(2, 2, 4)[0, 1, 1] == x5[0, 0, 1, 0, 0]
    assert out.reshape(2, 2, 4)[0, 0, 1] == 0.0
    # channels 2-3 unshifted
    np.testing.assert_allclose(out.reshape(2, 2, 4)[:, :, 2:],
                               x5[:, :, 2:, 0, 0])


def test_gather_tree():
    # reference operators/gather_tree_op.cc example
    ids = np.array([[[2, 2], [6, 1]], [[3, 9], [6, 1]], [[0, 1], [9, 0]]],
                   dtype="int64")
    parents = np.array([[[0, 0], [1, 1]], [[1, 0], [1, 0]],
                        [[0, 0], [0, 1]]], dtype="int64")
    out = np.asarray(nn.functional.gather_tree(ids, parents))
    expected = np.array([[[2, 2], [1, 6]], [[3, 3], [6, 1]], [[0, 1], [9, 0]]],
                        dtype="int64")
    np.testing.assert_array_equal(out, expected)


def test_dice_loss():
    probs = paddle.to_tensor(np.array([[0.9, 0.1], [0.2, 0.8]],
                                      dtype="float32"))
    labels = paddle.to_tensor(np.array([[0], [1]], dtype="int64"))
    loss = float(nn.functional.dice_loss(probs, labels))
    # per-sample dice = (2*0.9+eps)/(1+1+eps) -> loss ~= 1-0.9=0.1 ; ~0.2 avg: (0.1+0.2)/2
    assert abs(loss - 0.15) < 1e-3


def test_hsigmoid_loss_layer_and_grad():
    paddle.seed(7)
    m = nn.HSigmoidLoss(feature_size=8, num_classes=6)
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8).astype("float32"))
    y = paddle.to_tensor(np.array([0, 2, 4, 5], dtype="int64"))
    out = m(x, y)
    assert out.shape == (4, 1)
    assert np.all(np.asarray(out) > 0)  # -log sigmoid sums are positive
    # loss decreases under sgd on the functional path
    import jax
    w0 = np.asarray(m.weight.value)

    def loss_fn(w):
        return jnp.mean(nn.functional.hsigmoid_loss(
            jnp.asarray(x), y, 6, w, None))

    g = jax.grad(loss_fn)(m.weight.value)
    assert np.isfinite(np.asarray(g)).all()
    l0 = float(loss_fn(m.weight.value))
    l1 = float(loss_fn(m.weight.value - 0.1 * g))
    assert l1 < l0


def test_hsigmoid_custom_path():
    # custom tree: num_classes=4 with explicit path table/code
    path_table = np.array([[0, 1, -1], [0, 2, -1], [1, 0, -1], [2, 1, 0]],
                          dtype="int64")
    path_code = np.array([[0, 1, 0], [1, 0, 0], [0, 0, 0], [1, 1, 1]],
                         dtype="int64")
    w = np.random.RandomState(0).randn(4, 5).astype("float32")
    x = np.random.RandomState(1).randn(3, 5).astype("float32")
    y = np.array([0, 1, 3], dtype="int64")
    out = nn.functional.hsigmoid_loss(x, y, 4, w, None,
                                      path_table=path_table,
                                      path_code=path_code)
    assert out.shape == (3, 1) and np.isfinite(np.asarray(out)).all()
    # parity vs hand-computed reference math (hierarchical_sigmoid_op.h:
    # loss = sum_j softplus(z_j) - bit_j * z_j over valid path positions)
    expected = np.zeros((3, 1), dtype="float64")
    for i in range(3):
        nodes = path_table[int(y[i])]
        bits = path_code[int(y[i])]
        for j, (node, bit) in enumerate(zip(nodes, bits)):
            if node < 0:
                continue
            z = float(np.clip(np.dot(x[i], w[node]), -40.0, 40.0))
            expected[i, 0] += np.log1p(np.exp(z)) - bit * z
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4)


class _CellWrap:
    """Greedy argmax-deterministic toy cell: logits depend on input token."""

    def __init__(self, vocab, table):
        self.vocab = vocab
        self.table = table  # (vocab, vocab) next-token logits

    def __call__(self, inputs, states):
        logits = jnp.take(self.table, jnp.asarray(inputs).reshape(-1), axis=0)
        return logits, states


def test_beam_search_decode_follows_highest_prob_path():
    vocab, end = 5, 4
    # token t deterministically prefers token (t+1) % 5; token 3 prefers END
    table = np.full((vocab, vocab), -10.0, dtype="float32")
    for t in range(vocab):
        table[t, (t + 1) % vocab] = 10.0
    dec = nn.BeamSearchDecoder(
        _CellWrap(vocab, jnp.asarray(table)), start_token=0, end_token=end,
        beam_size=2)
    init_states = jnp.zeros((2, 1))  # batch=2 dummy states
    out, states = nn.dynamic_decode(dec, init_states, max_step_num=10)
    seq = np.asarray(out.predicted_ids)[0, :, 0]  # batch 0, best beam
    # path from start 0: 1,2,3,4(END)
    np.testing.assert_array_equal(seq[:4], [1, 2, 3, 4])
    assert bool(np.all(np.asarray(states.finished)))


def test_adadelta_decreases_loss():
    paddle.seed(0)
    lin = nn.Linear(4, 1)
    opt = paddle.optimizer.Adadelta(learning_rate=1.0,
                                    parameters=lin.parameters())
    x = paddle.to_tensor(np.random.RandomState(0).randn(16, 4).astype("float32"))
    y = paddle.to_tensor(np.random.RandomState(1).randn(16, 1).astype("float32"))

    def closure():
        return nn.functional.mse_loss(lin(x), y)

    l0 = float(closure())
    for _ in range(30):
        paddle.autograd.backward(lin, closure)
        opt.step()
        opt.clear_grad()
    assert float(closure()) < l0


def test_compat_shims():
    paddle.jit.set_verbosity(3)
    paddle.jit.set_code_level(50)
    pt = paddle.jit.ProgramTranslator.get_instance()
    pt.enable(False)
    assert not paddle.jit.ProgramTranslator.enable_to_static
    pt.enable(True)
    assert paddle.io.get_worker_info() is None
    assert paddle.utils.require_version("0.0.1")
    with pytest.raises(Exception):
        paddle.utils.require_version("999.0.0")
    np_mod = paddle.utils.try_import("numpy")
    assert np_mod is np
    with pytest.raises(ImportError):
        paddle.utils.try_import("definitely_not_a_module_xyz")
    assert paddle.vision.get_image_backend() == "pil"
    with pytest.raises(ValueError):
        paddle.vision.set_image_backend("bogus")
    from paddle_tpu.text import Imdb, WMT14  # noqa: F401
    assert paddle.nn.functional.elu_ is not None


class TestClassCenterSample:
    def test_positives_kept_and_remap_consistent(self):
        import paddle_tpu as paddle
        from paddle_tpu.nn import functional as F
        paddle.seed(0)
        label = jnp.asarray([3, 77, 3, 500, 77], jnp.int32)
        remapped, sampled = F.class_center_sample(label, 1000, 16)
        sampled = np.asarray(sampled)
        assert sampled.shape == (16,)
        assert len(set(sampled.tolist())) == 16          # no duplicates
        for cls in (3, 77, 500):
            assert cls in sampled                        # positives kept
        # remapped labels index into sampled and round-trip
        r = np.asarray(remapped)
        assert (r >= 0).all()
        np.testing.assert_array_equal(sampled[r], np.asarray(label))

    def test_deterministic_under_seed_and_jit(self):
        import jax as _jax
        import paddle_tpu as paddle
        from paddle_tpu.framework.random import rng_guard
        from paddle_tpu.nn import functional as F
        label = jnp.asarray([1, 2], jnp.int32)
        paddle.seed(7)
        _, s1 = F.class_center_sample(label, 100, 8)
        paddle.seed(7)
        _, s2 = F.class_center_sample(label, 100, 8)
        np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
        # under jit, scope the RNG like functional_call does (a raw
        # get_rng_key inside jit would leak a tracer — same contract as
        # dropout)
        key = _jax.random.PRNGKey(0)

        @_jax.jit
        def f(l, key):
            with rng_guard(key):
                return F.class_center_sample(l, 100, 8)

        _, s3 = f(label, key)
        assert len(set(np.asarray(s3).tolist())) == 8

    def test_validation(self):
        import paddle_tpu as paddle
        from paddle_tpu.nn import functional as F
        paddle.seed(0)
        with pytest.raises(ValueError, match="num_samples"):
            F.class_center_sample(jnp.asarray([1], jnp.int32), 10, 11)
        # out-of-range labels would silently clamp under XLA scatter
        with pytest.raises(ValueError, match="labels must be in"):
            F.class_center_sample(jnp.asarray([100], jnp.int32), 100, 8)
        with pytest.raises(ValueError, match="labels must be in"):
            F.class_center_sample(jnp.asarray([-1], jnp.int32), 100, 8)
