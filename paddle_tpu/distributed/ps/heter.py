"""HeterEmbedding — device-resident (HBM) hot embedding tier over the
host PS cold tier.

Capability map (reference): HeterPS keeps hot embedding rows ON the
accelerator in a GPU hash table with a device-side optimizer and
inter-device comm (`framework/fleet/heter_ps/hashtable.h:47`,
`heter_comm.h:50`, `heter_ps.cu`); the CPU parameter server is the full
(cold) store, exchanged with the device tier at pass boundaries.

TPU-native redesign — the hash table is SPLIT across host and device by
what each does best:
- the DEVICE owns the row data: a fixed-capacity ``(capacity, dim)``
  HBM-resident array (a normal trainable Parameter — XLA gathers at HBM
  bandwidth, the model optimizer updates hot rows on-device, exactly the
  HeterPS division where the accelerator applies updates);
- the HOST owns the hash map: key->slot assignment, LRU eviction, and
  the promote/flush traffic with the PS table happen in plain Python/
  numpy BETWEEN jitted steps (``prepare``), so the jitted step sees only
  static-shaped integer slot ids and touches the host zero times.

Per-step transfer is O(cache misses * row_width) instead of the
O(batch * dim) host round-trip the ``pure_callback`` path
(``embedding.py``) pays on every lookup.

Tier handoff moves FULL rows (value + optimizer slot columns) through
``SparseTable.export_rows/import_rows``: a promoted row carries its
host-side accumulator into the device optimizer's slot state, and an
evicted row carries the device accumulator back, so adagrad/adam
trajectories survive migration. When the device optimizer's slots are
not reachable (eager mode, wrapper optimizers), eviction preserves the
PS's existing slot columns and rewrites only the values.

Sharded mode (``shard_axis="model"``): the hot array carries
``P("model", None)`` so the engine places 1/mp of it per device;
lookups inside shard_map use the masked-gather + psum exchange (the
vocab-parallel pattern; for batch-sharded alltoall id-exchange see
``ops/sharded_embedding.alltoall_lookup``).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Optional

import numpy as np

import jax.numpy as jnp

from ...nn.layer import Layer
from .table import SparseTable

__all__ = ["HeterEmbedding"]

# native row layout per optimizer: value columns then these slot columns,
# named as the DEVICE optimizer's matching slot pytree keys
_SLOT_COLUMNS = {"sgd": (), "adagrad": ("moment",), "adam": ("m", "v")}




class HeterEmbedding(Layer):
    """Two-tier embedding: HBM hot rows + host PS cold store.

    Usage: call ``slots = emb.prepare(ids)`` on the host before each
    step (insert/evict happens here), then run the jitted step on
    ``slots``. With ``ParallelTrainer``, call ``emb.attach(trainer)``
    once after building the trainer so tier handoff reads/writes the
    live training state (including optimizer slots).
    """

    def __init__(self, dim: int, capacity: int,
                 optimizer: str = "adagrad", table: Optional[SparseTable]
                 = None, pooling: Optional[str] = None, seed: int = 0,
                 init_range: float = 0.01, shard_axis: Optional[str]
                 = None):
        super().__init__()
        from ...nn.initializer import Constant
        if table is not None and not hasattr(table, "export_rows"):
            raise TypeError("HeterEmbedding needs a table with the "
                            "export_rows/import_rows tier-exchange API "
                            "(local SparseTable)")
        self.dim = dim
        self.capacity = int(capacity)
        self.pooling = pooling  # None | "sum" | "mean"
        self.table = table if table is not None else SparseTable(
            dim, optimizer=optimizer, seed=seed, init_range=init_range)
        assert self.table.dim == dim
        self._slot_names = _SLOT_COLUMNS.get(self.table.optimizer, ())
        # hot rows: a regular trainable parameter — the model optimizer
        # IS the device-side optimizer of the hot tier
        self.hot = self.create_parameter((self.capacity, dim),
                                         initializer=Constant(0.0))
        self._shard_axis = shard_axis
        if shard_axis:
            from jax.sharding import PartitionSpec as P
            # an indivisible capacity would only surface later as an opaque
            # GSPMD sharding error — name the numbers here instead
            from ..mesh import get_mesh
            self._check_shard_capacity(get_mesh())
            self.hot.pspec = P(shard_axis, None)
        # host-side hash map mirror
        self._key2slot: dict = {}
        self._slot2key = np.full(self.capacity, -1, np.int64)
        self._lru: OrderedDict = OrderedDict()
        self._free = list(range(self.capacity - 1, -1, -1))
        self._trainer = None
        self._pname = None
        self.stats = {"lookups": 0, "hits": 0, "misses": 0, "evicts": 0}

    def _check_shard_capacity(self, mesh):
        if (self._shard_axis and mesh is not None
                and self._shard_axis in mesh.shape
                and self.capacity % mesh.shape[self._shard_axis]):
            raise ValueError(
                f"HeterEmbedding capacity ({self.capacity}) must be "
                f"divisible by mesh axis {self._shard_axis!r} size "
                f"({mesh.shape[self._shard_axis]}) to shard the hot tier")

    # -- live-state plumbing ------------------------------------------------
    def attach(self, trainer):
        """Bind to a ParallelTrainer so insert/evict act on live state.
        ParallelTrainer calls this automatically via _on_trainer_built;
        manual attach is only needed for hand-rolled training loops over
        trainer-style state."""
        name = trainer.param_name_of(self.hot)
        if name is None:
            raise ValueError("this HeterEmbedding's hot parameter is not "
                             "part of the trainer's model")
        self._check_shard_capacity(getattr(trainer, "mesh", None))
        self._trainer = trainer
        self._pname = name
        return self

    # ParallelTrainer auto-binds at construction: without it, prepare()
    # would write rows into the eager Parameter the jitted step never
    # reads, and evictions would flush zeros over real PS rows
    _on_trainer_built = attach

    def _get_values(self):
        if self._trainer is not None:
            return self._trainer.get_param(self._pname)
        return self.hot.value

    def _set_values(self, v):
        if self._trainer is not None:
            self._trainer.set_param(self._pname, v)
        else:
            self.hot.value = v

    def _get_slot(self, slot_name):
        if self._trainer is not None:
            return self._trainer.get_opt_slot(self._pname, slot_name)
        return None

    def _set_slot(self, slot_name, v):
        if self._trainer is not None:
            self._trainer.set_opt_slot(self._pname, slot_name, v)

    # -- tier exchange ------------------------------------------------------
    def _flush(self, slots: np.ndarray, keys: np.ndarray):
        """Evicted rows -> PS, carrying optimizer slots when reachable."""
        vals = np.asarray(self._get_values()[slots], np.float32)
        slot_arrays = [self._get_slot(sn) for sn in self._slot_names]
        if all(a is not None for a in slot_arrays):
            cols = [vals] + [np.asarray(a[slots], np.float32)
                             for a in slot_arrays]
            self.table.import_rows(keys, np.concatenate(cols, axis=1))
        else:
            # device slot state unreachable: keep the PS's existing slot
            # columns, rewrite only the values
            cur = self.table.export_rows(keys, create_missing=True)
            cur[:, :self.dim] = vals
            self.table.import_rows(keys, cur)

    def _promote(self, slots: np.ndarray, keys: np.ndarray):
        """PS rows -> device (values + optimizer slot columns). Every
        reachable device slot array is written for the reused slots:
        mapped columns get the PS state, anything else resets to zero —
        a promoted key must never inherit the evicted key's accumulator
        or momentum."""
        rows = self.table.export_rows(keys, create_missing=True)
        self._set_values(
            self._get_values().at[slots].set(rows[:, :self.dim]))
        mapped = {sn: rows[:, (1 + j) * self.dim:(2 + j) * self.dim]
                  for j, sn in enumerate(self._slot_names)}
        for sn in self._device_slot_names():
            arr = self._get_slot(sn)
            if arr is None:
                continue
            col = mapped.get(sn)
            self._set_slot(sn, arr.at[slots].set(
                col if col is not None else 0.0))

    def _device_slot_names(self):
        if self._trainer is not None:
            return self._trainer.opt_slot_names(self._pname)
        return ()  # eager mode: no optimizer slot state is reachable

    def _check_handoff(self):
        """Warn once when optimizer state cannot migrate between tiers
        (eager mode, wrapper optimizers, or a device optimizer whose
        slots don't match the table's): values still move correctly but
        adagrad/adam trajectories will diverge from the host-PS path."""
        if getattr(self, "_handoff_checked", False):
            return
        self._handoff_checked = True
        if not self._slot_names:
            return  # sgd: nothing to migrate
        reachable = [sn for sn in self._slot_names
                     if self._get_slot(sn) is not None]
        if len(reachable) != len(self._slot_names):
            import warnings
            warnings.warn(
                f"HeterEmbedding: table optimizer "
                f"{self.table.optimizer!r} keeps slot columns "
                f"{self._slot_names} but the device optimizer exposes "
                f"{self._device_slot_names() or 'none'} — optimizer "
                f"state will NOT migrate on evict/promote (values "
                f"still do). Match the training optimizer to the table "
                f"optimizer, or attach() a ParallelTrainer.",
                stacklevel=3)

    # -- per-step host work -------------------------------------------------
    def prepare(self, ids) -> np.ndarray:
        """Map raw keys -> hot slots, inserting misses and evicting LRU
        rows as needed. Returns int32 slots shaped like ``ids`` (-1
        padding preserved). Host-only; call OUTSIDE the jitted step."""
        self._check_handoff()
        ids_np = np.asarray(ids)
        flat = ids_np.reshape(-1)
        valid = flat >= 0
        uniq = np.unique(flat[valid])
        k2s = self._key2slot
        misses = [k for k in uniq.tolist() if k not in k2s]
        self.stats["lookups"] += int(uniq.size)
        self.stats["misses"] += len(misses)
        self.stats["hits"] += int(uniq.size) - len(misses)

        need = len(misses) - len(self._free)
        if need > 0:
            current = set(uniq.tolist())
            evict_keys = []
            for k in self._lru:
                if k not in current:
                    evict_keys.append(k)
                    if len(evict_keys) == need:
                        break
            if len(evict_keys) < need:
                raise RuntimeError(
                    f"HeterEmbedding capacity {self.capacity} cannot hold "
                    f"the {uniq.size} distinct keys of this batch")
            slots = np.asarray([k2s[k] for k in evict_keys], np.int64)
            self._flush(slots, np.asarray(evict_keys, np.int64))
            for k, s in zip(evict_keys, slots.tolist()):
                del k2s[k]
                del self._lru[k]
                self._slot2key[s] = -1
                self._free.append(s)
            self.stats["evicts"] += len(evict_keys)

        if misses:
            new_slots = np.asarray([self._free.pop() for _ in misses],
                                   np.int64)
            mkeys = np.asarray(misses, np.int64)
            self._promote(new_slots, mkeys)
            for k, s in zip(misses, new_slots.tolist()):
                k2s[k] = s
                self._slot2key[s] = k

        for k in uniq.tolist():
            self._lru[k] = None
            self._lru.move_to_end(k)

        out = np.full(flat.shape, -1, np.int64)
        out[valid] = [k2s[k] for k in flat[valid].tolist()]
        return out.reshape(ids_np.shape).astype(np.int32)

    # -- jitted lookup ------------------------------------------------------
    def forward(self, slot_ids):
        slot_ids = jnp.asarray(slot_ids)
        mask = slot_ids >= 0
        safe = jnp.where(mask, slot_ids, 0)
        if self._shard_axis:
            from ..meta_parallel.parallel_layers.mp_layers import (
                _in_shard_map)
            if _in_shard_map(self._shard_axis):
                emb = self._sharded_gather(safe)
            else:
                emb = self.hot.value[safe]
        else:
            emb = self.hot.value[safe]
        emb = emb * mask[..., None].astype(emb.dtype)
        if self.pooling is None:
            return emb
        s = jnp.sum(emb, axis=-2)  # padded rows already zeroed above
        if self.pooling == "sum":
            return s
        cnt = jnp.maximum(
            jnp.sum(mask.astype(jnp.float32)[..., None], axis=-2), 1.0)
        return s / cnt

    def _sharded_gather(self, safe):
        """Masked local gather + forward-psum over the shard axis (the
        vocab-parallel exchange). The psum must be the identity-backward
        variant: under shard_map a plain lax.psum transposes to another
        psum, scaling every hot-row gradient by the axis size (see
        mp_layers.reduce_from_parallel_region)."""
        from jax import lax

        from ..meta_parallel.parallel_layers.mp_layers import (
            reduce_from_parallel_region)
        local = self.hot.value            # (capacity/mp, dim) this shard
        per = local.shape[0]
        rank = lax.axis_index(self._shard_axis)
        lo = rank * per
        mine = (safe >= lo) & (safe < lo + per)
        idx = jnp.clip(safe - lo, 0, per - 1)
        rows = jnp.where(mine[..., None], local[idx], 0.0)
        return reduce_from_parallel_region(rows, self._shard_axis)

    # -- persistence --------------------------------------------------------
    def flush_all(self):
        """Write every hot row back to the PS table (checkpoint/export
        boundary; the cache stays valid)."""
        live = np.where(self._slot2key >= 0)[0]
        if live.size:
            self._flush(live, self._slot2key[live])

    def save(self, path: str):
        self.flush_all()
        self.table.save(path)

    def load(self, path: str):
        self.table.load(path)
        # drop the cache: rows re-promote lazily with fresh table state
        self._key2slot.clear()
        self._lru.clear()
        self._slot2key[:] = -1
        self._free = list(range(self.capacity - 1, -1, -1))

    @property
    def hit_rate(self) -> float:
        n = self.stats["lookups"]
        return self.stats["hits"] / n if n else 0.0
