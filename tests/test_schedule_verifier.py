"""Tests for the static collective-schedule verifier (ISSUE 17):
``analysis/schedule.py`` extraction + fingerprints, the four deadlock
rules (seeded-violation fixtures: exactly one violation, rule fires
exactly once, clean variant stays silent — the test_analysis contract),
program families, the cross-rank bootstrap check, and the hostsim
schedule-divergence abort.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu import telemetry
from paddle_tpu.analysis import schedule as S
from paddle_tpu.analysis.rules import run_rules


def mesh2():
    return Mesh(np.array(jax.devices()[:2]), ("data",))


def smap(fn, mesh, ins=None, outs=None):
    return jax.shard_map(fn, mesh=mesh,
                         in_specs=P("data") if ins is None else ins,
                         out_specs=P("data") if outs is None else outs,
                         check_vma=False)


def hits(findings, rule):
    return [f for f in findings if f.rule == rule]


def run_sched_rules(closed, mesh):
    return run_rules(closed, mesh=mesh, rules=S.SCHEDULE_RULE_IDS)


# ---------------------------------------------------------------------------
# extraction + fingerprints (walker corners the verifier depends on)
# ---------------------------------------------------------------------------

class TestScheduleExtraction:
    def test_psum_site_identity(self):
        mesh = mesh2()
        cj = jax.make_jaxpr(smap(lambda x: lax.psum(x, "data"), mesh,
                                 outs=P()))(jnp.ones((8, 4), jnp.float32))
        sched = S.extract_schedule(cj, mesh=mesh)
        assert len(sched) == 1
        s = sched[0]
        assert s.kind == "psum" and s.axes == ("data",)
        assert s.wire_dtype == "float32"
        # 4x4 f32 shard = 64 B, already a power of two
        assert s.payload_bucket == 64
        assert s.link == "ici"
        assert s.context == ("shard_map",)

    def test_while_cond_vs_body_contexts(self):
        """Collectives in a while's PREDICATE and BODY must both be
        extracted, with distinguishable control-flow contexts — the
        global-termination-vote pattern puts a psum in the cond."""
        mesh = mesh2()

        def f(x):
            def cond(c):
                return lax.psum(c[1].sum(), "data") < 100.0

            def body(c):
                return (c[0] + 1, c[1] + lax.psum(c[1], "data"))

            return lax.while_loop(cond, body, (0, x))[1]

        cj = jax.make_jaxpr(smap(f, mesh))(jnp.ones((8, 4), jnp.float32))
        sched = S.extract_schedule(cj, mesh=mesh)
        contexts = sorted(s.context for s in sched)
        assert contexts == [("shard_map", "while[body]"),
                            ("shard_map", "while[cond]")]
        assert all(s.in_loop for s in sched)

    def test_shard_map_closed_over_axis_names(self):
        """An inner function referencing axis names through a closure
        (not parameters) still extracts with the right axes bound."""
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                    ("data", "model"))
        axis = "model"  # closed over

        def inner(v):
            return lax.psum(v, axis)

        f = jax.shard_map(inner, mesh=mesh,
                          in_specs=P("data", "model"),
                          out_specs=P("data", None), check_vma=False)
        cj = jax.make_jaxpr(f)(jnp.ones((4, 8), jnp.float32))
        sched = S.extract_schedule(cj, mesh=mesh)
        assert len(sched) == 1
        assert sched[0].axes == ("model",)
        # clean under the whole schedule rule set, too
        assert not run_sched_rules(cj, mesh)

    def test_psum2_rewrite_normalized(self):
        """check_vma=True traces psum as psum2; the schedule must
        normalize so both trace modes fingerprint identically."""
        mesh = mesh2()
        x = jnp.ones((8, 4), jnp.float32)
        plain = jax.make_jaxpr(smap(lambda v: lax.psum(v, "data"), mesh,
                                    outs=P()))(x)
        rewritten = jax.make_jaxpr(jax.shard_map(
            lambda v: lax.psum(v, "data"), mesh=mesh, in_specs=P("data"),
            out_specs=P(), check_vma=True))(x)
        s1 = S.extract_schedule(plain, mesh=mesh)
        s2 = S.extract_schedule(rewritten, mesh=mesh)
        assert [s.kind for s in s2] == ["psum"]
        assert S.fingerprint(s1) == S.fingerprint(s2)

    def test_payload_bucketed_to_pow2(self):
        mesh = mesh2()
        # 6x4 f32 shard = 96 B -> bucket 128
        cj = jax.make_jaxpr(smap(lambda x: lax.psum(x, "data"), mesh,
                                 outs=P()))(jnp.ones((12, 4), jnp.float32))
        assert S.extract_schedule(cj, mesh=mesh)[0].payload_bucket == 128

    def test_fingerprint_stable_and_schedule_sensitive(self):
        mesh = mesh2()
        x = jnp.ones((8, 4), jnp.float32)
        f = smap(lambda v: lax.psum(v, "data"), mesh, outs=P())
        fp1 = S.program_fingerprint(jax.make_jaxpr(f)(x), mesh)
        fp2 = S.program_fingerprint(jax.make_jaxpr(f)(x), mesh)
        assert fp1 == fp2  # retrace-stable
        g = smap(lambda v: lax.pmax(v, "data"), mesh, outs=P())
        assert fp1 != S.program_fingerprint(jax.make_jaxpr(g)(x), mesh)
        # payload bucket is part of the identity
        big = jax.make_jaxpr(f)(jnp.ones((64, 64), jnp.float32))
        assert fp1 != S.program_fingerprint(big, mesh)
        # collective-free program: stable empty-schedule fingerprint
        empty = jax.make_jaxpr(lambda v: v * 2)(x)
        assert S.extract_schedule(empty) == []
        assert S.program_fingerprint(empty) == S.fingerprint([])

    def test_format_and_rows(self):
        mesh = mesh2()
        cj = jax.make_jaxpr(smap(lambda x: lax.psum(x, "data"), mesh,
                                 outs=P()))(jnp.ones((8, 4), jnp.float32))
        sched = S.extract_schedule(cj, mesh=mesh)
        rows = S.schedule_rows(sched)
        assert rows[0]["kind"] == "psum" and rows[0]["link"] == "ici"
        txt = S.format_schedule(sched)
        assert "psum" in txt and "data" in txt
        assert S.format_schedule([]) == "  (no collectives)"


# ---------------------------------------------------------------------------
# the four deadlock rules: seeded fixtures fire exactly once; clean
# variants stay silent
# ---------------------------------------------------------------------------

class TestDeadlockRules:
    def test_order_divergence_fires_once(self):
        mesh = mesh2()

        def div(x):
            pred = x.sum() > 0
            return lax.cond(pred, lambda v: lax.psum(v, "data"),
                            lambda v: v * 2.0, x)

        cj = jax.make_jaxpr(smap(div, mesh))(jnp.ones((8, 4), jnp.float32))
        fs = run_sched_rules(cj, mesh)
        assert len(hits(fs, "collective-order-divergence")) == 1
        assert hits(fs, "collective-order-divergence")[0].severity == \
            "error"

    def test_order_divergence_clean_identical_branches(self):
        mesh = mesh2()

        def same(x):
            pred = x.sum() > 0
            return lax.cond(pred, lambda v: lax.psum(v, "data"),
                            lambda v: lax.psum(v * 2.0, "data"), x)

        cj = jax.make_jaxpr(smap(same, mesh))(
            jnp.ones((8, 4), jnp.float32))
        assert not hits(run_sched_rules(cj, mesh),
                        "collective-order-divergence")

    def test_order_divergence_remat_clone_dedup(self):
        """jax.checkpoint re-traces the divergent cond inside the
        backward pass: the fwd and bwd clones share source + branch
        signature and must collapse to ONE finding."""
        mesh = mesh2()

        def loss(x):
            @jax.checkpoint
            def blk(v):
                pred = v.sum() > 0
                return lax.cond(pred, lambda u: lax.psum(u, "data"),
                                lambda u: u * 2.0, v)
            return blk(x).sum()

        cj = jax.make_jaxpr(smap(jax.grad(loss), mesh))(
            jnp.ones((8, 4), jnp.float32))
        fs = hits(run_sched_rules(cj, mesh), "collective-order-divergence")
        assert len(fs) == 1, [f.message for f in fs]

    def test_data_dependent_while_fires_once(self):
        mesh = mesh2()

        def f(x):
            return lax.while_loop(lambda c: c.sum() < 100.0,
                                  lambda c: c + lax.psum(c, "data"), x)

        cj = jax.make_jaxpr(smap(f, mesh))(jnp.ones((8, 4), jnp.float32))
        fs = hits(run_sched_rules(cj, mesh),
                  "collective-in-data-dependent-while")
        assert len(fs) == 1 and fs[0].severity == "error"

    def test_data_dependent_while_clean_counter(self):
        """fori_loop-style scalar-integer counter predicate: the trip
        count is rank-invariant, collectives in the body are safe."""
        mesh = mesh2()

        def f(x):
            def cond(c):
                return c[0] < 4

            def body(c):
                return (c[0] + 1, c[1] + lax.psum(c[1], "data"))

            return lax.while_loop(cond, body, (0, x))[1]

        cj = jax.make_jaxpr(smap(f, mesh))(jnp.ones((8, 4), jnp.float32))
        assert not hits(run_sched_rules(cj, mesh),
                        "collective-in-data-dependent-while")

    def test_data_dependent_while_clean_no_collectives(self):
        mesh = mesh2()

        def f(x):
            return lax.while_loop(lambda c: c.sum() < 100.0,
                                  lambda c: c * 1.5, x)

        cj = jax.make_jaxpr(smap(f, mesh))(jnp.ones((8, 4), jnp.float32))
        assert not run_sched_rules(cj, mesh)

    def test_rank_dependent_cond_fires_once(self):
        """Identical branch sequences do NOT save a rank-varying
        predicate: different staged program points = different channel
        ids. This is the hazard order-divergence alone cannot see."""
        mesh = mesh2()

        def f(x):
            idx = lax.axis_index("data")
            return lax.cond(idx == 0, lambda v: lax.psum(v, "data"),
                            lambda v: lax.psum(v * 2, "data"), x)

        cj = jax.make_jaxpr(smap(f, mesh))(jnp.ones((8, 4), jnp.float32))
        fs = hits(run_sched_rules(cj, mesh),
                  "rank-dependent-collective-schedule")
        assert len(fs) == 1 and fs[0].severity == "error"
        # and identical branches keep order-divergence silent, so the
        # program carries exactly this one hazard
        assert not hits(run_sched_rules(cj, mesh),
                        "collective-order-divergence")

    def test_rank_dependent_clean_uniform_predicate(self):
        mesh = mesh2()

        def f(x, k):
            return lax.cond(k > 0, lambda v: lax.psum(v, "data"),
                            lambda v: lax.psum(v * 2, "data"), x)

        cj = jax.make_jaxpr(
            lambda x, k: smap(lambda v: f(v, k), mesh)(x))(
                jnp.ones((8, 4), jnp.float32), jnp.int32(3))
        assert not hits(run_sched_rules(cj, mesh),
                        "rank-dependent-collective-schedule")

    def test_rank_dependent_while_fires_once(self):
        """A while whose trip BOUND is derived from axis_index: the
        counter predicate looks rank-invariant shape-wise, but taint
        through the carry proves it is not."""
        mesh = mesh2()

        def f(x):
            idx = lax.axis_index("data")

            def cond(c):
                return c[0] < idx + 2

            def body(c):
                return (c[0] + 1, c[1] + lax.psum(c[1], "data"))

            return lax.while_loop(cond, body, (0, x))[1]

        cj = jax.make_jaxpr(smap(f, mesh))(jnp.ones((8, 4), jnp.float32))
        fs = hits(run_sched_rules(cj, mesh),
                  "rank-dependent-collective-schedule")
        assert len(fs) == 1

    def test_axis_index_alone_is_clean(self):
        """axis_index feeding plain data flow (per-rank seeds, labels)
        is the normal SPMD idiom — no finding without a collective-
        bearing predicate downstream."""
        mesh = mesh2()

        def f(x):
            idx = lax.axis_index("data")
            return x + idx.astype(x.dtype)

        cj = jax.make_jaxpr(smap(f, mesh))(jnp.ones((8, 4), jnp.float32))
        assert not run_sched_rules(cj, mesh)


# ---------------------------------------------------------------------------
# program families
# ---------------------------------------------------------------------------

def _tracer(mesh, fn):
    return lambda: jax.make_jaxpr(smap(fn, mesh))(
        jnp.ones((8, 4), jnp.float32))


class TestProgramFamily:
    def test_drift_fires_once_on_primary(self):
        mesh = mesh2()
        fam = S.ProgramFamily(
            name="t-drift", selector="undeclared host flag",
            rank_invariant=False,
            members={"sync": _tracer(mesh, lambda v: lax.psum(v, "data")),
                     "nosync": _tracer(mesh, lambda v: v * 2.0)},
            mesh=mesh)
        res = S.verify_family(fam)
        assert not res["ok"]
        assert res["fingerprints"]["sync"] != res["fingerprints"]["nosync"]
        drift = [f for m in res["members"].values()
                 for f in m["findings"]
                 if f["rule"] == "program-family-schedule-drift"]
        assert len(drift) == 1  # exactly once, on the primary
        assert not res["members"]["sync"]["ok"]
        assert res["members"]["nosync"]["ok"]

    def test_drift_clean_when_declared_rank_invariant(self):
        mesh = mesh2()
        fam = S.ProgramFamily(
            name="t-ok", selector="step_no % k_steps (host-replicated "
            "step counter)", rank_invariant=True,
            members={"sync": _tracer(mesh, lambda v: lax.psum(v, "data")),
                     "nosync": _tracer(mesh, lambda v: v * 2.0)},
            mesh=mesh)
        res = S.verify_family(fam)
        assert res["ok"]
        assert all(m["ok"] for m in res["members"].values())

    def test_drift_clean_when_schedules_identical(self):
        mesh = mesh2()
        fam = S.ProgramFamily(
            name="t-same", selector="anything", rank_invariant=False,
            members={"a": _tracer(mesh, lambda v: lax.psum(v, "data")),
                     "b": _tracer(mesh,
                                  lambda v: lax.psum(v + 1.0, "data"))},
            mesh=mesh)
        res = S.verify_family(fam)
        assert res["ok"]
        assert res["fingerprints"]["a"] == res["fingerprints"]["b"]

    def test_member_hazard_fails_family(self):
        mesh = mesh2()

        def bad(v):
            pred = v.sum() > 0
            return lax.cond(pred, lambda u: lax.psum(u, "data"),
                            lambda u: u * 2.0, v)

        fam = S.ProgramFamily(
            name="t-bad-member", selector="step bucket",
            rank_invariant=True,
            members={"m": _tracer(mesh, bad)}, mesh=mesh)
        res = S.verify_family(fam)
        assert not res["ok"]
        rules = [f["rule"] for f in res["members"]["m"]["findings"]]
        assert "collective-order-divergence" in rules

    def test_registry_duplicate_raises(self):
        mesh = mesh2()
        fam = S.ProgramFamily(
            name="t-dup", selector="s", rank_invariant=True,
            members={"m": _tracer(mesh, lambda v: v)}, mesh=mesh)
        try:
            S.register_family(fam)
            with pytest.raises(ValueError):
                S.register_family(fam)
            S.register_family(fam, replace=True)  # explicit replace ok
        finally:
            S.FAMILIES.pop("t-dup", None)

    def test_empty_family_rejected(self):
        with pytest.raises(ValueError):
            S.register_family(S.ProgramFamily(
                name="t-empty", selector="s", rank_invariant=True,
                members={}))


# ---------------------------------------------------------------------------
# shipped family hooks (trainer / LocalSGD / decode executors)
# ---------------------------------------------------------------------------

class TestShippedFamilyHooks:
    def test_parallel_trainer_family(self):
        """ParallelTrainer.program_family: the integrity do_check pair,
        declared rank-invariant (step-counter cadence), both members
        hang-free; fingerprints differ (do_check adds compare
        collectives)."""
        from paddle_tpu.resilience.hostsim import (_tiny_batches,
                                                   _tiny_trainer)
        trainer = _tiny_trainer()
        x, y = _tiny_batches()[0]
        fam = trainer.program_family(x, y)
        assert set(fam.members) == {"step", "step-check"}
        assert fam.rank_invariant
        res = S.verify_family(fam)
        assert res["ok"], json.dumps(res, indent=2)
        assert res["fingerprints"]["step"] != \
            res["fingerprints"]["step-check"]

    def test_localsgd_family(self):
        """LocalSGDTrainer.program_family: the sync/no-sync pair —
        divergent schedules by design, safe because the k-step cadence
        is a host-replicated counter."""
        import paddle_tpu as paddle
        from paddle_tpu import nn
        from paddle_tpu.distributed.mesh import build_mesh
        from paddle_tpu.distributed.meta_parallel.localsgd import \
            LocalSGDTrainer

        paddle.seed(5)
        mesh = build_mesh({"data": 2})
        model = nn.Linear(8, 4)
        opt = paddle.optimizer.SGD(0.05, parameters=model.parameters())
        # compressed param sync => the averaging collectives are
        # EXPLICIT primitives the schedule extractor sees (fp32 sync is
        # a GSPMD-implicit all-reduce outside the explicit schedule)
        tr = LocalSGDTrainer(model, opt,
                             lambda out, y: jnp.mean((out - y) ** 2),
                             mesh=mesh, k_steps=4, param_sync="int8")
        x = np.zeros((8, 8), np.float32)
        y = np.zeros((8, 4), np.float32)
        fam = tr.program_family(x, y)
        assert set(fam.members) == {"sync", "no-sync"}
        assert fam.rank_invariant
        res = S.verify_family(fam)
        assert res["ok"], json.dumps(res, indent=2)
        # sync exchanges gradients; no-sync must not
        assert res["members"]["sync"]["num_collectives"] > \
            res["members"]["no-sync"]["num_collectives"]

    def test_decode_executor_family(self):
        """The decode/mixed/verify executor router registered as a
        family keyed on batch composition (host-uniform per dispatch)."""
        import importlib
        lint = importlib.import_module("tools.lint_program")
        fam = lint._decode_family(smoke=True)
        assert set(fam.members) == {"mixed", "decode", "verify"}
        res = S.verify_family(fam)
        assert res["ok"], json.dumps(res, indent=2)


# ---------------------------------------------------------------------------
# cross-rank bootstrap agreement
# ---------------------------------------------------------------------------

def _fresh_registry():
    old = telemetry.get_registry()
    reg = telemetry.Registry()
    telemetry._set_registry(reg)
    telemetry.enable()
    return old, reg


class TestCrossRank:
    def test_agreement_passes_and_counts(self, tmp_path):
        from paddle_tpu.resilience.elastic import FileCoordinator
        hosts = ["a", "b"]
        old, reg = _fresh_registry()
        out, errs = {}, {}

        def _run(h):
            coord = FileCoordinator(str(tmp_path), job_id="j", host=h,
                                    poll=0.01)
            try:
                out[h] = S.crossrank_verify(
                    coord, {"train-step": "fp0", "check": "fp1"},
                    lambda: hosts, timeout=30.0)
            except Exception as e:  # pragma: no cover
                errs[h] = e

        try:
            ts = [threading.Thread(target=_run, args=(h,)) for h in hosts]
            [t.start() for t in ts]
            [t.join() for t in ts]
            assert not errs
            for h in hosts:
                assert set(out[h]) == {"a", "b"}
            assert reg.counter("schedule_verify_total").value() == 2.0
            assert reg.counter(
                "collective_schedule_mismatch_total").value() == 0.0
        finally:
            telemetry.disable()
            telemetry._set_registry(old)

    def test_divergence_aborts_with_diff(self, tmp_path):
        from paddle_tpu.resilience.elastic import FileCoordinator
        hosts = ["a", "b"]
        old, reg = _fresh_registry()
        errs = {}

        def _run(h, fp):
            coord = FileCoordinator(str(tmp_path), job_id="j", host=h,
                                    poll=0.01)
            try:
                S.crossrank_verify(coord, {"train-step": fp},
                                   lambda: hosts, timeout=30.0)
            except S.ScheduleMismatch as e:
                errs[h] = e

        try:
            ts = [threading.Thread(target=_run, args=("a", "fpA")),
                  threading.Thread(target=_run, args=("b", "fpB"))]
            [t.start() for t in ts]
            [t.join() for t in ts]
            # every host aborts with the same per-host diff
            assert set(errs) == {"a", "b"}
            for e in errs.values():
                assert e.diff == {"train-step": {"a": "fpA", "b": "fpB"}}
                assert "diverge" in str(e)
            assert reg.counter(
                "collective_schedule_mismatch_total").value() == 2.0
        finally:
            telemetry.disable()
            telemetry._set_registry(old)


# ---------------------------------------------------------------------------
# hostsim: a deliberate schedule divergence aborts with a diff, fast
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.multihost(timeout=420)
def test_hostsim_schedule_divergence_aborts_with_diff(tmp_path):
    """2 subprocess hosts; host1 is forced onto a different program (the
    integrity do_check step) at fingerprint time. The bootstrap check
    must abort EVERY host with a diffed report — quickly, not at the
    hang-watchdog deadline (armed at 600 s here)."""
    from paddle_tpu.resilience import hostsim
    cluster = hostsim.SimCluster(str(tmp_path), n_hosts=2, np_spec="2:2",
                                 steps=6, hb_timeout=1.0, step_delay=0.05,
                                 hang_timeout=600.0)
    t0 = time.time()
    out = cluster.run(desync_hosts={1}, timeout=240)
    elapsed = time.time() - t0
    assert out["hosts_hung"] == 0
    assert elapsed < 240.0
    for h, code in out["exit_codes"].items():
        assert code == hostsim.SCHEDULE_MISMATCH_EXIT, (h, code,
                                                        out["stderr"][h])
    for h, res in out["results"].items():
        assert res is not None, (h, out["stderr"][h])
        assert res["status"] == "schedule_mismatch"
        diff = res["schedule_diff"]
        assert "train-step" in diff
        fps = diff["train-step"]
        assert fps["host0"] != fps["host1"]
        assert hostsim._counter_total(
            res["telemetry"], "collective_schedule_mismatch_total") >= 1
