"""Jittered exponential backoff with timeout + telemetry.

The reference platform retries at many layers (HDFS client command retry
in fleet/utils/fs.py, etcd re-registration in fleet/elastic.py, RPC
re-sends in the PS core). Here that policy lives in ONE decorator applied
at the I/O seams: checkpoint save/restore, the elastic KV directory, and
dataloader fetches.

Every absorbed failure counts ``retries_total{site=...}``; giving up
counts ``retry_exhausted_total{site=...}`` and re-raises the last error.
Jitter is deterministic per (site, seed, attempt) so tests replay
byte-identical schedules; ``sleep`` is injectable for zero-wall-time
tests.

Byte budget: attempts bounded by wall time alone let a flaky remote fs
re-upload a multi-GB checkpoint every retry.  ``attempt_bytes`` declares
what one attempt moves and ``byte_budget`` caps the total; once the NEXT
attempt would exceed the cap, :class:`RetryBytesExhausted` is raised
(``retry_bytes_abandoned_total{site}``) so the caller can degrade — the
checkpoint layer falls back to local-disk staging instead of re-uploading
(``ckpt_retry_bytes_abandoned_total``).  The first attempt always runs,
whatever the budget.
"""
from __future__ import annotations

import functools
import random
import time
import zlib
from typing import Callable, Optional, Tuple, Type

__all__ = ["retry", "call_with_retry", "RetryBytesExhausted"]


class RetryBytesExhausted(RuntimeError):
    """Retrying was stopped by the byte budget, not by the error going
    away. ``last`` is the final underlying exception; ``bytes_spent``
    what the attempts already moved."""

    def __init__(self, site: str, bytes_spent: float, byte_budget: float,
                 last: Optional[BaseException]):
        super().__init__(
            f"retry[{site}]: next attempt would exceed the byte budget "
            f"({bytes_spent:.0f} of {byte_budget:.0f} bytes already "
            f"spent); last error: {last!r}")
        self.site = site
        self.bytes_spent = bytes_spent
        self.byte_budget = byte_budget
        self.last = last


def _backoff(attempt: int, base_delay: float, factor: float,
             max_delay: float, jitter: float, site: str, seed: int) -> float:
    delay = min(max_delay, base_delay * (factor ** (attempt - 1)))
    if jitter:
        u = random.Random(
            zlib.crc32(f"{site}:{seed}:{attempt}".encode())).random()
        delay *= 1.0 + jitter * u
    return delay


def retry(tries: int = 3, base_delay: float = 0.05, factor: float = 2.0,
          max_delay: float = 2.0, jitter: float = 0.5,
          timeout: Optional[float] = None,
          retry_on: Tuple[Type[BaseException], ...] = (OSError,),
          site: str = "", seed: int = 0,
          sleep: Callable[[float], None] = time.sleep,
          attempt_bytes: Optional[float] = None,
          byte_budget: Optional[float] = None):
    """Decorator: retry ``fn`` on ``retry_on`` with jittered exponential
    backoff, at most ``tries`` attempts, within ``timeout`` seconds of the
    first attempt, and — when ``attempt_bytes``/``byte_budget`` are given
    — within a total moved-bytes budget (the first attempt always runs;
    a retry that would push past the budget raises
    :class:`RetryBytesExhausted` instead of re-running)."""

    def deco(fn):
        label = site or fn.__name__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            deadline = (time.monotonic() + timeout) if timeout else None
            last: Optional[BaseException] = None
            bytes_spent = 0.0
            for attempt in range(1, tries + 1):
                try:
                    return fn(*args, **kwargs)
                except retry_on as e:  # noqa: PERF203 - the whole point
                    last = e
                    if attempt_bytes:
                        bytes_spent += attempt_bytes
                    from .. import telemetry
                    tel = telemetry.enabled()
                    if attempt >= tries:
                        break
                    if attempt_bytes and byte_budget is not None and \
                            bytes_spent + attempt_bytes > byte_budget:
                        if tel:
                            telemetry.counter(
                                "retry_bytes_abandoned_total",
                                "retries abandoned by the byte budget, "
                                "by call site"
                            ).inc(site=label)
                        raise RetryBytesExhausted(
                            label, bytes_spent, byte_budget, last) from last
                    delay = _backoff(attempt, base_delay, factor, max_delay,
                                     jitter, label, seed)
                    if deadline is not None and \
                            time.monotonic() + delay > deadline:
                        break
                    if tel:
                        telemetry.counter(
                            "retries_total",
                            "absorbed transient failures, by call site"
                        ).inc(site=label)
                    sleep(delay)
            from .. import telemetry
            if telemetry.enabled():
                telemetry.counter(
                    "retry_exhausted_total",
                    "operations that failed after all retries"
                ).inc(site=label)
            raise last

        return wrapper

    return deco


def call_with_retry(fn, *args, **retry_kwargs):
    """One-shot form: ``call_with_retry(fn, site="ckpt_save", tries=5)``.
    Positional args beyond ``fn`` are passed to ``fn``."""
    return retry(**retry_kwargs)(fn)(*args)
