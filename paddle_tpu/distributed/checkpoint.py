"""Distributed (sharded, async) checkpointing + auto-resume.

Capability map (reference):
- per-rank sharded checkpoints       ← sharding/hybrid save (tests
  dist_sharding_save.py; fleet `save_persistables`) — here orbax writes each
  shard from the device holding it (mesh-keyed, the SURVEY.md §5 TPU
  translation of per-rank files).
- auto-checkpoint for preemption     ← incubate/checkpoint/auto_checkpoint.py
  :265 TrainEpochRange, :598 train_epoch_range — snapshot + transparent
  resume keyed by job id.
- HDFS/AFS remote fs                 ← fleet/utils/fs.py — orbax talks to
  any fsspec/gcs path; local paths here (zero-egress box).

Async: orbax's async checkpointer overlaps the device→host gather and file
write with training (the reference's PS tier saved asynchronously via its
own threads; XLA-side this is the idiomatic equivalent).

Crash consistency: orbax already writes each step into a temp dir and
atomically renames it, but a crash BETWEEN the rename and the end of the
file writes' journey to stable storage (or plain on-disk rot) can still
leave a step directory that lists as present yet does not restore. The
manager therefore runs a two-phase commit on top: after the write
completes it fsyncs every file, records a CRC32-checksum ``MANIFEST.json``
(tmp + fsync + atomic rename + dir fsync), and only then counts the step
as committed. ``restore()`` verifies the manifest and falls back to the
newest step that checks out (counting ``ckpt_restore_fallbacks_total``);
retention GC runs only after a verified commit and never removes the last
valid step.

Byte budget: save retries are additionally bounded by bytes moved
(``CKPT_RETRY_BYTE_BUDGET_X`` × state size) — a flaky remote fs re-uploads
the full state every attempt, so past the budget the save DEGRADES to
local-disk staging (``PADDLE_TPU_CKPT_STAGING`` or a tempdir; counted in
``ckpt_retry_bytes_abandoned_total``) instead of burning the link.
``restore()`` falls back to the newest verified staged step when no
primary step restores.

Async commit pipeline (ISSUE 13): with ``async_commit=True`` the manager
takes the whole write→fsync→CRC→MANIFEST→rename two-phase commit off the
step path. ``save()`` only snapshots the device arrays into a host-side
staging buffer (``jax.device_get`` — a donation-safe copy, so step N+1
can freely mutate the live state while step N persists) and returns; a
background committer thread runs the commit. The pipeline is
double-buffered: at most one snapshot is being committed and one is
staged — a newer snapshot arriving while one is staged SUPERSEDES it
(``ckpt_suppressed_total{reason=superseded}``) so save cadence degrades
gracefully under backpressure instead of stalling the step loop. A
``dirty_probe`` callable is consulted at COMMIT time (not snapshot
time): a quarantine verdict arriving while a tainted snapshot is in
flight suppresses the commit (``reason=dirty``). Every snapshot
terminates as exactly one of committed / superseded / suppressed /
failed / abandoned (``accounted()``).

Async crash consistency: before the committer starts writing step N it
durably records a ``PENDING.N`` intent marker in the root; the marker is
removed only after the manifest lands. A step directory carrying a live
marker and no manifest is an aborted async commit — ``restore()`` and
``latest_valid_step()`` skip it WITHOUT counting a restore fallback (it
was never committed; nothing was lost) and retention GC removes the
debris. A crash anywhere in the pipeline therefore leaves the previous
``latest_valid_step()`` intact.

Hierarchical tiers: ``deep_every=M`` makes every M-th save a DEEP save
(per-array content digests in the manifest, PR 9) and the rest cheap
(file CRCs only). ``restore(prefer_deep=True)`` prefers the newest
deep-verified step and falls back through the cheap tier with the
existing ``ckpt_restore_fallbacks_total{reason}`` accounting. Digests
for async deep saves are computed on the committer thread from the host
snapshot — off the step path, so deep tiers no longer defeat async.
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
import time
import warnings
import zlib
from typing import Any, Callable, List, Optional

import jax

__all__ = ["save_checkpoint", "load_checkpoint", "CheckpointManager",
           "TrainEpochRange", "train_epoch_range",
           "write_manifest", "verify_manifest", "MANIFEST_NAME",
           "CKPT_RETRY_BYTE_BUDGET_X", "staging_root", "stall_seconds",
           "attributing_stall", "STALL_BUCKETS_MS"]

MANIFEST_NAME = "MANIFEST.json"
PENDING_PREFIX = "PENDING."

# ms-denominated buckets for the step-stall/snapshot/commit histograms
# (DEFAULT_BUCKETS are seconds-scaled and too coarse under 1ms)
STALL_BUCKETS_MS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0)

# retries may move at most this multiple of the state size before the
# save degrades to local staging (first attempt always runs)
CKPT_RETRY_BYTE_BUDGET_X = 3.0


def staging_root() -> str:
    """Local-disk home for degraded saves: ``PADDLE_TPU_CKPT_STAGING``
    or a tempdir fallback. Must be a genuinely local path — it is where
    saves land when the REMOTE fs is the thing failing."""
    env = os.environ.get("PADDLE_TPU_CKPT_STAGING")
    if env:
        return env
    return os.path.join(tempfile.gettempdir(), "paddle_tpu_ckpt_staging")


def _state_nbytes(state: Any) -> float:
    return float(sum(getattr(v, "nbytes", 0) or 0
                     for v in jax.tree_util.tree_leaves(state)))


# -- step-stall attribution --------------------------------------------------
#
# Every checkpoint path that can block a training step records its
# host-blocking duration here (sync saves: the whole write+commit; async
# saves: the device→host snapshot only). The step-time instrumentation
# (hapi TelemetryCallback) reads the ledger to EXCLUDE save stall from
# ``step_time_seconds``, so MFU / tokens-per-sec stop dipping on
# checkpoint steps — the stall is its own headline series.

_stall_lock = threading.Lock()
_stall_seconds_total = 0.0


def stall_seconds() -> float:
    """Cumulative host-blocking checkpoint time this process (seconds).
    Step-time instrumentation diffs this across a timed window to carve
    save stall out of ``step_time_seconds``."""
    with _stall_lock:
        return _stall_seconds_total


def _record_stall(dt: float):
    """Attribute ``dt`` seconds of step-loop blocking to checkpointing:
    the ``ckpt_step_stall_ms`` histogram (the headline async-vs-sync
    metric) plus the process-wide ledger."""
    global _stall_seconds_total
    with _stall_lock:
        _stall_seconds_total += dt
    from .. import telemetry
    if telemetry.enabled():
        telemetry.histogram(
            "ckpt_step_stall_ms",
            "time the step loop blocked on a checkpoint save (async: "
            "snapshot only; sync: the full write+commit)",
            buckets=STALL_BUCKETS_MS).observe(dt * 1000.0)


class attributing_stall:
    """Context manager: attribute the wrapped block's wall time to
    checkpoint stall (used by save paths outside this module, e.g. the
    hapi ModelCheckpoint callback)."""

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        _record_stall(time.perf_counter() - self._t0)
        return False


def _save_retry_kwargs(nbytes: float) -> dict:
    """Retry policy for checkpoint saves. With a known state size the byte
    budget binds first — floor(3×/1×) = 3 upload attempts, then degrade —
    and the try count is only a backstop; a zero-byte state keeps the
    plain 3-try policy."""
    if not nbytes:
        return {"tries": 3}
    return {"tries": 6, "attempt_bytes": nbytes,
            "byte_budget": CKPT_RETRY_BYTE_BUDGET_X * nbytes}


def _count_staged(nbytes: float):
    from .. import telemetry
    if telemetry.enabled():
        telemetry.counter(
            "ckpt_retry_bytes_abandoned_total",
            "checkpoint bytes NOT re-uploaded because the retry byte "
            "budget degraded the save to local staging").inc(nbytes)


_cached = {}  # one checkpointer per mode: async saves barrier on reuse


def _record(op: str, dt: float, state: Any):
    """Telemetry: save/restore wall time + bytes moved. For async saves the
    duration is the dispatch (host-blocking) portion — the part that stalls
    training — not the background write."""
    from .. import telemetry
    if not telemetry.enabled():
        return
    telemetry.histogram(
        f"checkpoint_{op}_seconds",
        f"checkpoint {op} wall time (host-blocking part)").observe(dt)
    nbytes = float(sum(getattr(v, "nbytes", 0) or 0
                       for v in jax.tree_util.tree_leaves(state)))
    if nbytes:
        telemetry.counter(
            "checkpoint_bytes_total", "checkpointed bytes").inc(
                nbytes, op=op)
    telemetry.emit("checkpoint", op=op, seconds=dt, bytes=nbytes)


def _checkpointer(use_async: bool):
    import orbax.checkpoint as ocp
    key = "async" if use_async else "sync"
    if key not in _cached:
        _cached[key] = (
            ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
            if use_async else
            ocp.Checkpointer(ocp.StandardCheckpointHandler()))
    return _cached[key]


# -- checksum manifest (two-phase commit) -----------------------------------

def _fsync_file(path: str):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str):
    try:
        _fsync_file(path)
    except OSError:
        pass  # some filesystems refuse directory fsync; rename is still atomic


def _crc_file(path: str, chunk: int = 1 << 20) -> int:
    c = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            c = zlib.crc32(b, c)
    return c & 0xFFFFFFFF


def write_manifest(step_dir: str, arrays: Optional[dict] = None) -> dict:
    """Commit marker: fsync every file under ``step_dir``, then atomically
    write a CRC32/size manifest. The manifest is written LAST (tmp + fsync +
    rename + dir fsync), so its presence proves every byte it attests to
    reached stable storage — a kill -9 at any point leaves either no
    manifest (step invalid, restore falls back) or a complete one.

    ``arrays`` (leaf-path → content digest, from
    ``resilience.integrity.tree_digests``) is recorded under an ``"arrays"``
    key: file CRCs attest the *bytes on disk*, array digests attest the
    *decoded values* — deep verify re-hashes the restored pytree against
    them, catching corruption the file layer re-encodes (and giving
    ``replay_step`` its reference digest)."""
    files = {}
    for root, _dirs, names in os.walk(step_dir):
        for n in sorted(names):
            if n in (MANIFEST_NAME, MANIFEST_NAME + ".tmp"):
                continue
            p = os.path.join(root, n)
            _fsync_file(p)  # durability BEFORE attestation
            files[os.path.relpath(p, step_dir)] = {
                "size": os.path.getsize(p), "crc32": _crc_file(p)}
    manifest = {"version": 1, "files": files}
    if arrays:
        manifest["arrays"] = dict(arrays)
    tmp = os.path.join(step_dir, MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(step_dir, MANIFEST_NAME))
    _fsync_dir(step_dir)
    return manifest


def verify_manifest(step_dir: str, level: str = "full") -> Optional[bool]:
    """Three-valued: ``True`` — manifest present and every attested file
    matches; ``False`` — manifest present but unreadable, or a file is
    missing/corrupt (torn checkpoint); ``None`` — no manifest (a legacy
    checkpoint from before this commit protocol; restore attempts it and
    relies on orbax's own errors).

    ``level="size"`` checks existence + recorded byte size only (a stat per
    file, no reads) — the cheap pre-reject used when scanning many steps;
    ``level="full"`` also re-CRCs every file."""
    mpath = os.path.join(step_dir, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return False
    for rel, meta in manifest.get("files", {}).items():
        p = os.path.join(step_dir, rel)
        try:
            if os.path.getsize(p) != meta["size"]:
                return False
            if level != "size" and _crc_file(p) != meta["crc32"]:
                return False
        except OSError:
            return False
    return True


# -- async-commit intent markers --------------------------------------------

def _pending_marker(root: str, step: int) -> str:
    return os.path.join(root, PENDING_PREFIX + str(step))


def _write_pending_marker(root: str, step: int):
    """Durably record "step is being committed" BEFORE any byte of the
    step is written: marker file fsync'd, then the root dir fsync'd so
    the dirent survives a crash. A step dir found later with a live
    marker and no manifest is an aborted commit, never a committed step."""
    p = _pending_marker(root, step)
    with open(p, "w") as f:
        f.write(str(step))
        f.flush()
        os.fsync(f.fileno())
    _fsync_dir(root)


def _clear_pending_marker(root: str, step: int):
    try:
        os.remove(_pending_marker(root, step))
    except OSError:
        pass


def _is_uncommitted(root: str, step: int) -> bool:
    """Aborted async commit: intent marker present, manifest absent.
    (A marker WITH a manifest is just a crash between manifest write and
    marker removal — the commit completed; the stale marker is ignored
    and cleaned up by GC.)"""
    return (os.path.exists(_pending_marker(root, step))
            and not os.path.exists(
                os.path.join(root, str(step), MANIFEST_NAME)))


def _corrupt_one_file(step_dir: str):
    """Fault-injection helper (ckpt_torn): truncate the largest data file —
    what a machine loss mid-flush leaves behind."""
    best, size = None, -1
    for root, _dirs, names in os.walk(step_dir):
        for n in names:
            p = os.path.join(root, n)
            s = os.path.getsize(p)
            if s > size:
                best, size = p, s
    if best is not None:
        with open(best, "r+b") as f:
            f.truncate(max(1, size // 2))


def _stage_save(dest: str, state: Any, nbytes: float,
                err: BaseException, arrays: Optional[dict] = None) -> str:
    """Degraded save path: a plain sync orbax write onto local disk, no
    fault hooks and no retry — if LOCAL disk is failing too there is
    nothing left to degrade to. Manifested like any committed step so
    restore can verify it."""
    import orbax.checkpoint as ocp
    dest = os.path.abspath(dest)
    if os.path.isdir(dest):
        shutil.rmtree(dest)
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    ocp.Checkpointer(ocp.StandardCheckpointHandler()).save(
        dest, args=ocp.args.StandardSave(state), force=True)
    write_manifest(dest, arrays=arrays)
    _count_staged(nbytes)
    warnings.warn(
        f"checkpoint save exceeded its retry byte budget ({err}); "
        f"staged to local disk at {dest}", RuntimeWarning)
    return dest


def save_checkpoint(path: str, state: Any, overwrite: bool = True,
                    use_async: bool = False,
                    staging_dir: Optional[str] = None):
    """Save a pytree of (possibly sharded) jax arrays. Each host writes only
    the shards it owns. With ``use_async`` the write overlaps training; the
    module keeps ONE async checkpointer, so a subsequent save waits for the
    in-flight one (no torn writes) — call ``wait_until_finished`` on the
    returned checkpointer before process exit.

    Retries are byte-budgeted (``CKPT_RETRY_BYTE_BUDGET_X`` × state size);
    past the budget the save lands in ``staging_dir`` (default
    ``staging_root()/<basename(path)>``) instead of re-uploading."""
    import orbax.checkpoint as ocp
    from ..resilience import faults
    from ..resilience.retry import RetryBytesExhausted, call_with_retry
    ckptr = _checkpointer(use_async)
    nbytes = _state_nbytes(state)
    t0 = time.perf_counter()

    def _write():
        faults.maybe_raise("ckpt_io", site="save_checkpoint",
                            msg="injected ckpt_io on save")
        ckptr.save(os.path.abspath(path), args=ocp.args.StandardSave(state),
                   force=overwrite)

    try:
        # with a byte budget armed, IT is the binding limit (3× the state
        # = 3 uploads), so the try count is just a backstop
        call_with_retry(_write, site="ckpt_save", base_delay=0.01,
                        **_save_retry_kwargs(nbytes))
    except RetryBytesExhausted as e:
        dest = staging_dir or os.path.join(
            staging_root(), os.path.basename(os.path.abspath(path)))
        _stage_save(dest, state, nbytes, e)
    _record("save", time.perf_counter() - t0, state)
    return ckptr


def load_checkpoint(path: str, template: Optional[Any] = None):
    """Restore a pytree. ``template`` (a pytree of arrays or
    ShapeDtypeStruct with .sharding) restores each leaf sharded directly to
    its devices; without it, arrays land replicated on the default device."""
    import orbax.checkpoint as ocp
    from ..resilience.retry import call_with_retry
    ckptr = _checkpointer(False)
    t0 = time.perf_counter()

    def _read():
        if template is not None:
            abstract = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype,
                    sharding=getattr(x, "sharding", None))
                if hasattr(x, "shape") else x,
                template)
            return ckptr.restore(os.path.abspath(path),
                                 args=ocp.args.StandardRestore(abstract))
        return ckptr.restore(os.path.abspath(path))

    out = call_with_retry(_read, site="ckpt_restore", tries=2,
                          base_delay=0.01)
    _record("restore", time.perf_counter() - t0, out)
    return out


class CheckpointManager:
    """Step-numbered checkpoints with retention + save-interval policy
    (reference capability: ModelCheckpoint callback hapi/callbacks.py:533 +
    auto_checkpoint retention), hardened with a two-phase commit:

    1. write — orbax writes the step (tmp dir + atomic rename), possibly
       async; the step is tracked as *pending*.
    2. commit — after the write finishes, every file is fsynced and a CRC32
       ``MANIFEST.json`` is atomically recorded; only then does retention GC
       run. GC keeps the newest ``max_to_keep`` VALID steps and never
       removes the last valid one, so a torn newest step can always fall
       back to a good predecessor.

    ``restore()`` (no explicit step) scans newest→oldest, skipping steps
    that fail verification or error mid-restore, counting each skip in
    ``ckpt_restore_fallbacks_total``.

    ``deep_digests=True`` (opt-in) records per-array content digests in
    the manifest so ``verify(step, deep=True)`` / ``restore(deep=True)``
    and ``replay_step`` have a value-level reference. ``deep_every=M``
    is the tiered form: every M-th save is deep, the rest are cheap
    (file CRCs only) — frequent cheap saves interleaved with rare
    verified ones.

    ``async_commit=True`` moves the whole commit off the step path (see
    the module docstring): ``save()`` snapshots device arrays host-side
    and returns; a background committer thread writes, manifests, and
    GCs. ``dirty_probe`` (settable any time, typically by
    ``run_resilient``) is consulted at commit time — a True answer
    suppresses the commit (``ckpt_suppressed_total{reason=dirty}``).
    ``commit_delay`` artificially slows each commit (test/chaos knob for
    racing a verdict against an in-flight snapshot).
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1, use_async: bool = True,
                 staging_dir: Optional[str] = None,
                 deep_digests: bool = False,
                 async_commit: bool = False, deep_every: int = 0,
                 dirty_probe: Optional[Callable[[], bool]] = None,
                 commit_delay: float = 0.0):
        import orbax.checkpoint as ocp
        self._dir = os.path.abspath(directory)
        self._staging = staging_dir or os.path.join(
            staging_root(), os.path.basename(self._dir))
        self._max_to_keep = max_to_keep
        # our committer thread IS the async layer: orbax stays sync under it
        self._async_commit = bool(async_commit)
        self._use_async = use_async and not self._async_commit
        # retention is OURS (post-commit, validity-aware): orbax counting
        # torn steps toward max_to_keep could GC the last valid one.
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=None,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=self._use_async))
        self._save_interval = max(1, int(save_interval_steps))
        self._deep_digests = deep_digests
        self._deep_every = max(0, int(deep_every))
        self._save_seq = 0              # save() calls, drives the tier cadence
        self._pending: List[int] = []   # written (maybe in flight), no manifest yet
        self._pending_digests = {}      # step -> tree_digests, until committed
        self._vcache = {}               # step -> verify_manifest result
        self.restore_fallbacks_total = 0   # corrupt steps skipped over
        self.last_restored_step: Optional[int] = None
        # -- async commit pipeline state --
        self.dirty_probe = dirty_probe  # consulted at COMMIT time
        self.commit_delay = float(commit_delay)
        self._fs_lock = threading.RLock()   # serializes disk ops vs committer
        self._cv = threading.Condition()
        self._staged = None             # (step, host_state, deep) double buffer
        self._committing: Optional[int] = None
        self._commit_thread: Optional[threading.Thread] = None
        self._commit_gate = threading.Event()  # cleared = commits paused
        self._commit_gate.set()
        self._stopping = False
        self._thread_error: Optional[BaseException] = None
        # snapshot accounting: every snapshot must terminate as exactly
        # one of these (or still be in flight)
        self.snapshots_total = 0
        self.committed_total = 0
        self.superseded_total = 0
        self.suppressed_dirty_total = 0
        self.failed_total = 0
        self.abandoned_total = 0

    def _step_dir(self, step: int) -> str:
        return os.path.join(self._dir, str(step))

    def _staged_step_dir(self, step: int) -> str:
        return os.path.join(self._staging, str(step))

    def staged_steps(self) -> List[int]:
        """Steps that degraded to local staging (newest last)."""
        if not os.path.isdir(self._staging):
            return []
        out = []
        for n in os.listdir(self._staging):
            if n.isdigit() and os.path.isdir(
                    os.path.join(self._staging, n)):
                out.append(int(n))
        return sorted(out)

    def _verify(self, step: int) -> Optional[bool]:
        if step not in self._vcache:
            self._vcache[step] = verify_manifest(self._step_dir(step))
        return self._vcache[step]

    def _uncommitted(self, step: int) -> bool:
        """Aborted async commit (live intent marker, no manifest) —
        never a restore candidate, never a counted fallback."""
        return _is_uncommitted(self._dir, step)

    # -- async commit pipeline ----------------------------------------------

    @property
    def async_commit(self) -> bool:
        return self._async_commit

    @property
    def deep_every(self) -> int:
        return self._deep_every

    @deep_every.setter
    def deep_every(self, value: int):
        self._deep_every = max(0, int(value))

    def accounted(self) -> bool:
        """Every snapshot terminated (committed / superseded / suppressed
        / failed / abandoned) and none is still in flight."""
        with self._cv:
            in_flight = (self._staged is not None
                         or self._committing is not None)
            total = (self.committed_total + self.superseded_total
                     + self.suppressed_dirty_total + self.failed_total
                     + self.abandoned_total)
            return not in_flight and total == self.snapshots_total

    def inflight(self) -> int:
        """Snapshots staged or mid-commit (0..2 — double-buffered)."""
        with self._cv:
            return int(self._staged is not None) + \
                int(self._committing is not None)

    def pause_commits(self):
        """Hold the committer before its next commit (test/chaos hook:
        the deterministic 'between snapshot and commit' window)."""
        self._commit_gate.clear()

    def resume_commits(self):
        self._commit_gate.set()
        with self._cv:
            self._cv.notify_all()

    def _count_suppressed(self, reason: str):
        from .. import telemetry
        if reason == "dirty":
            self.suppressed_dirty_total += 1
        else:
            self.superseded_total += 1
        if telemetry.enabled():
            telemetry.counter(
                "ckpt_suppressed_total",
                "async snapshots whose commit was suppressed "
                "(dirty: quarantine verdict arrived while in flight; "
                "superseded: a newer snapshot replaced it)").inc(
                    reason=reason)

    def _set_inflight_gauge(self):
        from .. import telemetry
        if telemetry.enabled():
            telemetry.gauge(
                "ckpt_inflight",
                "snapshots staged or mid-commit (async pipeline)").set(
                    self.inflight())

    def _snapshot_host(self, state: Any):
        """Device→host copy of the state tree (numpy leaves): the staging
        buffer the committer persists. After this returns, nothing in the
        snapshot aliases device memory, so the next step may donate/mutate
        the live state freely."""
        import numpy as np

        def _leaf(x):
            if isinstance(x, np.ndarray):
                return np.array(x)  # private copy: caller may mutate theirs
            if isinstance(x, np.generic):
                return np.asarray(x)
            if hasattr(x, "shape"):
                try:
                    return np.asarray(jax.device_get(x))
                except Exception:
                    return x  # non-addressable shard: let orbax handle it
            return x
        from .. import telemetry
        t0 = time.perf_counter()
        host = jax.tree_util.tree_map(_leaf, state)
        dt = time.perf_counter() - t0
        if telemetry.enabled():
            telemetry.histogram(
                "ckpt_snapshot_ms",
                "device→host staging-buffer copy time (the only part of "
                "an async save on the step path)",
                buckets=STALL_BUCKETS_MS).observe(dt * 1000.0)
        return host

    def _ensure_committer(self):
        if self._commit_thread is None or not self._commit_thread.is_alive():
            self._stopping = False
            self._commit_thread = threading.Thread(
                target=self._commit_loop, name="ckpt-committer", daemon=True)
            self._commit_thread.start()

    def _commit_loop(self):
        while True:
            with self._cv:
                while not self._stopping and (
                        self._staged is None
                        or not self._commit_gate.is_set()):
                    self._cv.wait(timeout=0.05)
                if self._stopping and self._staged is None:
                    return
                if self._staged is None or not self._commit_gate.is_set():
                    continue
                step, host, deep, trace = self._staged
                self._staged = None
                self._committing = step
            try:
                self._commit_one(step, host, deep, trace=trace)
            except BaseException as e:  # noqa: BLE001 — surfaced via flush()
                self.failed_total += 1
                self._thread_error = e
                if trace is not None:
                    trace.close("failed", error=repr(e))
            finally:
                with self._cv:
                    self._committing = None
                    self._cv.notify_all()
                self._set_inflight_gauge()

    def _commit_one(self, step: int, host_state: Any, deep: bool,
                    trace=None):
        """The off-step-path half of an async save: dirty check, intent
        marker, orbax write (retry/byte-budgeted like the sync path),
        two-phase manifest commit, retention GC.

        ``trace`` is the ckpt_save trace opened on the step thread; the
        commit span below therefore ends on the committer thread — the
        cross-thread handoff the span record's ``end_thread`` attribute
        documents."""
        import orbax.checkpoint as ocp
        from ..resilience import faults
        from ..resilience.retry import RetryBytesExhausted, call_with_retry
        from .. import telemetry
        sp = trace.span("commit", step=step) if trace is not None else None

        def _finish(outcome: str, **attrs):
            if sp is not None and not sp._ended:
                sp.end(outcome, **attrs)
            if trace is not None:
                trace.close(outcome)

        if self.commit_delay > 0:
            time.sleep(self.commit_delay)
        # the subtle interaction: consult the dirty flag at COMMIT time —
        # a quarantine verdict that arrived after the snapshot was taken
        # must keep the tainted state off disk
        probe = self.dirty_probe
        if probe is not None and probe():
            self._count_suppressed("dirty")
            if telemetry.enabled():
                telemetry.emit("ckpt_commit", step=step, outcome="dirty")
            _finish("dirty")
            return
        t0 = time.perf_counter()
        arrays = None
        if deep:
            from ..resilience.integrity import tree_digests
            arrays = tree_digests(host_state)  # host-side, off the step path
        with self._fs_lock:
            # crash window proof: the marker lands durably before any byte
            _write_pending_marker(self._dir, step)
            if step in (self._mngr.all_steps() or []):
                self._mngr.delete(step)
                self._vcache.pop(step, None)

            def _write():
                faults.maybe_raise(
                    "ckpt_io", step=step, site="async_commit",
                    msg=f"injected ckpt_io committing step {step}")
                return self._mngr.save(
                    step, args=ocp.args.StandardSave(host_state))

            nbytes = _state_nbytes(host_state)
            try:
                saved = call_with_retry(
                    _write, site="ckpt_save", base_delay=0.01,
                    **_save_retry_kwargs(nbytes))
            except RetryBytesExhausted as e:
                _stage_save(self._staged_step_dir(step), host_state,
                            nbytes, e, arrays=arrays)
                _clear_pending_marker(self._dir, step)
                self.committed_total += 1  # durable, just degraded
                _finish("degraded", bytes_budget=True)
                return
            if not saved:
                self.superseded_total += 1  # orbax interval-skipped it
                _finish("superseded")
                return
            self._mngr.wait_until_finished()
            sdir = self._step_dir(step)
            if faults.fires("ckpt_torn", step=step, site="ckpt_commit"):
                # the kill -9 window: torn payload, no manifest, marker
                # left live — the step must stay invisible to restores
                _corrupt_one_file(sdir)
                self._vcache.pop(step, None)
                raise faults.SimulatedCrash(
                    f"simulated kill -9 committing checkpoint step {step}")
            if os.environ.get("PADDLE_TPU_TEST_COMMIT_CRASH") == str(step):
                # chaos hook: a REAL kill -9 after the payload write but
                # before the manifest — the torn-dir crash window
                import signal as _signal
                os.kill(os.getpid(), _signal.SIGKILL)
            write_manifest(sdir, arrays=arrays)
            _clear_pending_marker(self._dir, step)
            self._vcache[step] = True
            self.committed_total += 1
            self._gc()
        dt = time.perf_counter() - t0
        _record("save", dt, host_state)
        if telemetry.enabled():
            telemetry.histogram(
                "ckpt_commit_ms",
                "background write→fsync→CRC→manifest→GC time per "
                "committed step (off the step path)",
                buckets=STALL_BUCKETS_MS).observe(dt * 1000.0)
            telemetry.emit("ckpt_commit", step=step,
                           outcome="committed", deep=bool(deep),
                           commit_ms=dt * 1000.0)
        _finish("committed", commit_ms=dt * 1000.0)

    def _save_async(self, step: int, state: Any, deep: bool) -> bool:
        """The on-step-path half: snapshot + stage + return. Never blocks
        on IO; a staged-but-not-started older snapshot is superseded."""
        from ..telemetry import tracing as _tracing
        self._raise_thread_error()
        if self._save_interval > 1 and step % self._save_interval:
            return False
        t0 = time.perf_counter()
        tr = _tracing.start_trace("ckpt_save", step=step, deep=bool(deep))
        if tr is not None:
            with tr.span("snapshot", step=step):
                host = self._snapshot_host(state)
        else:
            host = self._snapshot_host(state)
        with self._cv:
            self.snapshots_total += 1
            if self._staged is not None:
                # double buffer full: the newer state supersedes — cadence
                # degrades under backpressure, the step loop never waits
                self._count_suppressed("superseded")
                old_tr = self._staged[3]
                if old_tr is not None:
                    old_tr.close("superseded", superseded_by=step)
            # the trace rides the staged tuple across to the committer
            # thread (explicit handoff; the commit span ends over there)
            self._staged = (step, host, deep, tr)
            self._cv.notify_all()
        self._ensure_committer()
        self._set_inflight_gauge()
        dt = time.perf_counter() - t0
        _record_stall(dt)
        from .. import telemetry
        if telemetry.enabled():
            telemetry.emit("ckpt_snapshot", step=step, deep=bool(deep),
                           snapshot_ms=dt * 1000.0)
        return True

    def _raise_thread_error(self):
        """Re-raise a committer-thread SimulatedCrash (the injected
        kill -9) at the step boundary so run_resilient's restart path
        sees it exactly like the sync pipeline's. Other commit failures
        stay recorded (failed_total) without killing the run."""
        err, self._thread_error = self._thread_error, None
        if err is not None:
            from ..resilience import faults
            if isinstance(err, faults.SimulatedCrash):
                raise err
            warnings.warn(f"async checkpoint commit failed: {err!r}",
                          RuntimeWarning)

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Block until no snapshot is staged or mid-commit (drain /
        pre-restore barrier). Returns False on timeout. Re-raises a
        committer SimulatedCrash."""
        if self._async_commit and not self._commit_gate.is_set() and \
                (self._staged is not None or self._committing is not None):
            warnings.warn("flush() while commits are paused — resuming",
                          RuntimeWarning)
            self.resume_commits()
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._staged is not None or self._committing is not None:
                wait = 0.1 if deadline is None else min(
                    0.1, deadline - time.monotonic())
                if deadline is not None and wait <= 0:
                    return False
                self._cv.wait(timeout=wait)
        self._raise_thread_error()
        return True

    def abandon(self):
        """Drop any staged snapshot without committing it (the in-process
        stand-in for dying mid-pipeline; chaos uses a real SIGKILL)."""
        with self._cv:
            if self._staged is not None:
                tr = self._staged[3]
                if tr is not None:
                    tr.close("abandoned")
                self._staged = None
                self.abandoned_total += 1
            self._cv.notify_all()

    def _tier_deep(self, explicit: Optional[bool]) -> bool:
        """Tier decision for one save: explicit flag wins; else
        ``deep_digests`` (every save) or the ``deep_every`` cadence
        (save #0, #M, #2M, ... are deep — a run always has a deep anchor)."""
        if explicit is not None:
            return bool(explicit)
        if self._deep_digests:
            return True
        if self._deep_every:
            return self._save_seq % self._deep_every == 0
        return False

    def _commit_pending(self):
        """Phase 2: barrier on in-flight writes, manifest each pending step,
        then GC. An injected ``ckpt_torn`` fault corrupts the step and skips
        its manifest before raising SimulatedCrash — the kill -9 window."""
        if not self._pending:
            return
        self._mngr.wait_until_finished()
        from ..resilience import faults
        while self._pending:
            step = self._pending.pop(0)
            sdir = self._step_dir(step)
            if faults.fires("ckpt_torn", step=step, site="ckpt_commit"):
                _corrupt_one_file(sdir)
                self._vcache.pop(step, None)
                self._pending_digests.pop(step, None)
                raise faults.SimulatedCrash(
                    f"simulated kill -9 committing checkpoint step {step}")
            if os.path.isdir(sdir):
                write_manifest(sdir,
                               arrays=self._pending_digests.pop(step, None))
                # a sync replay of a step whose async commit was aborted:
                # the commit just completed, retire the stale intent marker
                _clear_pending_marker(self._dir, step)
                self._vcache[step] = True
            else:
                self._pending_digests.pop(step, None)
        self._gc()

    def _gc(self):
        """Retention, run only after a verified commit. Keeps the newest
        ``max_to_keep`` valid steps; steps that fail verification and fall
        outside the kept window are deleted too (torn debris), but if
        NOTHING verifies, nothing is deleted."""
        if not self._max_to_keep:
            return
        steps = sorted(self._mngr.all_steps() or [])
        # aborted async commits (live marker, no manifest) are debris:
        # always collectable, never restore candidates, and their stale
        # markers go with them
        debris = {s for s in steps if self._uncommitted(s)}
        valid = [s for s in steps
                 if s not in debris and self._verify(s) is not False]
        if not valid:
            return
        keep = set(valid[-self._max_to_keep:])
        for s in steps:
            if s in keep or (s in self._pending and s not in debris):
                continue
            if s == self._committing:
                continue  # mid-commit on the committer thread
            try:
                self._mngr.delete(s)
            except Exception:
                continue
            _clear_pending_marker(self._dir, s)
            self._vcache.pop(s, None)
        # markers whose step dir is already gone (GC'd debris or a crash
        # before any byte landed)
        try:
            for name in os.listdir(self._dir):
                if not name.startswith(PENDING_PREFIX):
                    continue
                try:
                    s = int(name[len(PENDING_PREFIX):])
                except ValueError:
                    continue
                if _is_uncommitted(self._dir, s) and \
                        not os.path.isdir(self._step_dir(s)):
                    _clear_pending_marker(self._dir, s)
        except OSError:
            pass

    def save(self, step: int, state: Any,
             deep: Optional[bool] = None) -> bool:
        """Persist ``state`` as ``step``. ``deep`` pins this save's tier
        (None = the manager's ``deep_digests``/``deep_every`` policy).
        In async mode the call returns after the host snapshot; the
        two-phase commit happens on the committer thread."""
        import numpy as np
        import orbax.checkpoint as ocp
        from ..resilience import faults
        from ..resilience.retry import RetryBytesExhausted, call_with_retry
        tier_deep = self._tier_deep(deep)
        self._save_seq += 1
        if self._async_commit:
            return self._save_async(step, state, tier_deep)
        # numpy scalars (np.int32(3) etc.) are not in orbax's supported
        # leaf types — promote them to 0-d ndarrays
        state = jax.tree_util.tree_map(
            lambda x: np.asarray(x) if isinstance(x, np.generic) else x,
            state)
        self._commit_pending()  # previous async write: barrier + manifest
        if step in (self._mngr.all_steps() or []):
            # a restart legitimately replays the step it crashed in — clear
            # the stale (possibly torn) attempt so orbax doesn't refuse
            self._mngr.delete(step)
            self._vcache.pop(step, None)
            self._pending_digests.pop(step, None)
        arrays = None
        if tier_deep:
            # content digests are taken from the live state at save time —
            # the ground truth the payload must still decode to at restore
            from ..resilience.integrity import tree_digests
            arrays = tree_digests(state)

        def _write():
            faults.maybe_raise("ckpt_io", step=step, site="manager_save",
                               msg=f"injected ckpt_io at step {step}")
            return self._mngr.save(step, args=ocp.args.StandardSave(state))

        nbytes = _state_nbytes(state)
        t0 = time.perf_counter()
        try:
            saved = call_with_retry(
                _write, site="ckpt_save", base_delay=0.01,
                **_save_retry_kwargs(nbytes))
        except RetryBytesExhausted as e:
            # budget blown: the primary dir (likely remote) is too
            # expensive to keep re-uploading — stage locally instead.
            # Staged steps live OUTSIDE orbax's step tracking (no
            # pending/GC) and are picked up by restore() only when no
            # primary step verifies.
            _stage_save(self._staged_step_dir(step), state, nbytes, e,
                        arrays=arrays)
            dt = time.perf_counter() - t0
            _record("save", dt, state)
            _record_stall(dt)  # a sync save stalls the step for its wall
            return True
        if saved:  # interval-skipped saves shouldn't pollute the histogram
            self._pending.append(step)
            if arrays is not None:
                self._pending_digests[step] = arrays
            if not self._use_async:
                self._commit_pending()
            dt = time.perf_counter() - t0
            _record("save", dt, state)
            _record_stall(dt)  # a sync save stalls the step for its wall
        return saved

    def _restore_step(self, step: int, template: Optional[Any]):
        import orbax.checkpoint as ocp
        if template is not None:
            abstract = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=getattr(x, "sharding", None))
                if hasattr(x, "shape") else x, template)
            return self._mngr.restore(
                step, args=ocp.args.StandardRestore(abstract))
        # installed orbax refuses a bare restore (no registered handler
        # for the saved "default" item) — an explicit StandardRestore
        # with no abstract tree restores everything replicated on the
        # host
        return self._mngr.restore(step, args=ocp.args.StandardRestore())

    def _count_fallbacks(self, n: int, reason: str = "manifest"):
        if not n:
            return
        self.restore_fallbacks_total += n
        from .. import telemetry
        if telemetry.enabled():
            telemetry.counter(
                "ckpt_restore_fallbacks_total",
                "restores that skipped corrupt/torn checkpoints").inc(
                    n, reason=reason)

    def _manifest_arrays(self, step: int) -> Optional[dict]:
        """The recorded content digests for ``step`` (None when the step
        predates deep digests or has no readable manifest)."""
        mpath = os.path.join(self._step_dir(step), MANIFEST_NAME)
        try:
            with open(mpath) as f:
                return json.load(f).get("arrays") or None
        except (OSError, ValueError):
            return None

    def _deep_verify(self, step: int, template: Optional[Any] = None):
        """Restore the step's payload and re-hash every array against the
        digests recorded at save time. Returns ``(verdict, payload)``:
        ``True`` — all match, and ``payload`` is the restored tree so a
        deep restore can reuse it instead of reading the step a second
        time; ``False`` — a mismatch or an unreadable payload (rot the
        file CRCs re-encoded away, or plain corruption); ``None`` — no
        digests recorded. ``payload`` is None unless the verdict is
        ``True``."""
        from ..resilience.integrity import compare_digests, tree_digests
        recorded = self._manifest_arrays(step)
        if not recorded:
            return None, None
        try:
            out = self._restore_step(step, template)
        except Exception:
            return False, None
        if compare_digests(recorded, tree_digests(out)):
            return False, None
        return True, out

    def verify(self, step: int, deep: bool = False) -> Optional[bool]:
        """On-demand integrity check of a committed step. Shallow verifies
        the file layer (size + CRC32); ``deep=True`` additionally restores
        the payload and re-hashes every array against the save-time content
        digests. Three-valued like :func:`verify_manifest` (``None`` when
        the relevant attestation was never recorded)."""
        self._vcache.pop(step, None)
        shallow = self._verify(step)
        if shallow is False or not deep:
            return shallow
        dv, _ = self._deep_verify(step)
        if dv is None:  # no digests recorded: report the shallow verdict
            return shallow
        return dv

    def restore(self, step: Optional[int] = None,
                template: Optional[Any] = None, deep: bool = False,
                prefer_deep: bool = False):
        from ..resilience.retry import call_with_retry
        if self._async_commit:
            self.flush()  # only committed steps are restore candidates
        self._commit_pending()
        if step is not None:  # explicit step: verify, no fallback
            if self._uncommitted(step):
                raise OSError(
                    f"checkpoint step {step} was never committed "
                    f"(aborted async save)")
            # re-verify from disk (not the cache): restore is rare and this
            # catches rot that happened after the commit
            self._vcache.pop(step, None)
            if self._verify(step) is False:
                raise OSError(
                    f"checkpoint step {step} failed manifest verification")
            t0 = time.perf_counter()
            out = call_with_retry(self._restore_step, step, template,
                                  site="ckpt_restore", tries=2,
                                  base_delay=0.01)
            if deep:
                recorded = self._manifest_arrays(step)
                if recorded:
                    from ..resilience.integrity import (compare_digests,
                                                        tree_digests)
                    bad = compare_digests(recorded, tree_digests(out))
                    if bad:
                        raise OSError(
                            f"checkpoint step {step} failed deep "
                            f"verification: {bad[:4]}")
            _record("restore", time.perf_counter() - t0, out)
            self.last_restored_step = step
            return out
        steps_desc = sorted(self._mngr.all_steps() or [], reverse=True)
        deep_failed: set = set()
        if prefer_deep:
            # tier-aware pass 1: the newest DEEP-verified step wins —
            # cheap-tier steps (no digests) are not candidates yet and
            # cost no fallback here; they are pass 2's job
            for s in steps_desc:
                if self._uncommitted(s):
                    continue  # aborted async commit: debris, not a fallback
                if self._manifest_arrays(s) is None:
                    continue  # cheap tier
                self._vcache.pop(s, None)
                if self._verify(s) is False:
                    self._count_fallbacks(1, reason="manifest")
                    deep_failed.add(s)
                    continue
                t0 = time.perf_counter()
                dv, out = self._deep_verify(s, template)
                if dv is True:
                    _record("restore", time.perf_counter() - t0, out)
                    self.last_restored_step = s
                    return out
                self._count_fallbacks(1, reason="deep")
                deep_failed.add(s)
            # no deep anchor survived: fall back through the cheap tiers
        for s in steps_desc:
            if s in deep_failed:
                continue  # already counted above
            if self._uncommitted(s):
                continue  # aborted async commit: debris, not a fallback
            self._vcache.pop(s, None)
            if self._verify(s) is False:
                self._count_fallbacks(1, reason="manifest")
                continue
            if deep:
                t0 = time.perf_counter()
                dv, out = self._deep_verify(s, template)
                if dv is False:
                    # bytes check out but the decoded values do not —
                    # silent corruption between file layer and arrays
                    self._count_fallbacks(1, reason="deep")
                    continue
                if dv:
                    # the verified payload IS the restore — one read
                    _record("restore", time.perf_counter() - t0, out)
                    self.last_restored_step = s
                    return out
                # dv None: no digests recorded — plain restore below
            try:
                t0 = time.perf_counter()
                out = call_with_retry(self._restore_step, s, template,
                                      site="ckpt_restore", tries=2,
                                      base_delay=0.01)
            except Exception:
                # no manifest (legacy) or rot the manifest couldn't see —
                # orbax/tensorstore raised; fall back to an older step
                self._count_fallbacks(1, reason="restore")
                continue
            _record("restore", time.perf_counter() - t0, out)
            self.last_restored_step = s
            return out
        # no primary step restored: fall back to locally staged saves
        # (degraded by the retry byte budget), newest first
        for s in sorted(self.staged_steps(), reverse=True):
            sdir = self._staged_step_dir(s)
            if verify_manifest(sdir) is False:
                self._count_fallbacks(1, reason="staged")
                continue
            try:
                t0 = time.perf_counter()
                out = load_checkpoint(sdir, template=template)
            except Exception:
                self._count_fallbacks(1, reason="staged")
                continue
            self.last_restored_step = s
            return out
        return None

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def latest_valid_step(self) -> Optional[int]:
        """Newest step that passes (or predates) manifest verification.
        A size-only pre-pass (one stat per file, no reads) rejects
        truncated/missing payloads before the full CRC pass — this runs in
        the elastic restore barrier on every host, so the common
        all-healthy case should not re-read whole checkpoints."""
        for s in sorted(self._mngr.all_steps() or [], reverse=True):
            if self._uncommitted(s):
                continue  # aborted async commit — never latest_valid
            if verify_manifest(self._step_dir(s), level="size") is False:
                self._vcache[s] = False
                continue
            if self._verify(s) is not False:
                return s
        return None

    def all_steps(self):
        return self._mngr.all_steps()

    def wait_until_finished(self):
        if self._async_commit:
            self.flush()
        self._mngr.wait_until_finished()
        self._commit_pending()

    def close(self):
        try:
            if self._async_commit:
                self.flush()
                with self._cv:
                    self._stopping = True
                    self._cv.notify_all()
                if self._commit_thread is not None:
                    self._commit_thread.join(timeout=5.0)
            self._commit_pending()
        finally:
            self._mngr.close()


class TrainEpochRange:
    """Manual epoch-level checkpoint/resume over CheckpointManager.

    This is the explicit-control variant: the caller decides when to
    ``save``. The reference-faithful env-gated variant (PADDLE_JOB_ID
    activation, save-interval seconds, add_state registration) is
    ``incubate.checkpoint.auto_checkpoint.TrainEpochRange``, which builds on
    the same CheckpointManager — use that one for transparent resume
    (reference: incubate/checkpoint/auto_checkpoint.py:265).

    Usage::

        r = TrainEpochRange(max_epoch, name, checkpoint_dir=...)
        for epoch in r.get():          # resumes after the last saved epoch
            ...train...
            r.save(state_pytree)       # state: e.g. trainer.state
        restored = r.restored_state    # non-None when resuming
    """

    def __init__(self, max_epoch_num: int, name: str,
                 checkpoint_dir: Optional[str] = None, save_last_only=False,
                 template: Optional[Any] = None):
        self.max_epoch_num = max_epoch_num
        self.name = name
        base = checkpoint_dir or os.environ.get(
            "PADDLE_AUTO_CHECKPOINT_DIR", "./auto_checkpoint")
        job = os.environ.get("PADDLE_JOB_ID", "job_default")
        self._dir = os.path.join(base, job, name)
        self._mngr = CheckpointManager(
            self._dir, max_to_keep=1 if save_last_only else 2,
            use_async=False)
        self._epoch = -1
        last = self._mngr.latest_step()
        self.restored_state = None
        if last is not None:
            self._epoch = last
            self.restored_state = self._mngr.restore(last, template=template)

    def get(self):
        for e in range(self._epoch + 1, self.max_epoch_num):
            self._epoch = e
            yield e

    def save(self, state: Any):
        self._mngr.save(self._epoch, state)
        self._mngr.wait_until_finished()


def train_epoch_range(max_epoch_num: int, name: str = "default",
                      get_state=None, **kwargs):
    """Generator form (reference: auto_checkpoint.py:598 — which snapshots
    transparently at each epoch end). Pass ``get_state`` (a zero-arg callable
    returning the state pytree, e.g. ``lambda: trainer.state``) to auto-save
    at each epoch boundary; without it nothing is saved and resume has
    nothing to restore — use TrainEpochRange directly for manual control."""
    r = TrainEpochRange(max_epoch_num, name, **kwargs)
    for e in r.get():
        yield e
        if get_state is not None:
            r.save(get_state())
