"""Pipeline layer description (reference:
fleet/meta_parallel/parallel_layers/pp_layers.py — LayerDesc, SharedLayerDesc:62,
SegmentLayers:23 uniform/param-count partition, PipelineLayer:76).

TPU-native: PipelineLayer partitions a LayerDesc list into pp_degree stages.
The SPMD pipeline engine (pipeline_parallel.py) requires the *middle* stages
to be structurally identical (the classic stacked-stage trick: per-stage
params carry a leading "pipe" dim sharded over the pipe axis); embedding and
head live on the first/last stage via the engine's cond-dispatch.
"""
from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from ....nn.layer import Layer


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_cls, Layer):
            raise TypeError(f"{layer_cls} must be a Layer subclass")

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """Tied layers across stages (reference: pp_layers.py:62 — e.g. embedding
    weights shared with the LM head). The engine keeps ONE copy of the shared
    params (replicated over the pipe axis) and psums their grads over the
    stages that use them — the TPU version of the reference's allreduce over
    the shared-comm group."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Partition N layer descs into `num_parts` stages (reference:
    pp_layers.py:23): uniform or parameter-count weighted."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.layers_desc = layers_desc
        self.num_parts = num_parts
        self.method = method
        assert len(layers_desc) >= num_parts, \
            f"{len(layers_desc)} layers < {num_parts} stages"

    def do_segment(self) -> List[int]:
        if self.method == "uniform":
            return self.uniform(len(self.layers_desc), self.num_parts)
        if self.method.startswith("layer:"):
            # segment so each stage has equal count of the named layer type
            name = self.method.split(":", 1)[1]
            marks = [i for i, d in enumerate(self.layers_desc)
                     if d.layer_cls.__name__ == name]
            per = len(marks) // self.num_parts
            assert per > 0
            bounds = [0]
            for p in range(1, self.num_parts):
                bounds.append(marks[p * per])
            bounds.append(len(self.layers_desc))
            return bounds
        raise ValueError(f"unknown segment method {self.method}")

    @staticmethod
    def uniform(num_items, num_parts) -> List[int]:
        return [int(np.round(i * num_items / num_parts))
                for i in range(num_parts + 1)]


class PipelineLayer(Layer):
    """Holds the full desc list + this build's stage assignment.

    Unlike the reference (which materializes only the local stage's layers per
    rank), the single-controller SPMD engine materializes ALL stages' layers
    and shards their (stacked) parameters over the "pipe" mesh axis — each
    device stores only its own stage's shard, same memory as the reference.
    """

    def __init__(self, layers: List[LayerDesc], num_stages: int,
                 loss_fn: Optional[Callable] = None, seg_method="uniform",
                 topology=None, **kwargs):
        super().__init__()
        self.descs = layers
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.segment = SegmentLayers(layers, num_stages, seg_method).do_segment()
        from ....nn.layers.container import LayerList
        built = [d.build_layer() for d in layers]
        self.runs = LayerList(built)
        self.shared_keys = {d.layer_name for d in layers
                            if isinstance(d, SharedLayerDesc)}

    def stage_layers(self, stage_id: int):
        lo, hi = self.segment[stage_id], self.segment[stage_id + 1]
        return list(self.runs)[lo:hi]

    def forward(self, x):
        """Non-pipelined reference forward (single-device semantics)."""
        shared = {}
        for desc, layer in zip(self.descs, self.runs):
            if isinstance(desc, SharedLayerDesc):
                if desc.layer_name not in shared:
                    shared[desc.layer_name] = layer
                    x = layer(x)
                else:
                    owner = shared[desc.layer_name]
                    if desc.forward_func is not None:
                        x = desc.forward_func(
                            x, getattr(owner, desc.shared_weight_attr))
                    else:
                        x = owner(x)
            else:
                x = layer(x)
        return x
