"""Comparison / logical ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp


def equal(x, y, name=None):
    return jnp.equal(x, y)


def not_equal(x, y, name=None):
    return jnp.not_equal(x, y)


def greater_than(x, y, name=None):
    return jnp.greater(x, y)


def greater_equal(x, y, name=None):
    return jnp.greater_equal(x, y)


def less_than(x, y, name=None):
    return jnp.less(x, y)


def less_equal(x, y, name=None):
    return jnp.less_equal(x, y)


def logical_and(x, y, out=None, name=None):
    return jnp.logical_and(x, y)


def logical_or(x, y, out=None, name=None):
    return jnp.logical_or(x, y)


def logical_xor(x, y, out=None, name=None):
    return jnp.logical_xor(x, y)


def logical_not(x, out=None, name=None):
    return jnp.logical_not(x)


def bitwise_and(x, y, out=None, name=None):
    return jnp.bitwise_and(x, y)


def bitwise_or(x, y, out=None, name=None):
    return jnp.bitwise_or(x, y)


def bitwise_xor(x, y, out=None, name=None):
    return jnp.bitwise_xor(x, y)


def bitwise_not(x, out=None, name=None):
    return jnp.bitwise_not(x)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def equal_all(x, y, name=None):
    return jnp.array_equal(x, y)


def is_empty(x, name=None):
    return jnp.asarray(x.size == 0)


def is_tensor(x):
    import jax
    return isinstance(x, jax.Array)
