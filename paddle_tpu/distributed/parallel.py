"""DataParallel wrapper (reference: fluid/dygraph/parallel.py:382
DataParallel + the C++ Reducer imperative/reducer.cc).

TPU-native design: there is no bucketed-allreduce Reducer — gradients are
averaged with a single lax.pmean over the "data" mesh axis inside the jitted
step (XLA fuses and overlaps the collective with backward compute via its
latency-hiding scheduler, which is what reducer.cc:798 hand-implements).
``DataParallel`` therefore only 1) marks the module for DP, 2) installs the
grad-sync hook used by the training engine, and 3) keeps API parity
(scale_loss, no_sync, state_dict passthrough).
"""
from __future__ import annotations

import contextlib

from jax import lax

from ..nn.layer import Layer


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.axis_name = group.axis_name if group is not None else "data"
        self._grad_sync_enabled = True
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss

    @contextlib.contextmanager
    def no_sync(self):
        """Skip grad sync (gradient accumulation, reference parallel.py:563)."""
        self._grad_sync_enabled = False
        try:
            yield
        finally:
            self._grad_sync_enabled = True

    def sync_gradients(self, grads: dict) -> dict:
        """Average grads over the data axis — called by the training engine
        inside the jitted/shard_mapped step."""
        if not self._grad_sync_enabled:
            return grads
        try:
            lax.axis_index(self.axis_name)
        except Exception:
            return grads
        return {k: None if g is None else lax.pmean(g, self.axis_name)
                for k, g in grads.items()}

    # passthrough API parity
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)


def sync_params_buffers(model, comm_group=None, src_rank=0):
    """Broadcast params from src (reference: parallel.py sync_params_buffers).
    Under SPMD replication this is implicit; kept for API parity."""
    return model
