"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from ..initializer import Constant
from ..layer import Layer


def _simple(name, fn_name=None, **defaults):
    fn = getattr(F, fn_name or name.lower())

    class _Act(Layer):
        def __init__(self, name=None, **kwargs):
            super().__init__()
            self._kwargs = {**defaults, **{k: v for k, v in kwargs.items() if k != "name"}}

        def forward(self, x):
            return fn(x, **self._kwargs)

    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


CELU = _simple("CELU", "celu")
ELU = _simple("ELU", "elu")
GELU = _simple("GELU", "gelu")
Hardshrink = _simple("Hardshrink", "hardshrink")
Hardsigmoid = _simple("Hardsigmoid", "hardsigmoid")
Hardswish = _simple("Hardswish", "hardswish")
Hardtanh = _simple("Hardtanh", "hardtanh")
LogSigmoid = _simple("LogSigmoid", "log_sigmoid")
Mish = _simple("Mish", "mish")
ReLU = _simple("ReLU", "relu")
ReLU6 = _simple("ReLU6", "relu6")
SELU = _simple("SELU", "selu")
Sigmoid = _simple("Sigmoid", "sigmoid")
Silu = _simple("Silu", "silu")
Softplus = _simple("Softplus", "softplus")
Softshrink = _simple("Softshrink", "softshrink")
Softsign = _simple("Softsign", "softsign")
Swish = _simple("Swish", "swish")
Tanh = _simple("Tanh", "tanh")
Tanhshrink = _simple("Tanhshrink", "tanhshrink")
ThresholdedReLU = _simple("ThresholdedReLU", "thresholded_relu")


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr, initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight.value, data_format=self.data_format)


class RReLU(Layer):
    def __init__(self, lower=0.125, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self.axis)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, axis=self.axis)
