"""paddle_tpu — a TPU-native deep-learning framework with the capabilities of
the reference PaddlePaddle tree (see SURVEY.md), designed from scratch on
JAX/XLA/Pallas/pjit.

Top-level namespace mirrors the reference's ``paddle`` module
(reference: python/paddle/__init__.py): tensor ops, nn, optimizer, amp, io,
distributed, vision, metric, jit, static-free.
"""
from __future__ import annotations

__version__ = "0.1.0"

import jax as _jax

# -- core types --------------------------------------------------------------
Tensor = _jax.Array

from .framework import dtype as _dtype_mod  # noqa: E402
from .framework.dtype import (  # noqa: F401,E402
    bfloat16, bool_, complex64, complex128, float16, float32, float64,
    get_default_dtype, int8, int16, int32, int64, set_default_dtype, uint8)
from .framework import (  # noqa: F401,E402
    get_device, is_compiled_with_cuda, is_compiled_with_npu,
    is_compiled_with_tpu, is_compiled_with_xpu, set_device)
from .framework.random import get_rng_state_tracker, seed  # noqa: F401,E402

# -- tensor ops at top level (paddle.add, paddle.reshape, ...) ---------------
from .tensor import *  # noqa: F401,F403,E402
from .tensor import linalg, logic, manipulation, math, random, stat  # noqa: F401,E402

# -- subpackages -------------------------------------------------------------
from . import nn  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from . import metric  # noqa: F401,E402
from . import io  # noqa: F401,E402
from . import amp  # noqa: F401,E402
from . import jit  # noqa: F401,E402
from . import vision  # noqa: F401,E402
from . import text  # noqa: F401,E402
from . import distribution  # noqa: F401,E402
from . import profiler  # noqa: F401,E402
from . import monitor  # noqa: F401,E402
from . import static  # noqa: F401,E402
from . import regularizer  # noqa: F401,E402
from . import device  # noqa: F401,E402
from . import sysconfig  # noqa: F401,E402
from . import onnx  # noqa: F401,E402
from . import inference  # noqa: F401,E402
from . import hub  # noqa: F401,E402
from . import utils  # noqa: F401,E402
from .framework.flags import get_flags, set_flags  # noqa: F401,E402
from .framework_io import load, save  # noqa: F401,E402
from .autograd import grad, no_grad  # noqa: F401,E402
from .nn.layer import Parameter  # noqa: F401,E402
from .nn.initializer import ParamAttr  # noqa: F401,E402
from .hapi.model import Model  # noqa: F401,E402
from .hapi import callbacks, model_summary  # noqa: F401,E402
from .hapi.model_summary import flops, summary  # noqa: F401,E402


def is_tensor(x):
    return isinstance(x, _jax.Array)


def numpy(x):
    import numpy as _np
    return _np.asarray(x)


def in_dynamic_mode() -> bool:
    """Eager-by-default: True outside jit tracing (the reference's
    dygraph/static switch collapses; reference fluid/framework.py:185)."""
    import jax.core as _core
    try:
        return not isinstance(_jax.numpy.zeros(()), _core.Tracer)
    except Exception:
        return True


def disable_static():
    pass


def enable_static():
    raise NotImplementedError(
        "paddle_tpu has no global static-graph mode switch: jax.jit staging "
        "replaces it. Use paddle_tpu.jit.to_static(layer_or_fn) or the "
        "paddle_tpu.static namespace (Program.trace / Executor).")
