"""Collective-exchange micro-benchmark: fp32 vs bf16 vs int8 gradient sync.

Measures the bucketed compressed exchange (distributed/compressed.py) over
a forced-host-device mesh (or real TPU devices when present) and prints ONE
JSON line:

    {"metric": "int8_vs_fp32_bytes_x", "value": ..., "unit": "x",
     "extra": {per-policy: {wire_bytes_per_rank, ms_per_exchange,
                            buckets, rel_err}}}

Bytes-on-wire come from the analytic ring model in
``compressed.wire_bytes_per_rank`` (what each rank moves for one mean:
all-reduce counts 2(n-1)/n payloads, the int8 figure counts both phases
plus every scale exchange). Latency is wall-clock on whatever backend runs
— on forced host devices it measures the code path, not ICI; on TPUs it is
the real exchange time.

Usage:
    python tools/bench_collectives.py                     # defaults
    python tools/bench_collectives.py --numel 4194304 --devices 4 \
        --block 256 --bucket-mb 4 --iters 20
    python tools/bench_collectives.py --smoke   # tiny shapes + telemetry
                                                # self-check (CI)
"""
from __future__ import annotations

import argparse
import json
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--numel", type=int, default=1 << 22,
                    help="total gradient elements (fp32)")
    ap.add_argument("--devices", type=int, default=4,
                    help="forced host device count when no accelerator")
    ap.add_argument("--block", type=int, default=256,
                    help="int8 quantization block")
    ap.add_argument("--bucket-mb", type=int, default=4,
                    help="flat bucket size in MiB")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes + telemetry self-check; asserts the "
                         "registry saw the per-policy wire-byte counters")
    args = ap.parse_args()
    if args.smoke:
        args.numel, args.devices, args.block = 4096, 2, 64
        args.iters, args.warmup = 2, 1

    from _mesh_setup import (data_mesh, ensure_repo_on_path,
                             force_host_devices)
    force_host_devices(args.devices)
    ensure_repo_on_path()

    import numpy as np
    import jax
    import jax.numpy as jnp

    from paddle_tpu import telemetry
    from paddle_tpu.distributed.compressed import (
        bucket_sizes, compressed_tree_mean, init_residuals,
        wire_bytes_per_rank)
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = data_mesh(args.devices)
    n = mesh.devices.size
    bucket_bytes = args.bucket_mb << 20
    align = n * args.block
    numel = ((args.numel + align - 1) // align) * align
    nbuckets = len(bucket_sizes(numel, max(bucket_bytes // 4, align), align))

    rng = np.random.RandomState(0)
    # per-rank distinct gradients, replica-major then sharded over "data"
    g = rng.randn(n, numel).astype(np.float32)
    g_dev = jax.device_put(jnp.asarray(g),
                           NamedSharding(mesh, P("data", None)))
    exact = g.mean(axis=0)

    tel_cm = telemetry.scope(profile=False)
    tel = tel_cm.__enter__()
    reg = tel.registry
    extra = {}
    for policy in ("fp32", "bf16", "int8"):
        residuals = {"g": jnp.zeros((n, numel), jnp.float32)} \
            if policy == "int8" else None

        def exchange(x, res):
            def f(xs, rs):
                tree = {"g": xs[0]}
                r = {"g": rs["g"][0]} if rs else None
                mean, r = compressed_tree_mean(
                    tree, "data", policy=policy, block=args.block,
                    bucket_bytes=bucket_bytes, residuals=r)
                out_r = {"g": r["g"][None]} if rs else {}
                return mean["g"][None], out_r

            return jax.shard_map(
                f, mesh=mesh,
                in_specs=(P("data", None),
                          {"g": P("data", None)} if res else {}),
                out_specs=(P("data", None),
                           {"g": P("data", None)} if res else {}),
                check_vma=False)(x, res if res else {})

        jfn = jax.jit(exchange)
        res_in = residuals if residuals is not None else {}
        out, _ = jfn(g_dev, res_in)
        for _ in range(args.warmup):
            out, _ = jfn(g_dev, res_in)
        np.asarray(out)  # sync
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out, _ = jfn(g_dev, res_in)
        np.asarray(out)
        dt = (time.perf_counter() - t0) / args.iters

        got = np.asarray(out)[0]
        rel = float(np.abs(got - exact).max() /
                    (np.abs(exact).max() + 1e-12))
        wire = wire_bytes_per_rank(numel, n, policy, block=args.block)
        telemetry.counter(
            "grad_sync_bytes_total",
            "logical wire bytes per rank of the bucketed grad "
            "exchange").inc(wire * args.iters, policy=policy)
        telemetry.histogram(
            "grad_sync_exchange_seconds",
            "one compressed_tree_mean wall time").observe(dt, policy=policy)
        extra[policy] = {
            "wire_bytes_per_rank": wire,
            "ms_per_exchange": round(dt * 1e3, 3),
            "ms_per_bucket": round(dt * 1e3 / nbuckets, 3),
            "buckets": nbuckets,
            "rel_err": rel,
        }

    ratio = (extra["fp32"]["wire_bytes_per_rank"] /
             max(extra["int8"]["wire_bytes_per_rank"], 1e-9))
    extra["telemetry"] = {
        "wire_bytes": {p: reg.get("grad_sync_bytes_total").value(policy=p)
                       for p in ("fp32", "bf16", "int8")},
        "prometheus_bytes": len(telemetry.prometheus_text(reg)),
    }
    tel_cm.__exit__(None, None, None)
    if args.smoke:
        prom = telemetry.prometheus_text(reg)
        wb = extra["telemetry"]["wire_bytes"]
        assert "grad_sync_bytes_total" in prom, "telemetry missing metric"
        assert wb["int8"] > 0 and wb["fp32"] > wb["int8"], wb
    print(json.dumps({
        "metric": "int8_vs_fp32_bytes_x",
        "value": round(ratio, 3),
        "unit": "x",
        "vs_baseline": 1.0,
        "extra": {"numel": numel, "devices": n, "block": args.block,
                  "bucket_mb": args.bucket_mb, "smoke": bool(args.smoke),
                  **extra},
    }))


if __name__ == "__main__":
    main()
