"""Vision datasets (reference: python/paddle/vision/datasets/ — MNIST, CIFAR,
FashionMNIST; legacy python/paddle/dataset/).

This environment has zero egress, so the download path is gated: datasets
read local files in the reference's formats (IDX for MNIST, pickled batches
for CIFAR) and FakeData provides deterministic synthetic samples for tests
and benchmarks.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io.dataset import Dataset

DATA_HOME = os.path.expanduser(os.environ.get("PADDLE_TPU_DATA_HOME",
                                              "~/.cache/paddle_tpu/datasets"))


class FakeData(Dataset):
    """Deterministic synthetic dataset for tests/benchmarks."""

    def __init__(self, size=1024, image_shape=(1, 28, 28), num_classes=10,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.rand(*self.image_shape).astype(np.float32)
        label = np.asarray(rng.randint(0, self.num_classes), dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.size


def _read_idx_images(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(n, rows, cols)


def _read_idx_labels(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data


class MNIST(Dataset):
    """reference: python/paddle/vision/datasets/mnist.py. Reads local IDX
    files; pass image_path/label_path or place files under DATA_HOME/mnist."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode.lower()
        self.transform = transform
        base = os.path.join(DATA_HOME, self.NAME)
        stem = "train" if self.mode == "train" else "t10k"
        if image_path is None:
            for suffix in ("-images-idx3-ubyte.gz", "-images-idx3-ubyte"):
                cand = os.path.join(base, stem + suffix)
                if os.path.exists(cand):
                    image_path = cand
                    break
        if label_path is None:
            for suffix in ("-labels-idx1-ubyte.gz", "-labels-idx1-ubyte"):
                cand = os.path.join(base, stem + suffix)
                if os.path.exists(cand):
                    label_path = cand
                    break
        if image_path is None or label_path is None:
            raise FileNotFoundError(
                f"MNIST files not found under {base}; this environment has no "
                f"network egress — place IDX files there or use "
                f"paddle_tpu.vision.datasets.FakeData for tests")
        self.images = _read_idx_images(image_path)
        self.labels = _read_idx_labels(label_path)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None, :, :] / 255.0
        label = np.asarray(self.labels[idx], dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """reference: python/paddle/vision/datasets/cifar.py — reads the original
    python-pickle tar from a local path."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        mode = mode.lower()
        data_file = data_file or os.path.join(DATA_HOME, "cifar",
                                              "cifar-10-python.tar.gz")
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"{data_file} not found; no network egress — place the CIFAR "
                f"archive locally or use FakeData")
        names = ([f"data_batch_{i}" for i in range(1, 6)] if mode == "train"
                 else ["test_batch"])
        xs, ys = [], []
        with tarfile.open(data_file, "r:*") as tar:
            for member in tar.getmembers():
                if any(member.name.endswith(n) for n in names):
                    batch = pickle.load(tar.extractfile(member), encoding="bytes")
                    xs.append(batch[b"data"])
                    ys.extend(batch.get(b"labels", batch.get(b"fine_labels")))
        self.data = np.concatenate(xs).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(ys, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.data[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        data_file = data_file or os.path.join(DATA_HOME, "cifar",
                                              "cifar-100-python.tar.gz")
        super().__init__(data_file, mode, transform, download, backend)
