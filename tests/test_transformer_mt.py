"""MT Transformer model + beam search (reference InferTransformerModel
capability; WMT datasets live in text/datasets)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.text.models import InferTransformerModel, TransformerModel

V, BOS, EOS = 20, 0, 1


def _model(cls=TransformerModel, **kw):
    paddle.seed(0)
    return cls(V, V, max_length=32, num_encoder_layers=1,
               num_decoder_layers=1, n_head=2, d_model=32, d_inner_hid=64,
               dropout=0.0, bos_id=BOS, eos_id=EOS, **kw)


def test_forward_shapes_and_causality():
    m = _model()
    m.eval()
    rs = np.random.RandomState(0)
    src = rs.randint(2, V, (3, 7)).astype("i4")
    trg = rs.randint(2, V, (3, 5)).astype("i4")
    logits = np.asarray(m(src, trg))
    assert logits.shape == (3, 5, V)
    # causality: changing trg[t] must not affect logits before t
    trg2 = trg.copy(); trg2[:, 3] = (trg2[:, 3] + 1) % (V - 2) + 2
    logits2 = np.asarray(m(src, trg2))
    np.testing.assert_allclose(logits[:, :3], logits2[:, :3], atol=1e-5)
    assert not np.allclose(logits[:, 3:], logits2[:, 3:])


def test_weight_sharing_ties_embeddings():
    m = _model(weight_sharing=True)
    assert m.trg_emb is m.src_emb
    src = np.asarray([[2, 3, 4]], "i4")
    out = m(src, src)
    assert out.shape == (1, 3, V)


@pytest.mark.slow
def test_copy_task_trains_and_beam_decodes():
    """Learn the copy task, then beam search must reproduce the source
    (the classic seq2seq sanity fixture)."""
    from paddle_tpu.jit.functionalization import functional_call, state_of
    m = _model()
    m.eval()
    params, buffers = state_of(m)
    rs = np.random.RandomState(1)
    L = 6

    def batch(n=64):
        body = rs.randint(2, V, (n, L)).astype("i4")
        src = body
        trg_in = np.concatenate(
            [np.full((n, 1), BOS, "i4"), body[:, :-1]], 1)
        # teacher forcing predicts body tokens
        return src, trg_in, body

    opt = paddle.optimizer.Adam(2e-3, parameters=m.parameters())
    opt_state = opt.init_state(params)

    @jax.jit
    def step(params, opt_state, src, trg_in, label):
        def lf(p):
            out, _ = functional_call(m, p, buffers, src, trg_in)
            return nn.functional.cross_entropy(out, label)
        loss, g = jax.value_and_grad(lf)(params)
        new_p, new_s = opt.apply_gradients(params, g, opt_state, lr=2e-3)
        return loss, new_p, new_s

    losses = []
    for _ in range(150):
        src, trg_in, lbl = batch()
        l, params, opt_state = step(params, opt_state, jnp.asarray(src),
                                    jnp.asarray(trg_in), jnp.asarray(lbl))
        losses.append(float(l))
    assert losses[-1] < 0.3, (losses[0], losses[-1])

    infer = _model(InferTransformerModel, beam_size=3, max_out_len=L)
    infer.eval()
    # copy trained weights (same architecture/naming)
    inf_params, inf_buffers = state_of(infer)
    assert set(inf_params) == set(params)
    src, _, body = batch(4)
    ids, scores = functional_call(
        infer, params, inf_buffers, jnp.asarray(src))[0]
    best = np.asarray(ids)[:, 0, :L]
    assert (best == body).mean() > 0.9, (best, body)
    assert np.all(np.diff(np.asarray(scores), axis=1) <= 1e-5)  # sorted


def test_jit_save_load_mt(tmp_path):
    from paddle_tpu.jit import InputSpec
    m = _model()
    m.eval()
    path = str(tmp_path / "mt" / "model")
    paddle.jit.save(m, path, input_spec=[
        InputSpec([1, 7], dtype="int32"), InputSpec([1, 5], dtype="int32")])
    loaded = paddle.jit.load(path)
    rs = np.random.RandomState(3)
    src = rs.randint(2, V, (1, 7)).astype("i4")
    trg = rs.randint(2, V, (1, 5)).astype("i4")
    np.testing.assert_allclose(np.asarray(m(src, trg)),
                               np.asarray(loaded(src, trg)),
                               rtol=1e-4, atol=1e-4)
