"""Distributed (sharded, async) checkpointing + auto-resume.

Capability map (reference):
- per-rank sharded checkpoints       ← sharding/hybrid save (tests
  dist_sharding_save.py; fleet `save_persistables`) — here orbax writes each
  shard from the device holding it (mesh-keyed, the SURVEY.md §5 TPU
  translation of per-rank files).
- auto-checkpoint for preemption     ← incubate/checkpoint/auto_checkpoint.py
  :265 TrainEpochRange, :598 train_epoch_range — snapshot + transparent
  resume keyed by job id.
- HDFS/AFS remote fs                 ← fleet/utils/fs.py — orbax talks to
  any fsspec/gcs path; local paths here (zero-egress box).

Async: orbax's async checkpointer overlaps the device→host gather and file
write with training (the reference's PS tier saved asynchronously via its
own threads; XLA-side this is the idiomatic equivalent).

Crash consistency: orbax already writes each step into a temp dir and
atomically renames it, but a crash BETWEEN the rename and the end of the
file writes' journey to stable storage (or plain on-disk rot) can still
leave a step directory that lists as present yet does not restore. The
manager therefore runs a two-phase commit on top: after the write
completes it fsyncs every file, records a CRC32-checksum ``MANIFEST.json``
(tmp + fsync + atomic rename + dir fsync), and only then counts the step
as committed. ``restore()`` verifies the manifest and falls back to the
newest step that checks out (counting ``ckpt_restore_fallbacks_total``);
retention GC runs only after a verified commit and never removes the last
valid step.

Byte budget: save retries are additionally bounded by bytes moved
(``CKPT_RETRY_BYTE_BUDGET_X`` × state size) — a flaky remote fs re-uploads
the full state every attempt, so past the budget the save DEGRADES to
local-disk staging (``PADDLE_TPU_CKPT_STAGING`` or a tempdir; counted in
``ckpt_retry_bytes_abandoned_total``) instead of burning the link.
``restore()`` falls back to the newest verified staged step when no
primary step restores.
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
import warnings
import zlib
from typing import Any, List, Optional

import jax

__all__ = ["save_checkpoint", "load_checkpoint", "CheckpointManager",
           "TrainEpochRange", "train_epoch_range",
           "write_manifest", "verify_manifest", "MANIFEST_NAME",
           "CKPT_RETRY_BYTE_BUDGET_X", "staging_root"]

MANIFEST_NAME = "MANIFEST.json"

# retries may move at most this multiple of the state size before the
# save degrades to local staging (first attempt always runs)
CKPT_RETRY_BYTE_BUDGET_X = 3.0


def staging_root() -> str:
    """Local-disk home for degraded saves: ``PADDLE_TPU_CKPT_STAGING``
    or a tempdir fallback. Must be a genuinely local path — it is where
    saves land when the REMOTE fs is the thing failing."""
    env = os.environ.get("PADDLE_TPU_CKPT_STAGING")
    if env:
        return env
    return os.path.join(tempfile.gettempdir(), "paddle_tpu_ckpt_staging")


def _state_nbytes(state: Any) -> float:
    return float(sum(getattr(v, "nbytes", 0) or 0
                     for v in jax.tree_util.tree_leaves(state)))


def _save_retry_kwargs(nbytes: float) -> dict:
    """Retry policy for checkpoint saves. With a known state size the byte
    budget binds first — floor(3×/1×) = 3 upload attempts, then degrade —
    and the try count is only a backstop; a zero-byte state keeps the
    plain 3-try policy."""
    if not nbytes:
        return {"tries": 3}
    return {"tries": 6, "attempt_bytes": nbytes,
            "byte_budget": CKPT_RETRY_BYTE_BUDGET_X * nbytes}


def _count_staged(nbytes: float):
    from .. import telemetry
    if telemetry.enabled():
        telemetry.counter(
            "ckpt_retry_bytes_abandoned_total",
            "checkpoint bytes NOT re-uploaded because the retry byte "
            "budget degraded the save to local staging").inc(nbytes)


_cached = {}  # one checkpointer per mode: async saves barrier on reuse


def _record(op: str, dt: float, state: Any):
    """Telemetry: save/restore wall time + bytes moved. For async saves the
    duration is the dispatch (host-blocking) portion — the part that stalls
    training — not the background write."""
    from .. import telemetry
    if not telemetry.enabled():
        return
    telemetry.histogram(
        f"checkpoint_{op}_seconds",
        f"checkpoint {op} wall time (host-blocking part)").observe(dt)
    nbytes = float(sum(getattr(v, "nbytes", 0) or 0
                       for v in jax.tree_util.tree_leaves(state)))
    if nbytes:
        telemetry.counter(
            "checkpoint_bytes_total", "checkpointed bytes").inc(
                nbytes, op=op)
    telemetry.emit("checkpoint", op=op, seconds=dt, bytes=nbytes)


def _checkpointer(use_async: bool):
    import orbax.checkpoint as ocp
    key = "async" if use_async else "sync"
    if key not in _cached:
        _cached[key] = (
            ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
            if use_async else
            ocp.Checkpointer(ocp.StandardCheckpointHandler()))
    return _cached[key]


# -- checksum manifest (two-phase commit) -----------------------------------

def _fsync_file(path: str):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str):
    try:
        _fsync_file(path)
    except OSError:
        pass  # some filesystems refuse directory fsync; rename is still atomic


def _crc_file(path: str, chunk: int = 1 << 20) -> int:
    c = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            c = zlib.crc32(b, c)
    return c & 0xFFFFFFFF


def write_manifest(step_dir: str, arrays: Optional[dict] = None) -> dict:
    """Commit marker: fsync every file under ``step_dir``, then atomically
    write a CRC32/size manifest. The manifest is written LAST (tmp + fsync +
    rename + dir fsync), so its presence proves every byte it attests to
    reached stable storage — a kill -9 at any point leaves either no
    manifest (step invalid, restore falls back) or a complete one.

    ``arrays`` (leaf-path → content digest, from
    ``resilience.integrity.tree_digests``) is recorded under an ``"arrays"``
    key: file CRCs attest the *bytes on disk*, array digests attest the
    *decoded values* — deep verify re-hashes the restored pytree against
    them, catching corruption the file layer re-encodes (and giving
    ``replay_step`` its reference digest)."""
    files = {}
    for root, _dirs, names in os.walk(step_dir):
        for n in sorted(names):
            if n in (MANIFEST_NAME, MANIFEST_NAME + ".tmp"):
                continue
            p = os.path.join(root, n)
            _fsync_file(p)  # durability BEFORE attestation
            files[os.path.relpath(p, step_dir)] = {
                "size": os.path.getsize(p), "crc32": _crc_file(p)}
    manifest = {"version": 1, "files": files}
    if arrays:
        manifest["arrays"] = dict(arrays)
    tmp = os.path.join(step_dir, MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(step_dir, MANIFEST_NAME))
    _fsync_dir(step_dir)
    return manifest


def verify_manifest(step_dir: str, level: str = "full") -> Optional[bool]:
    """Three-valued: ``True`` — manifest present and every attested file
    matches; ``False`` — manifest present but unreadable, or a file is
    missing/corrupt (torn checkpoint); ``None`` — no manifest (a legacy
    checkpoint from before this commit protocol; restore attempts it and
    relies on orbax's own errors).

    ``level="size"`` checks existence + recorded byte size only (a stat per
    file, no reads) — the cheap pre-reject used when scanning many steps;
    ``level="full"`` also re-CRCs every file."""
    mpath = os.path.join(step_dir, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return False
    for rel, meta in manifest.get("files", {}).items():
        p = os.path.join(step_dir, rel)
        try:
            if os.path.getsize(p) != meta["size"]:
                return False
            if level != "size" and _crc_file(p) != meta["crc32"]:
                return False
        except OSError:
            return False
    return True


def _corrupt_one_file(step_dir: str):
    """Fault-injection helper (ckpt_torn): truncate the largest data file —
    what a machine loss mid-flush leaves behind."""
    best, size = None, -1
    for root, _dirs, names in os.walk(step_dir):
        for n in names:
            p = os.path.join(root, n)
            s = os.path.getsize(p)
            if s > size:
                best, size = p, s
    if best is not None:
        with open(best, "r+b") as f:
            f.truncate(max(1, size // 2))


def _stage_save(dest: str, state: Any, nbytes: float,
                err: BaseException, arrays: Optional[dict] = None) -> str:
    """Degraded save path: a plain sync orbax write onto local disk, no
    fault hooks and no retry — if LOCAL disk is failing too there is
    nothing left to degrade to. Manifested like any committed step so
    restore can verify it."""
    import orbax.checkpoint as ocp
    dest = os.path.abspath(dest)
    if os.path.isdir(dest):
        shutil.rmtree(dest)
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    ocp.Checkpointer(ocp.StandardCheckpointHandler()).save(
        dest, args=ocp.args.StandardSave(state), force=True)
    write_manifest(dest, arrays=arrays)
    _count_staged(nbytes)
    warnings.warn(
        f"checkpoint save exceeded its retry byte budget ({err}); "
        f"staged to local disk at {dest}", RuntimeWarning)
    return dest


def save_checkpoint(path: str, state: Any, overwrite: bool = True,
                    use_async: bool = False,
                    staging_dir: Optional[str] = None):
    """Save a pytree of (possibly sharded) jax arrays. Each host writes only
    the shards it owns. With ``use_async`` the write overlaps training; the
    module keeps ONE async checkpointer, so a subsequent save waits for the
    in-flight one (no torn writes) — call ``wait_until_finished`` on the
    returned checkpointer before process exit.

    Retries are byte-budgeted (``CKPT_RETRY_BYTE_BUDGET_X`` × state size);
    past the budget the save lands in ``staging_dir`` (default
    ``staging_root()/<basename(path)>``) instead of re-uploading."""
    import orbax.checkpoint as ocp
    from ..resilience import faults
    from ..resilience.retry import RetryBytesExhausted, call_with_retry
    ckptr = _checkpointer(use_async)
    nbytes = _state_nbytes(state)
    t0 = time.perf_counter()

    def _write():
        faults.maybe_raise("ckpt_io", site="save_checkpoint",
                            msg="injected ckpt_io on save")
        ckptr.save(os.path.abspath(path), args=ocp.args.StandardSave(state),
                   force=overwrite)

    try:
        # with a byte budget armed, IT is the binding limit (3× the state
        # = 3 uploads), so the try count is just a backstop
        call_with_retry(_write, site="ckpt_save", base_delay=0.01,
                        **_save_retry_kwargs(nbytes))
    except RetryBytesExhausted as e:
        dest = staging_dir or os.path.join(
            staging_root(), os.path.basename(os.path.abspath(path)))
        _stage_save(dest, state, nbytes, e)
    _record("save", time.perf_counter() - t0, state)
    return ckptr


def load_checkpoint(path: str, template: Optional[Any] = None):
    """Restore a pytree. ``template`` (a pytree of arrays or
    ShapeDtypeStruct with .sharding) restores each leaf sharded directly to
    its devices; without it, arrays land replicated on the default device."""
    import orbax.checkpoint as ocp
    from ..resilience.retry import call_with_retry
    ckptr = _checkpointer(False)
    t0 = time.perf_counter()

    def _read():
        if template is not None:
            abstract = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype,
                    sharding=getattr(x, "sharding", None))
                if hasattr(x, "shape") else x,
                template)
            return ckptr.restore(os.path.abspath(path),
                                 args=ocp.args.StandardRestore(abstract))
        return ckptr.restore(os.path.abspath(path))

    out = call_with_retry(_read, site="ckpt_restore", tries=2,
                          base_delay=0.01)
    _record("restore", time.perf_counter() - t0, out)
    return out


class CheckpointManager:
    """Step-numbered checkpoints with retention + save-interval policy
    (reference capability: ModelCheckpoint callback hapi/callbacks.py:533 +
    auto_checkpoint retention), hardened with a two-phase commit:

    1. write — orbax writes the step (tmp dir + atomic rename), possibly
       async; the step is tracked as *pending*.
    2. commit — after the write finishes, every file is fsynced and a CRC32
       ``MANIFEST.json`` is atomically recorded; only then does retention GC
       run. GC keeps the newest ``max_to_keep`` VALID steps and never
       removes the last valid one, so a torn newest step can always fall
       back to a good predecessor.

    ``restore()`` (no explicit step) scans newest→oldest, skipping steps
    that fail verification or error mid-restore, counting each skip in
    ``ckpt_restore_fallbacks_total``.

    ``deep_digests=True`` (opt-in) records per-array content digests in
    the manifest so ``verify(step, deep=True)`` / ``restore(deep=True)``
    and ``replay_step`` have a value-level reference. The digests are
    computed from the live state on the save path — a full device→host
    transfer plus CRC32 per save, which serializes against async writes
    — so it stays off unless the integrity features are wanted.
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1, use_async: bool = True,
                 staging_dir: Optional[str] = None,
                 deep_digests: bool = False):
        import orbax.checkpoint as ocp
        self._dir = os.path.abspath(directory)
        self._staging = staging_dir or os.path.join(
            staging_root(), os.path.basename(self._dir))
        self._max_to_keep = max_to_keep
        self._use_async = use_async
        # retention is OURS (post-commit, validity-aware): orbax counting
        # torn steps toward max_to_keep could GC the last valid one.
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=None,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=use_async))
        self._deep_digests = deep_digests
        self._pending: List[int] = []   # written (maybe in flight), no manifest yet
        self._pending_digests = {}      # step -> tree_digests, until committed
        self._vcache = {}               # step -> verify_manifest result
        self.restore_fallbacks_total = 0   # corrupt steps skipped over
        self.last_restored_step: Optional[int] = None

    def _step_dir(self, step: int) -> str:
        return os.path.join(self._dir, str(step))

    def _staged_step_dir(self, step: int) -> str:
        return os.path.join(self._staging, str(step))

    def staged_steps(self) -> List[int]:
        """Steps that degraded to local staging (newest last)."""
        if not os.path.isdir(self._staging):
            return []
        out = []
        for n in os.listdir(self._staging):
            if n.isdigit() and os.path.isdir(
                    os.path.join(self._staging, n)):
                out.append(int(n))
        return sorted(out)

    def _verify(self, step: int) -> Optional[bool]:
        if step not in self._vcache:
            self._vcache[step] = verify_manifest(self._step_dir(step))
        return self._vcache[step]

    def _commit_pending(self):
        """Phase 2: barrier on in-flight writes, manifest each pending step,
        then GC. An injected ``ckpt_torn`` fault corrupts the step and skips
        its manifest before raising SimulatedCrash — the kill -9 window."""
        if not self._pending:
            return
        self._mngr.wait_until_finished()
        from ..resilience import faults
        while self._pending:
            step = self._pending.pop(0)
            sdir = self._step_dir(step)
            if faults.fires("ckpt_torn", step=step, site="ckpt_commit"):
                _corrupt_one_file(sdir)
                self._vcache.pop(step, None)
                self._pending_digests.pop(step, None)
                raise faults.SimulatedCrash(
                    f"simulated kill -9 committing checkpoint step {step}")
            if os.path.isdir(sdir):
                write_manifest(sdir,
                               arrays=self._pending_digests.pop(step, None))
                self._vcache[step] = True
            else:
                self._pending_digests.pop(step, None)
        self._gc()

    def _gc(self):
        """Retention, run only after a verified commit. Keeps the newest
        ``max_to_keep`` valid steps; steps that fail verification and fall
        outside the kept window are deleted too (torn debris), but if
        NOTHING verifies, nothing is deleted."""
        if not self._max_to_keep:
            return
        steps = sorted(self._mngr.all_steps() or [])
        valid = [s for s in steps if self._verify(s) is not False]
        if not valid:
            return
        keep = set(valid[-self._max_to_keep:])
        for s in steps:
            if s in keep or s in self._pending:
                continue
            try:
                self._mngr.delete(s)
            except Exception:
                continue
            self._vcache.pop(s, None)

    def save(self, step: int, state: Any) -> bool:
        import numpy as np
        import orbax.checkpoint as ocp
        from ..resilience import faults
        from ..resilience.retry import RetryBytesExhausted, call_with_retry
        # numpy scalars (np.int32(3) etc.) are not in orbax's supported
        # leaf types — promote them to 0-d ndarrays
        state = jax.tree_util.tree_map(
            lambda x: np.asarray(x) if isinstance(x, np.generic) else x,
            state)
        self._commit_pending()  # previous async write: barrier + manifest
        if step in (self._mngr.all_steps() or []):
            # a restart legitimately replays the step it crashed in — clear
            # the stale (possibly torn) attempt so orbax doesn't refuse
            self._mngr.delete(step)
            self._vcache.pop(step, None)
            self._pending_digests.pop(step, None)
        arrays = None
        if self._deep_digests:
            # content digests are taken from the live state at save time —
            # the ground truth the payload must still decode to at restore
            from ..resilience.integrity import tree_digests
            arrays = tree_digests(state)

        def _write():
            faults.maybe_raise("ckpt_io", step=step, site="manager_save",
                               msg=f"injected ckpt_io at step {step}")
            return self._mngr.save(step, args=ocp.args.StandardSave(state))

        nbytes = _state_nbytes(state)
        t0 = time.perf_counter()
        try:
            saved = call_with_retry(
                _write, site="ckpt_save", base_delay=0.01,
                **_save_retry_kwargs(nbytes))
        except RetryBytesExhausted as e:
            # budget blown: the primary dir (likely remote) is too
            # expensive to keep re-uploading — stage locally instead.
            # Staged steps live OUTSIDE orbax's step tracking (no
            # pending/GC) and are picked up by restore() only when no
            # primary step verifies.
            _stage_save(self._staged_step_dir(step), state, nbytes, e,
                        arrays=arrays)
            _record("save", time.perf_counter() - t0, state)
            return True
        if saved:  # interval-skipped saves shouldn't pollute the histogram
            self._pending.append(step)
            if arrays is not None:
                self._pending_digests[step] = arrays
            if not self._use_async:
                self._commit_pending()
            _record("save", time.perf_counter() - t0, state)
        return saved

    def _restore_step(self, step: int, template: Optional[Any]):
        import orbax.checkpoint as ocp
        if template is not None:
            abstract = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=getattr(x, "sharding", None))
                if hasattr(x, "shape") else x, template)
            return self._mngr.restore(
                step, args=ocp.args.StandardRestore(abstract))
        # installed orbax refuses a bare restore (no registered handler
        # for the saved "default" item) — an explicit StandardRestore
        # with no abstract tree restores everything replicated on the
        # host
        return self._mngr.restore(step, args=ocp.args.StandardRestore())

    def _count_fallbacks(self, n: int, reason: str = "manifest"):
        if not n:
            return
        self.restore_fallbacks_total += n
        from .. import telemetry
        if telemetry.enabled():
            telemetry.counter(
                "ckpt_restore_fallbacks_total",
                "restores that skipped corrupt/torn checkpoints").inc(
                    n, reason=reason)

    def _manifest_arrays(self, step: int) -> Optional[dict]:
        """The recorded content digests for ``step`` (None when the step
        predates deep digests or has no readable manifest)."""
        mpath = os.path.join(self._step_dir(step), MANIFEST_NAME)
        try:
            with open(mpath) as f:
                return json.load(f).get("arrays") or None
        except (OSError, ValueError):
            return None

    def _deep_verify(self, step: int, template: Optional[Any] = None):
        """Restore the step's payload and re-hash every array against the
        digests recorded at save time. Returns ``(verdict, payload)``:
        ``True`` — all match, and ``payload`` is the restored tree so a
        deep restore can reuse it instead of reading the step a second
        time; ``False`` — a mismatch or an unreadable payload (rot the
        file CRCs re-encoded away, or plain corruption); ``None`` — no
        digests recorded. ``payload`` is None unless the verdict is
        ``True``."""
        from ..resilience.integrity import compare_digests, tree_digests
        recorded = self._manifest_arrays(step)
        if not recorded:
            return None, None
        try:
            out = self._restore_step(step, template)
        except Exception:
            return False, None
        if compare_digests(recorded, tree_digests(out)):
            return False, None
        return True, out

    def verify(self, step: int, deep: bool = False) -> Optional[bool]:
        """On-demand integrity check of a committed step. Shallow verifies
        the file layer (size + CRC32); ``deep=True`` additionally restores
        the payload and re-hashes every array against the save-time content
        digests. Three-valued like :func:`verify_manifest` (``None`` when
        the relevant attestation was never recorded)."""
        self._vcache.pop(step, None)
        shallow = self._verify(step)
        if shallow is False or not deep:
            return shallow
        dv, _ = self._deep_verify(step)
        if dv is None:  # no digests recorded: report the shallow verdict
            return shallow
        return dv

    def restore(self, step: Optional[int] = None,
                template: Optional[Any] = None, deep: bool = False):
        from ..resilience.retry import call_with_retry
        self._commit_pending()
        if step is not None:  # explicit step: verify, no fallback
            # re-verify from disk (not the cache): restore is rare and this
            # catches rot that happened after the commit
            self._vcache.pop(step, None)
            if self._verify(step) is False:
                raise OSError(
                    f"checkpoint step {step} failed manifest verification")
            t0 = time.perf_counter()
            out = call_with_retry(self._restore_step, step, template,
                                  site="ckpt_restore", tries=2,
                                  base_delay=0.01)
            if deep:
                recorded = self._manifest_arrays(step)
                if recorded:
                    from ..resilience.integrity import (compare_digests,
                                                        tree_digests)
                    bad = compare_digests(recorded, tree_digests(out))
                    if bad:
                        raise OSError(
                            f"checkpoint step {step} failed deep "
                            f"verification: {bad[:4]}")
            _record("restore", time.perf_counter() - t0, out)
            self.last_restored_step = step
            return out
        for s in sorted(self._mngr.all_steps() or [], reverse=True):
            self._vcache.pop(s, None)
            if self._verify(s) is False:
                self._count_fallbacks(1, reason="manifest")
                continue
            if deep:
                t0 = time.perf_counter()
                dv, out = self._deep_verify(s, template)
                if dv is False:
                    # bytes check out but the decoded values do not —
                    # silent corruption between file layer and arrays
                    self._count_fallbacks(1, reason="deep")
                    continue
                if dv:
                    # the verified payload IS the restore — one read
                    _record("restore", time.perf_counter() - t0, out)
                    self.last_restored_step = s
                    return out
                # dv None: no digests recorded — plain restore below
            try:
                t0 = time.perf_counter()
                out = call_with_retry(self._restore_step, s, template,
                                      site="ckpt_restore", tries=2,
                                      base_delay=0.01)
            except Exception:
                # no manifest (legacy) or rot the manifest couldn't see —
                # orbax/tensorstore raised; fall back to an older step
                self._count_fallbacks(1, reason="restore")
                continue
            _record("restore", time.perf_counter() - t0, out)
            self.last_restored_step = s
            return out
        # no primary step restored: fall back to locally staged saves
        # (degraded by the retry byte budget), newest first
        for s in sorted(self.staged_steps(), reverse=True):
            sdir = self._staged_step_dir(s)
            if verify_manifest(sdir) is False:
                self._count_fallbacks(1, reason="staged")
                continue
            try:
                t0 = time.perf_counter()
                out = load_checkpoint(sdir, template=template)
            except Exception:
                self._count_fallbacks(1, reason="staged")
                continue
            self.last_restored_step = s
            return out
        return None

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def latest_valid_step(self) -> Optional[int]:
        """Newest step that passes (or predates) manifest verification.
        A size-only pre-pass (one stat per file, no reads) rejects
        truncated/missing payloads before the full CRC pass — this runs in
        the elastic restore barrier on every host, so the common
        all-healthy case should not re-read whole checkpoints."""
        for s in sorted(self._mngr.all_steps() or [], reverse=True):
            if verify_manifest(self._step_dir(s), level="size") is False:
                self._vcache[s] = False
                continue
            if self._verify(s) is not False:
                return s
        return None

    def all_steps(self):
        return self._mngr.all_steps()

    def wait_until_finished(self):
        self._mngr.wait_until_finished()
        self._commit_pending()

    def close(self):
        try:
            self._commit_pending()
        finally:
            self._mngr.close()


class TrainEpochRange:
    """Manual epoch-level checkpoint/resume over CheckpointManager.

    This is the explicit-control variant: the caller decides when to
    ``save``. The reference-faithful env-gated variant (PADDLE_JOB_ID
    activation, save-interval seconds, add_state registration) is
    ``incubate.checkpoint.auto_checkpoint.TrainEpochRange``, which builds on
    the same CheckpointManager — use that one for transparent resume
    (reference: incubate/checkpoint/auto_checkpoint.py:265).

    Usage::

        r = TrainEpochRange(max_epoch, name, checkpoint_dir=...)
        for epoch in r.get():          # resumes after the last saved epoch
            ...train...
            r.save(state_pytree)       # state: e.g. trainer.state
        restored = r.restored_state    # non-None when resuming
    """

    def __init__(self, max_epoch_num: int, name: str,
                 checkpoint_dir: Optional[str] = None, save_last_only=False,
                 template: Optional[Any] = None):
        self.max_epoch_num = max_epoch_num
        self.name = name
        base = checkpoint_dir or os.environ.get(
            "PADDLE_AUTO_CHECKPOINT_DIR", "./auto_checkpoint")
        job = os.environ.get("PADDLE_JOB_ID", "job_default")
        self._dir = os.path.join(base, job, name)
        self._mngr = CheckpointManager(
            self._dir, max_to_keep=1 if save_last_only else 2,
            use_async=False)
        self._epoch = -1
        last = self._mngr.latest_step()
        self.restored_state = None
        if last is not None:
            self._epoch = last
            self.restored_state = self._mngr.restore(last, template=template)

    def get(self):
        for e in range(self._epoch + 1, self.max_epoch_num):
            self._epoch = e
            yield e

    def save(self, state: Any):
        self._mngr.save(self._epoch, state)
        self._mngr.wait_until_finished()


def train_epoch_range(max_epoch_num: int, name: str = "default",
                      get_state=None, **kwargs):
    """Generator form (reference: auto_checkpoint.py:598 — which snapshots
    transparently at each epoch end). Pass ``get_state`` (a zero-arg callable
    returning the state pytree, e.g. ``lambda: trainer.state``) to auto-save
    at each epoch boundary; without it nothing is saved and resume has
    nothing to restore — use TrainEpochRange directly for manual control."""
    r = TrainEpochRange(max_epoch_num, name, **kwargs)
    for e in r.get():
        yield e
        if get_state is not None:
            r.save(get_state())
