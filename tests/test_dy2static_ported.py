"""Dy2static scenarios ported from the reference's dygraph_to_static
suite (`python/paddle/fluid/tests/unittests/dygraph_to_static/` — the
round-3 verdict's depth item). Each test names its reference file. The
contract under test: supported constructs produce the same results as
eager execution; unsupported constructs raise Dy2StaticError with a
source location — never a silent mis-trace.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.jit.dy2static import Dy2StaticError, convert_function

X = jnp.asarray(np.random.RandomState(0).randn(3, 4).astype("float32"))


def run_both(fn, *args):
    """(eager result, jitted-converted result) — they must agree."""
    cf = convert_function(fn)
    return fn(*args), jax.jit(cf)(*args)


# -- test_list.py ------------------------------------------------------------
class TestList:
    def test_append_without_control_flow(self):
        # ref: test_list.py test_list_append_without_control_flow
        def f(x):
            a = []
            a.append(x)
            a.append(x * 2)
            return a[0] + a[1]

        e, s = run_both(f, X)
        np.testing.assert_allclose(np.asarray(e), np.asarray(s))

    def test_append_in_tensor_if(self):
        # ref: test_list.py test_list_append_in_if
        def f(x):
            a = [x]
            if x.sum() > 0:
                a.append(x * 2)
            else:
                a.append(x * 3)
            return a[-1]

        for sign in (1.0, -1.0):
            e, s = run_both(f, sign * jnp.abs(X) + 0.1 * sign)
            np.testing.assert_allclose(np.asarray(e), np.asarray(s))

    def test_append_in_python_for_with_concat(self):
        # ref: test_list.py test_list_append_in_for_loop_with_concat
        def f(x):
            a = []
            for i in range(3):
                a.append(x * (i + 1))
            return jnp.concatenate(a, axis=0)

        e, s = run_both(f, X)
        np.testing.assert_allclose(np.asarray(e), np.asarray(s))

    def test_append_in_tensor_while_diagnosed(self):
        # ref: test_list.py test_list_append_in_while_loop — the
        # reference stages this via TensorArray; here a growing carry
        # cannot stage, and the contract is a LOCATED diagnostic (it
        # used to silently append once at trace time)
        def f(x):
            a = []
            i = jnp.asarray(0)
            while i < 3:
                a.append(x)
                i = i + 1
            return a

        with pytest.raises(Dy2StaticError, match=r"\.py:\d+.*fixed"):
            jax.jit(convert_function(f))(X)

    def test_pop_in_tensor_if(self):
        # ref: test_list.py test_list_pop_in_if
        def f(x):
            a = [x, x * 2, x * 3]
            if x.sum() > 0:
                b = a.pop()
            else:
                b = a.pop()
            return b + a[-1]

        e, s = run_both(f, X)
        np.testing.assert_allclose(np.asarray(e), np.asarray(s))


# -- test_dict.py ------------------------------------------------------------
class TestDict:
    def test_cache_update_in_tensor_if(self):
        # ref: test_dict.py SubNetWithDict.forward cache update
        def f(x, cache):
            if x.sum() > 0:
                cache["k"] = cache["k"] + x
            return cache["k"]

        cache = {"k": X * 0.5}
        e = f(X, dict(cache))
        s = jax.jit(convert_function(f))(X, dict(cache))
        np.testing.assert_allclose(np.asarray(e), np.asarray(s),
                                   rtol=1e-6)

    def test_rollout_cache_over_steps(self):
        # ref: test_dict.py MainNetWithDict.forward — loop maintaining a
        # k/v cache dict across steps
        def f(x):
            cache = {"k": jnp.zeros_like(x), "v": jnp.zeros_like(x)}
            for t in range(4):
                cache["k"] = cache["k"] * 0.5 + x
                cache["v"] = cache["v"] + cache["k"]
            return cache["v"]

        e, s = run_both(f, X)
        np.testing.assert_allclose(np.asarray(e), np.asarray(s),
                                   rtol=1e-6)

    def test_dict_pop(self):
        # ref: test_dict.py test_dic_pop
        def f(x):
            d = {"a": x, "b": x * 2}
            v = d.pop("b")
            return v + d["a"]

        e, s = run_both(f, X)
        np.testing.assert_allclose(np.asarray(e), np.asarray(s))


# -- test_container.py -------------------------------------------------------
class TestContainer:
    def test_sequential_net_to_static_trains(self):
        # ref: test_container.py SequentialNet/TestSequential
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.seq = nn.Sequential(nn.Linear(4, 8), nn.ReLU(),
                                         nn.Linear(8, 2))

            def forward(self, x):
                y = self.seq(x)
                if y.sum() > 0:
                    y = y * 2.0
                return y

        paddle.seed(0)
        eager_net = Net()
        paddle.seed(0)
        static_net = paddle.jit.to_static(Net())
        x = jnp.ones((2, 4))
        np.testing.assert_allclose(np.asarray(eager_net(x)),
                                   np.asarray(static_net(x)), rtol=1e-6)

    def test_layerlist_iteration(self):
        # ref: test_container.py (LayerList traversal in forward)
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.blocks = nn.LayerList([nn.Linear(4, 4)
                                            for _ in range(3)])

            def forward(self, x):
                for blk in self.blocks:
                    x = blk(x)
                return x

        paddle.seed(0)
        net = Net()
        e = net(jnp.ones((2, 4)))
        s = paddle.jit.to_static(net)(jnp.ones((2, 4)))
        np.testing.assert_allclose(np.asarray(e), np.asarray(s),
                                   rtol=1e-6)


# -- test_convert_call.py ----------------------------------------------------
def _helper_scale(y):
    # module-level helper with tensor control flow, called from a
    # converted function (ref: test_convert_call.py dyfunc_with_if)
    if y.sum() > 0:
        out = y * 2
    else:
        out = y * 3
    return out


def _helper_outer(y):
    return _helper_scale(y) + 1  # two levels deep


class TestConvertCall:
    def test_nested_function_converted(self):
        def f(x):
            return _helper_scale(x)

        for sign in (1.0, -1.0):
            xx = sign * (jnp.abs(X) + 0.1)
            e, s = run_both(f, xx)
            np.testing.assert_allclose(np.asarray(e), np.asarray(s))

    def test_two_levels_deep(self):
        def f(x):
            return _helper_outer(x)

        e, s = run_both(f, X)
        np.testing.assert_allclose(np.asarray(e), np.asarray(s))

    def test_lambda(self):
        # ref: test_lambda.py
        def f(x):
            g = lambda v: v * 2 + 1  # noqa: E731
            return g(x)

        e, s = run_both(f, X)
        np.testing.assert_allclose(np.asarray(e), np.asarray(s))

    def test_method_callee_converted(self):
        class Helper:
            def scale(self, y):
                if y.sum() > 0:
                    r = y * 4
                else:
                    r = y
                return r

        h = Helper()

        def f(x):
            return h.scale(x)

        e, s = run_both(f, jnp.abs(X) + 0.1)
        np.testing.assert_allclose(np.asarray(e), np.asarray(s))


# -- test_assert.py ----------------------------------------------------------
class TestAssert:
    def test_tensor_assert_passes(self):
        def f(x):
            assert x.sum() > -1e9
            return x * 2

        e, s = run_both(f, X)
        np.testing.assert_allclose(np.asarray(e), np.asarray(s))

    def test_tensor_assert_fails_at_runtime(self):
        def f(x):
            assert x.sum() > 1e9, "impossible"
            return x

        with pytest.raises(Exception, match="assertion failed|impossible"):
            out = jax.jit(convert_function(f))(X)
            jax.block_until_ready(out)

    def test_python_assert_message(self):
        def f(x, flag):
            assert flag, "flag must be set"
            return x

        with pytest.raises(AssertionError, match="flag must be set"):
            convert_function(f)(X, False)


# -- test_len.py / test_cast.py / test_isinstance.py -------------------------
class TestBasicOps:
    def test_len_of_tensor(self):
        # ref: test_len.py len_with_tensor
        def f(x):
            n = len(x)
            return x * n

        e, s = run_both(f, X)
        np.testing.assert_allclose(np.asarray(e), np.asarray(s))

    def test_cast_in_control_flow(self):
        # ref: test_cast.py test_mix_cast
        def f(x):
            if x.sum() > 0:
                y = x.astype("int32")
            else:
                y = x.astype("int32") * 2
            return y.astype("float32")

        e, s = run_both(f, jnp.abs(X) + 1.0)
        np.testing.assert_allclose(np.asarray(e), np.asarray(s))

    def test_isinstance_dispatch(self):
        # ref: test_isinstance.py
        def f(x):
            if isinstance(x, (int, float)):
                return jnp.asarray(float(x))
            return x * 2

        e, s = run_both(f, X)
        np.testing.assert_allclose(np.asarray(e), np.asarray(s))
        np.testing.assert_allclose(np.asarray(convert_function(f)(3)), 3.0)


# -- test_slice.py / test_tensor_shape.py ------------------------------------
class TestSliceAndShape:
    def test_slice_write_in_converted_loop(self):
        # ref: test_slice.py test_set_value (functional .at form)
        def f(x):
            out = jnp.zeros_like(x)
            for i in range(3):
                out = out.at[i].set(x[i] * (i + 1))
            return out

        e, s = run_both(f, X)
        np.testing.assert_allclose(np.asarray(e), np.asarray(s))

    def test_shape_in_condition(self):
        # ref: test_tensor_shape.py dyfunc_tensor_shape_basic
        def f(x):
            if x.shape[0] > 2:
                r = x.reshape(-1)
            else:
                r = x
            return r

        e, s = run_both(f, X)
        np.testing.assert_allclose(np.asarray(e), np.asarray(s))


# -- test_return.py ----------------------------------------------------------
class TestReturn:
    def test_python_cond_multi_return(self):
        def f(x, flag):
            if flag:
                return x * 2
            return x

        np.testing.assert_allclose(
            np.asarray(convert_function(f)(X, True)),
            np.asarray(X * 2))

    def test_tensor_cond_early_return_diagnosed(self):
        def f(x):
            if x.sum() > 0:
                return x * 2
            return x

        with pytest.raises(Dy2StaticError, match=r"\.py:\d+"):
            jax.jit(convert_function(f))(X)


# -- test_loop.py ------------------------------------------------------------
class TestLoopDepth:
    def test_nested_tensor_while_loop_local_var(self):
        # ref: test_loop.py nested while; the inner induction var is
        # loop-LOCAL (first bound inside the outer body)
        def f(x):
            i = jnp.asarray(0)
            s = jnp.zeros(())
            while i < 4:
                j = jnp.asarray(0)
                while j < 3:
                    s = s + x[0, 0]
                    j = j + 1
                i = i + 1
            return s

        e, s_ = run_both(f, X)
        np.testing.assert_allclose(np.asarray(e), np.asarray(s_),
                                   rtol=1e-6)

    def test_python_for_with_tensor_if_inside(self):
        # ref: test_loop.py for_loop_dyfunc + ifelse composition
        def f(x):
            total = jnp.zeros(())
            for i in range(3):
                for j in range(2):
                    if x[i, j] > 0:
                        total = total + x[i, j]
            return total

        e, s = run_both(f, X)
        np.testing.assert_allclose(np.asarray(e), np.asarray(s),
                                   rtol=1e-6)


# -- test_grad.py ------------------------------------------------------------
class TestGrad:
    def test_grad_through_converted_control_flow(self):
        def f(x):
            if x.sum() > 0:
                r = (x * x).sum()
            else:
                r = x.sum()
            return r

        g_pos = jax.grad(convert_function(f))(jnp.abs(X) + 0.1)
        np.testing.assert_allclose(np.asarray(g_pos),
                                   np.asarray(2 * (jnp.abs(X) + 0.1)),
                                   rtol=1e-6)
        g_neg = jax.grad(convert_function(f))(-jnp.abs(X) - 0.1)
        np.testing.assert_allclose(np.asarray(g_neg), 1.0)


# -- test_program_translator.py (try/except around control flow) -------------
class TestTryExcept:
    def test_try_except_around_tensor_if(self):
        def f(x):
            try:
                if x.sum() > 0:
                    y = x * 2
                else:
                    y = x
            except ValueError:
                y = x * 0
            return y

        e, s = run_both(f, X)
        np.testing.assert_allclose(np.asarray(e), np.asarray(s))


# -- full models: test_mnist.py / test_yolov3.py -----------------------------
class TestFullModels:
    def test_mnist_style_cnn_to_static_step(self):
        # ref: test_mnist.py MNIST to_static training parity
        class SmallCNN(nn.Layer):
            def __init__(self):
                super().__init__()
                self.conv = nn.Conv2D(1, 4, 3, padding=1)
                self.fc = nn.Linear(4 * 8 * 8, 10)

            def forward(self, x):
                h = nn.functional.relu(self.conv(x))
                h = h.reshape(h.shape[0], -1)
                logits = self.fc(h)
                # control flow on a traced statistic
                if jnp.mean(jnp.abs(logits)) > 1e6:
                    logits = logits / 10.0
                return logits

        x = jnp.asarray(np.random.RandomState(1)
                        .randn(2, 1, 8, 8).astype("float32"))
        paddle.seed(0)
        eager = SmallCNN()
        paddle.seed(0)
        static = paddle.jit.to_static(SmallCNN())
        np.testing.assert_allclose(np.asarray(eager(x)),
                                   np.asarray(static(x)), rtol=1e-5)

    def test_yolo_style_box_head(self):
        # ref: test_yolov3.py yolov3.py:335 — per-anchor loop building
        # boxes from a feature grid, with confidence gating
        def box_head(feat, anchors):
            b, _, h, w = feat.shape
            outs = []
            for a in range(len(anchors)):
                aw, ah = anchors[a]
                raw = feat[:, a * 4:(a + 1) * 4]
                cx = jax.nn.sigmoid(raw[:, 0])
                cy = jax.nn.sigmoid(raw[:, 1])
                bw = jnp.exp(jnp.clip(raw[:, 2], -5, 5)) * aw
                bh = jnp.exp(jnp.clip(raw[:, 3], -5, 5)) * ah
                outs.append(jnp.stack([cx, cy, bw, bh], axis=1))
            boxes = jnp.stack(outs, axis=1)        # (b, A, 4, h, w)
            if boxes.sum() > 1e9:
                boxes = boxes * 0.0
            return boxes

        feat = jnp.asarray(np.random.RandomState(2)
                           .randn(2, 8, 5, 5).astype("float32"))
        anchors = [(10.0, 13.0), (16.0, 30.0)]
        e = box_head(feat, anchors)
        s = jax.jit(convert_function(box_head),
                    static_argnums=())(feat, anchors)
        np.testing.assert_allclose(np.asarray(e), np.asarray(s),
                                   rtol=1e-5)
