"""paddle.sysconfig (reference: python/paddle/sysconfig.py — get_include /
get_lib for building custom C++ ops against the installed wheel).

Here the native surface is ``csrc/`` (the C++ runtime tier); custom-op
builds via paddle_tpu.utils.cpp_extension compile against these headers.
"""
from __future__ import annotations

import os


def _root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def get_include() -> str:
    """Directory of C headers for custom-op extensions."""
    return os.path.join(_root(), "csrc")


def get_lib() -> str:
    """Directory containing built native libraries (csrc/ build output)."""
    return os.path.join(_root(), "csrc", "build")
