"""paddle_tpu.profiler — host event tracing + device (XLA) profiling.

Capability map (reference, not copied):
- ``RecordEvent`` RAII host ranges     ← platform/profiler.h:127 RecordEvent
- ``start_profiler``/``stop_profiler`` ← fluid/profiler.py:190,257 and
  platform/profiler.h:213 EnableProfiler/DisableProfiler
- ``profiler`` context manager         ← fluid/profiler.py:314
- device tracing                       ← platform/device_tracer.h:43 (CUPTI);
  here the device side is jax.profiler (XPlane/TensorBoard) — XLA already
  correlates host/device, so no hand-rolled CUPTI analogue is needed.
- chrome-trace export                  ← tools/timeline.py (proto → chrome);
  here host events are written directly in the chrome://tracing JSON format.

Host events nest via a thread-local stack; on TPU each event also opens a
``jax.named_scope`` so the range shows up inside the XLA trace viewer.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import defaultdict
from typing import Optional

import jax

__all__ = [
    "RecordEvent", "record_event", "start_profiler", "stop_profiler",
    "reset_profiler", "profiler", "is_profiler_enabled", "export_chrome_tracing",
    "snapshot_events", "thread_names",
]

_state = threading.local()
_lock = threading.Lock()
_enabled = False
_events = []          # completed: (name, parent_path, start_ns, end_ns, tid)
_tid_names = {}       # tid -> thread name at record time (export metadata)
_trace_dir = None     # jax.profiler output dir when device tracing is on
_start_wall_ns = 0
_session = 0          # bumped by start/stop; pairs RecordEvent begin/end


def _stack():
    if not hasattr(_state, "stack"):
        _state.stack = []
    return _state.stack


def is_profiler_enabled() -> bool:
    return _enabled


class RecordEvent:
    """Named host range; usable as context manager or start()/end() pair.

    reference: platform/profiler.h:127 (RAII RecordEvent) and the public
    paddle.profiler.RecordEvent of later versions.

    Pair-safe across profiler state changes: each ``begin()`` captures the
    profiler session it started in, and ``end()`` only records the range
    if the SAME session is still active — a start/stop between the pair
    silently drops the range instead of writing garbage timestamps into
    the new session. The nesting stack holds the event objects themselves
    (removed by identity), so an ``end()`` arriving out of LIFO order can
    never pop another event's entry; the ``jax.named_scope`` is always
    exited iff it was entered.
    """

    def __init__(self, name: str):
        self.name = name
        self._t0 = None
        self._scope = None
        self._session = None

    def begin(self):
        if _enabled:
            self._session = _session
            self._t0 = time.perf_counter_ns()
            _stack().append(self)
            self._scope = jax.named_scope(self.name)
            self._scope.__enter__()
        return self

    def end(self):
        if self._t0 is None:
            return
        t1 = time.perf_counter_ns()
        stack = _stack()
        try:
            stack.remove(self)
        except ValueError:
            pass  # stack was cleared by a profiler restart
        if _enabled and self._session == _session:
            parent = "/".join(e.name for e in stack
                              if e._session == _session)
            cur = threading.current_thread()
            with _lock:
                _events.append((self.name, parent, self._t0, t1, cur.ident))
                _tid_names[cur.ident] = cur.name
        if self._scope is not None:
            self._scope.__exit__(None, None, None)
            self._scope = None
        self._t0 = None
        self._session = None

    __enter__ = begin

    def __exit__(self, *exc):
        self.end()
        return False


@contextlib.contextmanager
def record_event(name: str):
    ev = RecordEvent(name)
    ev.begin()
    try:
        yield ev
    finally:
        ev.end()


def reset_profiler():
    """reference: fluid/profiler.py:168."""
    global _events
    with _lock:
        _events = []


def start_profiler(state: str = "All", tracer_option: str = "Default",
                   trace_dir: Optional[str] = None):
    """Enable host-event recording; if ``state`` includes the device
    ("GPU"/"TPU"/"All") also start jax.profiler device tracing.

    reference: fluid/profiler.py:190 (states CPU/GPU/All).
    """
    global _enabled, _trace_dir, _start_wall_ns, _session
    if state not in ("CPU", "GPU", "TPU", "All"):
        raise ValueError(f"state must be CPU/GPU/TPU/All, got {state}")
    reset_profiler()
    _session += 1  # invalidate RecordEvents begun before this point
    _start_wall_ns = time.perf_counter_ns()
    _enabled = True
    if state in ("GPU", "TPU", "All") and tracer_option != "HostOnly":
        _trace_dir = trace_dir or os.path.join(
            os.getcwd(), "profiler_output")
        try:
            jax.profiler.start_trace(_trace_dir)
        except Exception:   # already tracing / backend without profiler
            _trace_dir = None


def stop_profiler(sorted_key: Optional[str] = None,
                  profile_path: str = "/tmp/profile",
                  verbose: bool = True):
    """Disable recording; print a summary table sorted by ``sorted_key``
    (total/calls/max/min/ave) and write chrome tracing json to
    ``profile_path``. ``verbose=False`` suppresses the summary print
    (telemetry.scope stops the profiler quietly and exports its own
    merged trace).

    reference: fluid/profiler.py:257.
    """
    global _enabled, _trace_dir, _session
    if not _enabled:
        return
    _enabled = False
    _session += 1  # RecordEvents still open will not record into the next run
    if _trace_dir is not None:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        _trace_dir = None
    if profile_path:
        try:
            export_chrome_tracing(profile_path)
        except OSError:
            pass
    if verbose:
        _print_summary(sorted_key)


def _aggregate():
    agg = defaultdict(lambda: [0, 0.0, 0.0, float("inf")])  # calls,total,max,min
    with _lock:
        events = list(_events)
    for name, parent, t0, t1, _tid in events:
        ms = (t1 - t0) / 1e6
        key = f"{parent}/{name}" if parent else name
        a = agg[key]
        a[0] += 1
        a[1] += ms
        a[2] = max(a[2], ms)
        a[3] = min(a[3], ms)
    return agg


def _print_summary(sorted_key):
    agg = _aggregate()
    if not agg:
        return
    rows = [(k, c, tot, tot / c, mx, mn)
            for k, (c, tot, mx, mn) in agg.items()]
    order = {"total": 2, "calls": 1, "ave": 3, "max": 4, "min": 5}
    rows.sort(key=lambda r: r[order.get(sorted_key or "total", 2)],
              reverse=True)
    name_w = max(len(r[0]) for r in rows)
    name_w = max(name_w, len("Event"))
    print(f"{'Event':<{name_w}}  {'Calls':>7} {'Total(ms)':>11} "
          f"{'Avg(ms)':>9} {'Max(ms)':>9} {'Min(ms)':>9}")
    for name, calls, tot, ave, mx, mn in rows:
        print(f"{name:<{name_w}}  {calls:>7} {tot:>11.3f} {ave:>9.3f} "
              f"{mx:>9.3f} {mn:>9.3f}")


def snapshot_events():
    """Raw completed events + the session start timestamp, for exporters
    that merge host ranges with other timelines (telemetry.export)."""
    with _lock:
        return list(_events), _start_wall_ns


def thread_names():
    """tid -> thread-name map observed while recording (chrome ``ph:"M"``
    thread_name metadata in the merged export)."""
    with _lock:
        return dict(_tid_names)


def export_chrome_tracing(path: str):
    """Write completed host events as chrome://tracing JSON (the reference
    reaches the same format via tools/timeline.py over profiler.proto).

    The time origin is the EARLIEST of the session start and any recorded
    event's begin — events that slipped in from before ``start_profiler``
    reset ``_start_wall_ns`` must not produce negative timestamps (chrome
    silently drops those)."""
    events, start_ns = snapshot_events()
    base = min([start_ns] + [t0 for _n, _p, t0, _t1, _tid in events])
    trace = []
    for name, parent, t0, t1, tid in events:
        trace.append({
            "name": name, "cat": "host", "ph": "X",
            "ts": (t0 - base) / 1e3,
            "dur": (t1 - t0) / 1e3,
            "pid": os.getpid(), "tid": tid,
            "args": {"parent": parent} if parent else {},
        })
    with open(path, "w") as f:
        json.dump({"traceEvents": trace}, f)


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: Optional[str] = None,
             profile_path: str = "/tmp/profile", tracer_option: str = "Default"):
    """reference: fluid/profiler.py:314 — the `with profiler(...)` guard."""
    start_profiler(state, tracer_option=tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key=sorted_key, profile_path=profile_path)


def get_events():
    """Completed host events as dicts (for tests / tooling)."""
    with _lock:
        return [dict(name=n, parent=p, dur_ms=(t1 - t0) / 1e6, tid=tid)
                for n, p, t0, t1, tid in _events]
