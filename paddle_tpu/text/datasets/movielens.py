"""MovieLens-1M rating dataset (reference:
python/paddle/text/datasets/movielens.py — ml-1m zip with movies.dat /
users.dat / ratings.dat in the `a::b::c` format; random train/test split by
test_ratio).
"""
from __future__ import annotations

import re
import zipfile

import numpy as np

from ...io.dataset import Dataset
from ...utils.download import DATA_HOME, get_path_from_url

URL = "https://dataset.bj.bcebos.com/movielens%2Fml-1m.zip"
MD5 = "c4d9eecfca2ab87c1945afe126590906"

_AGES = [1, 18, 25, 35, 45, 50, 56]


class MovieInfo:
    """reference movielens.py MovieInfo: index/categories/title + value()."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, movie_title_dict):
        return [
            [self.index],
            [categories_dict[c] for c in self.categories],
            [movie_title_dict[w.lower()] for w in self.title.split()],
        ]

    def __repr__(self):
        return (f"<MovieInfo id({self.index}), title({self.title}), "
                f"categories({self.categories})>")


class UserInfo:
    """reference movielens.py UserInfo: index/gender/age-bucket/job."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.positive_gender = gender == "M"
        self.age = _AGES.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [[self.index], [0 if self.positive_gender else 1],
                [self.age], [self.job_id]]

    def __repr__(self):
        return (f"<UserInfo id({self.index}), "
                f"gender({'M' if self.positive_gender else 'F'}), "
                f"age({_AGES[self.age]}), job({self.job_id})>")


class Movielens(Dataset):
    """Samples: usr.value() + mov.value(...) + [[rating]] flattened to a
    tuple of np arrays (reference movielens.py __getitem__)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        if data_file is None:
            assert download, "data_file not set and download disabled"
            data_file = get_path_from_url(URL, DATA_HOME + "/movielens",
                                          decompress=False)
        self.data_file = data_file
        self.test_ratio = test_ratio
        np.random.seed(rand_seed)
        self._load_meta()
        self._load_ratings()

    def _load_meta(self):
        pattern = re.compile(r"^(.*)\((\d+)\)$")
        self.movie_info, self.user_info = {}, {}
        title_words, categories = set(), set()
        with zipfile.ZipFile(self.data_file) as z:
            with z.open("ml-1m/movies.dat") as f:
                for line in f:
                    mid, title, cats = line.decode("latin1").strip().split("::")
                    cats = cats.split("|")
                    categories.update(cats)
                    m = pattern.match(title)
                    title = m.group(1) if m else title
                    title_words.update(w.lower() for w in title.split())
                    self.movie_info[int(mid)] = MovieInfo(mid, cats, title)
            with z.open("ml-1m/users.dat") as f:
                for line in f:
                    uid, gender, age, job, _ = \
                        line.decode("latin1").strip().split("::")
                    self.user_info[int(uid)] = UserInfo(uid, gender, age, job)
        self.movie_title_dict = {w: i for i, w in enumerate(title_words)}
        self.categories_dict = {c: i for i, c in enumerate(categories)}

    def _load_ratings(self):
        is_test = self.mode == "test"
        self.data = []
        with zipfile.ZipFile(self.data_file) as z:
            with z.open("ml-1m/ratings.dat") as f:
                for line in f:
                    if (np.random.random() < self.test_ratio) != is_test:
                        continue
                    uid, mid, rating, _ = \
                        line.decode("latin1").strip().split("::")
                    usr = self.user_info[int(uid)]
                    mov = self.movie_info[int(mid)]
                    self.data.append(
                        usr.value()
                        + mov.value(self.categories_dict,
                                    self.movie_title_dict)
                        + [[float(rating)]])

    def __getitem__(self, idx):
        return tuple(np.array(x) for x in self.data[idx])

    def __len__(self):
        return len(self.data)
