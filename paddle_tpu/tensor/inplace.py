"""Trailing-underscore ("inplace") op variants.

Reference: python/paddle/tensor/math.py / manipulation.py register the
``add_`` / ``reshape_`` / ``squeeze_`` ... inplace APIs (dygraph-only in the
reference, mutating the VarBase buffer).

TPU translation: jax.Arrays are immutable — under jit, XLA's buffer donation
and liveness analysis already reuse dead buffers, which is what the
reference's inplace ops exist to achieve. These variants therefore RETURN the
result (callers must rebind), keeping source compatibility for code written
against the reference's API while letting XLA own memory reuse.
"""
from __future__ import annotations

import jax.numpy as jnp

from . import manipulation as _manip
from . import math as _math

__all__ = [
    "add_", "subtract_", "ceil_", "clip_", "exp_", "flatten_", "floor_",
    "reciprocal_", "reshape_", "round_", "rsqrt_", "scale_", "scatter_",
    "sqrt_", "squeeze_", "tanh_", "unsqueeze_", "zero_", "fill_",
]


def add_(x, y, name=None):
    return _math.add(x, y)


def subtract_(x, y, name=None):
    return _math.subtract(x, y)


def ceil_(x, name=None):
    return jnp.ceil(x)


def clip_(x, min=None, max=None, name=None):
    return _math.clip(x, min=min, max=max)


def exp_(x, name=None):
    return jnp.exp(x)


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    return _manip.flatten(x, start_axis, stop_axis)


def floor_(x, name=None):
    return jnp.floor(x)


def reciprocal_(x, name=None):
    return jnp.reciprocal(x)


def reshape_(x, shape, name=None):
    return _manip.reshape(x, shape)


def round_(x, name=None):
    return jnp.round(x)


def rsqrt_(x, name=None):
    return _math.rsqrt(x)


def scale_(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    return _math.scale(x, scale=scale, bias=bias,
                       bias_after_scale=bias_after_scale, act=act)


def scatter_(x, index, updates, overwrite=True, name=None):
    return _manip.scatter(x, index, updates, overwrite=overwrite)


def sqrt_(x, name=None):
    return jnp.sqrt(x)


def squeeze_(x, axis=None, name=None):
    return _manip.squeeze(x, axis)


def tanh_(x, name=None):
    return jnp.tanh(x)


def unsqueeze_(x, axis, name=None):
    return _manip.unsqueeze(x, axis)


def zero_(x, name=None):
    return jnp.zeros_like(x)


def fill_(x, value, name=None):
    return jnp.full_like(x, value)
