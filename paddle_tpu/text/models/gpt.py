"""GPT model family — the flagship hybrid-parallel transformer.

Capability target: the reference trains GPT/ERNIE-class models with
DP×TP×PP×sharding (BASELINE.md configs 2-4; TP layers
fleet/meta_parallel/parallel_layers/mp_layers.py, PP pp_layers.py).
This implementation is TPU-first:
- attention goes through F.scaled_dot_product_attention → Pallas flash
  attention on TPU (O(S) memory, no S×S materialization);
- QKV/MLP matmuls are Column/RowParallelLinear (model-axis sharding on MXU);
- a LayerDesc factory (`gpt_pipeline_descs`) exposes the same network as a
  PipelineLayer for the pipe axis;
- weights default to master-fp32 with bf16 compute via amp.auto_cast.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from ... import nn
from ...nn import functional as F
from ...nn.initializer import Normal
from ...nn.layer import Layer
from ...distributed.meta_parallel.parallel_layers.mp_layers import (
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding)
from ...distributed.meta_parallel.parallel_layers.pp_layers import (
    LayerDesc, PipelineLayer, SharedLayerDesc)


def _sep_axis_bound() -> bool:
    import jax.lax as lax
    try:
        return lax.axis_size("sep") > 1
    except Exception:
        return False


class GPTEmbeddings(Layer):
    def __init__(self, vocab_size, hidden_size, max_position_embeddings=1024,
                 hidden_dropout_prob=0.1, initializer_range=0.02,
                 tensor_parallel=True):
        super().__init__()
        emb_cls = VocabParallelEmbedding if tensor_parallel else nn.Embedding
        self.word_embeddings = emb_cls(vocab_size, hidden_size)
        self.position_embeddings = nn.Embedding(
            max_position_embeddings, hidden_size,
            weight_attr=None)
        self.dropout = nn.Dropout(hidden_dropout_prob)

    def forward(self, input_ids, position_ids=None):
        if position_ids is None:
            s = input_ids.shape[-1]
            position_ids = jnp.arange(s, dtype=jnp.int32)[None, :]
            if _sep_axis_bound():
                # context parallelism: this device holds sequence chunk
                # [i*s_local, (i+1)*s_local) — positions must be GLOBAL
                import jax.lax as lax
                position_ids = position_ids + lax.axis_index("sep") * s
        w = self.word_embeddings(input_ids)
        p = self.position_embeddings(position_ids)
        return self.dropout(w + p)


class GPTAttention(Layer):
    def __init__(self, hidden_size, num_heads, attn_dropout=0.1,
                 resid_dropout=0.1, tensor_parallel=True, mp_degree=1,
                 use_flash=True, causal=True):
        super().__init__()
        self.num_heads = num_heads
        self.causal = causal
        self.head_dim = hidden_size // num_heads
        self.mp_degree = mp_degree if tensor_parallel else 1
        self.local_heads = num_heads // max(self.mp_degree, 1)
        if tensor_parallel:
            self.qkv_proj = ColumnParallelLinear(hidden_size, 3 * hidden_size,
                                                 gather_output=False)
            self.out_proj = RowParallelLinear(hidden_size, hidden_size,
                                              input_is_parallel=True)
        else:
            self.qkv_proj = nn.Linear(hidden_size, 3 * hidden_size)
            self.out_proj = nn.Linear(hidden_size, hidden_size)
        self.attn_dropout = attn_dropout
        self.resid_dropout = nn.Dropout(resid_dropout)

    def forward(self, x, attn_mask=None):
        b, s, _ = x.shape
        qkv = self.qkv_proj(x)  # (b, s, 3*h/mp)
        local_h = qkv.shape[-1] // 3
        heads = local_h // self.head_dim
        qkv = jnp.reshape(qkv, (b, s, heads, 3 * self.head_dim))
        q, k, v = jnp.split(qkv, 3, axis=-1)
        if _sep_axis_bound():
            # context parallelism: sequence sharded over the "sep" axis →
            # ring attention (SURVEY.md §5 long-context capability). A plain
            # attention fallback here would attend only within the local
            # shard — silently wrong — so unsupported options must raise.
            if attn_mask is not None or (self.attn_dropout != 0.0 and
                                         self.training):
                raise NotImplementedError(
                    "sequence ('sep') parallelism requires attn_mask=None "
                    "and attn_dropout=0.0: ring attention has no mask/"
                    "dropout support, and local attention would be wrong")
            from ...ops.ring_attention import ring_flash_attention
            out = ring_flash_attention(q, k, v, causal=self.causal)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, dropout_p=self.attn_dropout,
                is_causal=self.causal and attn_mask is None,
                training=self.training)
        out = jnp.reshape(out, (b, s, local_h))
        return self.resid_dropout(self.out_proj(out))


class GPTMLP(Layer):
    def __init__(self, hidden_size, intermediate_size, dropout=0.1,
                 tensor_parallel=True):
        super().__init__()
        if tensor_parallel:
            self.fc_in = ColumnParallelLinear(hidden_size, intermediate_size,
                                              gather_output=False)
            self.fc_out = RowParallelLinear(intermediate_size, hidden_size,
                                            input_is_parallel=True)
        else:
            self.fc_in = nn.Linear(hidden_size, intermediate_size)
            self.fc_out = nn.Linear(intermediate_size, hidden_size)
        self.dropout = nn.Dropout(dropout)

    def forward(self, x):
        return self.dropout(self.fc_out(F.gelu(self.fc_in(x), approximate=True)))


class GPTBlock(Layer):
    def __init__(self, hidden_size, num_heads, intermediate_size=None,
                 attn_dropout=0.1, resid_dropout=0.1, layer_norm_epsilon=1e-5,
                 tensor_parallel=True, mp_degree=1):
        super().__init__()
        intermediate_size = intermediate_size or 4 * hidden_size
        self.ln_1 = nn.LayerNorm(hidden_size, epsilon=layer_norm_epsilon)
        self.attn = GPTAttention(hidden_size, num_heads, attn_dropout,
                                 resid_dropout, tensor_parallel, mp_degree)
        self.ln_2 = nn.LayerNorm(hidden_size, epsilon=layer_norm_epsilon)
        self.mlp = GPTMLP(hidden_size, intermediate_size, resid_dropout,
                          tensor_parallel)

    def forward(self, x, attn_mask=None):
        x = x + self.attn(self.ln_1(x), attn_mask)
        x = x + self.mlp(self.ln_2(x))
        return x


class GPTModel(Layer):
    """Decoder-only transformer trunk."""

    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=None,
                 max_position_embeddings=1024, attn_dropout=0.1,
                 hidden_dropout=0.1, layer_norm_epsilon=1e-5,
                 tensor_parallel=True, mp_degree=1):
        super().__init__()
        self.hidden_size = hidden_size
        self.embeddings = GPTEmbeddings(vocab_size, hidden_size,
                                        max_position_embeddings,
                                        hidden_dropout,
                                        tensor_parallel=tensor_parallel)
        self.h = nn.LayerList([
            GPTBlock(hidden_size, num_heads, intermediate_size, attn_dropout,
                     hidden_dropout, layer_norm_epsilon, tensor_parallel,
                     mp_degree)
            for _ in range(num_layers)])
        self.ln_f = nn.LayerNorm(hidden_size, epsilon=layer_norm_epsilon)

    def forward(self, input_ids, attn_mask=None):
        x = self.embeddings(input_ids)
        for block in self.h:
            x = block(x, attn_mask)
        return self.ln_f(x)


class GPTLMHead(Layer):
    """Projection to (sharded) vocab logits; optionally tied to the word
    embedding (SharedLayerDesc semantics in the PP variant)."""

    def __init__(self, hidden_size, vocab_size, embedding_weight=None,
                 tensor_parallel=True):
        super().__init__()
        if embedding_weight is not None:
            self.weight = embedding_weight  # tied Parameter (vocab, hidden)
            self._tied = True
        else:
            self.weight = self.create_parameter(
                (vocab_size, hidden_size), initializer=Normal(0.0, 0.02))
            self._tied = False
            if tensor_parallel:
                from jax.sharding import PartitionSpec as P
                self.weight.pspec = P("model", None)

    def forward(self, x):
        from ...distributed.meta_parallel.parallel_layers.mp_layers import (
            _in_shard_map, copy_to_model_parallel)
        if _in_shard_map():
            # vocab-sharded projection: backward needs the psum-over-model
            # identity so upstream (replicated) grads are complete
            x = copy_to_model_parallel(x)
        return jnp.matmul(x, jnp.swapaxes(self.weight.value, 0, 1))


class GPTForPretraining(Layer):
    """Trunk + tied LM head + parallel CE loss (BASELINE.md config 3)."""

    def __init__(self, gpt: GPTModel = None, tensor_parallel=True, **kwargs):
        super().__init__()
        self.gpt = gpt or GPTModel(tensor_parallel=tensor_parallel, **kwargs)
        self.lm_head = GPTLMHead(
            self.gpt.hidden_size, 0,
            embedding_weight=self.gpt.embeddings.word_embeddings.weight)
        self.parallel_loss = ParallelCrossEntropy()
        self.tensor_parallel = tensor_parallel

    def forward(self, input_ids, attn_mask=None):
        h = self.gpt(input_ids, attn_mask)
        return self.lm_head(h)

    def loss(self, logits, labels):
        per_tok = self.parallel_loss(logits, labels)
        return jnp.mean(per_tok)

    def fused_head_loss(self, input_ids, labels, chunk: int = 8192,
                        attn_mask=None, ce_kernel: str = "chunked"):
        """Trunk -> fused head+CE: the (B, S, vocab) logits are never
        materialized. ce_kernel picks the implementation —
        ``"chunked"`` (ops/chunked_ce.py jnp online-logsumexp scan,
        ``chunk`` classes per step), ``"pallas"`` (the Mosaic kernel in
        ops/pallas/fused_ce.py, interpret mode auto-selected off-TPU),
        or ``"auto"`` (pallas on TPU, chunked elsewhere). Single-device
        / DP path (the TP path keeps the vocab-sharded head +
        ParallelCrossEntropy, which already splits the logits tensor
        over "model")."""
        from ...distributed.meta_parallel.parallel_layers.mp_layers import (
            _in_shard_map)
        from ...nn.functional.loss import fused_linear_cross_entropy
        if self.tensor_parallel and _in_shard_map():
            # vocab-sharded head: local weight covers only V/mp columns —
            # the fused ops would silently miss every off-shard label.
            raise RuntimeError(
                "fused_head_loss is the single-device/DP path; under "
                "tensor parallelism use forward() + the vocab-sharded "
                "ParallelCrossEntropy loss")
        h = self.gpt(input_ids, attn_mask)
        w = jnp.swapaxes(self.lm_head.weight.value, 0, 1)   # (H, V)
        return fused_linear_cross_entropy(h, w, labels, chunk=chunk,
                                          kernel=ce_kernel)


# -- pipeline variant --------------------------------------------------------
class _EmbeddingPipe(GPTEmbeddings):
    def forward(self, input_ids):
        return super().forward(input_ids)


class _LNHeadPipe(Layer):
    """Final LN + untied head for the PP build (the tied variant shares the
    first stage's embedding weight via SharedLayerDesc instead)."""

    def __init__(self, hidden_size, vocab_size, epsilon=1e-5,
                 tensor_parallel=True):
        super().__init__()
        self.ln_f = nn.LayerNorm(hidden_size, epsilon=epsilon)
        self.head = GPTLMHead(hidden_size, vocab_size,
                              tensor_parallel=tensor_parallel)

    def forward(self, x):
        return self.head(self.ln_f(x))


def _tied_head_forward(x, weight):
    """LM head against the (stage-0-owned) embedding weight — the
    SharedLayerDesc forward_func (reference pp_layers.py:62 tied embedding:
    the weight lives once; here it is replicated over pipe and the engine's
    pipe-axis grad psum sums the embedding-stage and head-stage
    contributions)."""
    from ...distributed.meta_parallel.parallel_layers.mp_layers import (
        _in_shard_map, copy_to_model_parallel)
    if _in_shard_map():
        # vocab-sharded weight (TP): replicate the activation grad psum
        x = copy_to_model_parallel(x)
    return jnp.matmul(x, jnp.swapaxes(weight, 0, 1))


def gpt_pipeline_descs(vocab_size=50304, hidden_size=768, num_layers=12,
                       num_heads=12, max_position_embeddings=1024,
                       dropout=0.1, tensor_parallel=True,
                       tie_embeddings=True):
    """LayerDesc list for PipelineLayer (reference pp_layers.py usage).

    With ``tie_embeddings`` (reference default) the LM head reuses the word
    embedding weight across stages via SharedLayerDesc; the final LayerNorm
    stays a plain last-stage layer."""
    if tie_embeddings:
        descs = [SharedLayerDesc(
            "embed", _EmbeddingPipe,
            shared_weight_attr="word_embeddings.weight",
            vocab_size=vocab_size, hidden_size=hidden_size,
            max_position_embeddings=max_position_embeddings,
            hidden_dropout_prob=dropout, tensor_parallel=tensor_parallel)]
    else:
        descs = [LayerDesc(_EmbeddingPipe, vocab_size, hidden_size,
                           max_position_embeddings, dropout,
                           tensor_parallel=tensor_parallel)]
    for _ in range(num_layers):
        descs.append(LayerDesc(GPTBlock, hidden_size, num_heads,
                               attn_dropout=dropout, resid_dropout=dropout,
                               tensor_parallel=tensor_parallel))
    if tie_embeddings:
        descs.append(LayerDesc(nn.LayerNorm, hidden_size))
        descs.append(SharedLayerDesc(
            "embed", _EmbeddingPipe, forward_func=_tied_head_forward,
            shared_weight_attr="word_embeddings.weight",
            vocab_size=vocab_size, hidden_size=hidden_size,
            max_position_embeddings=max_position_embeddings,
            hidden_dropout_prob=dropout, tensor_parallel=tensor_parallel))
    else:
        descs.append(LayerDesc(_LNHeadPipe, hidden_size, vocab_size,
                               tensor_parallel=tensor_parallel))
    return descs


def gpt_tiny(**kw):
    cfg = dict(vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4,
               max_position_embeddings=256)
    cfg.update(kw)
    return cfg


def gpt_1p3b(**kw):
    """GPT-3 1.3B config (BASELINE.json configs[3])."""
    cfg = dict(vocab_size=50304, hidden_size=2048, num_layers=24,
               num_heads=16, max_position_embeddings=1024)
    cfg.update(kw)
    return cfg
