"""Out-of-tree custom C++ ops (reference:
python/paddle/utils/cpp_extension/ — CppExtension/CUDAExtension/setup/load
JIT-building ops registered with PD_BUILD_OP in
paddle/fluid/extension/, loaded by framework/custom_operator.cc).

TPU-native design: the C++ kernel is a host function behind the C ABI in
``csrc/paddle_ext.h``; ``load()`` compiles it with g++, binds via ctypes
(no pybind11 in this image) and wraps each registered op as a JAX op —
``jax.pure_callback`` for the forward, ``jax.custom_vjp`` when a backward
is registered, so the op composes with grad/jit/vmap-on-batch like any
other primitive. Device placement: the callback runs on host; XLA moves
data HBM↔host around it (same topology as the reference's CPU custom op
under a GPU program, via data transfer).
"""
from .extension_utils import CppExtension, load, setup  # noqa: F401
