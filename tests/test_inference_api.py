"""Base inference handle API + serving satellites: Config state
preservation across set_model, Tensor handle direction checks, the
per-prefix load cache a PredictorPool shares, the _n_user_inputs
fallback for non-conforming exports, and int8/int4 weight-only
quantization of served models (inference/quant.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import inference, nn
from paddle_tpu.inference import quant
from paddle_tpu.jit import InputSpec


@pytest.fixture()
def saved_model(tmp_path):
    paddle.seed(3)
    net = nn.Linear(32, 16)
    net.eval()
    prefix = str(tmp_path / "model")
    paddle.jit.save(net, prefix,
                    input_spec=[InputSpec([None, 32], "float32")])
    return net, prefix


# -- Config -------------------------------------------------------------------

def test_set_model_preserves_device_profile_and_quant():
    cfg = inference.Config("/a/model")
    cfg.disable_gpu()
    cfg.enable_profile()
    cfg.enable_weight_quantize("int8", block=64)
    cfg.set_model("/b/other")
    assert cfg.prog_file() == "/b/other.stablehlo"
    assert cfg.params_file() == "/b/other.pdiparams"
    assert cfg._device == "cpu"
    assert cfg._enable_profile is True
    assert cfg._weight_quant == ("int8", 64)
    # suffixes are normalized away like in __init__
    cfg.set_model("/c/m.pdmodel")
    assert cfg.prog_file() == "/c/m.stablehlo"
    assert cfg._device == "cpu"


def test_enable_weight_quantize_validates_policy():
    cfg = inference.Config("/a/model")
    with pytest.raises(ValueError, match="int8/int4"):
        cfg.enable_weight_quantize("fp8")
    cfg.enable_weight_quantize("int4")
    assert cfg._weight_quant == ("int4", None)


# -- Tensor handles -----------------------------------------------------------

def test_tensor_handle_direction_enforced(saved_model):
    _, prefix = saved_model
    pred = inference.create_predictor(inference.Config(prefix))
    x = np.zeros((2, 32), "float32")
    inp = pred.get_input_handle("x0")
    inp.copy_from_cpu(x)
    assert inp.shape() == [2, 32]
    assert inp.name() == "x0"
    with pytest.raises(AssertionError):
        inp.copy_to_cpu()  # cannot read an input handle
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0])
    assert out.copy_to_cpu().shape == (2, 16)
    with pytest.raises(AssertionError):
        out.copy_from_cpu(x)  # cannot write an output handle


# -- load cache / pool sharing ------------------------------------------------

def test_pool_shares_one_loaded_layer(saved_model, monkeypatch):
    from paddle_tpu import jit as jit_mod
    _, prefix = saved_model
    inference.clear_layer_cache()
    calls = []
    real_load = jit_mod.load

    def counting_load(p, *a, **kw):
        calls.append(p)
        return real_load(p, *a, **kw)

    monkeypatch.setattr(jit_mod, "load", counting_load)
    pool = inference.PredictorPool(inference.Config(prefix), 3)
    assert len(calls) == 1, "pool members must share the cached layer"
    layers = {id(pool.retrieve(i)._layer) for i in range(3)}
    assert len(layers) == 1
    # a different quant spec is a different cache entry over the SAME
    # raw load (the quantized view derives from the cached fp layer)
    qcfg = inference.Config(prefix)
    qcfg.enable_weight_quantize("int8", block=64)
    qpred = inference.create_predictor(qcfg)
    assert len(calls) == 1
    assert id(qpred._layer) not in layers
    inference.clear_layer_cache()


def test_stale_artifact_is_reloaded(saved_model, monkeypatch):
    import os
    from paddle_tpu import jit as jit_mod
    _, prefix = saved_model
    inference.clear_layer_cache()
    calls = []
    real_load = jit_mod.load

    def counting_load(p, *a, **kw):
        calls.append(p)
        return real_load(p, *a, **kw)

    monkeypatch.setattr(jit_mod, "load", counting_load)
    inference.create_predictor(inference.Config(prefix))
    # touching the artifact invalidates the cache key (mtime_ns changed)
    st = os.stat(prefix + ".pdiparams")
    os.utime(prefix + ".pdiparams",
             ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
    inference.create_predictor(inference.Config(prefix))
    assert len(calls) == 2
    inference.clear_layer_cache()


# -- _n_user_inputs fallback --------------------------------------------------

def test_n_user_inputs_fallback_on_foreign_export():
    class _Stub:
        _exported = object()  # no in_tree at all

    p = inference.Predictor.__new__(inference.Predictor)
    p._layer = _Stub()
    assert p._n_user_inputs() == 1


# -- weight quantization ------------------------------------------------------

def test_quantize_roundtrip_error_bounds():
    rng = np.random.RandomState(0)
    x = (rng.randn(37, 19) * 3.0).astype("float32")
    for policy, levels in (("int8", 127.0), ("int4", 7.0)):
        qa = quant.quantize_array(x, policy, block=32)
        back = quant.dequantize_array(qa)
        assert back.shape == x.shape and back.dtype == x.dtype
        # per-block bound: |err| <= max|x| / levels (scale granularity)
        assert np.max(np.abs(back - x)) <= np.abs(x).max() / levels + 1e-6
    with pytest.raises(ValueError):
        quant.quantize_array(x, "fp8")


def test_quantize_state_passthrough_and_compression():
    rng = np.random.RandomState(1)
    state = {
        "w": rng.randn(64, 32).astype("float32"),
        "b": rng.randn(8).astype("float32"),      # smaller than a block
        "steps": np.arange(100, dtype="int64"),   # not float
    }
    q = quant.quantize_state(state, "int8", block=32)
    assert isinstance(q["w"], quant.QuantizedArray)
    assert isinstance(q["b"], np.ndarray)         # passthrough
    assert isinstance(q["steps"], np.ndarray)
    assert quant.state_bytes(q) < quant.state_bytes(
        {k: np.asarray(v) for k, v in state.items()})
    back = quant.dequantize_state(q)
    np.testing.assert_array_equal(back["b"], state["b"])
    np.testing.assert_array_equal(back["steps"], state["steps"])
    assert back["w"].shape == state["w"].shape
    # int4 nibble-packing halves the payload vs int8
    q4 = quant.quantize_state(state, "int4", block=32)
    assert q4["w"].q.nbytes == q["w"].q.nbytes // 2


def test_quantized_predictor_close_to_fp32(saved_model):
    net, prefix = saved_model
    inference.clear_layer_cache()
    cfg = inference.Config(prefix)
    cfg.enable_weight_quantize("int8", block=16)
    pred = inference.create_predictor(cfg)
    x = np.random.RandomState(5).rand(4, 32).astype("float32")
    got = pred.run([x])[0]
    want = np.asarray(net(x))
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=0.1, rtol=0.05)
    assert not np.allclose(got, want, atol=1e-9)  # quantization happened
    layer, stats = quant.quantized_layer(
        inference._load_layer(prefix), "int8", block=16)
    assert stats["n_quantized"] >= 1
    assert stats["compression_x"] > 3.0
    inference.clear_layer_cache()
