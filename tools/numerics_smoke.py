"""On-chip numerics smoke: validate the hand-written kernels' arithmetic
on the LIVE backend against dense jnp references.

Purpose (round-3 verdict item 10 / round-4 item 1b): every Pallas kernel
is trajectory-tested on the CPU interpreter, but TPU hardware rounds
differently (bf16 MXU accumulation, pltpu PRNG, revectorized reductions).
This script runs the hot kernels — flash attention fwd/bwd (causal,
kv-masked), chunked LM cross-entropy fwd/bwd, bf16 matmul — on whatever
backend is live and checks errors against fp32 dense references with
bf16-appropriate tolerances.

Usage: ``python tools/numerics_smoke.py`` — prints one JSON line per
check plus a final summary line ``{"numerics_ok": bool, ...}``; exit 0
iff every check passed. On CPU the Pallas kernels run under
``interpret=True`` (the script is backend-agnostic so the suite smokes
it without a chip; the point of running it ON the chip is the
interpret=False path).

Reference intent anchor: the reference validates fused CUDA kernels
against unfused graphs the same way
(fluid/operators/fused/multihead_matmul_op.cu + its unittest).
"""
from __future__ import annotations

import json
import os
import sys

# runnable from anywhere: the repo root (paddle_tpu's parent) on sys.path
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _ref_attention(q, k, v, causal, kv_lens, sm_scale):
    import jax.numpy as jnp

    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * sm_scale
    b, _, sq, sk = logits.shape
    mask = jnp.ones((b, 1, sq, sk), bool)
    if causal:
        mask &= jnp.tril(jnp.ones((sq, sk), bool))[None, None]
    if kv_lens is not None:
        mask &= (jnp.arange(sk)[None, :] < kv_lens[:, None])[:, None, None]
    logits = jnp.where(mask, logits, -1e30)
    p = jnp.exp(logits - logits.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", p, vf)


def check_flash_attention(interpret):
    import math

    import numpy as np
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    rs = np.random.RandomState(0)
    b, s, h, d = 2, 256, 4, 64
    q, k, v = (jnp.asarray(rs.randn(b, s, h, d), jnp.bfloat16)
               for _ in range(3))
    sm_scale = 1.0 / math.sqrt(d)
    results = []
    for name, kw in (("plain", {}), ("causal", dict(causal=True)),
                     ("kv_mask", dict(kv_lens=jnp.asarray([s, s // 2],
                                                          jnp.int32)))):
        out = flash_attention(q, k, v, interpret=interpret, **kw)
        ref = _ref_attention(q, k, v, kw.get("causal", False),
                             kw.get("kv_lens"), sm_scale)
        err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref)))
        # bf16 mantissa is 8 bits: |v|~O(1) rows give abs err ~1e-2
        results.append({"check": f"flash_fwd_{name}", "max_abs_err": err,
                        "tol": 5e-2, "ok": err < 5e-2})

    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       interpret=interpret)
                       .astype(jnp.float32) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_ref_attention(q, k, v, True, None, sm_scale) ** 2)

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    gerr = max(float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                     - b_.astype(jnp.float32))))
               for a, b_ in zip(g, gr))
    # backward accumulates over seq: looser than fwd
    results.append({"check": "flash_bwd_causal", "max_abs_err": gerr,
                    "tol": 0.5, "ok": gerr < 0.5})
    return results


def check_chunked_ce():
    import numpy as np
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.chunked_ce import chunked_lm_ce

    rs = np.random.RandomState(1)
    n, h, vocab, chunk = 512, 128, 1024, 256
    hidden = jnp.asarray(rs.randn(n, h) * 0.1, jnp.bfloat16)
    weight = jnp.asarray(rs.randn(h, vocab) * 0.1, jnp.bfloat16)
    labels = jnp.asarray(rs.randint(0, vocab, n), jnp.int32)
    labels = labels.at[::7].set(-100)  # exercise ignore_index

    def dense(hid, w):
        logits = (hid.astype(jnp.float32) @ w.astype(jnp.float32))
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        gather = jnp.take_along_axis(
            logits, jnp.maximum(labels, 0)[:, None], axis=1)[:, 0]
        valid = labels >= 0
        per = jnp.where(valid, lse - gather, 0.0)
        return per.sum() / jnp.maximum(valid.sum(), 1)

    loss_c = chunked_lm_ce(hidden, weight, labels, chunk=chunk)
    loss_d = dense(hidden, weight)
    lerr = abs(float(loss_c) - float(loss_d))
    out = [{"check": "chunked_ce_fwd", "max_abs_err": lerr, "tol": 2e-2,
            "ok": lerr < 2e-2}]
    gc = jax.grad(lambda a, b: chunked_lm_ce(a, b, labels, chunk=chunk),
                  argnums=(0, 1))(hidden, weight)
    gd = jax.grad(dense, argnums=(0, 1))(hidden, weight)
    gerr = max(float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                     - b.astype(jnp.float32))))
               for a, b in zip(gc, gd))
    out.append({"check": "chunked_ce_bwd", "max_abs_err": gerr,
                "tol": 2e-2, "ok": gerr < 2e-2})
    return out


def check_bf16_matmul():
    import numpy as np
    import jax.numpy as jnp

    rs = np.random.RandomState(2)
    a32 = rs.randn(512, 512).astype(np.float32)
    b32 = rs.randn(512, 512).astype(np.float32)
    prod = jnp.asarray(a32, jnp.bfloat16) @ jnp.asarray(b32, jnp.bfloat16)
    ref = np.asarray(a32 @ b32)
    # MXU accumulates in fp32: error comes from input rounding only —
    # relative to the row norms (~sqrt(512)*sigma), not the entries
    rel = float(np.max(np.abs(np.asarray(prod, np.float32) - ref))
                / np.abs(ref).max())
    return [{"check": "bf16_matmul", "max_rel_err": rel, "tol": 2e-2,
             "ok": rel < 2e-2}]


def main():
    import jax

    # the axon TPU plugin ignores the JAX_PLATFORMS env var; only the
    # config knob reliably forces CPU (same contract as bench.py children)
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    backend = jax.default_backend()
    interpret = backend != "tpu"
    checks = []
    for fn in (lambda: check_flash_attention(interpret), check_chunked_ce,
               check_bf16_matmul):
        try:
            checks.extend(fn())
        except Exception as e:  # a crash is a failed check, not a crash
            checks.append({"check": getattr(fn, "__name__", "lambda"),
                           "ok": False,
                           "error": f"{type(e).__name__}: {e}"})
    for c in checks:
        print(json.dumps(c))
    ok = all(c.get("ok") for c in checks)
    print(json.dumps({"numerics_ok": ok, "backend": backend,
                      "interpret": interpret, "n_checks": len(checks)}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
