"""Common functionals: linear/dropout/pad/embedding/interpolate/one_hot…
(reference: python/paddle/nn/functional/common.py, input.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.random import get_rng_key


def _unwrap(p):
    return p.value if hasattr(p, "value") else p


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with reference weight layout (in, out)
    (reference: operators/math/fc.cc; maps straight onto the MXU)."""
    weight, bias = _unwrap(weight), _unwrap(bias)
    from ...amp import cast_if_amp
    x, weight = cast_if_amp("linear", x, weight)
    out = jnp.matmul(x, weight)
    if bias is not None:
        out = out + bias.astype(out.dtype)
    return out


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        return x if mode == "upscale_in_train" else x * (1.0 - p)
    if p == 1.0:
        return jnp.zeros_like(x)
    key = get_rng_key()
    shape = list(x.shape)
    if axis is not None:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        shape = [s if i in axes else 1 for i, s in enumerate(shape)]
    keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
    if mode == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    return jnp.where(keep, x, 0.0).astype(x.dtype)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    a = ((1.0 - p) * (1.0 + p * alpha_p ** 2)) ** -0.5
    b = -a * alpha_p * p
    keep = jax.random.bernoulli(get_rng_key(), 1.0 - p, x.shape)
    return (a * jnp.where(keep, x, alpha_p) + b).astype(x.dtype)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Reference: operators/lookup_table_v2_op.*; on TPU a one-hot matmul or
    dynamic-gather — jnp.take lowers to an XLA gather."""
    weight = _unwrap(weight)
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return out


def one_hot(x, num_classes, name=None):
    return jax.nn.one_hot(x, num_classes)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    if isinstance(pad, (list, tuple)) and len(pad) == 2 * x.ndim:
        cfg = [(int(pad[2 * i]), int(pad[2 * i + 1])) for i in range(x.ndim)]
    else:
        # paddle semantics: pad applies to spatial dims (reversed last dims,
        # like torch) for NCHW-family formats
        pad = list(pad)
        n_spatial = len(pad) // 2
        cfg = [(0, 0)] * x.ndim
        channel_last = data_format[-1] == "C"
        spatial_axes = (list(range(1, 1 + n_spatial)) if channel_last
                        else list(range(x.ndim - n_spatial, x.ndim)))
        if not channel_last:
            # pad list is (last_dim_lo, last_dim_hi, second_last_lo, ...)? the
            # reference uses ascending spatial order [W, H, D]; map from the end.
            for i, ax in enumerate(reversed(spatial_axes)):
                cfg[ax] = (int(pad[2 * i]), int(pad[2 * i + 1]))
        else:
            for i, ax in enumerate(reversed(spatial_axes)):
                cfg[ax] = (int(pad[2 * i]), int(pad[2 * i + 1]))
    mode_map = {"constant": "constant", "reflect": "reflect",
                "replicate": "edge", "circular": "wrap"}
    if mode == "constant":
        return jnp.pad(x, cfg, mode="constant", constant_values=value)
    return jnp.pad(x, cfg, mode=mode_map[mode])


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.sqrt(jnp.sum(jnp.square(x1), axis=axis))
    n2 = jnp.sqrt(jnp.sum(jnp.square(x2), axis=axis))
    return dot / jnp.maximum(n1 * n2, eps)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1.0 - epsilon) * label + epsilon * prior_dist
    return (1.0 - epsilon) * label + epsilon / k


def bilinear(x1, x2, weight, bias=None, name=None):
    weight, bias = _unwrap(weight), _unwrap(bias)
    # weight: (out_features, in1, in2)
    out = jnp.einsum("bi,oij,bj->bo", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW", name=None):
    channel_last = data_format[-1] == "C"
    n_spatial = x.ndim - 2
    spatial_axes = (list(range(1, 1 + n_spatial)) if channel_last
                    else list(range(2, x.ndim)))
    in_sizes = [x.shape[a] for a in spatial_axes]
    if size is None:
        if scale_factor is None:
            raise ValueError("one of size/scale_factor required")
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
            else [scale_factor] * n_spatial
        size = [int(i * s) for i, s in zip(in_sizes, sf)]
    size = [int(s) for s in (size if isinstance(size, (list, tuple)) else [size] * n_spatial)]

    method = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
              "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
    if mode == "nearest":
        # index-map implementation (jax.image nearest differs from paddle rounding)
        out = x
        for ax, (i, o) in zip(spatial_axes, zip(in_sizes, size)):
            idx = jnp.floor(jnp.arange(o) * (i / o)).astype(jnp.int32)
            out = jnp.take(out, idx, axis=ax)
        return out
    new_shape = list(x.shape)
    for ax, o in zip(spatial_axes, size):
        new_shape[ax] = o
    if align_corners:
        # jax.image doesn't expose align_corners; emulate with explicit coords
        out = x
        for ax, (i, o) in zip(spatial_axes, zip(in_sizes, size)):
            if o == 1 or i == 1:
                coords = jnp.zeros(o)
            else:
                coords = jnp.arange(o) * ((i - 1) / (o - 1))
            lo = jnp.floor(coords).astype(jnp.int32)
            hi = jnp.clip(lo + 1, 0, i - 1)
            w = (coords - lo).astype(x.dtype)
            a = jnp.take(out, lo, axis=ax)
            b = jnp.take(out, hi, axis=ax)
            shape = [1] * x.ndim
            shape[ax] = o
            w = jnp.reshape(w, shape)
            out = a * (1 - w) + b * w
        return out
    return jax.image.resize(x, tuple(new_shape), method=method)


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference: operators/unfold_op.cc)."""
    from .conv import _tuplize
    k = _tuplize(kernel_sizes, 2)
    s = _tuplize(strides, 2)
    p = _tuplize(paddings, 2)
    d = _tuplize(dilations, 2)
    n, c, h, w = x.shape
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=k, window_strides=s,
        padding=[(p[0], p[0]), (p[1], p[1])], rhs_dilation=d,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # patches: (N, C*kh*kw, oh, ow) → (N, C*kh*kw, oh*ow)
    return jnp.reshape(patches, (n, patches.shape[1], -1))


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    from .conv import _tuplize
    k = _tuplize(kernel_sizes, 2)
    s = _tuplize(strides, 2)
    p = _tuplize(paddings, 2)
    d = _tuplize(dilations, 2)
    oh, ow = _tuplize(output_sizes, 2)
    n, ckk, l = x.shape
    c = ckk // (k[0] * k[1])
    # scatter-add each patch back (col2im)
    out_h_idx = np.arange(0, oh + 2 * p[0] - d[0] * (k[0] - 1), s[0])
    out_w_idx = np.arange(0, ow + 2 * p[1] - d[1] * (k[1] - 1), s[1])
    nh, nw = len(out_h_idx), len(out_w_idx)
    assert nh * nw == l, f"fold: {nh}x{nw} != {l}"
    cols = jnp.reshape(x, (n, c, k[0], k[1], nh, nw))
    out = jnp.zeros((n, c, oh + 2 * p[0], ow + 2 * p[1]), dtype=x.dtype)
    for i in range(k[0]):
        for j in range(k[1]):
            hi = out_h_idx + i * d[0]
            wi = out_w_idx + j * d[1]
            out = out.at[:, :, hi[:, None], wi[None, :]].add(cols[:, :, i, j])
    return out[:, :, p[0]:p[0] + oh, p[1]:p[1] + ow]


def class_center_sample(label, num_classes, num_samples, group=None):
    """PartialFC-style class-center sampling (reference
    operators/class_center_sample_op.cu; python/paddle/nn/functional/
    common.py class_center_sample): keep every positive class in the
    batch, fill the remaining of ``num_samples`` slots with random
    negative classes, and remap labels into the sampled index space.

    XLA-static formulation: rank all classes by (positive-first, random)
    and take the top ``num_samples`` via argsort — no dynamic shapes.
    Returns (remapped_label, sampled_class_index) with sampled shape
    (num_samples,). Labels whose class was not sampled (only possible
    when positives > num_samples) map to -1. Deterministic under
    paddle.seed via the framework RNG."""
    if num_samples > num_classes:
        raise ValueError(
            f"class_center_sample: num_samples ({num_samples}) must be "
            f"<= num_classes ({num_classes})")
    label = jnp.asarray(label).reshape(-1)
    # out-of-range labels would silently clamp under XLA scatter; check
    # when concrete (eager path — traced callers own their preconditions)
    if not isinstance(label, jax.core.Tracer):
        lab = np.asarray(label)
        if lab.size and (lab.min() < 0 or lab.max() >= num_classes):
            raise ValueError(
                f"class_center_sample: labels must be in [0, {num_classes})"
                f", got range [{lab.min()}, {lab.max()}]")
    present = jnp.zeros((num_classes,), bool).at[label].set(True)
    rand = jax.random.uniform(get_rng_key(), (num_classes,))
    # positives sort below every negative; negatives shuffle uniformly
    score = jnp.where(present, rand - 2.0, rand)
    sampled = jnp.argsort(score)[:num_samples].astype(label.dtype)
    inv = jnp.full((num_classes,), -1, label.dtype) \
        .at[sampled].set(jnp.arange(num_samples, dtype=label.dtype))
    return inv[label], sampled


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    lengths = jnp.asarray(lengths)
    if maxlen is None:
        maxlen = int(jnp.max(lengths))
    row = jnp.arange(maxlen)
    mask = row[None, :] < lengths[..., None]
    from ...framework import dtype as dtype_mod
    return mask.astype(dtype_mod.convert_dtype_to_jax(dtype))
