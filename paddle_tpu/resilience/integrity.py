"""Silent-corruption defense (ISSUE 9): fingerprints, deep verify,
replay, hang watchdog.

The resilience stack through PR 8 handles *loud* failures — NaN grads,
torn checkpoints, killed hosts. This module is the quiet-failure layer:

- **In-graph replica fingerprints** (:func:`fingerprint_array`): a
  bf16-safe chunked uint32 checksum computed INSIDE the jitted step on
  check steps. The engine compares fingerprints of data-replicated
  leaves across ranks with ``pmin``/``pmax`` (two scalar collectives
  per leaf, no host callback); a min/max mismatch means some replica's
  bytes differ — a flipped bit, a bad chip, a divergent update.
- **Majority-vote quarantine** (:func:`quarantine_outliers`): once the
  step flags divergence, host-side shard digests identify WHICH replica
  disagrees; each host attests the replicas it can address, the chains
  are allgathered over the elastic coordinator, and the majority
  fingerprint wins — the outlier's host is evicted (multi-host) or the
  state is rolled back (single-host). Without a coordinator the vote
  abstains from eviction unless the local view already proves a global
  majority.
- **Host content digests** (:func:`tree_digests`): per-array crc32
  recorded into MANIFEST.json at save time so
  ``CheckpointManager.verify(step, deep=True)`` can catch write-path
  rot that the file-level CRC cannot (that CRC hashes already-written
  bytes).
- **Deterministic replay** (:func:`replay_step`): re-execute step *s*
  from checkpoint *s−1* with the saved data cursor + RNG key and
  compare digests against the ones recorded at step *s* — run it twice
  and SDC (replays agree with each other, disagree with the record)
  separates from software nondeterminism (replays disagree).
- **Hang watchdog** (:class:`HangWatchdog`): a heartbeat-backed
  deadline around the staged step. A wedged collective can't be
  interrupted from a thread, but the watchdog CAN stop renewing the
  host's heartbeat — peers then reclassify it as lost through the
  existing staleness reaping and remesh around it — and optionally
  ``os._exit`` so the sim supervisor sees a distinct exit code.

Checksum design: values are bitcast to uint32 lanes (never summed in
float), multiplied by odd position-dependent weights and accumulated
with wrap-around uint32 addition. Wrap-add is associative and
commutative, so the result is bit-identical no matter how XLA
reorders the reduction — a hard requirement for cross-replica
comparison. The position weights make permutations detectable; the
dtype/length mix-in distinguishes same-bytes-different-shape leaves.
"""
from __future__ import annotations

import contextlib
import json
import os
import random as _pyrandom
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
from jax import lax

__all__ = [
    "FINGERPRINT_COLLECTIVES", "fingerprint_array", "fingerprint_tree",
    "count_fingerprint_collectives", "array_digest", "tree_digests",
    "compare_digests", "replica_coords", "vote_outliers",
    "quarantine_outliers", "inject_param_flip", "HangWatchdog",
    "hang_event", "simulate_hang", "replay_step",
]

# the only collective primitives the fingerprint check program emits —
# chaos_smoke asserts the NON-check program contains zero of these
# (walked recursively, so a pmin hidden inside a pjit still counts).
FINGERPRINT_COLLECTIVES = ("pmin", "pmax")

# odd 32-bit mixing constants (Knuth / xxhash primes)
_P1 = 2654435761
_P2 = 0x9E3779B9
_P3 = 0x85EBCA6B


# -- in-graph fingerprint ---------------------------------------------------

def _as_uint32(x):
    """Reinterpret any array's bytes as uint32 lanes (trace-safe).

    Sub-4-byte dtypes widen losslessly after a same-width bitcast;
    8-byte dtypes bitcast to a trailing lane pair. Never converts
    through float, so NaN payloads and signed zeros fingerprint too.
    """
    x = jnp.asarray(x)
    dt = x.dtype
    if dt == jnp.bool_:
        return x.astype(jnp.uint32)
    size = dt.itemsize
    if size == 4:
        return x if dt == jnp.uint32 else lax.bitcast_convert_type(x, jnp.uint32)
    if size == 2:
        u = x if dt == jnp.uint16 else lax.bitcast_convert_type(x, jnp.uint16)
        return u.astype(jnp.uint32)
    if size == 1:
        u = x if dt == jnp.uint8 else lax.bitcast_convert_type(x, jnp.uint8)
        return u.astype(jnp.uint32)
    # 8-byte dtypes: bitcast adds a trailing lane dim of size 2
    return lax.bitcast_convert_type(x, jnp.uint32)


def fingerprint_array(x, chunk: int = 1024) -> jnp.ndarray:
    """Deterministic uint32 checksum of an array's bytes (jit-safe).

    Chunked so XLA fuses it into one pass; position-weighted so
    permutations change the sum; closed under uint32 wrap-add so the
    value is reduction-order independent (bit-identical across
    replicas holding identical bytes, on any backend).
    """
    x = jnp.asarray(x)
    dt_mix = zlib.crc32(str(x.dtype).encode()) & 0xFFFFFFFF
    u = _as_uint32(x).reshape(-1)
    n = int(u.size)
    meta = jnp.uint32((n * _P2 + dt_mix) & 0xFFFFFFFF)
    if n == 0:
        return meta
    pad = (-n) % chunk
    if pad:
        u = jnp.concatenate([u, jnp.zeros((pad,), jnp.uint32)])
    m = u.reshape(-1, chunk)
    w = (jnp.arange(chunk, dtype=jnp.uint32) * jnp.uint32(_P1)
         + jnp.uint32(_P2)) | jnp.uint32(1)
    rows = jnp.sum(m * w[None, :], axis=1, dtype=jnp.uint32)
    rw = (jnp.arange(rows.size, dtype=jnp.uint32) * jnp.uint32(_P3)
          + jnp.uint32(_P2)) | jnp.uint32(1)
    return jnp.sum(rows * rw, dtype=jnp.uint32) + meta


def fingerprint_tree(tree, chunk: int = 1024) -> Dict[str, jnp.ndarray]:
    """Per-leaf fingerprints keyed by the leaf's keystr path."""
    flat, _ = jtu.tree_flatten_with_path(tree)
    return {jtu.keystr(path): fingerprint_array(v, chunk) for path, v in flat}


def count_fingerprint_collectives(closed) -> int:
    """How many FINGERPRINT pmin/pmax equations a (Closed)Jaxpr
    contains, walked recursively with the canonical analysis walker —
    the acceptance probe that the non-check program stays clean.
    Fingerprints are the only uint32 pmin/pmax users in the step (the
    int8/int4 exchange pmax-reduces FLOAT block scales), so the dtype
    disambiguates."""
    from ..analysis.walker import unwrap, walk
    jaxpr, _ = unwrap(closed)
    n = 0
    for site in walk(jaxpr):
        if site.eqn.primitive.name not in FINGERPRINT_COLLECTIVES:
            continue
        avals = [getattr(v, "aval", None) for v in site.eqn.outvars]
        if any(getattr(a, "dtype", None) == jnp.uint32 for a in avals):
            n += 1
    return n


# -- host-side content digests (deep checkpoint verify) ---------------------

def array_digest(x) -> str:
    """Content digest of one array: crc32 over dtype, shape and raw
    bytes — ``"crc32:<8 hex>:<nbytes>"``. Computed from the IN-MEMORY
    value, so a write path that rots bytes between device and disk is
    caught when the on-disk payload re-hashes differently."""
    a = np.asarray(jax.device_get(x))
    c = zlib.crc32(str(a.dtype).encode())
    c = zlib.crc32(repr(a.shape).encode(), c)
    c = zlib.crc32(np.ascontiguousarray(a).tobytes(), c)
    return "crc32:%08x:%d" % (c & 0xFFFFFFFF, a.nbytes)


def tree_digests(tree) -> Dict[str, str]:
    """Per-leaf :func:`array_digest`, keyed by keystr path. Leaves whose
    bytes this process cannot materialize (non-addressable multi-host
    shards) are skipped — each host attests what it holds."""
    out: Dict[str, str] = {}
    flat, _ = jtu.tree_flatten_with_path(tree)
    for path, v in flat:
        try:
            out[jtu.keystr(path)] = array_digest(v)
        except Exception:
            continue
    return out


def compare_digests(recorded: Dict[str, str],
                    actual: Dict[str, str]) -> List[str]:
    """Keys present in both maps whose digests differ, sorted."""
    return sorted(k for k in recorded
                  if k in actual and recorded[k] != actual[k])


# -- replica geometry / quarantine ------------------------------------------

def replica_coords(mesh, axes: Sequence[str]) -> Dict[Any, int]:
    """device -> linearized replica rank over the given mesh axes."""
    arr = np.asarray(mesh.devices)
    names = list(mesh.axis_names)
    idxs = [names.index(ax) for ax in axes if ax in names]
    out = {}
    for pos in np.ndindex(arr.shape):
        r = 0
        for i in idxs:
            r = r * arr.shape[i] + pos[i]
        out[arr[pos]] = int(r)
    return out


def _voting_leaves(trainer) -> List[str]:
    """Trainable params fully replicated over the check axes — the
    leaves whose per-replica bytes MUST agree, hence can vote."""
    axes = tuple(getattr(trainer, "integrity_axes", ()) or ())
    names = []
    for k, spec in trainer.param_specs.items():
        if not trainer.trainable.get(k, False):
            continue
        if any(_spec_mentions(spec, ax) for ax in axes):
            continue
        names.append(k)
    return names


def _spec_mentions(spec, axis: str) -> bool:
    return any(ax == axis or (isinstance(ax, tuple) and axis in ax)
               for ax in spec)


def vote_outliers(chains: Dict[int, int],
                  n_rep: int) -> Tuple[List[int], bool]:
    """Majority vote over *observed* per-replica digest chains.

    Returns ``(outliers, quorum)``: replicas whose chain differs from
    the largest agreeing group (ties break toward the group containing
    replica 0, the save-source replica), and whether that group is a
    provable majority of ALL ``n_rep`` replicas — not merely of the
    observed subset. Without quorum an eviction verdict would rest on a
    partial view (e.g. only the local host's shards) and must not be
    acted on."""
    votes: Dict[int, List[int]] = {}
    for r, c in chains.items():
        votes.setdefault(c, []).append(r)
    if len(votes) <= 1:
        return [], bool(votes) and 2 * len(chains) > n_rep
    majority = max(votes, key=lambda c: (len(votes[c]), 0 in votes[c]))
    outliers = sorted(r for c, rs in votes.items() if c != majority
                      for r in rs)
    return outliers, 2 * len(votes[majority]) > n_rep


def _local_digest_chains(trainer, rep_dev: Dict[int, Any]) -> Dict[int, int]:
    """crc32 chain per replica over the representative device's shard
    bytes, leaf order fixed by :func:`_voting_leaves`. Only replicas
    whose representative device is addressable from this process appear
    — each host attests exactly what it can observe (the representative
    choice is deterministic and identical on every host, so a replica is
    attested by precisely one process)."""
    crcs: Dict[int, int] = {}
    for name in _voting_leaves(trainer):
        v = trainer.state["params"][name]
        try:
            by_dev = {s.device: s for s in v.addressable_shards}
        except Exception:
            continue
        for r, d in rep_dev.items():
            s = by_dev.get(d)
            if s is None:
                continue
            a = np.ascontiguousarray(np.asarray(s.data))
            crcs[r] = zlib.crc32(a.tobytes(), crcs.get(r, 0))
    return crcs


def _gather_digest_chains(local: Dict[int, int], elastic) -> Dict[int, int]:
    """Merge every process's locally observed chains through the elastic
    coordinator's file-KV allgather (all hosts reach this point on the
    same check step — the divergence flag is itself a collective result,
    so the round is naturally synchronized). Returns the local view
    unchanged when no coordinator is reachable; the caller's quorum
    check then decides whether that partial view may evict anyone."""
    coord = getattr(elastic, "coordinator", None)
    if coord is None:
        return dict(local)
    hosts_fn = getattr(elastic, "_coord_hosts", None)
    if hosts_fn is None:
        mgr = getattr(elastic, "manager", None)
        hosts_fn = getattr(mgr, "hosts", None)
    if hosts_fn is None:
        return dict(local)
    try:
        gathered = coord.allgather(
            "integrity_digests",
            {str(r): int(c) for r, c in local.items()}, hosts_fn)
    except Exception:
        return dict(local)
    merged: Dict[int, int] = {}
    for h in sorted(gathered):
        for k, c in (gathered[h] or {}).items():
            merged.setdefault(int(k), int(c))
    merged.update(local)
    return merged


def quarantine_outliers(trainer, leaves: Optional[List[str]] = None,
                        elastic=None) -> Dict[str, Any]:
    """Identify which replica(s) diverged and decide the eviction.

    Digests every data-replicated trainable param per replica
    (host-side crc32 over one representative device's shard bytes),
    allgathers each host's locally observed chains through the elastic
    coordinator when one is available (``elastic`` being an
    ``ElasticRuntime``), and majority-votes: replicas whose digest chain
    differs from the majority are outliers. Ties break toward the group
    containing replica 0 (the save-source replica). Returns::

        {"outlier_replicas": [...], "outlier_hosts": [process ids],
         "quarantined": n, "action": "rollback"|"self_evict"|"peer_evict",
         "abstained": bool, "leaves": [...]}

    ``action`` is "rollback" single-process (the sim maps replicas to
    virtual hosts: rollback through the restore barrier replaces every
    replica's bytes from the last clean checkpoint, which is exactly
    the quarantine-and-recover semantics collapsed onto one host);
    multi-process, the outlier host self-evicts (raises HostLost in the
    runner) and the survivors remesh around it. When the digest exchange
    is unavailable and the agreeing group cannot be proven a majority of
    ALL replicas from this host's partial view, the vote ABSTAINS from
    eviction (``abstained=True``, action "rollback") — a partial view
    must never vote a host off the fleet, least of all this one.
    """
    from .. import telemetry
    axes = tuple(getattr(trainer, "integrity_axes", ()) or ())
    mesh = trainer.mesh
    n_rep = 1
    for ax in axes:
        n_rep *= int(mesh.shape.get(ax, 1))
    base = {"outlier_replicas": [], "outlier_hosts": [], "quarantined": 0,
            "action": "rollback", "abstained": False,
            "leaves": list(leaves or [])}
    if n_rep <= 1:
        return base
    coords = replica_coords(mesh, axes)
    rep_dev: Dict[int, Any] = {}
    for d, r in coords.items():
        rep_dev.setdefault(r, d)
    try:
        me, n_proc = jax.process_index(), jax.process_count()
    except Exception:
        me, n_proc = 0, 1
    chains = _local_digest_chains(trainer, rep_dev)
    if n_proc > 1:
        chains = _gather_digest_chains(chains, elastic)
    outliers, quorum = vote_outliers(chains, n_rep)
    if not outliers:
        return base
    outlier_hosts = sorted({rep_dev[r].process_index for r in outliers})
    action = "rollback"
    if n_proc > 1 and outlier_hosts:
        if not quorum:
            base["abstained"] = True
            return base
        action = "self_evict" if me in outlier_hosts else "peer_evict"
    if telemetry.enabled():
        telemetry.counter(
            "hosts_quarantined_total",
            "replicas/hosts evicted by majority-vote divergence quarantine",
        ).inc(len(outliers))
    return {"outlier_replicas": outliers, "outlier_hosts": outlier_hosts,
            "quarantined": len(outliers), "action": action,
            "abstained": False, "leaves": list(leaves or [])}


def inject_param_flip(trainer, seed: int = 0, step: Optional[int] = None,
                      leaf: Optional[str] = None,
                      replica: Optional[int] = None,
                      bit: Optional[int] = None) -> Dict[str, Any]:
    """Flip one low mantissa bit of one param element on ONE replica —
    the ``param_flip`` fault body (simulated SDC from a bad chip).

    Deterministic in (seed, step). Targets a non-zero replica by
    default so checkpoints saved from shard 0 between the flip and its
    detection stay clean and rollback genuinely recovers. The flipped
    bit is in the low mantissa (harmless magnitude) — the point is that
    the FINGERPRINT sees what the loss curve never would.
    """
    axes = tuple(getattr(trainer, "integrity_axes", ()) or ())
    mesh = trainer.mesh
    n_rep = 1
    for ax in axes:
        n_rep *= int(mesh.shape.get(ax, 1))
    rng = _pyrandom.Random((int(seed) * 1000003) ^ (0 if step is None
                                                    else int(step)))
    cands = [k for k in _voting_leaves(trainer)
             if jnp.issubdtype(trainer.state["params"][k].dtype,
                               jnp.floating)]
    if not cands:
        raise ValueError("no data-replicated floating param to flip")
    name = leaf if leaf is not None else cands[rng.randrange(len(cands))]
    v = trainer.state["params"][name]
    if replica is None:
        replica = rng.randrange(1, n_rep) if n_rep > 1 else 0
    if bit is None:
        bit = rng.randrange(0, 3)  # lowest mantissa bits
    elem = rng.randrange(max(1, int(np.prod(v.shape))))
    coords = replica_coords(mesh, axes)
    lane = {2: np.uint16, 4: np.uint32, 8: np.uint64}[v.dtype.itemsize]
    arrays = []
    for s in v.addressable_shards:
        a = np.array(s.data)
        if coords.get(s.device) == replica:
            a.reshape(-1).view(lane)[elem % max(1, a.size)] ^= lane(1) << bit
        arrays.append(jax.device_put(a, s.device))
    trainer.state["params"][name] = jax.make_array_from_single_device_arrays(
        v.shape, v.sharding, arrays)
    return {"leaf": name, "replica": int(replica), "element": int(elem),
            "bit": int(bit)}


# -- hang watchdog ----------------------------------------------------------

# Module-level latch: set when any watchdog fires. Heartbeat pumps (the
# watchdog's own, hostsim's _beat thread) consult it and STOP renewing
# the host's liveness file — which is the whole eviction mechanism: a
# hung host can't be interrupted, but its silence is what peers act on.
hang_event = threading.Event()


class HangWatchdog:
    """Deadline monitor around the staged step.

    While armed, a daemon thread pumps ``heartbeat_fn`` (the elastic
    membership heartbeat) every ``poll`` seconds; if ``timeout``
    elapses without a :meth:`disarm`, it fires ONCE per arm:
    counts ``hang_watchdog_fired_total``, sets :data:`hang_event`
    (stopping every heartbeat pump in the process so peers reclassify
    this host as lost), runs ``on_fire``, and — when ``exit_code`` is
    given (hostsim) — ``os._exit``\\ s so the supervisor can tell a
    hang from a crash. A fired watchdog cannot unwedge XLA; eviction +
    remesh by the survivors is the recovery, not interruption.
    """

    def __init__(self, timeout: float, heartbeat_fn: Optional[Callable] = None,
                 on_fire: Optional[Callable] = None,
                 exit_code: Optional[int] = None,
                 poll: Optional[float] = None):
        self.timeout = float(timeout)
        self.heartbeat_fn = heartbeat_fn
        self.on_fire = on_fire
        self.exit_code = exit_code
        self.poll = poll if poll is not None else max(
            0.02, min(0.25, self.timeout / 8.0))
        self.fired = 0
        self._deadline: Optional[float] = None
        self._step: Optional[int] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HangWatchdog":
        hang_event.clear()
        self._thread = threading.Thread(
            target=self._run, name="hang-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def arm(self, step: Optional[int] = None):
        with self._lock:
            self._deadline = time.monotonic() + self.timeout
            self._step = step

    def disarm(self):
        with self._lock:
            self._deadline = None

    @contextlib.contextmanager
    def guarding(self, step: Optional[int] = None):
        self.arm(step)
        try:
            yield self
        finally:
            self.disarm()

    def _run(self):
        while not self._stop.is_set():
            with self._lock:
                deadline, step = self._deadline, self._step
            if deadline is not None and time.monotonic() > deadline:
                with self._lock:
                    self._deadline = None  # one fire per arm
                self._fire(step)
            elif self.heartbeat_fn is not None and not hang_event.is_set():
                try:
                    self.heartbeat_fn()
                except Exception:
                    pass
            self._stop.wait(self.poll)

    def _fire(self, step):
        from .. import telemetry
        self.fired += 1
        if telemetry.enabled():
            telemetry.counter(
                "hang_watchdog_fired_total",
                "steps whose watchdog deadline expired (host presumed hung)",
            ).inc()
        hang_event.set()
        if self.on_fire is not None:
            try:
                self.on_fire(step)
            except Exception:
                pass
        if self.exit_code is not None:
            os._exit(self.exit_code)


def simulate_hang(max_seconds: float = 120.0):
    """The ``host_hang`` fault body: block like a wedged collective.

    Returns once a watchdog fires (:data:`hang_event`) or after
    ``max_seconds`` (test safety net). Under hostsim the armed watchdog
    carries an ``exit_code``, so the process dies inside this call —
    mid-"collective" — exactly like the real failure.
    """
    deadline = time.monotonic() + max_seconds
    while not hang_event.is_set() and time.monotonic() < deadline:
        time.sleep(0.02)


# -- deterministic step replay ----------------------------------------------

def _fast_forward(loader, batch: int):
    """Mirror runner._iter_from_cursor: skip `batch` items; a short
    epoch restarts the iterator (same semantics as the live run)."""
    it = iter(loader)
    for _ in range(batch):
        try:
            next(it)
        except StopIteration:
            return iter(loader)
    return it


def replay_step(ckpt_dir, step: int, trainer_factory: Callable,
                loader, repeats: int = 2, lr=None) -> Dict[str, Any]:
    """Re-execute global step ``step`` from checkpoint ``step - 1`` and
    compare the post-step state digests against the ones recorded in
    step ``step``'s MANIFEST.

    Each repeat builds a FRESH trainer (same mesh/config as the run,
    via ``trainer_factory``), restores step−1, restores the saved RNG
    key and data cursor, fetches the same batch with the runner's
    epoch-rollover semantics, and runs one train step. Verdicts:

    - ``"ok"``              — replays match each other AND the record.
    - ``"sdc"``             — replays agree with each other but differ
      from the record: the recorded state couldn't have come from this
      software on these inputs → hardware corruption at record time.
    - ``"nondeterminism"``  — replays disagree with each other: the
      step itself isn't reproducible; no SDC verdict is possible.
    - ``"no_reference"``    — step's manifest has no per-array digests.
    """
    from ..distributed.checkpoint import MANIFEST_NAME, CheckpointManager
    from .runner import _meta, _set_rng_key_data
    if hasattr(ckpt_dir, "restore"):
        mgr = ckpt_dir
    else:
        mgr = CheckpointManager(str(ckpt_dir), use_async=False)
    manifest = os.path.join(mgr._step_dir(step), MANIFEST_NAME)
    try:
        with open(manifest, "r", encoding="utf-8") as f:
            recorded = json.load(f).get("arrays") or {}
    except (OSError, ValueError):
        recorded = {}
    report: Dict[str, Any] = {"step": int(step), "repeats": int(repeats),
                              "restored_from": int(step) - 1}
    if not recorded:
        report.update(verdict="no_reference", mismatched_keys=[],
                      replay_mismatch_keys=[])
        return report
    runs: List[Dict[str, str]] = []
    for _ in range(max(1, int(repeats))):
        trainer = trainer_factory()
        template = {"trainer": trainer.state, "meta": _meta(0, 0, 0)}
        restored = mgr.restore(step=step - 1, template=template)
        if restored is None:
            report.update(verdict="no_reference", mismatched_keys=[],
                          replay_mismatch_keys=[],
                          error="checkpoint %d unrestorable" % (step - 1))
            return report
        trainer.state = restored["trainer"]
        meta = restored["meta"]
        _set_rng_key_data(meta["rng"])
        epoch, batch = int(meta["epoch"]), int(meta["batch"])
        it = _fast_forward(loader, batch)
        try:
            inputs, labels = next(it)
        except StopIteration:
            epoch, batch = epoch + 1, 0
            it = iter(loader)
            inputs, labels = next(it)
        trainer.train_step(inputs, labels, lr=lr)
        if hasattr(trainer, "consume_divergence"):
            trainer.consume_divergence()
        runs.append(tree_digests(
            {"trainer": trainer.state, "meta": _meta(step, epoch, batch + 1)}))
    replay_mismatch = (compare_digests(runs[0], runs[1])
                       if len(runs) > 1 else [])
    record_mismatch = compare_digests(recorded, runs[0])
    if replay_mismatch:
        verdict = "nondeterminism"
    elif record_mismatch:
        verdict = "sdc"
    else:
        verdict = "ok"
    report.update(verdict=verdict, mismatched_keys=record_mismatch,
                  replay_mismatch_keys=replay_mismatch)
    return report
