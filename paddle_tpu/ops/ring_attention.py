"""Ring attention — context/sequence parallelism over the "sep" mesh axis.

Capability class the reference lacks (SURVEY.md §5: no sequence/context
parallelism anywhere in that tree — long sequences were handled only by
recompute + TP + the O(S²) fused attention). This is the idiomatic TPU
version: shard the sequence over a mesh axis, keep Q local, rotate K/V
shards around the ring with lax.ppermute, and merge per-shard attention
partials with online-softmax statistics. Peak activation memory is O(S/n)
per device; the neighbor hops ride ICI and overlap with the previous
block's compute (XLA latency-hiding scheduler).

Math: each ring step j produces the *normalized* partial
  ô_j = softmax_j(QK_jᵀ) V_j      and      lse_j = log Σ_t exp(logit_t)
Merging two partials with weights exp(lse − logaddexp) is exact:
  out = Σ_j ô_j · exp(lse_j − LSE),   LSE = log Σ_j exp(lse_j).

Causal handling across shards: block (i=q_shard, j=kv_shard) is
  full attention if j < i; causal diagonal if j == i; skipped if j > i.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from .pallas.flash_attention import flash_attention, flash_supported

SEP_AXIS = "sep"
_NEG = -1e30


def _partial_attn(q, k, v, sm_scale, causal):
    """Normalized per-shard attention + logsumexp.

    q/k/v: (B, S, H, D). Returns (out (B,S,H,D) fp32, lse (B,H,Sq) fp32).
    """
    logits = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * sm_scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool))
        logits = jnp.where(mask, logits, _NEG)
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhst,bthd->bshd", p / jnp.maximum(l, 1e-30),
                     v.astype(jnp.float32))
    lse = (m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]  # (B,H,Sq)
    return out, lse


def _merge(o1, lse1, o2, lse2):
    lse_new = jnp.logaddexp(lse1, lse2)
    w1 = jnp.swapaxes(jnp.exp(lse1 - lse_new), 1, 2)[..., None]  # (B,S,H,1)
    w2 = jnp.swapaxes(jnp.exp(lse2 - lse_new), 1, 2)[..., None]
    return o1 * w1 + o2 * w2, lse_new


def ring_flash_attention(q, k, v, axis_name: str = SEP_AXIS, causal=False,
                         sm_scale=None):
    """q/k/v: (B, S_local, H, D) — local sequence shards inside shard_map
    over `axis_name`. Returns (B, S_local, H, D)."""
    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    try:
        n = lax.axis_size(axis_name)
    except Exception:
        n = 1
    if n == 1:
        if flash_supported(q, k):
            return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
        out, _ = _partial_attn(q, k, v, sm_scale, causal)
        return out.astype(q.dtype)

    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def block(j, k_j, v_j):
        """Attention of local q against the kv shard after j hops."""
        src = (my - j) % n  # owner shard of the kv currently held

        def do_full(_):
            return _partial_attn(q, k_j, v_j, sm_scale, False)

        def do_causal(_):
            return _partial_attn(q, k_j, v_j, sm_scale, True)

        def do_skip(_):
            return (jnp.zeros(q.shape, jnp.float32),
                    jnp.full((q.shape[0], q.shape[2], q.shape[1]), _NEG,
                             jnp.float32))

        if causal:
            branch = jnp.where(src == my, 1, jnp.where(src < my, 0, 2))
            return lax.switch(branch, [do_full, do_causal, do_skip], None)
        return do_full(None)

    def step(carry, j):
        o_acc, lse_acc, (k_j, v_j) = carry
        o_j, lse_j = block(j, k_j, v_j)
        o_acc, lse_acc = _merge(o_acc, lse_acc, o_j, lse_j)
        kv_next = jax.tree_util.tree_map(
            lambda x: lax.ppermute(x, axis_name, perm), (k_j, v_j))
        return (o_acc, lse_acc, kv_next), None

    o0 = jnp.zeros(q.shape, jnp.float32)
    lse0 = jnp.full((q.shape[0], q.shape[2], q.shape[1]), _NEG, jnp.float32)
    # n-1 compute+rotate steps in the scan; the final block is computed
    # outside so the ring sends exactly n-1 hops (no discarded last permute).
    (o, lse, (k_l, v_l)), _ = lax.scan(step, (o0, lse0, (k, v)),
                                       jnp.arange(n - 1))
    o_j, lse_j = block(jnp.asarray(n - 1, jnp.int32), k_l, v_l)
    o, _ = _merge(o, lse, o_j, lse_j)
    return o.astype(q.dtype)
