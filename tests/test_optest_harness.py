"""OpTest-style numeric-gradient harness (reference:
fluid/tests/unittests/op_test.py:270 OpTest — check_output vs reference impl,
check_grad vs finite differences :110,:1409)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp


def numeric_grad(fn, x, eps=1e-3):
    """Central finite differences of scalar fn at x."""
    x = np.asarray(x, dtype=np.float64)
    g = np.zeros_like(x)
    flat = x.reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        f1 = float(fn(jnp.asarray(x, dtype=jnp.float32)))
        flat[i] = orig - eps
        f0 = float(fn(jnp.asarray(x, dtype=jnp.float32)))
        flat[i] = orig
        gf[i] = (f1 - f0) / (2 * eps)
    return g


def check_grad(fn, x, rtol=5e-2, atol=5e-3):
    analytic = np.asarray(jax.grad(lambda v: fn(v).sum())(jnp.asarray(x)))
    numeric = numeric_grad(lambda v: fn(v).sum(), x)
    np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol)


class TestActivationGrads:
    @pytest.mark.parametrize("name", ["relu", "gelu", "sigmoid", "tanh",
                                      "softplus", "silu", "mish", "hardswish",
                                      "elu", "selu"])
    def test_grad_matches_numeric(self, name):
        from paddle_tpu.nn import functional as F
        fn = getattr(F, name)
        x = np.random.RandomState(0).randn(4, 5).astype(np.float32) + 0.3
        check_grad(fn, x)


class TestLossGrads:
    def test_cross_entropy_grad(self):
        from paddle_tpu.nn import functional as F
        rs = np.random.RandomState(1)
        logits = rs.randn(6, 4).astype(np.float32)
        label = rs.randint(0, 4, (6,))
        check_grad(lambda v: F.cross_entropy(v, jnp.asarray(label)), logits)

    def test_mse_matches_numpy(self):
        from paddle_tpu.nn import functional as F
        rs = np.random.RandomState(2)
        a, b = rs.randn(8, 3), rs.randn(8, 3)
        got = float(F.mse_loss(jnp.asarray(a, dtype=jnp.float32),
                               jnp.asarray(b, dtype=jnp.float32)))
        np.testing.assert_allclose(got, ((a - b) ** 2).mean(), rtol=1e-5)


class TestConvAgainstReference:
    def test_conv2d_matches_manual(self):
        """conv2d vs direct im2col computation."""
        from paddle_tpu.nn import functional as F
        rs = np.random.RandomState(3)
        x = rs.randn(2, 3, 8, 8).astype(np.float32)
        w = rs.randn(4, 3, 3, 3).astype(np.float32)
        out = np.asarray(F.conv2d(jnp.asarray(x), jnp.asarray(w), padding=1))
        # manual reference
        xp = np.pad(x, [(0, 0), (0, 0), (1, 1), (1, 1)])
        ref = np.zeros((2, 4, 8, 8), dtype=np.float32)
        for n in range(2):
            for o in range(4):
                for i in range(8):
                    for j in range(8):
                        ref[n, o, i, j] = np.sum(
                            xp[n, :, i:i + 3, j:j + 3] * w[o])
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_conv2d_grad(self):
        from paddle_tpu.nn import functional as F
        rs = np.random.RandomState(4)
        x = rs.randn(1, 2, 5, 5).astype(np.float32)
        w = jnp.asarray(rs.randn(3, 2, 3, 3).astype(np.float32))
        check_grad(lambda v: F.conv2d(v, w, padding=1), x)

    def test_conv2d_transpose_shape_inverts(self):
        from paddle_tpu.nn import functional as F
        x = jnp.ones((2, 4, 7, 7))
        w = jnp.ones((4, 5, 3, 3))  # (in, out, kh, kw)
        y = F.conv2d_transpose(x, w, stride=2, padding=1, output_padding=1)
        assert y.shape == (2, 5, 14, 14)


class TestNormOps:
    def test_layer_norm_stats(self):
        from paddle_tpu.nn import functional as F
        rs = np.random.RandomState(5)
        x = rs.randn(4, 16).astype(np.float32)
        y = np.asarray(F.layer_norm(jnp.asarray(x), 16))
        np.testing.assert_allclose(y.mean(-1), 0, atol=1e-5)
        np.testing.assert_allclose(y.std(-1), 1, atol=1e-2)

    def test_batch_norm_train_updates_stats(self):
        import paddle_tpu as paddle
        bn = paddle.nn.BatchNorm2D(3, momentum=0.5)
        x = jnp.asarray(np.random.RandomState(6).randn(4, 3, 5, 5),
                        dtype=jnp.float32)
        bn.train()
        _ = bn(x)
        assert not np.allclose(np.asarray(bn._mean), 0.0)

    def test_group_norm(self):
        from paddle_tpu.nn import functional as F
        x = jnp.asarray(np.random.RandomState(7).randn(2, 8, 4, 4),
                        dtype=jnp.float32)
        y = F.group_norm(x, num_groups=4)
        grouped = np.asarray(y).reshape(2, 4, 2, 4, 4)
        np.testing.assert_allclose(grouped.mean(axis=(2, 3, 4)), 0, atol=1e-4)


class TestPooling:
    def test_max_pool2d(self):
        from paddle_tpu.nn import functional as F
        x = jnp.arange(16.0).reshape(1, 1, 4, 4)
        y = F.max_pool2d(x, 2, 2)
        np.testing.assert_allclose(np.asarray(y)[0, 0],
                                   [[5, 7], [13, 15]])

    def test_adaptive_avg_pool(self):
        from paddle_tpu.nn import functional as F
        x = jnp.ones((2, 3, 8, 8))
        y = F.adaptive_avg_pool2d(x, 1)
        assert y.shape == (2, 3, 1, 1)
        np.testing.assert_allclose(np.asarray(y), 1.0)


class TestRNN:
    def test_lstm_forward_shapes(self):
        import paddle_tpu as paddle
        lstm = paddle.nn.LSTM(4, 8, num_layers=2)
        x = jnp.ones((3, 5, 4))
        out, (h, c) = lstm(x)
        assert out.shape == (3, 5, 8)
        assert h.shape == (2, 3, 8)
        assert c.shape == (2, 3, 8)

    def test_bidirectional_gru(self):
        import paddle_tpu as paddle
        gru = paddle.nn.GRU(4, 6, direction="bidirect")
        x = jnp.ones((2, 7, 4))
        out, h = gru(x)
        assert out.shape == (2, 7, 12)
        assert h.shape == (2, 2, 6)

    def test_lstm_grad_flows(self):
        import paddle_tpu as paddle
        from paddle_tpu.jit.functionalization import state_of, functional_call
        lstm = paddle.nn.LSTM(3, 4)
        params, buffers = state_of(lstm)
        x = jnp.ones((2, 5, 3))

        def loss(p):
            (out, _), _ = functional_call(lstm, p, buffers, x)
            return jnp.sum(out)

        g = jax.grad(loss)(params)
        assert all(np.isfinite(np.asarray(v)).all() for v in g.values())


class TestNewOpGrads:
    """Numeric-grad coverage for ops added in the parity sweeps (SURVEY §4:
    every op gets analytic-vs-finite-difference checking)."""

    def test_hsigmoid_loss_grad(self):
        from paddle_tpu.nn import functional as F
        rs = np.random.RandomState(0)
        w = rs.randn(5, 6).astype("float32")
        label = jnp.asarray([0, 3, 5])
        x0 = rs.randn(3, 6).astype("float32")
        check_grad(lambda x: F.hsigmoid_loss(x, label, 6, w, None), x0)

    def test_dice_loss_grad(self):
        from paddle_tpu.nn import functional as F
        rs = np.random.RandomState(1)
        probs = jax.nn.softmax(jnp.asarray(rs.randn(4, 3), jnp.float32))
        label = jnp.asarray(rs.randint(0, 3, (4, 1)))
        check_grad(lambda x: F.dice_loss(jax.nn.softmax(x), label),
                   rs.randn(4, 3).astype("float32"))

    def test_diag_embed_grad(self):
        from paddle_tpu.nn import functional as F
        rs = np.random.RandomState(2)
        check_grad(lambda x: F.diag_embed(x, offset=1),
                   rs.randn(2, 4).astype("float32"))

    def test_temporal_shift_grad(self):
        from paddle_tpu.nn import functional as F
        rs = np.random.RandomState(3)
        check_grad(lambda x: F.temporal_shift(x, seg_num=2, shift_ratio=0.25),
                   rs.randn(4, 4, 2, 2).astype("float32"))

    def test_cross_entropy_fast_path_grad(self):
        # the lse-gather hard-label fast path must match finite differences
        from paddle_tpu.nn import functional as F
        rs = np.random.RandomState(4)
        label = jnp.asarray(rs.randint(0, 7, (5,)))
        check_grad(lambda x: F.cross_entropy(x, label),
                   rs.randn(5, 7).astype("float32"))

    def test_cross_entropy_fast_path_matches_log_softmax(self):
        from paddle_tpu.nn import functional as F
        rs = np.random.RandomState(5)
        x = jnp.asarray(rs.randn(6, 9), jnp.float32)
        label = jnp.asarray(rs.randint(0, 9, (6,)))
        fast = F.cross_entropy(x, label)
        ref = -jnp.take_along_axis(jax.nn.log_softmax(x, -1),
                                   label[:, None], axis=-1).mean()
        np.testing.assert_allclose(float(fast), float(ref), rtol=1e-5)

    def test_cross_entropy_ignore_index_fast_path(self):
        from paddle_tpu.nn import functional as F
        rs = np.random.RandomState(6)
        x = jnp.asarray(rs.randn(4, 5), jnp.float32)
        label = jnp.asarray([1, -100, 3, -100])
        out = F.cross_entropy(x, label)
        ref = -jnp.take_along_axis(jax.nn.log_softmax(x, -1),
                                   jnp.asarray([[1], [0], [3], [0]]),
                                   axis=-1)[:, 0]
        expect = (ref[0] + ref[2]) / 2
        np.testing.assert_allclose(float(out), float(expect), rtol=1e-5)

    def test_sequence_conv_grad(self):
        from paddle_tpu import static
        rs = np.random.RandomState(7)
        from paddle_tpu.static import Scope, scope_guard
        with scope_guard(Scope()):
            # create deterministic params once
            x0 = rs.randn(2, 5, 3).astype("float32")
            static.nn.sequence_conv(jnp.asarray(x0), 4, filter_size=3,
                                    name="sconv_g")
            check_grad(lambda x: static.nn.sequence_conv(
                x, 4, filter_size=3, name="sconv_g"), x0)

    def test_row_conv_grad(self):
        from paddle_tpu import static
        from paddle_tpu.static import Scope, scope_guard
        rs = np.random.RandomState(8)
        with scope_guard(Scope()):
            x0 = rs.randn(2, 4, 3).astype("float32")
            static.nn.row_conv(jnp.asarray(x0), 2, name="rc_g")
            check_grad(lambda x: static.nn.row_conv(x, 2, name="rc_g"), x0)

    def test_adadelta_matches_reference_formula(self):
        import paddle_tpu as paddle
        opt = paddle.optimizer.Adadelta(learning_rate=1.0, rho=0.9,
                                        epsilon=1e-6)
        p = jnp.asarray([1.0, 2.0])
        g = jnp.asarray([0.5, -0.5])
        slots = opt.init_slots(p)
        new_p, new_slots = opt.update(p, g, slots, 1.0, jnp.asarray(1))
        asg = 0.1 * 0.25
        upd = 0.5 * np.sqrt(1e-6) / np.sqrt(asg + 1e-6)
        np.testing.assert_allclose(np.asarray(new_p)[0], 1.0 - upd, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(new_slots["avg_squared_grad"]),
                                   [asg, asg], rtol=1e-5)
