"""Profiler (C5) + monitor (C6) tests — host event recording, summary,
chrome-trace export, gauges. (reference test analogues:
fluid/tests/unittests/test_profiler.py, test_monitor.py)."""
import json
import threading

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import monitor, profiler


def test_record_event_and_summary(tmp_path, capsys):
    profiler.start_profiler("CPU")
    with profiler.RecordEvent("step"):
        with profiler.RecordEvent("forward"):
            jnp.ones((8, 8)) @ jnp.ones((8, 8))
        with profiler.RecordEvent("backward"):
            pass
    events = profiler.get_events()
    names = {e["name"] for e in events}
    assert {"step", "forward", "backward"} <= names
    fwd = next(e for e in events if e["name"] == "forward")
    assert fwd["parent"] == "step"
    out = tmp_path / "trace.json"
    profiler.stop_profiler(sorted_key="total", profile_path=str(out))
    captured = capsys.readouterr().out
    assert "forward" in captured and "Calls" in captured
    trace = json.loads(out.read_text())
    assert any(ev["name"] == "step" for ev in trace["traceEvents"])


def test_profiler_context_and_disabled():
    # outside profiling, RecordEvent is a no-op
    profiler.reset_profiler()
    with profiler.RecordEvent("ignored"):
        pass
    assert profiler.get_events() == []
    with profiler.profiler(state="CPU", profile_path=""):
        with profiler.record_event("inner"):
            pass
        assert profiler.is_profiler_enabled()
    assert not profiler.is_profiler_enabled()


def test_record_event_begun_before_start_not_recorded():
    """Pair-safety: a begin() while the profiler is off is inert — an end()
    after start_profiler must not write a garbage range into the new
    session (ISSUE 3 satellite)."""
    profiler.reset_profiler()
    ev = profiler.RecordEvent("orphan")
    ev.begin()                       # disabled: no-op
    profiler.start_profiler("CPU")
    ev.end()                         # must not record
    try:
        assert all(e["name"] != "orphan" for e in profiler.get_events())
    finally:
        profiler.stop_profiler(profile_path="", verbose=False)


def test_record_event_spanning_stop_start_not_recorded():
    """A range begun in session A whose end() arrives in session B is
    dropped (previously its stale timestamps landed in B's event list)."""
    profiler.start_profiler("CPU")
    ev = profiler.RecordEvent("spanning").begin()
    profiler.stop_profiler(profile_path="", verbose=False)
    profiler.start_profiler("CPU")
    ev.end()                         # session changed under it: dropped
    inner = profiler.RecordEvent("inner")
    with inner:
        pass
    events = profiler.get_events()
    profiler.stop_profiler(profile_path="", verbose=False)
    names = [e["name"] for e in events]
    assert "spanning" not in names
    assert "inner" in names
    # the dead event also must not linger on the nesting stack as a parent
    assert next(e for e in events if e["name"] == "inner")["parent"] == ""


def test_record_event_non_lifo_end_order():
    """Identity-based stack removal: ending the OUTER event first must not
    pop the inner one's entry (the old index-pop recorded wrong parents)."""
    profiler.start_profiler("CPU")
    outer = profiler.RecordEvent("outer").begin()
    inner = profiler.RecordEvent("inner").begin()
    outer.end()
    inner.end()
    events = {e["name"]: e for e in profiler.get_events()}
    profiler.stop_profiler(profile_path="", verbose=False)
    assert set(events) == {"outer", "inner"}
    assert events["outer"]["parent"] == "inner"   # still nested at its end
    assert events["inner"]["parent"] == ""


def test_export_chrome_tracing_clamps_negative_ts(tmp_path):
    """An event whose begin predates _start_wall_ns (stale session data)
    must not export a negative ts — chrome silently drops those."""
    profiler.start_profiler("CPU")
    with profiler.RecordEvent("ok"):
        pass
    with profiler._lock:
        early = profiler._start_wall_ns - 5_000_000   # 5ms before start
        profiler._events.append(("early", "", early, early + 1_000_000, 0))
    out = tmp_path / "trace.json"
    profiler.stop_profiler(profile_path=str(out), verbose=False)
    trace = json.loads(out.read_text())
    assert {e["name"] for e in trace["traceEvents"]} == {"ok", "early"}
    assert all(e["ts"] >= 0 for e in trace["traceEvents"])


def test_monitor_gauges():
    g = monitor.stat("STAT_test_mem")
    g.reset()
    g.increase(10)
    g.decrease(3)
    assert g.get() == 7
    assert monitor.stat("STAT_test_mem") is g   # registry returns same gauge
    assert monitor.get_all_stats()["STAT_test_mem"] == 7

    # thread safety smoke
    def bump():
        for _ in range(1000):
            g.increase()

    ts = [threading.Thread(target=bump) for _ in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert g.get() == 7 + 4000
    g.reset()
    assert g.get() == 0


def test_monitor_stat_exports_via_telemetry():
    """monitor.StatValue is a bridge onto the telemetry registry: its value
    shows up in the Prometheus text of the current default registry."""
    from paddle_tpu import telemetry
    g = monitor.stat("STAT_prom_bridge")
    g.reset()
    g.set(42)
    assert telemetry.get_registry().get("STAT_prom_bridge").value() == 42.0
    assert "STAT_prom_bridge 42" in telemetry.prometheus_text()
    g.reset()
