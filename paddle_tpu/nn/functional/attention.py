"""Attention functional.

Replaces the reference's fused attention CUDA kernels
(operators/fused/multihead_matmul_op.cu, math/bert_encoder_functor.cu) which
materialize the O(S²) score matrix. Default path here is the Pallas flash
attention kernel (paddle_tpu/ops/pallas/flash_attention.py) — blockwise,
O(S) memory; falls back to a pure-XLA implementation off-TPU or for tiny
shapes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _xla_attention(q, k, v, mask=None, scale=None, causal=False, dropout_p=0.0,
                   training=True):
    # q,k,v: (B, S, H, D)
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    logits = jnp.einsum("bshd,bthd->bhst", q, k) * scale
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((s, t), dtype=bool))
        logits = jnp.where(cm, logits, jnp.finfo(logits.dtype).min)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and training:
        from .common import dropout as _dropout
        probs = _dropout(probs, p=dropout_p, training=True)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 kv_lens=None, name=None):
    """query/key/value: (batch, seq, num_heads, head_dim).

    kv_lens: optional (batch,) valid key/value counts — the O(B) form of a
    trailing-padding key mask; keeps padded batches on the flash kernel
    (a dense (B,1,1,T) ``attn_mask`` falls back to XLA, since streaming an
    O(S²) mask forfeits flash's memory advantage anyway).
    """
    from ...ops.pallas.flash_attention import flash_attention, flash_supported
    # Round-3 re-sweep on a real v5e (fwd+bwd, b4 h12 d64, causal,
    # in-kernel dropout): flash+dropout 6.84/6.99/9.19 ms at s=512/1024/
    # 2048 vs XLA *without* dropout 7.12/6.85/10.64 — flash matches XLA's
    # undropped cost from s=512, and XLA-with-dropout pays an extra
    # (B,H,S,S) mask on top. Dropout and kv_lens padding masks run inside
    # the kernel; only dense attn_mask tensors force the XLA path.
    use_flash = (attn_mask is None and
                 flash_supported(query, key, min_seq=512))
    if not use_flash:
        from ...ops.pallas.tuner import record_fallback
        record_fallback("flash_attention")
    if use_flash:
        try:
            rate, seed = 0.0, None
            if dropout_p > 0.0 and training:
                from ...framework.random import get_rng_key
                rate = float(dropout_p)
                seed = jax.random.randint(get_rng_key(), (), 0,
                                          jnp.iinfo(jnp.int32).max,
                                          dtype=jnp.int32)
            return flash_attention(query, key, value, causal=is_causal,
                                   kv_lens=kv_lens, dropout_rate=rate,
                                   dropout_seed=seed)
        except Exception:
            from ...ops.pallas.tuner import record_fallback
            record_fallback("flash_attention")
    if kv_lens is not None:
        t = key.shape[1]
        lens_mask = (jnp.arange(t)[None, None, None, :] <
                     jnp.asarray(kv_lens).reshape(-1, 1, 1, 1))
        if attn_mask is None:
            attn_mask = lens_mask
        elif attn_mask.dtype == jnp.bool_:
            attn_mask = attn_mask & lens_mask
        else:  # additive bias: padding keys get -inf-like logits
            attn_mask = attn_mask + jnp.where(
                lens_mask, 0.0, jnp.finfo(jnp.float32).min)
    return _xla_attention(query, key, value, mask=attn_mask, causal=is_causal,
                          dropout_p=dropout_p, training=training)
