"""OpTest SWEEP (reference fluid/tests/unittests/op_test.py:270 + its
white_list exemptions): EVERY public callable in paddle_tpu.tensor and
paddle_tpu.nn.functional must be classified — differentiable ops get an
analytic-vs-finite-difference gradient check; non-differentiable /
utility / stochastic ops are listed explicitly; anything unclassified
FAILS the coverage test. Exemptions (ops we cannot grad-check) are capped
at <10 and carry reasons, like the reference's per-op white list.

Run with -s to print the coverage report.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.tensor as T
from paddle_tpu.nn import functional as F

def _x(shape=(2, 3), lo=0.35, hi=0.95):
    # DETERMINISTIC in (shape, lo, hi): config lambdas rebuild their
    # constants on every call, so _x must be a pure function or the
    # numeric diff compares different functions. Default domain avoids
    # poles/branch cuts of log/asin/atanh/erfinv and integer kinks of
    # floor/round; values distinct to dodge max/sort ties.
    n = int(np.prod(shape))
    vals = np.linspace(lo, hi, n)
    seed = (len(shape) * 1000003 + n * 7919 + int(lo * 100) * 31 +
            int(hi * 100))
    return np.random.RandomState(seed).permutation(vals) \
        .reshape(shape).astype("f4")


def _spd(n=3):
    a = np.random.RandomState(n).randn(n, n).astype("f4")
    return a @ a.T + n * np.eye(n, dtype="f4")


def scalarize(out):
    leaves = [l for l in jax.tree_util.tree_leaves(out)
              if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.floating)]
    if not leaves:
        return None
    return sum(jnp.sum(l) for l in leaves)


def numeric_grad(fn, x, eps=1e-3):
    x = np.asarray(x, dtype=np.float64)
    g = np.zeros_like(x)
    flat, gf = x.reshape(-1), g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        f1 = float(fn(jnp.asarray(x, jnp.float32)))
        flat[i] = orig - eps
        f0 = float(fn(jnp.asarray(x, jnp.float32)))
        flat[i] = orig
        gf[i] = (f1 - f0) / (2 * eps)
    return g


def check_grad(f, x, rtol=6e-2, atol=6e-3):
    lossf = lambda v: scalarize(f(v))  # noqa: E731
    analytic = np.asarray(jax.grad(lossf)(jnp.asarray(x, jnp.float32)),
                          dtype=np.float64)
    numeric = numeric_grad(lossf, x)
    np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol)


# ---------------------------------------------------------------------------
# classification tables
# ---------------------------------------------------------------------------
# ops whose output carries no useful gradient: integer/bool/index/shape/
# comparison/logical/creation/copy/query ops (reference OpTest skips these
# the same way — no grad kernel)
TENSOR_NONDIFF = {
    "all", "allclose", "any", "arange", "argmax", "argmin", "argsort",
    "bincount", "bitwise_and", "bitwise_not", "bitwise_or", "bitwise_xor",
    "broadcast_shape", "bucketize", "cast", "count_nonzero", "empty",
    "empty_like", "equal", "equal_all", "eye", "floor_divide", "full",
    "full_like", "gcd", "greater_equal", "greater_than", "histogram",
    "is_empty", "is_tensor", "isclose", "isfinite", "isinf", "isnan",
    "lcm", "less_equal", "less_than", "linspace", "logical_and",
    "logical_not", "logical_or", "logical_xor", "matrix_rank", "nonzero",
    "not_equal", "numel", "ones", "ones_like", "randint", "randint_like",
    "randperm", "rank", "searchsorted", "shape", "shard_index", "sign",
    "unique", "unique_consecutive", "zeros", "zeros_like",
    # zero-gradient-a.e. step functions (numeric grad is 0 off the kinks,
    # analytic grad is defined as 0 — checking 0==0 adds nothing)
    "ceil", "ceil_", "floor", "floor_", "round", "round_", "trunc",
}
# stochastic samplers: output depends on the global PRNG per call, so
# finite differences are meaningless (reference white-lists these too)
TENSOR_STOCHASTIC = {"bernoulli", "exponential_", "multinomial", "normal",
                     "poisson", "rand", "randn", "standard_normal",
                     "uniform", "get_rng_key"}
# host/utility surface, not array->array math
TENSOR_UTILITY = {"Tensor", "to_tensor", "tolist", "set_printoptions",
                  "assign", "clone", "check_shape", "create_array",
                  "array_read", "array_write", "array_length", "increment",
                  "fill_", "zero_", "view"}
# complex-valued domain (holomorphic grads are out of the f32 sweep's scope)
TENSOR_COMPLEX = {"angle", "as_complex", "as_real", "complex", "conj",
                  "eig", "eigvals", "imag", "real"}

# hand-written input builders: name -> (f, x) with f differentiable in x
TENSOR_CONFIGS = {
    "add": lambda: (lambda x: T.add(x, jnp.ones_like(x) * 0.3), _x()),
    "add_": lambda: (lambda x: T.add_(x, jnp.ones_like(x) * 0.3), _x()),
    "add_n": lambda: (lambda x: T.add_n([x, x * 2.0]), _x()),
    "addmm": lambda: (lambda x: T.addmm(
        jnp.ones((2, 2)), x, jnp.asarray(_x((3, 2)))), _x((2, 3))),
    "atan2": lambda: (lambda x: T.atan2(x, jnp.ones_like(x)), _x()),
    "bmm": lambda: (lambda x: T.bmm(x, jnp.asarray(_x((2, 3, 2)))),
                    _x((2, 2, 3))),
    "broadcast_tensors": lambda: (
        lambda x: T.broadcast_tensors([x, jnp.ones((2, 1))])[0], _x((1, 3))),
    "broadcast_to": lambda: (lambda x: T.broadcast_to(x, [2, 2, 3]), _x()),
    "cholesky": lambda: (lambda x: T.cholesky(
        x @ x.T + 3 * jnp.eye(3)), _x((3, 3))),
    "cholesky_solve": lambda: (lambda x: T.cholesky_solve(
        x, jnp.linalg.cholesky(jnp.asarray(_spd()))), _x((3, 2))),
    "chunk": lambda: (lambda x: T.chunk(x, 2, axis=1)[0], _x((2, 4))),
    "clip": lambda: (lambda x: T.clip(x, 0.4, 0.9), _x()),
    "clip_": lambda: (lambda x: T.clip_(x, 0.4, 0.9), _x()),
    "concat": lambda: (lambda x: T.concat([x, x * 2.0], axis=0), _x()),
    "crop": lambda: (lambda x: T.crop(x, shape=[1, 2], offsets=[0, 1]),
                     _x((2, 3))),
    "crop_tensor": lambda: (lambda x: T.crop_tensor(
        x, shape=[1, 2], offsets=[0, 1]), _x((2, 3))),
    "cross": lambda: (lambda x: T.cross(x, jnp.asarray(_x((2, 3)))), _x()),
    "diag": lambda: (lambda x: T.diag(x), _x((3,))),
    "diagflat": lambda: (lambda x: T.diagflat(x), _x((3,))),
    "dist": lambda: (lambda x: T.dist(x, jnp.zeros_like(x), p=2), _x()),
    "divide": lambda: (lambda x: T.divide(x, jnp.ones_like(x) * 1.3), _x()),
    "dot": lambda: (lambda x: T.dot(x, jnp.asarray(_x((4,)))), _x((4,))),
    "einsum": lambda: (lambda x: T.einsum("ij->i", x), _x()),
    "expand": lambda: (lambda x: T.expand(x, [2, 2, 3]), _x()),
    "expand_as": lambda: (lambda x: T.expand_as(x, jnp.ones((2, 2, 3))),
                          _x()),
    "fmax": lambda: (lambda x: T.fmax(x, jnp.full_like(x, 0.6)), _x()),
    "fmin": lambda: (lambda x: T.fmin(x, jnp.full_like(x, 0.6)), _x()),
    "gather": lambda: (lambda x: T.gather(x, jnp.asarray([0, 1, 0])), _x()),
    "gather_nd": lambda: (lambda x: T.gather_nd(
        x, jnp.asarray([[0, 1], [1, 2]])), _x()),
    "index_sample": lambda: (lambda x: T.index_sample(
        x, jnp.asarray([[0, 1], [2, 0]])), _x()),
    "index_select": lambda: (lambda x: T.index_select(
        x, jnp.asarray([0, 1]), axis=1), _x()),
    "inner": lambda: (lambda x: T.inner(x, jnp.asarray(_x((2, 3)))), _x()),
    "inverse": lambda: (lambda x: T.inverse(x @ x.T + 3 * jnp.eye(3)),
                        _x((3, 3))),
    "inv": lambda: (lambda x: T.inv(x @ x.T + 3 * jnp.eye(3)),
                    _x((3, 3))),
    "kron": lambda: (lambda x: T.kron(x, jnp.ones((2, 2))), _x()),
    "lerp": lambda: (lambda x: T.lerp(x, jnp.ones_like(x), 0.3), _x()),
    "logaddexp": lambda: (lambda x: T.logaddexp(x, jnp.zeros_like(x)),
                          _x()),
    "lstsq": lambda: (lambda x: T.lstsq(
        jnp.asarray(_spd()), x)[0], _x((3, 2))),
    "matmul": lambda: (lambda x: T.matmul(x, jnp.asarray(_x((3, 2)))),
                       _x((2, 3))),
    "matrix_power": lambda: (lambda x: T.matrix_power(x, 2), _x((3, 3))),
    "maximum": lambda: (lambda x: T.maximum(x, jnp.full_like(x, 0.6)),
                        _x()),
    "minimum": lambda: (lambda x: T.minimum(x, jnp.full_like(x, 0.6)),
                        _x()),
    "meshgrid": lambda: (lambda x: T.meshgrid(x, jnp.ones((2,)))[0],
                         _x((3,))),
    "mm": lambda: (lambda x: T.mm(x, jnp.asarray(_x((3, 2)))), _x((2, 3))),
    "mod": lambda: (lambda x: T.mod(x, jnp.full_like(x, 0.4)), _x()),
    "floor_mod": lambda: (lambda x: T.floor_mod(
        x, jnp.full_like(x, 0.4)), _x()),
    "remainder": lambda: (lambda x: T.remainder(
        x, jnp.full_like(x, 0.4)), _x()),
    "multi_dot": lambda: (lambda x: T.multi_dot(
        [x, jnp.asarray(_x((3, 2)))]), _x((2, 3))),
    "multiplex": lambda: (lambda x: T.multiplex(
        [x, x * 2.0], jnp.asarray([[0], [1]])), _x()),
    "multiply": lambda: (lambda x: T.multiply(x, jnp.full_like(x, 1.7)),
                         _x()),
    "mv": lambda: (lambda x: T.mv(x, jnp.asarray(_x((3,)))), _x((2, 3))),
    "outer": lambda: (lambda x: T.outer(x, jnp.asarray(_x((2,)))), _x((3,))),
    "pad": lambda: (lambda x: T.pad(x, [1, 1, 0, 0]), _x()),
    "pow": lambda: (lambda x: T.pow(x, 2.0), _x()),
    "put_along_axis": lambda: (lambda x: T.put_along_axis(
        x, jnp.asarray([[0, 0, 1]]), 0.5, axis=0), _x()),
    "qr": lambda: (lambda x: T.qr(x)[1], _x((3, 3))),
    "scale": lambda: (lambda x: T.scale(x, 2.0, bias=0.1), _x()),
    "scale_": lambda: (lambda x: T.scale_(x, 2.0, bias=0.1), _x()),
    "scatter": lambda: (lambda x: T.scatter(
        x, jnp.asarray([0, 1]), jnp.asarray(_x((2, 3)))), _x()),
    "scatter_": lambda: (lambda x: T.scatter_(
        x, jnp.asarray([0, 1]), jnp.asarray(_x((2, 3)))), _x()),
    "scatter_nd": lambda: (lambda x: T.scatter_nd(
        jnp.asarray([[1], [0]]), x, [3, 3]), _x()),
    "scatter_nd_add": lambda: (lambda x: T.scatter_nd_add(
        x, jnp.asarray([[0], [1]]), jnp.asarray(_x((2, 3)))), _x()),
    "slice": lambda: (lambda x: T.slice(x, [0, 1], [0, 1], [2, 3]), _x()),
    "solve": lambda: (lambda x: T.solve(jnp.asarray(_spd()), x), _x((3, 2))),
    "split": lambda: (lambda x: T.split(x, 3, axis=1)[1], _x()),
    "stack": lambda: (lambda x: T.stack([x, x * 2.0]), _x()),
    "strided_slice": lambda: (lambda x: T.strided_slice(
        x, [1], [0], [3], [2]), _x((2, 4))),
    "subtract": lambda: (lambda x: T.subtract(x, jnp.full_like(x, 0.2)),
                         _x()),
    "subtract_": lambda: (lambda x: T.subtract_(x, jnp.full_like(x, 0.2)),
                          _x()),
    "take_along_axis": lambda: (lambda x: T.take_along_axis(
        x, jnp.asarray([[0, 0, 1]]), axis=0), _x()),
    "tensordot": lambda: (lambda x: T.tensordot(
        x, jnp.asarray(_x((3, 2))), axes=1), _x((2, 3))),
    "tile": lambda: (lambda x: T.tile(x, [2, 1]), _x()),
    "triangular_solve": lambda: (lambda x: T.triangular_solve(
        jnp.tril(jnp.asarray(_spd())), x), _x((3, 2))),
    "where": lambda: (lambda x: T.where(
        jnp.asarray([[True, False, True], [False, True, False]]),
        x, x * 2.0), _x()),
    "topk": lambda: (lambda x: T.topk(x, 2)[0], _x()),
    "norm": lambda: (lambda x: T.norm(x, p=2), _x()),
    "acosh": lambda: (T.acosh, _x(lo=1.2, hi=2.2)),
    "cumprod": lambda: (lambda x: T.cumprod(x, dim=0), _x()),
    "nanquantile": lambda: (lambda x: T.nanquantile(x, 0.5), _x()),
    "quantile": lambda: (lambda x: T.quantile(x, 0.37), _x()),
    "repeat_interleave": lambda: (lambda x: T.repeat_interleave(x, 2),
                                  _x()),
    "roll": lambda: (lambda x: T.roll(x, 1), _x()),
    "unbind": lambda: (lambda x: T.unbind(x)[0], _x()),
    "flip": lambda: (lambda x: T.flip(x, axis=0), _x()),
    "reverse": lambda: (lambda x: T.reverse(x, axis=0), _x()),
    "moveaxis": lambda: (lambda x: T.moveaxis(x, 0, 1), _x()),
    "transpose": lambda: (lambda x: T.transpose(x, [1, 0]), _x()),
    "reshape": lambda: (lambda x: T.reshape(x, [3, 2]), _x()),
    "reshape_": lambda: (lambda x: T.reshape_(x, [3, 2]), _x()),
    "unsqueeze": lambda: (lambda x: T.unsqueeze(x, 1), _x()),
    "unsqueeze_": lambda: (lambda x: T.unsqueeze_(x, 1), _x()),
    "det": lambda: (lambda x: T.det(x @ x.T + 3 * jnp.eye(3)), _x((3, 3))),
    "slogdet": lambda: (lambda x: T.slogdet(
        x @ x.T + 3 * jnp.eye(3))[1], _x((3, 3))),
    "eigh": lambda: (lambda x: T.eigh(
        x @ x.T + 3 * jnp.eye(3))[0], _x((3, 3))),
    "eigvalsh": lambda: (lambda x: T.eigvalsh(
        x @ x.T + 3 * jnp.eye(3)), _x((3, 3))),
    "unstack": lambda: (lambda x: T.unstack(x)[0], _x()),
}

TENSOR_EXEMPT = {
    "svd": "f32 SVD grad needs distinct singular values; jax's VJP is "
           "numerically unstable at this tolerance",
    "pinv": "same SVD-derivative conditioning issue",
    "lgamma": "jax lgamma VJP uses digamma whose f32 polynomial differs "
              "from the fd estimate beyond sweep tolerance near 0.35",
    "masked_select": "host-side eager-only impl (data-dependent output "
                     "shape, like the reference's LoD output): jax.grad "
                     "cannot trace it",
}


F_NONDIFF = {"one_hot", "sequence_mask", "gather_tree",
             "class_center_sample"}  # integer sampling (tested in
                                     # test_nn_extras.py)
F_STOCHASTIC = {"dropout", "dropout2d", "dropout3d", "alpha_dropout",
                "rrelu", "gumbel_softmax"}
F_UTILITY = set()

F_CONFIGS = {
    "adaptive_avg_pool1d": lambda: (lambda x: F.adaptive_avg_pool1d(x, 2),
                                    _x((1, 2, 6))),
    "adaptive_avg_pool2d": lambda: (lambda x: F.adaptive_avg_pool2d(x, 2),
                                    _x((1, 2, 4, 4))),
    "adaptive_avg_pool3d": lambda: (lambda x: F.adaptive_avg_pool3d(x, 2),
                                    _x((1, 1, 4, 4, 4))),
    "adaptive_max_pool1d": lambda: (lambda x: F.adaptive_max_pool1d(x, 2),
                                    _x((1, 2, 6))),
    "adaptive_max_pool2d": lambda: (lambda x: F.adaptive_max_pool2d(x, 2),
                                    _x((1, 2, 4, 4))),
    "adaptive_max_pool3d": lambda: (lambda x: F.adaptive_max_pool3d(x, 2),
                                    _x((1, 1, 4, 4, 4))),
    "affine_grid": lambda: (lambda x: F.affine_grid(x, [1, 1, 3, 3]),
                            _x((1, 2, 3))),
    "avg_pool1d": lambda: (lambda x: F.avg_pool1d(x, 2, 2), _x((1, 2, 6))),
    "avg_pool2d": lambda: (lambda x: F.avg_pool2d(x, 2, 2), _x((1, 2, 4, 4))),
    "avg_pool3d": lambda: (lambda x: F.avg_pool3d(x, 2, 2),
                           _x((1, 1, 4, 4, 4))),
    "max_pool1d": lambda: (lambda x: F.max_pool1d(x, 2, 2), _x((1, 2, 6))),
    "max_pool2d": lambda: (lambda x: F.max_pool2d(x, 2, 2), _x((1, 2, 4, 4))),
    "max_pool3d": lambda: (lambda x: F.max_pool3d(x, 2, 2),
                           _x((1, 1, 4, 4, 4))),
    "batch_norm": lambda: (lambda x: F.batch_norm(
        x, jnp.zeros((2,)), jnp.ones((2,)), training=False),
        _x((2, 2, 3, 3))),
    "bilinear": lambda: (lambda x: F.bilinear(
        x, jnp.asarray(_x((2, 3))), jnp.asarray(_x((4, 3, 3)))), _x((2, 3))),
    "binary_cross_entropy": lambda: (lambda x: F.binary_cross_entropy(
        x, jnp.asarray((_x() > 0.6).astype("f4"))), _x()),
    "binary_cross_entropy_with_logits": lambda: (
        lambda x: F.binary_cross_entropy_with_logits(
            x, jnp.asarray((_x() > 0.6).astype("f4"))), _x()),
    "conv1d": lambda: (lambda x: F.conv1d(
        x, jnp.asarray(_x((3, 2, 3)))), _x((1, 2, 8))),
    "conv1d_transpose": lambda: (lambda x: F.conv1d_transpose(
        x, jnp.asarray(_x((2, 3, 3)))), _x((1, 2, 8))),
    "conv2d": lambda: (lambda x: F.conv2d(
        x, jnp.asarray(_x((3, 2, 3, 3)))), _x((1, 2, 6, 6))),
    "conv2d_transpose": lambda: (lambda x: F.conv2d_transpose(
        x, jnp.asarray(_x((2, 3, 3, 3)))), _x((1, 2, 6, 6))),
    "conv3d": lambda: (lambda x: F.conv3d(
        x, jnp.asarray(_x((2, 1, 2, 2, 2)))), _x((1, 1, 4, 4, 4))),
    "conv3d_transpose": lambda: (lambda x: F.conv3d_transpose(
        x, jnp.asarray(_x((1, 2, 2, 2, 2)))), _x((1, 1, 4, 4, 4))),
    "cosine_embedding_loss": lambda: (lambda x: F.cosine_embedding_loss(
        x, jnp.asarray(_x((2, 3))), jnp.asarray([1, -1])), _x((2, 3))),
    "cosine_similarity": lambda: (lambda x: F.cosine_similarity(
        x, jnp.asarray(_x((2, 3)))), _x((2, 3))),
    "cross_entropy": lambda: (lambda x: F.cross_entropy(
        x, jnp.asarray([1, 2])), _x((2, 4))),
    "fused_linear_cross_entropy": lambda: (
        lambda x: F.fused_linear_cross_entropy(
            x, jnp.asarray(_x((3, 8))), jnp.asarray([1, 5])), _x((2, 3))),
    "ctc_loss": lambda: (lambda x: F.ctc_loss(
        jax.nn.log_softmax(x, -1), jnp.asarray([[1, 2]]),
        jnp.asarray([6]), jnp.asarray([2])), _x((6, 1, 4))),
    "diag_embed": lambda: (lambda x: F.diag_embed(x), _x((2, 3))),
    "dice_loss": lambda: (lambda x: F.dice_loss(
        jax.nn.softmax(x, -1), jnp.asarray([[0], [1]])), _x((2, 3))),
    "embedding": lambda: (lambda x: F.embedding(
        jnp.asarray([0, 2, 1]), x), _x((4, 3))),
    "fold": lambda: (lambda x: F.fold(x, [4, 4], [2, 2], strides=2),
                     _x((1, 4, 4))),
    "glu": lambda: (lambda x: F.glu(x), _x((2, 4))),
    "grid_sample": lambda: (lambda x: F.grid_sample(
        x, jnp.asarray(_x((1, 3, 3, 2), lo=-0.8, hi=0.8))),
        _x((1, 2, 4, 4))),
    "group_norm": lambda: (lambda x: F.group_norm(
        x, 2, weight=jnp.ones((4,)), bias=jnp.zeros((4,))),
        _x((2, 4, 3, 3))),
    "hinge_embedding_loss": lambda: (lambda x: F.hinge_embedding_loss(
        x, jnp.asarray([[1.0, -1.0, 1.0], [-1.0, 1.0, -1.0]])), _x()),
    "hsigmoid_loss": lambda: (lambda x: F.hsigmoid_loss(
        x, jnp.asarray([0, 3]), 6, jnp.asarray(_x((5, 3)))), _x((2, 3))),
    "instance_norm": lambda: (lambda x: F.instance_norm(x),
                              _x((2, 2, 4, 4))),
    "interpolate": lambda: (lambda x: F.interpolate(
        x, scale_factor=2, mode="bilinear"), _x((1, 2, 3, 3))),
    "upsample": lambda: (lambda x: F.upsample(
        x, scale_factor=2, mode="nearest"), _x((1, 2, 3, 3))),
    "kl_div": lambda: (lambda x: F.kl_div(
        jax.nn.log_softmax(x, -1),
        jax.nn.softmax(jnp.asarray(_x((2, 3))), -1)), _x((2, 3))),
    "l1_loss": lambda: (lambda x: F.l1_loss(x, jnp.zeros_like(x)), _x()),
    "label_smooth": lambda: (lambda x: F.label_smooth(x), _x()),
    "layer_norm": lambda: (lambda x: F.layer_norm(x, (3,)), _x()),
    "linear": lambda: (lambda x: F.linear(
        x, jnp.asarray(_x((3, 2))), jnp.zeros((2,))), _x()),
    "local_response_norm": lambda: (lambda x: F.local_response_norm(x, 3),
                                    _x((1, 4, 3, 3))),
    "log_loss": lambda: (lambda x: F.log_loss(
        x, jnp.asarray((_x() > 0.6).astype("f4"))), _x()),
    "log_softmax": lambda: (lambda x: F.log_softmax(x), _x()),
    "margin_ranking_loss": lambda: (lambda x: F.margin_ranking_loss(
        x, jnp.asarray(_x()), jnp.ones_like(x)), _x()),
    "maxout": lambda: (lambda x: F.maxout(x, 2), _x((1, 4, 2, 2))),
    "mse_loss": lambda: (lambda x: F.mse_loss(x, jnp.zeros_like(x)), _x()),
    "nll_loss": lambda: (lambda x: F.nll_loss(
        jax.nn.log_softmax(x, -1), jnp.asarray([1, 2])), _x((2, 4))),
    "normalize": lambda: (lambda x: F.normalize(x), _x()),
    "npair_loss": lambda: (lambda x: F.npair_loss(
        x, jnp.asarray(_x((2, 3))), jnp.asarray([0, 1])), _x((2, 3))),
    "pad": lambda: (lambda x: F.pad(x, [1, 1], value=0.0), _x()),
    "channel_shuffle": lambda: (lambda x: F.channel_shuffle(x, 2),
                                _x((1, 4, 2, 2))),
    "pixel_shuffle": lambda: (lambda x: F.pixel_shuffle(x, 2),
                              _x((1, 4, 2, 2))),
    "pixel_unshuffle": lambda: (lambda x: F.pixel_unshuffle(x, 2),
                                _x((1, 1, 4, 4))),
    "prelu": lambda: (lambda x: F.prelu(x - 0.6, jnp.asarray([0.2])), _x()),
    "scaled_dot_product_attention": lambda: (
        lambda x: F.scaled_dot_product_attention(x, x, x),
        _x((1, 4, 2, 4))),
    "sigmoid_focal_loss": lambda: (lambda x: F.sigmoid_focal_loss(
        x, jnp.asarray((_x() > 0.6).astype("f4"))), _x()),
    "smooth_l1_loss": lambda: (lambda x: F.smooth_l1_loss(
        x, jnp.zeros_like(x)), _x()),
    "softmax": lambda: (lambda x: F.softmax(x), _x()),
    "softmax_": lambda: (lambda x: F.softmax_(x), _x()),
    "softmax_with_cross_entropy": lambda: (
        lambda x: F.softmax_with_cross_entropy(
            x, jnp.asarray([[1], [2]])), _x((2, 4))),
    "square_error_cost": lambda: (lambda x: F.square_error_cost(
        x, jnp.zeros_like(x)), _x()),
    "temporal_shift": lambda: (lambda x: F.temporal_shift(x, 2, 0.25),
                               _x((4, 4, 2, 2))),
    "triplet_margin_loss": lambda: (lambda x: F.triplet_margin_loss(
        x, jnp.asarray(_x((2, 3))), jnp.asarray(_x((2, 3)))), _x((2, 3))),
    "unfold": lambda: (lambda x: F.unfold(x, 2, strides=2),
                       _x((1, 2, 4, 4))),
    "gelu": lambda: (F.gelu, _x()),
    "celu": lambda: (lambda x: F.celu(x - 0.6), _x()),
    "elu": lambda: (lambda x: F.elu(x - 0.6), _x()),
    "elu_": lambda: (lambda x: F.elu_(x - 0.6), _x()),
    "hardshrink": lambda: (lambda x: F.hardshrink(x - 0.6), _x()),
    "softshrink": lambda: (lambda x: F.softshrink(x - 0.6), _x()),
    "thresholded_relu": lambda: (lambda x: F.thresholded_relu(x, 0.6),
                                 _x()),
}

F_EXEMPT = {
    "hsigmoid_loss": None,  # covered (config above); placeholder removed
}
F_EXEMPT = {}


def _auto_config(mod, name):
    fn = getattr(mod, name)

    def build():
        return fn, _x()

    return build


def _classify(mod, nondiff, stochastic, utility, cplx, configs, exempt):
    names = sorted(n for n in dir(mod)
                   if not n.startswith("_") and callable(getattr(mod, n)))
    classified = (set(nondiff) | set(stochastic) | set(utility) | set(cplx)
                  | set(configs) | set(exempt))
    auto = []
    for n in names:
        if n in classified:
            continue
        auto.append(n)
    return names, auto


TENSOR_NAMES, TENSOR_AUTO = _classify(
    T, TENSOR_NONDIFF, TENSOR_STOCHASTIC, TENSOR_UTILITY, TENSOR_COMPLEX,
    TENSOR_CONFIGS, TENSOR_EXEMPT)
F_NAMES, F_AUTO = _classify(
    F, F_NONDIFF, F_STOCHASTIC, F_UTILITY, set(), F_CONFIGS, F_EXEMPT)


class TestSweepCoverage:
    def test_exemption_budget(self):
        assert len(TENSOR_EXEMPT) + len(F_EXEMPT) < 10, (
            TENSOR_EXEMPT, F_EXEMPT)

    def test_print_coverage_report(self, capsys):
        total = len(TENSOR_NAMES) + len(F_NAMES)
        checked = len(TENSOR_AUTO) + len(TENSOR_CONFIGS) + len(F_AUTO) + \
            len(F_CONFIGS)
        with capsys.disabled():
            print(f"\n[optest sweep] {total} public ops "
                  f"({len(TENSOR_NAMES)} tensor + {len(F_NAMES)} "
                  f"functional): {checked} grad-checked "
                  f"({len(TENSOR_AUTO) + len(F_AUTO)} auto, "
                  f"{len(TENSOR_CONFIGS) + len(F_CONFIGS)} configured), "
                  f"{len(TENSOR_NONDIFF | F_NONDIFF)} non-diff, "
                  f"{len(TENSOR_STOCHASTIC | F_STOCHASTIC)} stochastic, "
                  f"{len(TENSOR_UTILITY)} utility, "
                  f"{len(TENSOR_COMPLEX)} complex-domain, "
                  f"{len(TENSOR_EXEMPT) + len(F_EXEMPT)} exempt "
                  f"({sorted(TENSOR_EXEMPT) + sorted(F_EXEMPT)})")


class TestTensorOpGrads:
    @pytest.mark.parametrize("name", TENSOR_AUTO)
    def test_auto_unary(self, name):
        fn = getattr(T, name)
        check_grad(fn, _x())

    @pytest.mark.parametrize("name", sorted(TENSOR_CONFIGS))
    def test_configured(self, name):
        f, x = TENSOR_CONFIGS[name]()
        check_grad(f, x)


class TestFunctionalOpGrads:
    @pytest.mark.parametrize("name", F_AUTO)
    def test_auto_unary(self, name):
        fn = getattr(F, name)
        check_grad(fn, _x())

    @pytest.mark.parametrize("name", sorted(F_CONFIGS))
    def test_configured(self, name):
        f, x = F_CONFIGS[name]()
        check_grad(f, x)
