"""paddle.static.amp — static-graph mixed precision (reference:
python/paddle/static/amp/__init__.py re-exporting
fluid/contrib/mixed_precision: decorate:37, fp16_lists.py
AutoMixedPrecisionLists, fp16_utils.py cast_model_to_fp16:322 /
cast_parameters_to_fp16:484, bf16/).

TPU translation: the reference rewrites the ProgramDesc op-by-op
(white/black lists decide per-op dtype, loss scaling wraps the
optimizer). Here a Program IS a traced jaxpr, so the same two levers
apply at trace time: `fp16_guard`/`auto_cast` scopes the policy-list
casting during Program.trace, and `decorate` wraps the optimizer with
dynamic loss scaling (the reference's OptimizerWithMixedPrecision).
bf16 is the native TPU half type — fp16 names are kept for source
compat and mapped to bf16 (no loss-scaling need, but the machinery is
honored when asked for)."""
from __future__ import annotations

from types import SimpleNamespace

from ..amp import amp_state, auto_cast
from ..amp import GradScaler as _GradScaler

__all__ = ["decorate", "CustomOpLists", "AutoMixedPrecisionLists",
           "fp16_guard", "cast_model_to_fp16", "cast_parameters_to_fp16",
           "bf16"]


class AutoMixedPrecisionLists:
    """White/black op lists (reference fp16_lists.py). The lists feed
    auto_cast's policy; black_varnames is accepted for source compat
    (per-var blacking has no analogue on a traced graph — documented)."""

    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None):
        self.white_list = set(custom_white_list or ())
        self.black_list = set(custom_black_list or ())
        self.black_varnames = set(custom_black_varnames or ())


CustomOpLists = AutoMixedPrecisionLists


class OptimizerWithMixedPrecision:
    """reference decorator.py:37 — loss-scaled optimizer wrapper. The
    scaler only engages when dynamic loss scaling is requested (bf16
    training doesn't need it; fp16 source compat does)."""

    def __init__(self, optimizer, amp_lists=None,
                 init_loss_scaling=2.0 ** 15,
                 incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
                 incr_ratio=2.0, decr_ratio=0.8,
                 use_dynamic_loss_scaling=True, use_pure_fp16=False):
        self._optimizer = optimizer
        self._amp_lists = amp_lists
        self._use_pure = use_pure_fp16
        self._scaler = (_GradScaler(
            init_loss_scaling=init_loss_scaling,
            incr_every_n_steps=incr_every_n_steps,
            decr_every_n_nan_or_inf=decr_every_n_nan_or_inf,
            incr_ratio=incr_ratio, decr_ratio=decr_ratio)
            if use_dynamic_loss_scaling else None)

    def __getattr__(self, name):
        return getattr(self._optimizer, name)

    def amp_init(self, place=None, scope=None, test_program=None,
                 use_fp16_test=False):
        """reference: cast params for pure-fp16 runs (here: bf16)."""
        return None

    def backward(self, loss, **kw):
        """Scale the loss before gradient computation (reference
        decorator.py backward: loss * loss_scaling). Compute grads from
        the RETURNED value; step()/minimize() unscale them."""
        if self._scaler is not None:
            return self._scaler.scale(loss)
        return loss

    def step(self):
        """Unscale Parameter.grad, skip the update on non-finite grads,
        and advance the dynamic scale (reference decorator.py
        apply_gradients: check_finite_and_unscale + update_loss_scaling)."""
        if self._scaler is None:
            return self._optimizer.step()
        return self._scaler.step(self._optimizer)

    def minimize(self, loss=None, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self.step()

    def init_state(self, params):
        """Inner optimizer state + a "loss_scale" sub-pytree so the dynamic
        scale moves UNDER JIT (reference decorator.py:446 puts
        update_loss_scaling into the graph; here the state is threaded
        through the traced step instead of mutated on the host)."""
        state = self._optimizer.init_state(params)
        if self._scaler is not None and isinstance(state, dict):
            state = dict(state)
            state["loss_scale"] = self._scaler.init_scale_state()
        return state

    def scale_loss(self, loss, state=None):
        """Scale a loss by the live scale. With a state pytree from
        init_state this is traced (jit-safe); without, the host float."""
        if self._scaler is None:
            return loss
        if isinstance(state, dict) and "loss_scale" in state:
            return self._scaler.scale_loss(loss, state["loss_scale"])
        return self._scaler.scale(loss)

    def apply_gradients(self, params, grads, state, lr=None,
                        lr_scales=None):
        """Functional path (jitted steps): unscale + finite-gate here.

        When ``state`` came from this wrapper's init_state it carries a
        "loss_scale" pytree: the unscale uses the TRACED scale and the
        incr/decr counters advance inside the graph, so persistent overflow
        actually backs the scale off under jit. Legacy states without the
        key fall back to the trace-time host float (scale never moves —
        callers owning their state should migrate to init_state)."""
        if self._scaler is None:
            return self._optimizer.apply_gradients(params, grads, state,
                                                   lr=lr,
                                                   lr_scales=lr_scales)
        import jax
        import jax.numpy as jnp

        carried = isinstance(state, dict) and "loss_scale" in state
        if carried:
            inner_state = {k: v for k, v in state.items()
                           if k != "loss_scale"}
            grads, found_inf, new_ls = self._scaler.unscale_and_update(
                dict(grads), state["loss_scale"])
        else:
            inner_state = state
            grads, found_inf = self._scaler.unscale_(dict(grads))
        new_p, new_s = self._optimizer.apply_gradients(
            params, grads, inner_state, lr=lr, lr_scales=lr_scales)
        # non-finite step: keep old params AND optimizer state (inf grads
        # would otherwise poison the moments) — traced-safe select
        keep = jnp.asarray(found_inf)
        new_p = jax.tree.map(lambda n, o: jnp.where(keep, o, n), new_p,
                             dict(params))
        new_s = jax.tree.map(lambda n, o: jnp.where(keep, o, n), new_s,
                             inner_state)
        if carried:
            new_s = dict(new_s)
            new_s["loss_scale"] = new_ls  # advances even on skipped steps
        return new_p, new_s

    def get_loss_scaling(self, state=None):
        if self._scaler is None:
            return 1.0
        if isinstance(state, dict) and "loss_scale" in state:
            return float(state["loss_scale"]["scale"])
        return float(self._scaler._scale)


def decorate(optimizer, amp_lists=None, init_loss_scaling=2.0 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True, use_pure_fp16=False,
             use_fp16_guard=None, use_bf16=True):
    """reference mixed_precision/decorator.py decorate."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists=amp_lists,
        init_loss_scaling=init_loss_scaling,
        incr_every_n_steps=incr_every_n_steps,
        decr_every_n_nan_or_inf=decr_every_n_nan_or_inf,
        incr_ratio=incr_ratio, decr_ratio=decr_ratio,
        use_dynamic_loss_scaling=use_dynamic_loss_scaling,
        use_pure_fp16=use_pure_fp16)


def fp16_guard():
    """reference fp16_utils.py fp16_guard: ops created inside run under
    the half-precision policy. Here: an auto_cast scope at trace time
    (bf16, the TPU half type)."""
    return auto_cast(True, level="O1")


def cast_model_to_fp16(model, amp_lists=None, use_fp16_guard=True):
    """reference fp16_utils.py:322 — cast a whole model half. For a
    Layer, Layer.bfloat16() is the pure-half path (O2)."""
    if hasattr(model, "bfloat16"):
        return model.bfloat16()
    raise TypeError(
        "cast_model_to_fp16 expects a Layer here (static Programs are "
        "traced jaxprs — wrap the trace in fp16_guard() instead)")


def cast_parameters_to_fp16(place=None, program=None, scope=None,
                            to_fp16_var_names=None, model=None):
    """reference fp16_utils.py:484 — parameter cast for pure-half runs."""
    if model is not None and hasattr(model, "bfloat16"):
        return model.bfloat16()
    return None


# bf16 sub-namespace (reference static/amp/bf16): on TPU bf16 IS the amp
# dtype, so these alias the primary machinery.
bf16 = SimpleNamespace(
    auto_cast=auto_cast,
    amp_state=amp_state,
    AutoMixedPrecisionListsBF16=AutoMixedPrecisionLists,
    decorate_bf16=decorate,
)
