"""Multi-host parameter-server service: RPC servers + key-hash routing.

Capability map (reference): distributed/service/brpc_ps_server.cc /
brpc_ps_client.cc (RPC pull/push of sharded tables) and
service/communicator.h:197 (the async Communicator: trainers push grads to
a send queue drained by a background thread — "geo"-style bounded
staleness). The transport here is the fresh blocking-socket layer in
csrc/ps/ps_service.cc; this module adds what the reference's
`table/common_sparse_table.cc` sharding does across hosts: every logical
key is owned by exactly one server, chosen by the same 64-bit hash mix the
native table uses internally (``table.shard_keys``).

Topology: each training process typically hosts ONE ``PsServer`` (its key
shard) and a ``DistributedSparseTable`` client routing to ALL servers —
rendezvous of "host:port" endpoints is left to the launcher (env vars /
shared filesystem), mirroring PADDLE_PSERVER_ENDPOINTS.
"""
from __future__ import annotations

import ctypes
import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence

import numpy as np

from .native import lib
from .table import SparseTable, shard_keys, _as_f32, _as_i64, _fp, _ip


class PsServer:
    """Serves one key shard of a sparse table — and optionally one NODE
    shard of a graph table (``graph_feat_dim``) — over TCP (reference:
    brpc_ps_server.cc serving common_sparse_table + common_graph_table).
    Owns the tables; keeps them accessible in-process (e.g. for
    checkpointing via ``table.save``)."""

    def __init__(self, dim: int, optimizer: str = "adagrad", port: int = 0,
                 host: str = "127.0.0.1",
                 graph_feat_dim: Optional[int] = None, **table_kwargs):
        from .table import GraphTable
        self.table = SparseTable(dim, optimizer, **table_kwargs)
        self.graph = (GraphTable(graph_feat_dim)
                      if graph_feat_dim is not None else None)
        self._lib = lib()
        self._h = self._lib.ps_server_start2(
            self.table._h, dim,
            self.graph._h if self.graph is not None else None,
            graph_feat_dim or 0, port)
        if not self._h:
            raise OSError(f"failed to start PS server on port {port}")
        self.host = host
        self.port = int(self._lib.ps_server_port(self._h))

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def stop(self):
        if getattr(self, "_h", None):
            self._lib.ps_server_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class _Conn:
    def __init__(self, endpoint: str):
        host, port = endpoint.rsplit(":", 1)
        self._lib = lib()
        self._h = self._lib.ps_client_connect(host.encode(), int(port))
        if not self._h:
            raise ConnectionError(f"cannot connect to PS at {endpoint}")
        self.dim = int(self._lib.ps_client_dim(self._h))
        # one CALL at a time per connection. The native c->mu serializes
        # whole blocking request/response pairs against each other, but
        # the pipelined halves take only send_mu/recv_mu — so a
        # pipelined call racing ANY other call on this connection would
        # interleave frames and mismatch FIFO replies (async-mode's
        # drain-thread push vs a concurrent pull, or two user threads
        # sharing a table). Every public entry point takes this lock;
        # the split halves do NOT (they run inside a locked pipeline).
        self.lock = threading.Lock()

    @property
    def feat_dim(self) -> int:
        return int(self._lib.ps_client_feat_dim(self._h))

    def graph_add_edges(self, src, dst, w=None):
        wp = _fp(w) if w is not None else None
        with self.lock:
            if not self._lib.ps_client_graph_add_edges(
                    self._h, _ip(src), _ip(dst), wp, src.size):
                raise ConnectionError("PS graph add_edges RPC failed")

    def graph_sample(self, keys, k, seed, weighted):
        out = np.empty((keys.size, k), dtype=np.int64)
        counts = np.empty((keys.size,), dtype=np.int64)
        with self.lock:
            if not self._lib.ps_client_graph_sample(
                    self._h, _ip(keys), keys.size, int(k), int(seed),
                    _ip(out), _ip(counts), 1 if weighted else 0):
                raise ConnectionError("PS graph sample RPC failed")
        return out, counts

    def graph_feature(self, keys, feat_dim):
        out = np.empty((keys.size, feat_dim), dtype=np.float32)
        with self.lock:
            if not self._lib.ps_client_graph_feature(self._h, _ip(keys),
                                                     keys.size, _fp(out)):
                raise ConnectionError("PS graph feature RPC failed")
        return out

    def graph_set_feature(self, keys, feats):
        with self.lock:
            if not self._lib.ps_client_graph_set_feature(
                    self._h, _ip(keys), keys.size, _fp(feats)):
                raise ConnectionError("PS graph set_feature RPC failed")

    def graph_num_nodes(self) -> int:
        with self.lock:
            n = int(self._lib.ps_client_graph_num_nodes(self._h))
        if n < 0:
            raise ConnectionError("PS graph num_nodes RPC failed")
        return n

    def pull(self, keys: np.ndarray, create: bool) -> np.ndarray:
        out = np.empty((keys.size, self.dim), dtype=np.float32)
        with self.lock:
            if not self._lib.ps_client_pull(self._h, _ip(keys), keys.size,
                                            _fp(out), 1 if create else 0):
                raise ConnectionError("PS pull RPC failed")
        return out

    def push(self, keys: np.ndarray, grads: np.ndarray, lr: float):
        with self.lock:
            if not self._lib.ps_client_push(self._h, _ip(keys), keys.size,
                                            _fp(grads), lr):
                raise ConnectionError("PS push RPC failed")

    # -- pipelined halves (many requests in flight per connection;
    # replies are FIFO on the ordered stream — see ps_service.cc) ------
    def pull_send(self, keys: np.ndarray, create: bool):
        if not self._lib.ps_client_pull_send(self._h, _ip(keys), keys.size,
                                             1 if create else 0):
            raise ConnectionError("PS pull_send failed")

    def pull_recv(self, out: np.ndarray, n: int):
        if not self._lib.ps_client_pull_recv(self._h, _fp(out), n):
            raise ConnectionError("PS pull_recv failed")

    def push_send(self, keys: np.ndarray, grads: np.ndarray, lr: float):
        if not self._lib.ps_client_push_send(self._h, _ip(keys), keys.size,
                                             _fp(grads), lr):
            raise ConnectionError("PS push_send failed")

    def push_recv(self):
        if not self._lib.ps_client_push_recv(self._h):
            raise ConnectionError("PS push_recv failed")

    def sample_send(self, keys: np.ndarray, k: int, seed: int,
                    weighted: bool):
        if not self._lib.ps_client_graph_sample_send(
                self._h, _ip(keys), keys.size, int(k), int(seed),
                1 if weighted else 0):
            raise ConnectionError("PS sample_send failed")

    def sample_recv(self, n: int, k: int):
        out = np.empty((n, k), dtype=np.int64)
        counts = np.empty((n,), dtype=np.int64)
        if not self._lib.ps_client_graph_sample_recv(
                self._h, n, int(k), _ip(out), _ip(counts)):
            raise ConnectionError("PS sample_recv failed")
        return out, counts

    def size(self) -> int:
        with self.lock:
            return int(self._lib.ps_client_size(self._h))

    def close(self):
        if getattr(self, "_h", None):
            self._lib.ps_client_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _ShardedClient:
    """Shared key-hash routing + concurrent per-shard fan-out (each _Conn
    has its own socket+lock — the reference brpc client's parallel
    fan-out; sequential round trips would cost n_shards x RTT).

    Within each connection large requests are PIPELINED: the key range is
    chunked and a dedicated sender thread streams request frames while
    the shard's worker drains replies concurrently (brpc_ps_client.cc's
    async stubs keep many calls in flight per channel the same way) —
    server-side hash work, network transfer, and client-side marshalling
    overlap instead of latency-stacking per shard in skewed fan-outs.
    ``stats`` records the in-flight depth."""

    # keys per in-flight request frame: small enough that several
    # requests fit in socket buffers, large enough to amortize syscalls
    PIPELINE_CHUNK = 8192

    def __init__(self, endpoints: Sequence[str],
                 pipeline: Optional[bool] = None):
        assert endpoints, "need at least one PS endpoint"
        self.conns: List[_Conn] = [_Conn(e) for e in endpoints]
        self.n_shards = len(self.conns)
        self._pool = (ThreadPoolExecutor(max_workers=self.n_shards)
                      if self.n_shards > 1 else None)
        # pipelining overlaps marshalling/network/server work across
        # THREADS, so it needs cores to run them: on a 1-core host the
        # sender thread only preempts the recv drain (measured loopback
        # 4 servers, 200k keys: 5.17M pulls/sec unpipelined vs 4.5M
        # chunked) — default on only where a second core exists
        import os as _os
        self.pipeline_enabled = ((_os.cpu_count() or 1) > 1
                                 if pipeline is None else bool(pipeline))
        self.stats = {"pipelined_calls": 0, "max_inflight_reqs": 1}

    def _route(self, keys: np.ndarray):
        assign = shard_keys(keys, self.n_shards)
        for s in range(self.n_shards):
            idx = np.nonzero(assign == s)[0]
            if idx.size:
                yield s, idx

    def _fan_out(self, jobs):
        if self._pool is None or len(jobs) <= 1:
            for j in jobs:
                j()
            return
        futs = [self._pool.submit(j) for j in jobs]
        for f in futs:
            f.result()  # re-raises ConnectionError from any shard

    def _chunks(self, idx: np.ndarray):
        if not self.pipeline_enabled:
            return [idx]
        ch = self.PIPELINE_CHUNK
        return [idx[i:i + ch] for i in range(0, idx.size, ch)]

    def _pipelined(self, conn, chunks, send_one, recv_one):
        """Stream requests from a sender thread while this thread drains
        replies (client always reading -> no send/write deadlock, the
        flow control a fixed window would need). Holds conn.lock for the
        WHOLE call: FIFO reply matching is per-connection state, so no
        other call (blocking or pipelined — e.g. async-mode's drain
        thread) may interleave frames on this connection meanwhile."""
        with conn.lock:
            return self._pipelined_locked(conn, chunks, send_one,
                                          recv_one)

    def _pipelined_locked(self, conn, chunks, send_one, recv_one):
        self.stats["pipelined_calls"] += 1
        self.stats["max_inflight_reqs"] = max(
            self.stats["max_inflight_reqs"], len(chunks))
        err: List[BaseException] = []
        sent = threading.Semaphore(0)  # recv only what was really sent
        #                                (a send-side error must not
        #                                leave the recv loop blocked on a
        #                                healthy socket forever)

        def send_all():
            try:
                for ch in chunks:
                    send_one(conn, ch)
                    sent.release()
            except BaseException as e:
                err.append(e)
                sent.release()  # unblock the waiter

        t = threading.Thread(target=send_all, name="ps-send", daemon=True)
        t.start()
        try:
            for ch in chunks:
                sent.acquire()
                if err:
                    break
                recv_one(conn, ch)
        finally:
            t.join()
        if err:
            raise err[0]

    def close(self):
        if self._pool is not None:
            self._pool.shutdown(wait=True)
        for c in self.conns:
            c.close()


class DistributedSparseTable(_ShardedClient):
    """Client view of a sparse table sharded across PS servers by key hash.

    ``pull``/``push`` route each key to its owning server (reference:
    brpc_ps_client pull_sparse/push_sparse fan-out). ``async_mode`` drains
    pushes from a bounded queue on a background thread — the reference
    Communicator's geo/async semantics (communicator.h:197): training does
    not block on the push RPC, staleness is bounded by the queue depth.
    """

    def __init__(self, endpoints: Sequence[str], async_mode: bool = False,
                 max_pending: int = 8, pipeline: Optional[bool] = None):
        super().__init__(endpoints, pipeline=pipeline)
        try:
            self.dim = self.conns[0].dim
            for e, c in zip(endpoints, self.conns):
                if c.dim != self.dim:
                    raise ValueError(
                        f"PS dim mismatch: {endpoints[0]} serves dim "
                        f"{self.dim} but {e} serves dim {c.dim}")
        except Exception:
            super().close()  # don't leak sockets/pool on a failed build
            raise
        self.async_mode = async_mode
        self._err: Optional[BaseException] = None
        if async_mode:
            self._q: "queue.Queue" = queue.Queue(maxsize=max_pending)
            self._worker = threading.Thread(target=self._drain,
                                            name="ps-async-drain",
                                            daemon=True)
            self._worker.start()

    def pull(self, keys, create_missing: bool = True) -> np.ndarray:
        keys = _as_i64(keys)
        flat = keys.reshape(-1)
        out = np.empty((flat.size, self.dim), dtype=np.float32)

        def job(s, idx):
            def go():
                chunks = self._chunks(idx)
                if len(chunks) <= 1:
                    out[idx] = self.conns[s].pull(
                        np.ascontiguousarray(flat[idx]), create_missing)
                    return

                def send_one(conn, ch):
                    conn.pull_send(np.ascontiguousarray(flat[ch]),
                                   create_missing)

                def recv_one(conn, ch):
                    buf = np.empty((ch.size, self.dim), np.float32)
                    conn.pull_recv(buf, ch.size)
                    out[ch] = buf

                self._pipelined(self.conns[s], chunks, send_one, recv_one)
            return go

        self._fan_out([job(s, idx) for s, idx in self._route(flat)])
        return out.reshape(keys.shape + (self.dim,))

    def _push_sync(self, keys: np.ndarray, grads: np.ndarray, lr: float):
        def job(s, idx):
            def go():
                chunks = self._chunks(idx)
                if len(chunks) <= 1:
                    self.conns[s].push(np.ascontiguousarray(keys[idx]),
                                       np.ascontiguousarray(grads[idx]),
                                       lr)
                    return

                def send_one(conn, ch):
                    conn.push_send(np.ascontiguousarray(keys[ch]),
                                   np.ascontiguousarray(grads[ch]), lr)

                def recv_one(conn, ch):
                    conn.push_recv()

                self._pipelined(self.conns[s], chunks, send_one, recv_one)
            return go

        self._fan_out([job(s, idx) for s, idx in self._route(keys)])

    def push(self, keys, grads, lr: float):
        keys = _as_i64(keys).reshape(-1)
        grads = _as_f32(grads).reshape(keys.size, self.dim)
        if not self.async_mode:
            self._push_sync(keys, grads, lr)
            return
        if self._err is not None:
            err, self._err = self._err, None
            raise err
        # copies: the caller may reuse/donate its buffers
        self._q.put((keys.copy(), grads.copy(), float(lr)))

    def _drain(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            try:
                self._push_sync(*item)
            except BaseException as e:  # surfaced on next push/flush
                self._err = e
            finally:
                self._q.task_done()

    def flush(self):
        """Barrier for async pushes (reference Communicator barrier)."""
        if self.async_mode:
            self._q.join()
            if self._err is not None:
                err, self._err = self._err, None
                raise err

    def shard_sizes(self) -> List[int]:
        return [c.size() for c in self.conns]

    def close(self):
        if self.async_mode and self._worker.is_alive():
            self._q.join()
            self._q.put(None)
            self._worker.join(timeout=5)
        super().close()


class DistributedGraphTable(_ShardedClient):
    """Client view of a graph table NODE-partitioned across PS servers
    (reference: common_graph_table.cc:1-596 served by brpc — each server
    owns the adjacency + features of its hash shard of the node space).

    Edges live with their SOURCE node's owner, so neighbor sampling for
    a node is one RPC to its owner; sampled neighbor ids may belong to
    ANY server — multi-hop sampling (``sample_hops``) re-routes each
    hop's frontier to the owning servers, which is the cross-server
    walk the reference's graph service performs.
    """

    def __init__(self, endpoints: Sequence[str],
                 pipeline: Optional[bool] = None):
        super().__init__(endpoints, pipeline=pipeline)
        try:
            self.feat_dim = self.conns[0].feat_dim
            for e, c in zip(endpoints, self.conns):
                if c.feat_dim != self.feat_dim:
                    raise ValueError(f"graph feat_dim mismatch at {e}")
            if self.feat_dim <= 0:
                raise ValueError(
                    "endpoints serve no graph table (PsServer was built "
                    "without graph_feat_dim) — graph RPCs against them "
                    "would close the connection")
        except Exception:
            super().close()  # don't leak sockets/pool on a failed build
            raise

    def add_edges(self, src, dst, weights=None):
        src = _as_i64(src).reshape(-1)
        dst = _as_i64(dst).reshape(-1)
        w = _as_f32(weights).reshape(-1) if weights is not None else None

        def job(s, idx):
            def go():
                self.conns[s].graph_add_edges(
                    np.ascontiguousarray(src[idx]),
                    np.ascontiguousarray(dst[idx]),
                    np.ascontiguousarray(w[idx]) if w is not None
                    else None)
            return go

        self._fan_out([job(s, i) for s, i in self._route(src)])

    def sample_neighbors(self, keys, k: int, seed: int = 0,
                         weighted: bool = False):
        """(neighbors (N, k) padded with -1, counts (N,)): each key's
        sample comes from its owning server's adjacency shard."""
        keys = _as_i64(keys).reshape(-1)
        out = np.full((keys.size, k), -1, dtype=np.int64)
        counts = np.zeros((keys.size,), dtype=np.int64)

        def job(s, idx):
            def go():
                chunks = self._chunks(idx)
                if len(chunks) <= 1:
                    o, c = self.conns[s].graph_sample(
                        np.ascontiguousarray(keys[idx]), k, seed, weighted)
                    out[idx] = o
                    counts[idx] = c
                    return

                def send_one(conn, ch):
                    conn.sample_send(np.ascontiguousarray(keys[ch]), k,
                                     seed, weighted)

                def recv_one(conn, ch):
                    o, c = conn.sample_recv(ch.size, k)
                    out[ch] = o
                    counts[ch] = c

                self._pipelined(self.conns[s], chunks, send_one, recv_one)
            return go

        self._fan_out([job(s, i) for s, i in self._route(keys)])
        return out, counts

    def sample_hops(self, keys, fanouts: Sequence[int], seed: int = 0,
                    weighted: bool = False):
        """Multi-hop neighborhood sampling: hop h samples ``fanouts[h]``
        neighbors of the previous frontier, re-routing every hop to the
        owners of its (possibly remote) nodes. Returns a list of
        (src (F,), neighbors (F, k), counts (F,)) per hop."""
        frontier = np.unique(_as_i64(keys).reshape(-1))
        out = []
        for h, k in enumerate(fanouts):
            nbrs, counts = self.sample_neighbors(frontier, k,
                                                 seed=seed + h,
                                                 weighted=weighted)
            out.append((frontier, nbrs, counts))
            nxt = nbrs[nbrs >= 0]
            if nxt.size == 0:
                break
            frontier = np.unique(nxt)
        return out

    def node_feature(self, keys) -> np.ndarray:
        keys = _as_i64(keys).reshape(-1)
        out = np.zeros((keys.size, self.feat_dim), dtype=np.float32)

        def job(s, idx):
            def go():
                out[idx] = self.conns[s].graph_feature(
                    np.ascontiguousarray(keys[idx]), self.feat_dim)
            return go

        self._fan_out([job(s, i) for s, i in self._route(keys)])
        return out

    def set_node_feature(self, keys, feats):
        keys = _as_i64(keys).reshape(-1)
        feats = _as_f32(feats).reshape(keys.size, self.feat_dim)

        def job(s, idx):
            def go():
                self.conns[s].graph_set_feature(
                    np.ascontiguousarray(keys[idx]),
                    np.ascontiguousarray(feats[idx]))
            return go

        self._fan_out([job(s, i) for s, i in self._route(keys)])

    def num_nodes(self) -> int:
        return sum(c.graph_num_nodes() for c in self.conns)
