"""dy2static — Python control flow over traced values staged into lax.

Reference: fluid/dygraph/dygraph_to_static/ast_transformer.py (the
IfElse/Loop/LogicalOp/Print transformer pipeline) and
program_translator.py:232 StaticFunction. The reference rewrites Python
source into ProgramDesc ops; here the same AST rewriting targets JAX:

    if cond: ...            ->  _jst.convert_ifelse(cond, true_fn, false_fn)
    while cond: ...         ->  _jst.convert_while(cond_fn, body_fn, vars)
    for i in range(...):    ->  while-form via normalize_range
    a and b / a or b / not  ->  lazy convert_logical_* (tensor-aware,
                                Python semantics preserved otherwise)
    print(x)                ->  convert_print (jax.debug.print when traced)

Each converter picks the lax primitive when the condition is a tracer and
plain Python otherwise, so converted functions behave identically outside
jit. Unsupported constructs under a tensor-dependent condition
(return/break/continue inside the statement) raise Dy2StaticError with the
original source location — the reference's error.py diagnostics contract.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import warnings

import jax
import jax.numpy as jnp

__all__ = ["convert_function", "Dy2StaticError"]


class Dy2StaticError(RuntimeError):
    pass


class _Undefined:
    """Sentinel for variables assigned in only some branches (reference
    dygraph_to_static undefined-var handling)."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<dy2static undefined>"

    def __bool__(self):
        raise Dy2StaticError(
            "variable is undefined on this control-flow path (assigned in "
            "only one branch of a converted statement)")


UNDEFINED = _Undefined()


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def _loc(filename, lineno):
    return f"{filename}:{lineno}" if lineno else filename


# ---------------------------------------------------------------------------
# runtime converters (called by the transformed code as _jst.*)
# ---------------------------------------------------------------------------
_STRUCTURE_ERR_HINTS = ("true_fun", "false_fun", "body_fun", "cond_fun",
                        "pytree", "not a valid JAX type", "tree structure",
                        "output must have", "must have same type structure",
                        "differs from", "mismatch")


def _is_structure_error(e: TypeError) -> bool:
    msg = str(e)
    return any(h in msg for h in _STRUCTURE_ERR_HINTS)


def convert_ifelse(cond, true_fn, false_fn, init, names,
                   filename="<dy2static>", lineno=0, attr_muts=()):
    """Branch fns take the CURRENT values of every assigned name as
    arguments (a branch that reads-then-writes a name would otherwise see
    it as an unbound local — the reference passes branch inputs the same
    way)."""
    if not _is_tracer(cond):
        return (true_fn if cond else false_fn)(*init)
    if attr_muts:
        # e.g. `self.cache.append(x)`: the container lives on an OBJECT
        # attribute — it cannot be threaded through lax.cond like a local
        # name, and tracing both branches would mutate it unconditionally
        raise Dy2StaticError(
            f"{_loc(filename, lineno)}: branch of a tensor-dependent "
            f"`if` mutates attribute container(s) "
            f"{sorted(set(attr_muts))} in place; bind the container to "
            f"a local variable before the `if` (locals thread through "
            f"the staged branches, attributes cannot)")
    # UNDEFINED placeholders are not JAX types: route them through the
    # closure, pass only real values as lax.cond operands (a branch that
    # assigns them returns arrays; a branch that doesn't returns UNDEFINED
    # and the output-structure mismatch raises the diagnostic below)
    defined = [i for i, v in enumerate(init) if v is not UNDEFINED]
    ops = tuple(init[i] for i in defined)

    def call(fn, t):
        full = list(init)
        for j, i in enumerate(defined):
            full[i] = t[j]
        return fn(*full)

    try:
        return jax.lax.cond(cond, lambda t: call(true_fn, t),
                            lambda t: call(false_fn, t), ops)
    except TypeError as e:
        if not _is_structure_error(e):
            raise  # a genuine user error inside a branch, not ours
        # fallback for branches whose outputs are not all jax-typed
        # (python ints, loop temporaries, UNDEFINED): evaluate BOTH
        # branches and select per variable (select computes both sides —
        # only paid when the strict lax.cond path cannot apply)
        return _select_branches(cond, true_fn, false_fn, init, names,
                                filename, lineno, e)


def _is_arrayish(v):
    return isinstance(v, (jax.Array, jax.core.Tracer, int, float, bool,
                          jnp.ndarray)) or (
        hasattr(v, "dtype") and hasattr(v, "shape"))


def _copy_containers(v):
    """Fresh list/dict/set shells (leaves by reference): branch bodies
    may MUTATE containers, and both branches of a tensor `if` are traced
    — without per-branch copies the second branch would see the first
    branch's mutations."""
    if isinstance(v, list):
        return [_copy_containers(x) for x in v]
    if isinstance(v, dict):
        return {k: _copy_containers(x) for k, x in v.items()}
    if isinstance(v, set):
        return set(v)
    if isinstance(v, tuple):
        return tuple(_copy_containers(x) for x in v)
    return v


def _select_branches(cond, true_fn, false_fn, init, names, filename,
                     lineno, orig_err):
    outs_t = true_fn(*_copy_containers(tuple(init)))
    outs_f = false_fn(*_copy_containers(tuple(init)))
    res = []
    for n, a, b in zip(names, outs_t, outs_f):
        if a is b:
            res.append(a)
            continue
        if a is UNDEFINED or b is UNDEFINED:
            # assigned on one path only: reading it on the other path is
            # undefined behavior in Python — like the reference's
            # RETURN_NO_VALUE handling, the defined side's value is kept
            # so the trace proceeds (the variable simply should not be
            # consumed when the other branch was taken)
            res.append(b if a is UNDEFINED else a)
            continue
        if _is_arrayish(a) and _is_arrayish(b):
            try:
                res.append(jnp.where(cond, a, b))
                continue
            except Exception:
                raise Dy2StaticError(
                    f"{_loc(filename, lineno)}: variable {n!r} has "
                    f"incompatible shape/dtype across tensor-dependent "
                    f"`if` branches") from orig_err
        try:
            same = type(a) is type(b) and bool(a == b)
        except Exception:  # tracer-holding containers: == concretizes
            same = False
        if same:
            res.append(a)
            continue
        raise Dy2StaticError(
            f"{_loc(filename, lineno)}: variable {n!r} takes different "
            f"non-tensor values per branch of a tensor-dependent `if` — "
            f"this cannot be staged") from orig_err
    return tuple(res)


_UNROLL_CAP = 512


def _carry_compatible(a, b):
    """Can a lax.while_loop carry go from `a` to `b`? Same pytree
    structure AND same per-leaf shape/dtype."""
    import jax.tree_util as jtu
    if jtu.tree_structure(a) != jtu.tree_structure(b):
        return False
    for la, lb in zip(jtu.tree_leaves(a), jtu.tree_leaves(b)):
        sa = jnp.shape(la) if _is_arrayish(la) else None
        sb = jnp.shape(lb) if _is_arrayish(lb) else None
        if sa != sb:
            return False
        if sa is not None and jnp.result_type(la) != jnp.result_type(lb):
            return False
    return True


def convert_while(cond_fn, body_fn, init, names, filename="<dy2static>",
                  lineno=0):
    first = cond_fn(*init)
    if not _is_tracer(first) and not any(_is_tracer(v) for v in init):
        vars_ = tuple(init)
        while cond_fn(*vars_):
            vars_ = tuple(body_fn(*vars_))
        return vars_

    def _stage_while(start_vars):
        """lax.while_loop from start_vars → (True, result) or, for a
        carry-structure mismatch, (False, the TypeError)."""
        staged = tuple(jnp.asarray(v) if isinstance(v, (int, float, bool))
                       else v for v in start_vars)
        try:
            return True, jax.lax.while_loop(lambda t: cond_fn(*t),
                                            lambda t: tuple(body_fn(*t)),
                                            staged)
        except TypeError as e:
            if not _is_structure_error(e):
                raise
            return False, e

    if _is_tracer(first):
        # tensor-dependent trip count: only the staged form exists
        if any(v is UNDEFINED for v in init):
            init = _seed_loop_locals(cond_fn, body_fn, init, names,
                                     filename, lineno)
        ok, res = _stage_while(init)
        if ok:
            return res
        raise Dy2StaticError(
            f"{_loc(filename, lineno)}: tensor-dependent `while` body "
            f"must keep every loop variable {list(names)} at a fixed "
            f"shape/dtype across iterations: {res}") from res
    # STATIC condition with traced carries. PEEL the first iteration —
    # running the body exactly once decides staged-vs-unrolled without a
    # throwaway trace (an aborted lax.while_loop attempt would already
    # have executed the body once, replaying its Python-side effects —
    # RNG counter draws, buffer writes — in whichever path ran next).
    if not first:
        return tuple(init)
    # snapshot the pre-body structure: the body may mutate carried
    # containers IN PLACE (acc.append), in which case init aliases the
    # body's output and a post-hoc comparison would see the list equal
    # to itself
    before = _copy_containers(tuple(init))
    try:
        vars_ = tuple(body_fn(*init))
    except Dy2StaticError:
        raise
    except Exception as e:
        undef = [n for n, v in zip(names, init) if v is UNDEFINED]
        if undef:  # the located diagnostic, not a raw _Undefined TypeError
            raise Dy2StaticError(
                f"{_loc(filename, lineno)}: loop variable {undef[0]!r} "
                "is not defined before this loop and is read before "
                "assignment in the body") from e
        raise
    if _carry_compatible(vars_, before):
        # structure-stable: stage the REMAINING iterations compactly
        ok, res = _stage_while(vars_)
        if ok:
            return res
        # stable iter0->iter1 but the staged trace failed later (the
        # structure evolves from iteration 2 on): that aborted trace
        # already re-ran the body's Python-side effects once, so
        # unrolling from here would silently diverge from eager — refuse
        raise Dy2StaticError(
            f"{_loc(filename, lineno)}: `while` loop variables change "
            f"structure after the first iteration ({res}); keep them at "
            f"fixed shapes across ALL iterations (preallocate and "
            f"index-update instead of appending)") from res
    # shape/structure-evolving carries with a static trip count (e.g. a
    # decoder appending per-step logits — the reference stages these via
    # TensorArray, test_seq2seq.py): unroll under the trace.
    n = 1
    cond = cond_fn(*vars_)
    while True:
        if _is_tracer(cond):
            # checked BEFORE `while cond` would bool()-concretize it
            raise Dy2StaticError(
                f"{_loc(filename, lineno)}: `while` condition became "
                f"tensor-dependent mid-loop while the body mutates "
                f"loop-variable structure — neither staged nor unrolled "
                f"form exists")
        if not cond:
            break
        n += 1
        if n > _UNROLL_CAP:
            raise Dy2StaticError(
                f"{_loc(filename, lineno)}: static-trip-count `while` "
                f"with structure-evolving loop variables exceeded the "
                f"{_UNROLL_CAP}-iteration unroll cap — the traced graph "
                f"would contain one copy of the body per iteration. "
                f"Keep loop variables at fixed shapes (preallocate and "
                f"index-update instead of appending) so the loop can "
                f"stage as one lax.while_loop")
        vars_ = tuple(body_fn(*vars_))
        cond = cond_fn(*vars_)
    return vars_


def _seed_loop_locals(cond_fn, body_fn, init, names, filename, lineno):
    """Loop variables first bound INSIDE the body (loop-locals — e.g. the
    induction var of a nested converted loop) have no pre-loop value.
    Probe the body once under jax.eval_shape with UNDEFINED
    placeholders: a variable that is genuinely assigned-before-read
    comes back with a shape/dtype that seeds a zero initial carry (the
    first iteration overwrites it); a variable that is READ first trips
    on the placeholder and gets the diagnostic. eval_shape performs
    abstract evaluation — the probe's effects (debug prints, assert
    callbacks) are discarded with the inner trace, and containers are
    copied so body mutations cannot touch the real pre-loop objects.

    Known semantic edge (documented, matches neither Python nor a
    silent crash): after a loop whose runtime trip count is ZERO, a
    seeded loop-local reads as zeros where plain Python would raise
    NameError."""
    def fail(n, cause=None):
        err = Dy2StaticError(
            f"{_loc(filename, lineno)}: loop variable {n!r} is not "
            "defined before this tensor-dependent loop and is read "
            "before assignment in the body; lax.while_loop needs an "
            "initial value for every variable assigned in the body")
        raise err from cause

    try:
        probe = jax.eval_shape(
            lambda: body_fn(*_copy_containers(tuple(init))))
    except Dy2StaticError:
        raise
    except Exception as e:
        undef = [n for n, v in zip(names, init) if v is UNDEFINED]
        fail(undef[0] if undef else "?", e)
    out = list(init)
    for i, (n, v) in enumerate(zip(names, init)):
        if v is not UNDEFINED:
            continue
        p = probe[i]
        if p is UNDEFINED:
            fail(n)
        if not (hasattr(p, "shape") and hasattr(p, "dtype")):
            fail(n)
        out[i] = jnp.zeros(p.shape, p.dtype)
    return tuple(out)


def init_loop_var(cur, fallback):
    """Give a converted for-loop's target a typed initial carry value
    (the range start) while preserving a pre-existing binding (Python
    keeps the prior value when the range is empty)."""
    return fallback if cur is UNDEFINED else cur


def is_tensor(x):
    """Runtime dispatch for `for v in X`: jax arrays (incl. tracers) take
    the staged row-loop, everything else the plain Python loop."""
    return isinstance(x, jax.Array)


def tensor_len(x, filename="<dy2static>", lineno=0):
    """Leading-axis length of a tensor — static under trace."""
    if not getattr(x, "shape", ()):
        raise Dy2StaticError(
            f"{_loc(filename, lineno)}: cannot iterate a 0-d tensor in a "
            "converted function")
    return x.shape[0]


def row_init(x):
    """Typed pre-loop init for the row variable of a staged
    `for v in tensor` (while_loop needs an initial value for every
    body-assigned name; the first iteration overwrites it)."""
    return jnp.zeros(x.shape[1:], x.dtype)


def row_at(x, i):
    """x[i] made trace-safe for a 0-row tensor: the staged loop body is
    TRACED even when the (static) trip count is zero, and indexing a
    size-0 axis raises at trace time although the body never runs."""
    if x.shape[0] == 0:
        return jnp.zeros(x.shape[1:], x.dtype)
    return x[i]


def normalize_range(*args):
    if len(args) == 1:
        return 0, args[0], 1
    if len(args) == 2:
        return args[0], args[1], 1
    return args[0], args[1], args[2]


def range_cond(i, stop, step):
    if _is_tracer(step):
        return jnp.where(step > 0, i < stop, i > stop)
    return (i < stop) if step > 0 else (i > stop)


def convert_logical_and(lhs, rhs_fn):
    if _is_tracer(lhs):
        return jnp.logical_and(lhs, rhs_fn())
    return lhs and rhs_fn()


def convert_logical_or(lhs, rhs_fn):
    if _is_tracer(lhs):
        return jnp.logical_or(lhs, rhs_fn())
    return lhs or rhs_fn()


def convert_logical_not(x):
    if _is_tracer(x):
        return jnp.logical_not(x)
    return not x


def convert_print(*args, **kwargs):
    if any(_is_tracer(a) for a in args):
        fmt = " ".join("{}" for _ in args)
        jax.debug.print(fmt, *args)
        return None
    return print(*args, **kwargs)


def convert_assert(test, msg_fn, filename="<dy2static>", lineno=0):
    """`assert` conversion (reference: convert_operators.convert_assert
    -> Assert op). A tensor condition becomes a host callback that
    raises when violated — checked at RUN time like the reference's
    graph Assert, not silently dropped at trace time. ``msg_fn`` is a
    thunk: Python only evaluates an assert message on FAILURE (the
    message expression may be invalid on the passing path)."""
    if not _is_tracer(test):
        if not test:
            msg = msg_fn() if msg_fn is not None else None
            raise AssertionError(msg if msg is not None else
                                 f"{_loc(filename, lineno)}: assertion "
                                 f"failed")
        return
    # the message must be evaluated NOW if it is ever to appear (the
    # callback outlives the trace), but only cheaply-formattable values
    # survive; failures inside the thunk fall back to the bare location
    try:
        msg = msg_fn() if msg_fn is not None else None
    except Exception:
        msg = None

    def check(ok):
        if not bool(ok):
            raise AssertionError(
                f"{_loc(filename, lineno)}: traced assertion failed"
                + (f": {msg}" if msg is not None else ""))

    jax.debug.callback(check, jnp.all(test))


_CALL_SKIP_MODULES = ("builtins", "jax", "numpy", "paddle_tpu", "functools",
                      "itertools", "operator", "math", "typing", "abc",
                      "collections", "copy", "warnings")
# bounded LRU: a nested `def` creates a fresh function object per call
# of its parent, so an unbounded cache would pin every instance (plus
# its closure snapshot — weak keys don't work either: the converted
# function's __wrapped__ back-reference would keep the key alive)
from collections import OrderedDict as _OrderedDict  # noqa: E402

_converted_cache: "_OrderedDict" = _OrderedDict()
_CACHE_CAP = 256


def convert_call(fn):
    """Recursive conversion of user callees (reference:
    convert_call_func.py convert_call): plain user functions/methods get
    the same AST pass (cached), library/builtin callables pass through
    untouched, so control flow inside helpers called from a converted
    function stages too."""
    if not callable(fn) or isinstance(fn, type):
        return fn
    inner = fn.__func__ if inspect.ismethod(fn) else fn
    if not inspect.isfunction(inner):
        return fn  # builtins, callables, layers: leave as-is
    if getattr(inner, "_dy2s_converted", False) or \
            getattr(inner, "_dy2s_is_conversion", False):
        return fn
    module = getattr(inner, "__module__", "") or ""
    if module.split(".")[0] in _CALL_SKIP_MODULES:
        return fn
    key = id(inner)
    cached = _converted_cache.get(key)
    if cached is not None and cached[0] is inner:
        _converted_cache.move_to_end(key)
        conv = cached[1]
    else:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # quiet fallback for callees
            conv = convert_function(inner)
        _converted_cache[key] = (inner, conv)
        _converted_cache.move_to_end(key)
        while len(_converted_cache) > _CACHE_CAP:
            _converted_cache.popitem(last=False)
    if inspect.ismethod(fn):
        return functools.partial(conv, fn.__self__)
    return conv


def assert_python_value(value, construct, filename="<dy2static>", lineno=0):
    """Guard for statements left in Python form because they contain
    constructs lax cannot stage (return/break/continue, or a for-loop that
    reassigns its own loop variable)."""
    if _is_tracer(value):
        raise Dy2StaticError(
            f"{_loc(filename, lineno)}: this `{construct}` contains "
            "return/break/continue (or reassigns its loop variable), which "
            "cannot be staged into a lax-converted control-flow op, but "
            "its condition depends on a traced tensor. Restructure to "
            "avoid early exits (accumulate a result and return after the "
            "statement), or hoist the decision out of the jitted function.")
    return value


# ---------------------------------------------------------------------------
# AST transformation
# ---------------------------------------------------------------------------
_JST = "__jst__"


def _assigned_names(stmts):
    """Names bound in the statement list — Store names, import aliases,
    nested def/class names — excluding nested function/class BODIES and
    comprehensions (their own scope in py3)."""
    names = []

    def add(n):
        if n not in names:
            names.append(n)

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            if not node.name.startswith("__dy2s_"):
                add(node.name)  # the binding, not the body's scope

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_ClassDef(self, node):
            add(node.name)

        def visit_Lambda(self, node):
            pass

        visit_ListComp = visit_Lambda
        visit_SetComp = visit_Lambda
        visit_DictComp = visit_Lambda
        visit_GeneratorExp = visit_Lambda

        def visit_Import(self, node):
            for a in node.names:
                add(a.asname or a.name.split(".")[0])

        def visit_ImportFrom(self, node):
            for a in node.names:
                add(a.asname or a.name)

        def visit_Name(self, node):
            # __dy2s_* are this pass's own temporaries (inner converted
            # loops' induction/cond/body names): capturing them as branch
            # variables of an ENCLOSING converted statement would demand
            # they match across branches, which they never do
            if isinstance(node.ctx, ast.Store) and \
                    not node.id.startswith("__dy2s_"):
                add(node.id)

    v = V()
    for s in stmts:
        v.visit(s)
    return names


_MUTATOR_METHODS = {"append", "extend", "insert", "pop", "popitem",
                    "remove", "clear", "update", "setdefault", "add",
                    "discard", "sort", "reverse"}


def _mutated_names(stmts):
    """Names whose CONTENTS a statement list may mutate in place —
    container method calls (x.append(...)) and subscript stores
    (x[i] = ..., del x[i]). These carry no ast.Store, but a tensor-`if`
    branch mutating them must thread them through convert_ifelse like
    any assigned name, or the mutation leaks branch-local tracers."""
    names = []

    def add(n):
        if n not in names and not n.startswith("__dy2s_"):
            names.append(n)

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            pass  # own scope

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_ClassDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

        def visit_Call(self, node):
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.attr in _MUTATOR_METHODS:
                add(f.value.id)
            self.generic_visit(node)

        def _sub_target(self, t):
            if isinstance(t, ast.Subscript) and \
                    isinstance(t.value, ast.Name):
                add(t.value.id)

        def visit_Assign(self, node):
            for t in node.targets:
                self._sub_target(t)
                if isinstance(t, ast.Tuple):
                    for e in t.elts:
                        self._sub_target(e)
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            self._sub_target(node.target)
            self.generic_visit(node)

        def visit_Delete(self, node):
            for t in node.targets:
                self._sub_target(t)
            self.generic_visit(node)

    v = V()
    for s in stmts:
        v.visit(s)
    return names


def _attr_mutations(stmts):
    """Dotted display names of ATTRIBUTE-held containers a statement
    list mutates in place (obj.attr.append(...), obj.attr[i] = ...).
    These cannot be threaded through staged branches like local names —
    convert_ifelse raises a located diagnostic when the condition is a
    tensor (they would otherwise mutate unconditionally at trace time)."""
    out = []

    def add(node):
        try:
            s = ast.unparse(node)
        except Exception:  # pragma: no cover
            s = "<attribute>"
        if s not in out:
            out.append(s)

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_ClassDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

        def visit_Call(self, node):
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Attribute) and \
                    f.attr in _MUTATOR_METHODS:
                add(f.value)
            self.generic_visit(node)

        def _sub_target(self, t):
            if isinstance(t, ast.Subscript) and \
                    isinstance(t.value, ast.Attribute):
                add(t.value)

        def visit_Assign(self, node):
            for t in node.targets:
                self._sub_target(t)
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            self._sub_target(node.target)
            self.generic_visit(node)

        def visit_Delete(self, node):
            for t in node.targets:
                self._sub_target(t)
            self.generic_visit(node)

    v = V()
    for s in stmts:
        v.visit(s)
    return out


def _loaded_names(node):
    out = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            out.add(n.id)
    return out


def _has_exits(stmts):
    """Exits that would escape THIS statement: returns anywhere (except
    nested defs), break/continue not owned by a nested loop."""
    found = []

    class Returns(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

        def visit_Return(self, node):
            found.append("return")

    class V(Returns):
        def visit_While(self, node):
            # a nested loop owns break/continue in its BODY; its else
            # clause's break/continue (and all returns) escape to us
            r = Returns()
            for s in node.body:
                r.visit(s)
            for s in node.orelse:
                self.visit(s)

        visit_For = visit_While

        def visit_Break(self, node):
            found.append("break")

        def visit_Continue(self, node):
            found.append("continue")

    v = V()
    for s in stmts:
        v.visit(s)
    return found


def _rewrite_exits(stmts, brk, cont):
    """Lower this loop's OWN break/continue into flag assignments
    (``brk``/``cont`` = True); statements following a possibly-flagging
    If are wrapped in ``if not (brk or cont): ...`` so the rest of the
    iteration is skipped. Nested loops own their exits and are left
    alone; code after a bare break/continue is unreachable and dropped.
    Returns the new statement list."""
    def flag_set(name):
        return ast.Assign(targets=[_name(name, ast.Store())],
                          value=_const(True))

    def skip_guard(rest):
        test = ast.UnaryOp(
            op=ast.Not(),
            operand=ast.BoolOp(op=ast.Or(),
                               values=[_name(brk), _name(cont)]))
        return ast.If(test=test, body=rest, orelse=[])

    out = []
    for i, s in enumerate(stmts):
        if isinstance(s, ast.Break):
            out.append(ast.copy_location(flag_set(brk), s))
            return out                      # rest of block unreachable
        if isinstance(s, ast.Continue):
            out.append(ast.copy_location(flag_set(cont), s))
            return out
        if isinstance(s, ast.If):
            body = _rewrite_exits(s.body, brk, cont)
            orelse = _rewrite_exits(s.orelse, brk, cont)
            flagged = (body != s.body or orelse != s.orelse)
            s = ast.copy_location(
                ast.If(test=s.test, body=body or [ast.Pass()],
                       orelse=orelse), s)
            ast.fix_missing_locations(s)
            out.append(s)
            rest = stmts[i + 1:]
            if flagged and rest:
                g = skip_guard(_rewrite_exits(rest, brk, cont))
                ast.copy_location(g, s)
                ast.fix_missing_locations(g)
                out.append(g)
                return out
            continue
        out.append(s)
    return out


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _jst_attr(attr):
    return ast.Attribute(value=_name(_JST), attr=attr, ctx=ast.Load())


def _call(fn_attr, args, keywords=None):
    return ast.Call(func=_jst_attr(fn_attr), args=args,
                    keywords=keywords or [])


def _const(v):
    return ast.Constant(value=v)


def _undef_guard(name):
    # vN = locals().get("vN", __jst__.UNDEFINED)
    return ast.Assign(
        targets=[_name(name, ast.Store())],
        value=ast.Call(
            func=ast.Attribute(
                value=ast.Call(func=_name("locals"), args=[], keywords=[]),
                attr="get", ctx=ast.Load()),
            args=[_const(name), _jst_attr("UNDEFINED")], keywords=[]))


def _tuple_of(names, ctx=None):
    return ast.Tuple(elts=[_name(n, ctx or ast.Load()) for n in names],
                     ctx=ctx or ast.Load())


def _fn_def(name, argnames, body):
    return ast.FunctionDef(
        name=name,
        args=ast.arguments(posonlyargs=[], args=[ast.arg(arg=a)
                                                 for a in argnames],
                           kwonlyargs=[], kw_defaults=[], defaults=[]),
        body=body, decorator_list=[], returns=None, type_params=[])


class _Transformer(ast.NodeTransformer):
    def __init__(self, filename):
        self.filename = filename
        self.counter = 0

    def _n(self, base):
        self.counter += 1
        return f"__dy2s_{base}_{self.counter}"

    # -- boolean ops (lazy, tensor-aware) ----------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        conv = ("convert_logical_and" if isinstance(node.op, ast.And)
                else "convert_logical_or")
        out = node.values[-1]
        for lhs in reversed(node.values[:-1]):
            out = _call(conv, [lhs, ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=out)])
        return ast.copy_location(out, node)

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.copy_location(
                _call("convert_logical_not", [node.operand]), node)
        return node

    # calls whose semantics depend on the calling frame or that the
    # converters/builtins already handle — never rerouted
    _CALL_SKIP_NAMES = {"locals", "globals", "vars", "super", "eval",
                        "exec", "print", "range", "enumerate", "zip",
                        "len", "isinstance", "issubclass", "getattr",
                        "setattr", "hasattr", "type", "id", "iter",
                        "next", "min", "max", "abs", "sum", "sorted",
                        "list", "tuple", "dict", "set", "int", "float",
                        "bool", "str", "repr", "format", "breakpoint"}

    def visit_Call(self, node):
        self.generic_visit(node)
        if isinstance(node.func, ast.Name) and node.func.id == "print" \
                and not node.keywords:
            return ast.copy_location(
                _call("convert_print", node.args), node)
        # recursive callee conversion (reference convert_call): wrap the
        # callable so user helpers with control flow stage too
        f = node.func
        skip = (isinstance(f, ast.Name)
                and (f.id in self._CALL_SKIP_NAMES
                     or f.id.startswith(("__dy2s_", "_dy2s_")))) or \
            (isinstance(f, ast.Attribute)
             and isinstance(f.value, ast.Name)
             and f.value.id == _JST)
        if not skip:
            node.func = ast.copy_location(
                _call("convert_call", [f]), f)
        return node

    def visit_Assert(self, node):
        self.generic_visit(node)
        # message as a thunk: Python evaluates it only on failure
        msg = (_const(None) if node.msg is None else ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            body=node.msg))
        return ast.copy_location(ast.Expr(value=_call(
            "convert_assert",
            [node.test, msg, _const(self.filename),
             _const(node.lineno)])), node)

    # -- if / while / for ---------------------------------------------------
    def visit_If(self, node):
        # mutation patterns (x.append / x[i]=) must be read off the RAW
        # body: generic_visit reroutes calls through convert_call and
        # hides them
        mutated = (_mutated_names(node.body) +
                   _mutated_names(node.orelse))
        attr_muts = (_attr_mutations(node.body) +
                     _attr_mutations(node.orelse))
        self.generic_visit(node)
        exits = _has_exits(node.body) + _has_exits(node.orelse)
        if exits:
            # leave in Python form, but fail loudly (with location) if the
            # condition turns out to be a tracer
            node.test = ast.copy_location(
                _call("assert_python_value",
                      [node.test, _const("if"), _const(self.filename),
                       _const(node.lineno)]), node.test)
            return node
        names = sorted(set(_assigned_names(node.body) +
                           _assigned_names(node.orelse) + mutated))
        tf, ff = self._n("true_fn"), self._n("false_fn")
        ret = ast.Return(value=_tuple_of(names))
        stmts = [_undef_guard(n) for n in names]
        stmts.append(_fn_def(tf, names, list(node.body) + [ret]))
        stmts.append(_fn_def(ff, names, (list(node.orelse) or [ast.Pass()])
                             + [ret]))
        assign = ast.Assign(
            targets=[_tuple_of(names, ast.Store())] if names else
                    [_name(self._n("void"), ast.Store())],
            value=_call("convert_ifelse",
                        [node.test, _name(tf), _name(ff), _tuple_of(names),
                         ast.Tuple(elts=[_const(n) for n in names],
                                   ctx=ast.Load()),
                         _const(self.filename), _const(node.lineno),
                         ast.Tuple(elts=[_const(a) for a in attr_muts],
                                   ctx=ast.Load())]))
        stmts.append(assign)
        return [ast.copy_location(ast.fix_missing_locations(s), node)
                for s in stmts]

    def _while_form(self, node, test_expr, body_stmts, extra_loop_names=()):
        names = sorted(set(_assigned_names(body_stmts))
                       | set(extra_loop_names)
                       | (_loaded_names(test_expr)
                          & set(_assigned_names(body_stmts))))
        cf, bf = self._n("while_cond"), self._n("while_body")
        stmts = [_undef_guard(n) for n in names]
        stmts.append(_fn_def(cf, names, [ast.Return(value=test_expr)]))
        stmts.append(_fn_def(
            bf, names, list(body_stmts) + [ast.Return(value=_tuple_of(names))]))
        assign = ast.Assign(
            targets=[_tuple_of(names, ast.Store())] if names else
                    [_name(self._n("void"), ast.Store())],
            value=_call("convert_while",
                        [_name(cf), _name(bf), _tuple_of(names),
                         ast.Tuple(elts=[_const(n) for n in names],
                                   ctx=ast.Load()),
                         _const(self.filename), _const(node.lineno)]))
        stmts.append(assign)
        return [ast.copy_location(ast.fix_missing_locations(s), node)
                for s in stmts]

    def _lower_loop_exits(self, node):
        """Try to lower break/continue in a raw (pre-visit) loop body into
        flag form. Returns (new_body, new_test_wrapper, setup_stmts) or
        None when lowering doesn't apply (returns present, or exits
        hiding where the rewriter can't reach, e.g. under with/try)."""
        exits = _has_exits(node.body)
        if not exits:
            return None
        if "return" in exits:
            return None
        # single-underscore prefix: unlike __dy2s_* temporaries, the flags
        # MUST be visible to _assigned_names so convert_ifelse branches
        # and the while state thread them through
        self.counter += 1
        brk = f"_dy2s_brk_{self.counter}"
        self.counter += 1
        cont = f"_dy2s_cont_{self.counter}"
        body = _rewrite_exits(list(node.body), brk, cont)
        if _has_exits(body):
            return None
        false_c = _const(False)
        reset_cont = ast.Assign(targets=[_name(cont, ast.Store())],
                                value=false_c)
        init = [ast.Assign(targets=[_name(f, ast.Store())], value=false_c)
                for f in (brk, cont)]
        for s in init + [reset_cont]:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)

        def wrap_test(test):
            t = ast.BoolOp(op=ast.And(),
                           values=[ast.UnaryOp(op=ast.Not(),
                                               operand=_name(brk)), test])
            ast.copy_location(t, test)
            return ast.fix_missing_locations(t)

        return [reset_cont] + body, wrap_test, init

    def visit_While(self, node):
        if node.orelse:
            self.generic_visit(node)
            return node  # while/else: Python-only construct, leave as-is
        mutated = _mutated_names(node.body)  # raw body (see visit_If)
        setup = []
        lowered = self._lower_loop_exits(node)
        if lowered is not None:
            body, wrap_test, setup = lowered
            node = ast.copy_location(
                ast.While(test=wrap_test(node.test), body=body, orelse=[]),
                node)
            ast.fix_missing_locations(node)
        self.generic_visit(node)
        if _has_exits(node.body):
            node.test = ast.copy_location(
                _call("assert_python_value",
                      [node.test, _const("while"), _const(self.filename),
                       _const(node.lineno)]), node.test)
            return setup + [node] if setup else node
        return setup + self._while_form(node, node.test, node.body,
                                        extra_loop_names=tuple(mutated))

    def _rewrite_tensor_loop(self, node, targets, sources, index=None,
                             mode="iter"):
        """Shared dual-form builder for `for v in X`, `for i, v in
        enumerate(X)` and `for a, b[, c] in zip(X, Y[, Z])`:

            __x_j = SOURCE_j ...
            if is_tensor(__x_0) [and ...]:
                t_j = init_loop_var(prior_or_UNDEFINED, row_init(__x_j))
                [i = init_loop_var(prior, 0)]
                for __row in range(min(tensor_len(__x_j, ...), ...)):
                    [i = __row]; t_j = __x_j[__row] ...; BODY
            else:
                for <original targets> in <original form over __x_j>: BODY

        Both copies are then transformed normally (the Python copy is
        marked to stop re-rewriting). The init_loop_var wrapper keeps a
        pre-existing binding when the leading dim is 0, matching Python's
        empty-loop semantics (same contract as the range path).

        Note: each nested non-range loop doubles its body (tensor +
        Python branch) — 2^k copies at nesting depth k. Acceptable for
        realistic nesting; revisit if it ever bites.
        """
        import copy as _copy
        xs = [self._n("iterable") for _ in sources]
        row = self._n("row")
        assigns = [ast.Assign(targets=[_name(x, ast.Store())], value=src)
                   for x, src in zip(xs, sources)]

        def keep_prior(name, fallback):
            prior = ast.Call(
                func=ast.Attribute(
                    value=ast.Call(func=_name("locals"), args=[],
                                   keywords=[]),
                    attr="get", ctx=ast.Load()),
                args=[_const(name), _jst_attr("UNDEFINED")], keywords=[])
            return ast.Assign(
                targets=[ast.Name(id=name, ctx=ast.Store())],
                value=_call("init_loop_var", [prior, fallback]))

        inits = [keep_prior(t, _call("row_init", [_name(x)]))
                 for t, x in zip(targets, xs)]
        sets = [ast.Assign(
            targets=[ast.Name(id=t, ctx=ast.Store())],
            value=_call("row_at", [_name(x), _name(row)]))
            for t, x in zip(targets, xs)]
        if index is not None:
            inits.insert(0, keep_prior(index, _const(0)))
            sets.insert(0, ast.Assign(
                targets=[ast.Name(id=index, ctx=ast.Store())],
                value=_name(row)))
        lens = [_call("tensor_len", [_name(x), _const(self.filename),
                                     _const(node.lineno)]) for x in xs]
        bound = lens[0] if len(lens) == 1 else ast.Call(
            func=ast.Name(id="min", ctx=ast.Load()), args=lens,
            keywords=[])
        tensor_for = ast.For(
            target=_name(row, ast.Store()),
            iter=ast.Call(func=ast.Name(id="range", ctx=ast.Load()),
                          args=[bound], keywords=[]),
            body=sets + _copy.deepcopy(node.body), orelse=[],
            type_comment=None)
        if mode == "iter":
            py_iter = _name(xs[0])
        else:
            py_iter = ast.Call(func=ast.Name(id=mode, ctx=ast.Load()),
                               args=[_name(x) for x in xs], keywords=[])
        python_for = ast.For(target=node.target, iter=py_iter,
                             body=node.body, orelse=[], type_comment=None)
        python_for._dy2s_plain = True
        test = _call("is_tensor", [_name(xs[0])])
        for x in xs[1:]:
            test = ast.BoolOp(op=ast.And(),
                              values=[test, _call("is_tensor", [_name(x)])])
        dispatch = ast.If(test=test, body=inits + [tensor_for],
                          orelse=[python_for])
        out = []
        for s in assigns + [dispatch]:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
            v = self.visit(s)
            out.extend(v if isinstance(v, list) else [v])
        return out

    def visit_For(self, node):
        mutated = _mutated_names(node.body)  # raw body (see visit_If)
        setup_exits = []
        test_wrap = None
        is_range_call = (isinstance(node.iter, ast.Call)
                         and isinstance(node.iter.func, ast.Name)
                         and node.iter.func.id == "range")
        if (isinstance(node.target, ast.Tuple) and not node.orelse
                and len(node.target.elts) == 2
                and all(isinstance(e, ast.Name) for e in node.target.elts)
                and not getattr(node, "_dy2s_plain", False)
                and isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Name)
                and node.iter.func.id == "enumerate"
                and len(node.iter.args) == 1 and not node.iter.keywords):
            return self._rewrite_tensor_loop(
                node, targets=[node.target.elts[1].id],
                sources=[node.iter.args[0]],
                index=node.target.elts[0].id, mode="enumerate")
        if (isinstance(node.target, ast.Tuple) and not node.orelse
                and len(node.target.elts) in (2, 3)
                and all(isinstance(e, ast.Name) for e in node.target.elts)
                and not getattr(node, "_dy2s_plain", False)
                and isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Name)
                and node.iter.func.id == "zip"
                and len(node.iter.args) == len(node.target.elts)
                and not node.iter.keywords):
            return self._rewrite_tensor_loop(
                node, targets=[e.id for e in node.target.elts],
                sources=list(node.iter.args), mode="zip")
        if (isinstance(node.target, ast.Name) and not node.orelse
                and not is_range_call
                and not getattr(node, "_dy2s_plain", False)
                and not isinstance(node.iter, (ast.List, ast.Tuple,
                                               ast.Dict, ast.Set))):
            return self._rewrite_tensor_loop(
                node, targets=[node.target.id], sources=[node.iter],
                mode="iter")
        if (isinstance(node.target, ast.Name) and not node.orelse
                and isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Name)
                and node.iter.func.id == "range"):
            lowered = self._lower_loop_exits(node)
            if lowered is not None:
                body, test_wrap, setup_exits = lowered
                node = ast.copy_location(
                    ast.For(target=node.target, iter=node.iter, body=body,
                            orelse=[], type_comment=None), node)
                ast.fix_missing_locations(node)
        self.generic_visit(node)
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and not node.iter.keywords
                    and isinstance(node.target, ast.Name)
                    # a body that reassigns the loop variable would corrupt
                    # the while-form induction (Python's range reassigns it
                    # fresh each iteration): leave such loops in Python
                    and node.target.id not in _assigned_names(node.body))
        if not is_range or node.orelse or _has_exits(node.body):
            if isinstance(node.iter, ast.Call) and \
                    isinstance(node.iter.func, ast.Name) and \
                    node.iter.func.id == "range" and not node.iter.keywords:
                node.iter.args = [ast.copy_location(
                    _call("assert_python_value",
                          [a, _const("for"), _const(self.filename),
                           _const(node.lineno)]), a)
                    for a in node.iter.args]
            if setup_exits:
                # lowered flag form still runs correctly in Python, but it
                # needs its not-yet-staged test guard: reinstate a plain
                # break on the flag at body top
                node.body.insert(0, ast.copy_location(
                    ast.fix_missing_locations(ast.If(
                        test=self.visit(ast.Name(
                            id=setup_exits[0].targets[0].id,
                            ctx=ast.Load())),
                        body=[ast.Break()], orelse=[])), node))
                return setup_exits + [node]
            return node
        t = node.target.id
        start_n, stop_n, step_n, it_n = (self._n("start"), self._n("stop"),
                                         self._n("step"), self._n("it"))
        setup = [
            ast.Assign(
                targets=[ast.Tuple(elts=[_name(start_n, ast.Store()),
                                         _name(stop_n, ast.Store()),
                                         _name(step_n, ast.Store())],
                                   ctx=ast.Store())],
                value=_call("normalize_range", list(node.iter.args))),
            ast.Assign(targets=[_name(it_n, ast.Store())],
                       value=_name(start_n)),
            # typed pre-loop init for the target (keeps a prior binding)
            ast.Assign(
                targets=[_name(t, ast.Store())],
                value=_call("init_loop_var", [
                    ast.Call(
                        func=ast.Attribute(
                            value=ast.Call(func=_name("locals"), args=[],
                                           keywords=[]),
                            attr="get", ctx=ast.Load()),
                        args=[_const(t), _jst_attr("UNDEFINED")],
                        keywords=[]),
                    _name(it_n)])),
        ]
        setup = [ast.copy_location(ast.fix_missing_locations(s), node)
                 for s in setup]
        # hidden induction variable: the USER-visible target is assigned at
        # body start and keeps its last-iteration value after the loop
        # (Python range semantics), instead of leaking the post-increment
        test = _call("range_cond", [_name(it_n), _name(stop_n),
                                    _name(step_n)])
        if test_wrap is not None:
            # break support: test becomes (not brk) and range_cond(...);
            # re-visit so the BoolOp/Not lower to the convert_* helpers
            test = self.visit(ast.fix_missing_locations(
                ast.copy_location(test_wrap(test), node)))
        set_t = ast.Assign(targets=[_name(t, ast.Store())],
                           value=_name(it_n))
        inc = ast.AugAssign(target=_name(it_n, ast.Store()), op=ast.Add(),
                            value=_name(step_n))
        return setup_exits + setup + self._while_form(
            node, test, [set_t] + list(node.body) + [inc],
            extra_loop_names=(it_n, t) + tuple(mutated))


class _GlobalsProxy(dict):
    """exec namespace that falls through to the function's LIVE module
    globals: names defined later in the module (helpers below the decorated
    function, monkeypatched globals, self-recursion) keep working. CPython
    supports dict subclasses with __missing__ as exec globals."""

    def __init__(self, live, extra):
        super().__init__(extra)
        self._live = live

    def __missing__(self, key):
        return self._live[key]


def convert_function(fn):
    """AST-convert `fn` (reference: program_translator StaticFunction).
    Falls back to the original function (with a warning) when the source
    is unavailable (builtins, REPL lambdas, already-compiled code) or the
    function needs a __class__ cell (zero-arg super())."""
    if "__class__" in fn.__code__.co_freevars:
        warnings.warn(
            f"dy2static: {fn.__qualname__} uses zero-arg super() — the "
            "__class__ cell cannot be rebuilt through recompilation; "
            "running without AST conversion")
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        filename = inspect.getsourcefile(fn) or "<dy2static>"
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError) as e:
        warnings.warn(f"dy2static: cannot convert {fn!r} ({e}); running "
                      "without AST conversion")
        return fn
    # diagnostics and tracebacks must point at the real file lines
    ast.increment_lineno(tree, fn.__code__.co_firstlineno - 1)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        warnings.warn(f"dy2static: {fn!r} is not a plain function; running "
                      "without AST conversion")
        return fn
    if any(isinstance(n, (ast.Global, ast.Nonlocal))
           for n in ast.walk(fdef)):
        # global/nonlocal stores would land in the exec proxy (or a
        # generated branch fn's locals), silently diverging from the
        # original's side effects — decline rather than corrupt
        warnings.warn(
            f"dy2static: {fn.__qualname__} uses global/nonlocal "
            "declarations; running without AST conversion")
        return fn
    fdef.decorator_list = []  # don't re-apply @to_static etc.
    try:
        _Transformer(filename).visit(fdef)
        ast.fix_missing_locations(tree)
        code = compile(tree, filename=filename, mode="exec")
    except Exception as e:  # a transformer defect must degrade, not crash
        warnings.warn(f"dy2static: AST conversion of {fn.__qualname__} "
                      f"failed ({type(e).__name__}: {e}); running without "
                      "conversion")
        return fn
    import paddle_tpu.jit.dy2static as _self
    extra = {_JST: _self}
    if fn.__closure__:
        # re-bind free variables by value (cells cannot be carried through
        # recompilation; late rebinding of closed-over names is not
        # supported — the reference has the same snapshot semantics)
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                extra[name] = cell.cell_contents
            except ValueError:
                pass
    # the import machinery reads module-context dunders with dict.get
    # (which bypasses __missing__): seed them into the proxy's own storage
    for dunder in ("__name__", "__package__", "__loader__", "__spec__",
                   "__builtins__"):
        if dunder in fn.__globals__:
            extra.setdefault(dunder, fn.__globals__[dunder])
    namespace = _GlobalsProxy(fn.__globals__, extra)
    exec(code, namespace)
    new_fn = namespace[fdef.name]
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    functools.update_wrapper(new_fn, fn, updated=[])
    new_fn.__wrapped__ = fn
    new_fn._dy2s_converted = True  # convert_call must not re-convert
    return new_fn
