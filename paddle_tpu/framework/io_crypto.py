"""Model encryption for save/load.

Reference: paddle/fluid/framework/io/crypto/ (C35 in SURVEY.md §2) —
``CipherFactory``/``AESCipher`` (AES-GCM, cipher.cc) encrypting serialized
programs/params so models at rest are unreadable without the key.

Primary construction: **AES-256-GCM** via the ``cryptography`` package when
importable (it is in this image) — same cipher family as the reference's
AESCipher. Fallback when ``cryptography`` is absent: a pure-stdlib
authenticated stream cipher (SHAKE-256 keystream, HMAC-SHA256 tag,
encrypt-then-MAC), keystream per 64MB chunk (SHAKE-256 over
key||nonce||chunk_offset — offset domain separation) XORed via numpy.
Formats (self-describing by magic; decrypt reads both):
``PTPUENC3 || nonce(12) || ct+tag`` (AES-GCM) and
``PTPUENC2 || nonce(16) || ciphertext || tag(32)`` (SHAKE fallback).
"""
from __future__ import annotations

import hashlib
import hmac
import os

__all__ = ["Cipher", "CipherFactory", "encrypt_bytes", "decrypt_bytes",
           "encrypt_file", "decrypt_file"]

_MAGIC_GCM = b"PTPUENC3"  # v3: AES-256-GCM (reference-parity cipher)
_MAGIC = b"PTPUENC2"  # v2: chunked offset-keyed SHAKE keystream (fallback)
_MAGIC_V1 = b"PTPUENC1"  # pre-release whole-buffer keystream (unsupported)
_NONCE = 16
_GCM_NONCE = 12
_TAG = 32


def _aesgcm():
    """AESGCM class when ``cryptography`` is importable, else None."""
    try:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
        return AESGCM
    except ImportError:
        return None


_CHUNK = 64 * 1024 * 1024


def _xor_stream(data: bytes, key: bytes, nonce: bytes) -> bytes:
    """XOR ``data`` with the SHAKE-256 keystream in bounded chunks: numpy
    bitwise_xor per 64MB block keeps peak memory ~1 chunk above the output
    (a whole-buffer big-int XOR would peak at ~5x the plaintext)."""
    import numpy as _np
    out = bytearray(len(data))
    view = memoryview(data)
    for off in range(0, len(data), _CHUNK):
        block = view[off:off + _CHUNK]
        ks = hashlib.shake_256(
            key + nonce + off.to_bytes(8, "little")).digest(len(block))
        out[off:off + _CHUNK] = _np.bitwise_xor(
            _np.frombuffer(block, dtype=_np.uint8),
            _np.frombuffer(ks, dtype=_np.uint8)).tobytes()
    return bytes(out)


def _derive(key: bytes, purpose: bytes) -> bytes:
    return hmac.new(key, purpose, hashlib.sha256).digest()


def encrypt_bytes(data: bytes, key: bytes) -> bytes:
    AESGCM = _aesgcm()
    if AESGCM is not None:
        nonce = os.urandom(_GCM_NONCE)
        ct = AESGCM(_derive(key, b"aes")).encrypt(nonce, data, _MAGIC_GCM)
        return _MAGIC_GCM + nonce + ct
    nonce = os.urandom(_NONCE)
    enc_key = _derive(key, b"enc")
    mac_key = _derive(key, b"mac")
    ct = _xor_stream(data, enc_key, nonce)
    tag = hmac.new(mac_key, nonce + ct, hashlib.sha256).digest()
    return _MAGIC + nonce + ct + tag


def decrypt_bytes(blob: bytes, key: bytes) -> bytes:
    if blob.startswith(_MAGIC_GCM):
        AESGCM = _aesgcm()
        if AESGCM is None:
            raise ValueError(
                "blob is AES-GCM encrypted but the 'cryptography' package "
                "is not importable in this environment")
        nonce = blob[len(_MAGIC_GCM):len(_MAGIC_GCM) + _GCM_NONCE]
        ct = blob[len(_MAGIC_GCM) + _GCM_NONCE:]
        try:
            return AESGCM(_derive(key, b"aes")).decrypt(nonce, ct,
                                                        _MAGIC_GCM)
        except Exception:
            raise ValueError(
                "decryption failed: wrong key or corrupted data") from None
    if blob.startswith(_MAGIC_V1):
        # v1 used a different keystream derivation; XORing with the v2
        # stream would return garbage that still passes the (ciphertext)
        # MAC — fail loudly instead
        raise ValueError(
            "blob uses the pre-release PTPUENC1 format, which this version "
            "no longer decrypts — re-encrypt with the current release")
    if not blob.startswith(_MAGIC):
        raise ValueError("not an encrypted paddle_tpu blob")
    nonce = blob[len(_MAGIC):len(_MAGIC) + _NONCE]
    ct = blob[len(_MAGIC) + _NONCE:-_TAG]
    tag = blob[-_TAG:]
    mac_key = _derive(key, b"mac")
    expect = hmac.new(mac_key, nonce + ct, hashlib.sha256).digest()
    if not hmac.compare_digest(tag, expect):
        raise ValueError("decryption failed: wrong key or corrupted data")
    enc_key = _derive(key, b"enc")
    return _xor_stream(ct, enc_key, nonce)


def encrypt_file(src: str, dst: str, key: bytes):
    with open(src, "rb") as f:
        data = f.read()
    with open(dst, "wb") as f:
        f.write(encrypt_bytes(data, key))


def decrypt_file(src: str, dst: str, key: bytes):
    with open(src, "rb") as f:
        blob = f.read()
    with open(dst, "wb") as f:
        f.write(decrypt_bytes(blob, key))


class Cipher:
    """reference crypto/cipher.h Cipher interface."""

    def __init__(self, key: bytes = None):
        self._key = key or os.urandom(32)

    @property
    def key(self) -> bytes:
        return self._key

    def encrypt(self, plaintext: bytes) -> bytes:
        return encrypt_bytes(plaintext, self._key)

    def decrypt(self, ciphertext: bytes) -> bytes:
        return decrypt_bytes(ciphertext, self._key)

    def encrypt_to_file(self, plaintext: bytes, path: str):
        with open(path, "wb") as f:
            f.write(self.encrypt(plaintext))

    def decrypt_from_file(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return self.decrypt(f.read())


class CipherFactory:
    """reference crypto/cipher_factory — key management helper."""

    @staticmethod
    def create_cipher(key: bytes = None) -> Cipher:
        return Cipher(key)

    @staticmethod
    def generate_key() -> bytes:
        return os.urandom(32)
