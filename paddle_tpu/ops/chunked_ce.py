"""Chunked LM-head cross entropy: hidden @ W -> softmax CE without ever
materializing the (tokens, vocab) logits tensor.

Capability target: the reference fuses softmax+CE per-op
(operators/softmax_with_cross_entropy_op.cu) but still materializes the
logits produced by the head matmul. On TPU the (B*S, V) bf16 logits of a
50k-vocab model are the single largest HBM tensor in the step (e.g.
8x1024x50304 = 824 MB written + re-read in fwd and bwd). This op scans
the vocab in chunks with an online logsumexp (flash-attention's trick
applied to the classifier): peak extra memory is O(tokens * chunk), and
the backward recomputes each chunk's logits instead of re-reading them.

The weight is sliced in place per chunk (lax.dynamic_slice) — no
(n_chunks, H, chunk) relayout of the full weight enters the scan, and
the backward accumulates dW into one fp32 buffer via
dynamic_update_slice instead of stacking per-chunk outputs.

Numerics: logits accumulate in fp32 regardless of input dtype; the
returned loss is the mean over tokens with label != ignore_index.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["chunked_lm_ce"]


def _pad_w(weight, chunk):
    """Zero-pad (H, V) to a chunk multiple. One O(H*pad) concat at most
    (pad < chunk); zero columns are masked to -inf logits downstream."""
    h, v = weight.shape
    n = -(-v // chunk)
    pad = n * chunk - v
    if pad:
        weight = jnp.concatenate(
            [weight, jnp.zeros((h, pad), weight.dtype)], axis=1)
    return weight, n, v


def _fwd_scan(hid32, wpad, labels, v, chunk, n_chunks):
    """Online LSE over vocab chunks. hid32 (N,H) fp32, wpad (H, n*chunk)."""
    n_tok = hid32.shape[0]

    def step(carry, c0):
        m, s, tgt = carry
        w_c = lax.dynamic_slice_in_dim(wpad, c0, chunk, axis=1)
        logits = hid32 @ w_c.astype(jnp.float32)             # (N, C)
        col = c0 + jnp.arange(chunk)
        logits = jnp.where(col[None, :] < v, logits, -jnp.inf)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        s = s * jnp.exp(m - m_new) + \
            jnp.exp(logits - m_new[:, None]).sum(axis=-1)
        in_chunk = (labels >= c0) & (labels < c0 + chunk)
        local = jnp.clip(labels - c0, 0, chunk - 1)
        picked = jnp.take_along_axis(logits, local[:, None],
                                     axis=1)[:, 0]
        tgt = jnp.where(in_chunk, picked, tgt)
        return (m_new, s, tgt), None

    c0s = jnp.arange(n_chunks) * chunk
    init = (jnp.full((n_tok,), -jnp.inf, jnp.float32),
            jnp.zeros((n_tok,), jnp.float32),
            jnp.zeros((n_tok,), jnp.float32))
    (m, s, tgt), _ = lax.scan(step, init, c0s)
    lse = m + jnp.log(s)
    return lse, tgt


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def chunked_lm_ce(hidden, weight, labels, chunk: int = 8192,
                  ignore_index: int = -100):
    """Mean CE of softmax(hidden @ weight) vs integer labels.

    hidden: (..., H); weight: (H, V); labels: (...) int. Returns a scalar
    (fp32). Differentiable wrt hidden and weight."""
    loss, _ = _ce_fwd(hidden, weight, labels, chunk, ignore_index)
    return loss


def _ce_fwd(hidden, weight, labels, chunk, ignore_index):
    h_dim = hidden.shape[-1]
    hid32 = hidden.reshape(-1, h_dim).astype(jnp.float32)
    lbl = labels.reshape(-1)
    wpad, n_chunks, v = _pad_w(weight, chunk)
    valid = lbl != ignore_index
    safe = jnp.where(valid, lbl, 0)
    lse, tgt = _fwd_scan(hid32, wpad, safe, v, chunk, n_chunks)
    per_tok = jnp.where(valid, lse - tgt, 0.0)
    denom = jnp.maximum(valid.sum().astype(jnp.float32), 1.0)
    loss = per_tok.sum() / denom
    return loss, (hidden, weight, labels, lse, denom)


def _ce_bwd(chunk, ignore_index, res, g):
    hidden, weight, labels, lse, denom = res
    h_dim = hidden.shape[-1]
    hid32 = hidden.reshape(-1, h_dim).astype(jnp.float32)
    lbl = labels.reshape(-1)
    valid = lbl != ignore_index
    safe = jnp.where(valid, lbl, 0)
    wpad, n_chunks, v = _pad_w(weight, chunk)
    scale = (g / denom) * valid.astype(jnp.float32)          # (N,)

    def step(carry, c0):
        dh, dw = carry
        w_c = lax.dynamic_slice_in_dim(wpad, c0, chunk, axis=1)
        w32 = w_c.astype(jnp.float32)
        logits = hid32 @ w32
        col = c0 + jnp.arange(chunk)
        logits = jnp.where(col[None, :] < v, logits, -jnp.inf)
        p = jnp.exp(logits - lse[:, None])                   # softmax chunk
        in_chunk = (safe >= c0) & (safe < c0 + chunk)
        local = jnp.clip(safe - c0, 0, chunk - 1)
        onehot = (jnp.arange(chunk)[None, :] == local[:, None]) \
            & in_chunk[:, None]
        d_logits = (p - onehot.astype(jnp.float32)) * scale[:, None]
        dh = dh + d_logits @ w32.T
        dw = lax.dynamic_update_slice_in_dim(
            dw, hid32.T @ d_logits, c0, axis=1)
        return (dh, dw), None

    c0s = jnp.arange(n_chunks) * chunk
    init = (jnp.zeros_like(hid32),
            jnp.zeros((h_dim, n_chunks * chunk), jnp.float32))
    (dh, dw), _ = lax.scan(step, init, c0s)
    dw = dw[:, :v]
    return (dh.reshape(hidden.shape).astype(hidden.dtype),
            dw.astype(weight.dtype), None)


chunked_lm_ce.defvjp(_ce_fwd, _ce_bwd)
