"""Pipeline-parallel engine (reference:
fleet/meta_parallel/pipeline_parallel.py:114 train_batch — micro-batch
forward :156 / backward :199 loops with p2p send/recv
(pp_utils/p2p_communication.py:84,:93); static 1F1B in
framework/section_worker.cc:139-183).

TPU-native schedule: the whole pipeline is ONE SPMD program under shard_map
over the "pipe" mesh axis. Activations move between stages with
lax.ppermute; the schedule is a lax.scan over M + S - 1 ticks (GPipe fill +
steady state). The *backward* pipeline is not hand-written: jax AD
differentiates through the scan, transposing every ppermute into the
reverse-direction hop — producing exactly the reversed communication pattern
that pipeline_parallel.py:199 implements manually. Per-microbatch activation
memory is bounded with jax.checkpoint (remat) over each stage application.

Parameter memory: the transformer body lives in _StackedStage parameters
(pp_layers.py) whose leading member dim is sharded over "pipe" — inside the
shard_map each device's slice is exactly its own stage's members, applied
with a lax.scan. First/last-stage layers (embedding, norm, head) are
replicated over pipe; their gradients are psum'd over "pipe" by the engine
so the replication is genuine (each stage contributes zeros for layers it
does not run).

Stage dispatch: when the layer plan decomposes as prologue -> uniform
stacked body -> epilogue (PipelineLayer.uniform_split — the canonical
transformer shape), every device executes the SAME pre/stack/post program
each tick with the heterogeneous parts masked by stage id. This is the
collective-safe form: collectives inside the layers (ring attention's
ppermute over "sep", TP psums) are issued by all devices in the same
order. The older dispatch — a lax.switch on the stage id — is kept as a
fallback for non-decomposable plans, but collectives under a per-device
switch branch are undefined behavior in SPMD (devices join different op
instances: ppermute deadlocks or silently exchanges the wrong tensors),
so the engine refuses that fallback when the mesh has a "sep" axis.
"""
from __future__ import annotations

from collections import OrderedDict

import jax
import jax.numpy as jnp
from jax import lax

from ...jit.functionalization import functional_call
from ...nn.layer import Layer

PIPE_AXIS = "pipe"


def _extract(state, prefix):
    """Sub-dict of a flat name->array dict under `prefix.`."""
    plen = len(prefix) + 1
    return {k[plen:]: v for k, v in state.items()
            if k.startswith(prefix + ".")}


class PipelineParallel(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        from .parallel_layers.pp_layers import PipelineLayer
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel requires a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self.accumulate_steps = 1
        self.schedule = "gpipe"
        if strategy is not None:
            self.accumulate_steps = int(
                strategy.pipeline_configs.get("accumulate_steps", 1))
            self.schedule = strategy.pipeline_configs.get(
                "schedule", self.schedule)
        self.dispatch = "auto"
        if strategy is not None:
            self.dispatch = strategy.pipeline_configs.get("dispatch",
                                                          self.dispatch)
        self._compiled = None

    # -- single-device semantics (debug/eval) ------------------------------
    def forward(self, x):
        return self._layers(x)

    def _prepost_collective_free(self):
        """True iff the prologue/epilogue bodies can run under a per-stage
        ``lax.cond`` (only first/last stages pay for embedding and the
        vocab head) instead of being executed+masked on every device.

        Gating is safe exactly when pre/post contain no collectives: a
        branch taken only by some devices must not issue channel ops
        (round-4 finding: collectives under device-varying branches
        deadlock or silently mispair). TP shards the embedding/head over
        "model" (psum inside) and sequence parallelism can put sep
        collectives in custom heads, so the gate is on only for
        model==sep==1 — the measured ~3x redundant-FLOPs case the gate
        exists to kill (VERDICT r4 weak #3) is exactly that pipe-only
        shape."""
        from ..mesh import get_mesh
        mesh = get_mesh()  # the live mesh names the axes that actually
        if mesh is not None:  # carry collectives (topos often omit "sep")
            return (mesh.shape.get("model", 1) == 1
                    and mesh.shape.get("sep", 1) == 1)
        try:
            mp = self._hcg.get_model_parallel_world_size()
        except Exception:
            mp = 1
        return mp == 1

    # -- uniform (collective-safe) building blocks --------------------------
    def _apply_plain_items(self, items, params, buffers, x, key):
        """Apply a run of non-stacked plan items functionally."""
        layers = self._layers
        for i, ent in items:
            kind = ent[0]
            if kind == "layer":
                mod = getattr(layers, f"mod{i}")
                x, _ = functional_call(
                    mod, _extract(params, f"mod{i}"),
                    _extract(buffers, f"mod{i}"), x,
                    rng=jax.random.fold_in(key, i))
            elif kind == "shared":
                _, owner_i, fw, attr = ent
                if fw is not None:
                    w = params[layers.owner_weight_key(owner_i, attr)]
                    x = fw(x, w)
                else:
                    owner = getattr(layers, f"mod{owner_i}")
                    x, _ = functional_call(
                        owner, _extract(params, f"mod{owner_i}"),
                        _extract(buffers, f"mod{owner_i}"), x,
                        rng=jax.random.fold_in(key, i))
            else:  # pragma: no cover - uniform_split guarantees no stacks
                raise AssertionError("stacked item in plain run")
        return x

    def _uniform_fns(self):
        """(pre_fn, stack_fn, post_fn) for the uniform schedules, or None.

        Each takes (params, buffers, x, key) and is executed by EVERY
        device every tick: pre/post touch only pipe-replicated params, so
        they compute identically everywhere (results masked by stage id
        at the call site); stack_fn applies this device's k local stacked
        members — structurally identical across stages, so any
        collectives inside line up."""
        split = self._layers.uniform_split()
        if split is None:
            return None
        pre_items, gid, post_items = split
        layers = self._layers
        stack = getattr(layers, f"stack{gid}")
        k = layers.groups[gid][2]
        a = layers.groups[gid][0]

        def pre_fn(params, buffers, x, key):
            return self._apply_plain_items(pre_items, params, buffers, x,
                                           key)

        def stack_fn(params, buffers, x, key):
            from .parallel_layers.pp_layers import _escape
            sp = _extract(params, f"stack{gid}")
            sb = _extract(buffers, f"stack{gid}")
            # rng folds with the GLOBAL member index (stage offset +
            # local j): folding with the local index alone would hand
            # every stage's j-th block the same dropout stream
            j0 = a + lax.axis_index(PIPE_AXIS) * k

            def blk(h_c, xs):
                pj, bj, j = xs
                pj = {n: pj[_escape(n)] for n in stack.param_names}
                bj = {n: bj[_escape(n)] for n in stack.buffer_names}
                out, _ = functional_call(
                    stack._template, pj, bj, h_c,
                    rng=jax.random.fold_in(key, j0 + j))
                return out, None

            x, _ = lax.scan(jax.checkpoint(blk), x,
                            (sp, sb, jnp.arange(k)))
            return x

        def post_fn(params, buffers, x, key):
            return self._apply_plain_items(post_items, params, buffers, x,
                                           key)

        return pre_fn, stack_fn, post_fn

    # -- per-stage functional forward (switch fallback) ---------------------
    def _stage_forward_fn(self, s):
        """Build fwd(params, buffers, h, key) applying stage `s`'s items.

        `params`/`buffers` are the FLAT model dicts as seen inside the
        active shard_map: _StackedStage entries hold the LOCAL (per-device)
        member slice — which on the device executing branch `s` is exactly
        stage s's members — while mod{i} entries are replicated.
        """
        layers = self._layers
        items = layers.stage_items(s)
        k_local = {gid: k for gid, (_, _, k) in enumerate(layers.groups)}

        def fwd(params, buffers, h, key):
            x = h
            idx = 0
            n = len(items)
            while idx < n:
                i, ent = items[idx]
                kind = ent[0]
                if kind == "stacked":
                    _, gid, m0 = ent
                    stack = getattr(layers, f"stack{gid}")
                    k = k_local[gid]
                    # contiguous run of this stack's members in this stage
                    run = 1
                    while idx + run < n and items[idx + run][1][0] == "stacked" \
                            and items[idx + run][1][1] == gid:
                        run += 1
                    assert run == k, (
                        f"stage {s}: stacked run {run} != per-stage k {k}")
                    sp = _extract(params, f"stack{gid}")
                    sb = _extract(buffers, f"stack{gid}")

                    def blk(h_c, xs, _stack=stack, _i0=i):
                        from .parallel_layers.pp_layers import _escape
                        pj, bj, j = xs
                        pj = {n: pj[_escape(n)] for n in _stack.param_names}
                        bj = {n: bj[_escape(n)] for n in _stack.buffer_names}
                        out, _ = functional_call(
                            _stack._template, pj, bj, h_c,
                            rng=jax.random.fold_in(key, _i0 + j))
                        return out, None

                    js = jnp.arange(k)
                    x, _ = lax.scan(jax.checkpoint(blk), x, (sp, sb, js))
                    idx += run
                    continue
                if kind == "layer":
                    mod = getattr(layers, f"mod{i}")
                    x, _ = functional_call(
                        mod, _extract(params, f"mod{i}"),
                        _extract(buffers, f"mod{i}"), x,
                        rng=jax.random.fold_in(key, i))
                elif kind == "shared":
                    _, owner_i, fw, attr = ent
                    if fw is not None:
                        w = params[layers.owner_weight_key(owner_i, attr)]
                        x = fw(x, w)
                    else:
                        owner = getattr(layers, f"mod{owner_i}")
                        x, _ = functional_call(
                            owner, _extract(params, f"mod{owner_i}"),
                            _extract(buffers, f"mod{owner_i}"), x,
                            rng=jax.random.fold_in(key, i))
                idx += 1
            return x

        return fwd

    # -- the SPMD pipelined loss -------------------------------------------
    def build_pipeline_loss_fn(self, loss_fn, micro_batches: int):
        """Return pure_loss(params, buffers, rng, inputs, labels) that runs
        the selected schedule inside an active shard_map over the pipe axis.

        inputs/labels are the FULL batch (replicated over pipe); they are
        re-split into `micro_batches` microbatches here (reference
        pipeline_parallel.py _load_micro_batch).
        """
        uniform = self._pick_uniform()
        if uniform is not None:
            return self._uniform_pipeline_loss(loss_fn, micro_batches,
                                               uniform)
        return self._switch_pipeline_loss(loss_fn, micro_batches)

    def _pick_uniform(self):
        """Dispatch selection: the uniform form when the plan decomposes
        (collective-safe; with the pre/post cond-gate it matches the
        switch form's per-tick cost), the lax.switch fallback otherwise
        or when strategy pipeline_configs["dispatch"]="switch" forces it
        (only valid for collective-free stage bodies — engine.py refuses
        switch under a 'sep' mesh)."""
        if self.dispatch == "switch":
            # the engine's sep guard checks only the no-decomposition
            # fallback (engine.py:202); an EXPLICIT switch override must
            # enforce the same collective-safety rule itself
            from ..mesh import get_mesh
            mesh = get_mesh()
            if mesh is not None and (mesh.shape.get("sep", 1) > 1
                                     or mesh.shape.get("model", 1) > 1):
                raise ValueError(
                    "pipeline_configs dispatch='switch' is unsafe on this "
                    f"mesh (model={mesh.shape.get('model', 1)}, "
                    f"sep={mesh.shape.get('sep', 1)}): stage bodies issue "
                    "collectives, and collectives under per-device "
                    "lax.switch branches deadlock or silently mispair "
                    "(round-4 finding) — use dispatch='auto'")
            return None
        uniform = self._uniform_fns()
        if uniform is None and self.dispatch == "uniform":
            raise ValueError(
                "pipeline_configs dispatch='uniform' but the layer plan "
                "does not decompose into prologue/stack/epilogue")
        return uniform

    def _uniform_pipeline_loss(self, loss_fn, M, uniform):
        """Collective-safe GPipe: every tick, every device runs the SAME
        pre -> stack -> post program; stage identity only selects inputs
        and masks outputs. jax AD transposes the scan into the reverse
        pipeline with the same uniformity."""
        S = self.num_stages
        pre_fn, stack_fn, post_fn = uniform
        gate = self._prepost_collective_free()

        def pure_loss(params, buffers, key, inputs, labels):
            sid = lax.axis_index(PIPE_AXIS)
            is_first = sid == 0
            is_last = sid == S - 1
            mb = inputs.shape[0] // M
            micro_in = inputs.reshape((M, mb) + inputs.shape[1:])
            micro_lb = labels.reshape((M, mb) + labels.shape[1:])

            probe = jax.eval_shape(
                lambda: stack_fn(params, buffers,
                                 pre_fn(params, buffers, micro_in[0],
                                        key), key))
            h_shape, h_dtype = probe.shape, probe.dtype
            zeros_h = jnp.zeros(h_shape, h_dtype)

            def compute(h_recv, m, k_t):
                if gate:
                    # collective-free pre/post: only the stages that own
                    # them pay for them (lax.cond on the pipe coordinate —
                    # kills the every-stage-runs-the-vocab-head redundancy)
                    x0 = lax.cond(
                        is_first,
                        lambda: pre_fn(params, buffers, micro_in[m],
                                       k_t).astype(h_dtype),
                        lambda: h_recv)
                    h_out = stack_fn(params, buffers, x0, k_t)
                    l = lax.cond(
                        is_last,
                        lambda: jnp.asarray(
                            loss_fn(post_fn(params, buffers, h_out, k_t),
                                    micro_lb[m]), jnp.float32),
                        lambda: jnp.zeros((), jnp.float32))
                    return h_out.astype(h_dtype), l
                x_pre = pre_fn(params, buffers, micro_in[m], k_t)
                x0 = jnp.where(is_first, x_pre.astype(h_dtype), h_recv)
                h_out = stack_fn(params, buffers, x0, k_t)
                # non-last stages feed ZEROS to the epilogue: the value
                # is discarded by the mask below, and zeros keep the
                # head numerics finite (no inf*0 NaNs in the transpose)
                x_post = jnp.where(is_last, h_out, zeros_h)
                out = post_fn(params, buffers, x_post, k_t)
                l = loss_fn(out, micro_lb[m])
                return h_out.astype(h_dtype), l

            def tick(carry, t):
                h_recv, loss_acc = carry
                m = jnp.clip(t - sid, 0, M - 1)
                valid = (t - sid >= 0) & (t - sid < M)
                k_t = jax.random.fold_in(key, t)
                if gate:
                    # collective-free bodies: fill/drain ticks skip the
                    # compute outright instead of computing masked garbage
                    h_out, l = lax.cond(
                        valid,
                        lambda: jax.checkpoint(compute)(h_recv, m, k_t),
                        lambda: (zeros_h, jnp.zeros((), jnp.float32)))
                else:
                    h_out, l = jax.checkpoint(compute)(h_recv, m, k_t)
                loss_acc = loss_acc + jnp.where(valid & is_last, l, 0.0)
                h_next = lax.ppermute(
                    h_out, PIPE_AXIS, [(i, (i + 1) % S) for i in range(S)])
                return (h_next, loss_acc), None

            (h_last, loss_acc), _ = lax.scan(
                tick, (zeros_h, jnp.zeros((), jnp.float32)),
                jnp.arange(M + S - 1))
            from .parallel_layers.mp_layers import \
                reduce_from_parallel_region
            total = reduce_from_parallel_region(loss_acc, axis=PIPE_AXIS)
            return total / M

        return pure_loss

    def _switch_pipeline_loss(self, loss_fn, M):
        """lax.switch stage dispatch — fallback for plans that do not
        decompose into pre/stack/post. Only safe when stages contain no
        collectives (see module docstring)."""
        S = self.num_stages
        stage_fns = [self._stage_forward_fn(s) for s in range(S)]

        def pure_loss(params, buffers, key, inputs, labels):
            sid = lax.axis_index(PIPE_AXIS)
            mb = inputs.shape[0] // M
            micro_in = inputs.reshape((M, mb) + inputs.shape[1:])
            micro_lb = labels.reshape((M, mb) + labels.shape[1:])

            # probe the carry shape: trace stage0 on microbatch 0
            probe = jax.eval_shape(
                lambda: stage_fns[0](params, buffers, micro_in[0], key))
            h_shape, h_dtype = probe.shape, probe.dtype

            def apply_stage(s, m, key):
                """Branch for stage s; every branch returns (h, loss)."""
                def branch(h):
                    x0 = micro_in[m] if s == 0 else h
                    out = stage_fns[s](params, buffers, x0, key)
                    if s == S - 1:
                        l = loss_fn(out, micro_lb[m])
                        return (out.astype(h_dtype)
                                if out.shape == h_shape
                                else jnp.zeros(h_shape, h_dtype)), l
                    return out, jnp.zeros((), jnp.float32)
                return branch

            def tick(carry, t):
                h_recv, loss_acc = carry
                m = jnp.clip(t - sid, 0, M - 1)
                valid = (t - sid >= 0) & (t - sid < M)
                k_t = jax.random.fold_in(key, t)
                branches = [jax.checkpoint(apply_stage(s, m, k_t))
                            for s in range(S)]
                h_out, l = lax.switch(sid, branches, h_recv)
                l = jnp.where(valid, l, 0.0)
                loss_acc = loss_acc + l
                h_next = lax.ppermute(
                    h_out, PIPE_AXIS, [(i, (i + 1) % S) for i in range(S)])
                return (h_next, loss_acc), None

            h0 = jnp.zeros(h_shape, h_dtype)
            (h_last, loss_acc), _ = lax.scan(
                tick, (h0, jnp.zeros((), jnp.float32)), jnp.arange(M + S - 1))
            # only the last stage accumulated loss; broadcast it
            from .parallel_layers.mp_layers import \
                reduce_from_parallel_region
            total = reduce_from_parallel_region(loss_acc, axis=PIPE_AXIS)
            return total / M

        return pure_loss

    # -- 1F1B schedule (manual VJP) ----------------------------------------
    def build_pipeline_grads_fn(self, loss_fn, micro_batches: int):
        """Return pure_grads(params, buffers, rng, inputs, labels, wrt) ->
        (loss, grads) running the 1F1B schedule (reference:
        framework/section_worker.cc:139-183 — startup forwards, then
        alternating backward/forward in steady state).

        Unlike the GPipe scan (whose AD transpose stashes one activation
        per tick, O(M + S)), this schedule differentiates each stage
        locally with jax.vjp inside the tick and carries at most S stashed
        stage inputs plus one gradient accumulator — in-flight microbatches
        are bounded by num_stages, the 1F1B memory guarantee.

        Timing (stage s, microbatch m, S stages), PACKED variant — each
        tick carries one forward AND one backward phase (round-5: the
        one-phase-per-tick parity form burned 2x the ticks for the same
        work, VERDICT r4 weak #3):
          forward:  t = s + f
          backward: t = 2S - 2 - s + m
        Producers still run exactly one tick before consumers in both
        directions (fwd: (s+1)+f = t+1; bwd: 2S-2-(s-1)+m = t+1), so one
        ppermute carry per direction suffices, no inter-stage queues. The
        last stage's backward of m lands on the same tick as its forward
        of m — its vjp consumes the stash slot written earlier that tick.
        Total ticks: M + 2S - 2 (was 2(M + S - 1)). In-flight stashes per
        stage: t_b - t_f = 2(S - 1 - s), so the stash ring holds 2S - 1
        activations (1F1B-bounded, not O(M)). Each backward recomputes
        its stage forward from the stashed input (remat semantics, like
        the GPipe path's jax.checkpoint), so a stash slot is one
        activation, not a residual set — per-tick cost is one body
        forward + one body vjp; with the pre/post cond-gate the measured
        overhead vs an ideal remat-1F1B is the (M + 2S - 2)/M bubble
        (tools/pipeline_flops.py prints it per config).
        """
        M = micro_batches
        uniform = self._pick_uniform()
        if uniform is not None:
            return self._uniform_pipeline_grads(loss_fn, M, uniform)
        return self._switch_pipeline_grads(loss_fn, M)

    def _uniform_pipeline_grads(self, loss_fn, M, uniform):
        """Collective-safe 1F1B: each tick every device runs the uniform
        forward body AND the uniform backward body (a jax.vjp of the same
        body), with stage identity only masking which results commit.
        In the steady state different stages genuinely do forward and
        backward work at the same tick — under the switch dispatch their
        collectives would pair across phases (the silent-corruption
        variant of the switch UB); here both phases' collective sequences
        are issued by every device in the same order."""
        S = self.num_stages
        pre_fn, stack_fn, post_fn = uniform
        gate = self._prepost_collective_free()
        R = max(2 * S - 1, 1)  # stash ring: in-flight <= 2(S-1) + 1

        def pure_grads(params, buffers, key, inputs, labels, wrt):
            sid = lax.axis_index(PIPE_AXIS)
            is_first = sid == 0
            is_last = sid == S - 1
            mb = inputs.shape[0] // M
            micro_in = inputs.reshape((M, mb) + inputs.shape[1:])
            micro_lb = labels.reshape((M, mb) + labels.shape[1:])
            wrt_params = {k: params[k] for k in wrt}
            rest = {k: v for k, v in params.items() if k not in wrt}

            probe = jax.eval_shape(
                lambda: stack_fn(params, buffers,
                                 pre_fn(params, buffers, micro_in[0],
                                        key), key))
            h_shape, h_dtype = probe.shape, probe.dtype
            zeros_h = jnp.zeros(h_shape, h_dtype)
            gzero = jax.tree_util.tree_map(
                lambda v: jnp.zeros(jnp.shape(v), jnp.float32), wrt_params)

            def body_fwd(wp, x0b, m, k_m):
                full = dict(rest)
                full.update(wp)
                if gate:
                    x0 = lax.cond(
                        is_first,
                        lambda: pre_fn(full, buffers, micro_in[m],
                                       k_m).astype(h_dtype),
                        lambda: x0b)
                else:
                    x_pre = pre_fn(full, buffers, micro_in[m], k_m)
                    x0 = jnp.where(is_first, x_pre.astype(h_dtype), x0b)
                return stack_fn(full, buffers, x0, k_m).astype(h_dtype)

            def body_full(wp, x0b, m, k_m):
                h = body_fwd(wp, x0b, m, k_m)
                full = dict(rest)
                full.update(wp)
                if gate:
                    l = lax.cond(
                        is_last,
                        lambda: jnp.asarray(
                            loss_fn(post_fn(full, buffers, h, k_m),
                                    micro_lb[m]), jnp.float32),
                        lambda: jnp.zeros((), jnp.float32))
                    return h, l
                x_post = jnp.where(is_last, h, zeros_h)
                out = post_fn(full, buffers, x_post, k_m)
                return h, loss_fn(out, micro_lb[m])

            fwd_perm = [(i, (i + 1) % S) for i in range(S)]
            bwd_perm = [(i, (i - 1) % S) for i in range(S)]

            def tick(carry, t):
                h_recv, cot_recv, stash, gacc, loss_acc = carry
                # -- forward phase (t = s + f; packed timing, see
                # build_pipeline_grads_fn docstring) --
                td = t - sid
                fwd_valid = (td >= 0) & (td < M)
                f_idx = jnp.clip(td, 0, M - 1)

                def run_fwd():
                    return body_fwd(wrt_params, h_recv, f_idx,
                                    jax.random.fold_in(key, f_idx))

                # collective-free bodies: fill/drain ticks skip compute
                # outright (per-device cond) instead of masked garbage
                h_out = (lax.cond(fwd_valid, run_fwd, lambda: zeros_h)
                         if gate else run_fwd())
                slot = f_idx % R
                stash = stash.at[slot].set(
                    jnp.where(fwd_valid, h_recv, stash[slot]))
                # -- backward phase (t = 2S - 2 - s + m; the last stage's
                # bwd of m shares its fwd tick and reads the slot written
                # just above) --
                bd = t - (2 * S - 2 - sid)
                bwd_valid = (bd >= 0) & (bd < M)
                m_idx = jnp.clip(bd, 0, M - 1)
                k_b = jax.random.fold_in(key, m_idx)
                h_in = stash[m_idx % R]

                def run_bwd():
                    (h_b, l_m), vjp = jax.vjp(
                        lambda wp, x0b: body_full(wp, x0b, m_idx, k_b),
                        wrt_params, h_in)
                    # last stage seeds the loss cotangent; others propagate
                    # the received activation cotangent (their h feeds the
                    # next stage, never the loss)
                    cot_h = jnp.where(is_last, jnp.zeros_like(cot_recv),
                                      cot_recv)
                    cot_l = jnp.where(is_last, jnp.float32(1.0 / M),
                                      jnp.float32(0.0))
                    gw, gx = vjp((cot_h, cot_l.astype(l_m.dtype)))
                    gw = jax.tree_util.tree_map(
                        lambda g: g.astype(jnp.float32), gw)
                    return gw, gx.astype(h_dtype), \
                        jnp.asarray(l_m, jnp.float32)

                def skip_bwd():
                    return gzero, zeros_h, jnp.zeros((), jnp.float32)

                if gate:
                    gw, gx, l_m = lax.cond(bwd_valid, run_bwd, skip_bwd)
                else:
                    gw, gx, l_m = run_bwd()
                gacc = jax.tree_util.tree_map(
                    lambda a, g: a + jnp.where(bwd_valid, g, 0.0),
                    gacc, gw)
                loss_acc = loss_acc + jnp.where(bwd_valid & is_last,
                                                l_m, 0.0)
                # -- communicate --
                h_next = lax.ppermute(
                    jnp.where(fwd_valid, h_out, zeros_h), PIPE_AXIS,
                    fwd_perm)
                cot_next = lax.ppermute(
                    jnp.where(bwd_valid, gx, zeros_h),
                    PIPE_AXIS, bwd_perm)
                return (h_next, cot_next, stash, gacc, loss_acc), None

            stash0 = jnp.zeros((R,) + h_shape, h_dtype)
            carry0 = (zeros_h, zeros_h, stash0, gzero,
                      jnp.zeros((), jnp.float32))
            (h_l, c_l, st_l, gacc, loss_acc), _ = lax.scan(
                tick, carry0, jnp.arange(M + 2 * S - 2))
            from .parallel_layers.mp_layers import \
                reduce_from_parallel_region
            total = reduce_from_parallel_region(loss_acc, axis=PIPE_AXIS)
            return total / M, gacc

        return pure_grads

    def _switch_pipeline_grads(self, loss_fn, M):
        """lax.switch 1F1B — fallback for non-decomposable plans; same
        collective-safety caveat as _switch_pipeline_loss."""
        S = self.num_stages
        stage_fns = [self._stage_forward_fn(s) for s in range(S)]

        def pure_grads(params, buffers, key, inputs, labels, wrt):
            sid = lax.axis_index(PIPE_AXIS)
            mb = inputs.shape[0] // M
            micro_in = inputs.reshape((M, mb) + inputs.shape[1:])
            micro_lb = labels.reshape((M, mb) + labels.shape[1:])
            wrt_params = {k: params[k] for k in wrt}
            rest = {k: v for k, v in params.items() if k not in wrt}

            def run_stage(s, wp, x0, m):
                full = dict(rest)
                full.update(wp)
                return stage_fns[s](full, buffers, x0,
                                    jax.random.fold_in(key, m))

            probe = jax.eval_shape(
                lambda: run_stage(0, wrt_params, micro_in[0], 0))
            h_shape, h_dtype = probe.shape, probe.dtype
            zeros_h = jnp.zeros(h_shape, h_dtype)
            gzero = jax.tree_util.tree_map(
                lambda v: jnp.zeros(jnp.shape(v), jnp.float32), wrt_params)

            def fwd_branch(s):
                def go(ops):
                    h_recv, m = ops
                    if s == S - 1:
                        # last stage defers fwd to its backward's vjp
                        return zeros_h
                    x0 = micro_in[m] if s == 0 else h_recv
                    out = run_stage(s, wrt_params, x0, m)
                    return out.astype(h_dtype)
                return go

            def bwd_branch(s):
                def go(ops):
                    h_in, cot_in, m = ops
                    if s == S - 1:
                        if s == 0:
                            # single-stage pipeline: input comes from the
                            # microbatch, not the (never-written) stash
                            def f0(wp):
                                out = run_stage(0, wp, micro_in[m], m)
                                return loss_fn(out, micro_lb[m])
                            loss_m, vjp = jax.vjp(f0, wrt_params)
                            (gw,) = vjp(jnp.float32(1.0 / M))
                            return gw, zeros_h, loss_m

                        def f(wp, h):
                            out = run_stage(s, wp, h, m)
                            return loss_fn(out, micro_lb[m])
                        loss_m, vjp = jax.vjp(f, wrt_params, h_in)
                        gw, gh = vjp(jnp.float32(1.0 / M))
                        return gw, gh.astype(h_dtype), loss_m
                    if s == 0:
                        def f(wp):
                            return run_stage(0, wp, micro_in[m], m)
                        _, vjp = jax.vjp(f, wrt_params)
                        (gw,) = vjp(cot_in)
                        return gw, zeros_h, jnp.zeros((), jnp.float32)

                    def f(wp, h):
                        return run_stage(s, wp, h, m)
                    _, vjp = jax.vjp(f, wrt_params, h_in)
                    gw, gh = vjp(cot_in)
                    return gw, gh.astype(h_dtype), jnp.zeros((), jnp.float32)
                return go

            fwd_branches = [fwd_branch(s) for s in range(S)]
            bwd_branches = [bwd_branch(s) for s in range(S)]

            fwd_perm = [(i, (i + 1) % S) for i in range(S)]
            bwd_perm = [(i, (i - 1) % S) for i in range(S)]

            R = max(2 * S - 1, 1)  # stash ring: in-flight <= 2(S-1) + 1

            def tick(carry, t):
                h_recv, cot_recv, stash, gacc, loss_acc = carry
                # -- forward phase: t_f(s, f) = s + f (packed 1F1B: every
                # tick carries one forward AND one backward; producers
                # still run exactly one tick before consumers in both
                # directions, so the single ppermute carry per direction
                # needs no inter-stage queue — see
                # build_pipeline_grads_fn's timing notes) --
                td = t - sid
                fwd_valid = (td >= 0) & (td < M)
                f_idx = jnp.clip(td, 0, M - 1)
                h_out = lax.switch(sid, fwd_branches, (h_recv, f_idx))
                # stash this stage's INPUT for its later backward (stage 0
                # re-reads micro_in at backward time instead)
                slot = f_idx % R
                stash = stash.at[slot].set(
                    jnp.where(fwd_valid & (sid > 0), h_recv, stash[slot]))
                # -- backward phase (t = 2S - 2 - s + m) --
                bd = t - (2 * S - 2 - sid)
                bwd_valid = (bd >= 0) & (bd < M)
                m_idx = jnp.clip(bd, 0, M - 1)
                h_in = stash[m_idx % R]
                gw, gh, loss_m = lax.switch(
                    sid, bwd_branches, (h_in, cot_recv, m_idx))
                gacc = jax.tree_util.tree_map(
                    lambda a, g: a + jnp.where(bwd_valid, g, 0.0), gacc, gw)
                loss_acc = loss_acc + jnp.where(bwd_valid, loss_m, 0.0)
                # -- communicate --
                h_next = lax.ppermute(
                    jnp.where(fwd_valid, h_out, zeros_h), PIPE_AXIS, fwd_perm)
                cot_next = lax.ppermute(
                    jnp.where(bwd_valid, gh, zeros_h), PIPE_AXIS, bwd_perm)
                return (h_next, cot_next, stash, gacc, loss_acc), None

            stash0 = jnp.zeros((R,) + h_shape, h_dtype)
            carry0 = (zeros_h, zeros_h, stash0, gzero,
                      jnp.zeros((), jnp.float32))
            (h_l, c_l, st_l, gacc, loss_acc), _ = lax.scan(
                tick, carry0, jnp.arange(M + 2 * S - 2))
            from .parallel_layers.mp_layers import \
                reduce_from_parallel_region
            total = reduce_from_parallel_region(loss_acc, axis=PIPE_AXIS)
            return total / M, gacc

        return pure_grads

    # passthrough
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, sd, *args, **kwargs):
        return self._layers.set_state_dict(sd, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def named_buffers(self, prefix="", include_sublayers=True):
        # delegate like named_parameters: buffer names must match the
        # mod{i}./stack{g}. prefixes the stage forward extracts
        return self._layers.named_buffers(prefix, include_sublayers)

    def named_buffer_pspecs(self):
        return self._layers.named_buffer_pspecs()
