"""paddle_tpu.inference.executor_cache — persistent compiled-executor
warm set, so scale-up and respawn stop paying ``serving_recompiles_total``
cold starts (ISSUE 19 tentpole support).

The serving batcher keeps every batch inside a small closed set of
``(input signature, row bucket)`` shapes; each first-seen pair costs one
XLA compile. That set is a property of the MODEL ARTIFACT, not of any
single server instance — a freshly scaled-up replica will serve exactly
the shapes the incumbents already compiled. This module persists the set
the way the Pallas tuning DB persists kernel configs (JSON manifest,
atomic replace, corrupt file degrades to empty with a warning, env
override) and replays it into new servers:

- ``attach(server, key, cache)`` hooks the server's ``shape_observer``
  so every first-seen shape is recorded (and the manifest saved).
- ``prime(server, key, cache)`` runs one synthesized zero-batch per
  recorded shape through every replica executor BEFORE the server takes
  traffic — paying the compiles off the serving path — then seeds
  ``server.warm_start`` so those shapes never count as recompiles.

Signatures are stored as ``repr`` of the request signature tuple
(per-row shape + numpy dtype str per input) and parsed back with
``ast.literal_eval`` — the same stringify-don't-pickle discipline as the
tuning DB keys. Executors whose inputs cannot be synthesized from the
signature alone (e.g. decode-step executors over a live KV cache) pass a
custom ``prime_fn``.
"""
from __future__ import annotations

import ast
import json
import os
import threading
import warnings
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["ExecutorCache", "artifact_key", "default_cache_path",
           "attach", "prime"]

_VERSION = 1


def default_cache_path() -> str:
    """``PADDLE_TPU_EXECUTOR_CACHE`` or a user-cache-dir default."""
    env = os.environ.get("PADDLE_TPU_EXECUTOR_CACHE")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                        "executor_cache.json")


def artifact_key(prefix: str, quant=None) -> str:
    """Stable per-artifact key: the model path + quant spec. Deliberately
    NOT the mtime/size layer-cache key — a hot-swapped generation at the
    same path serves the same shape set, so the warm set survives model
    updates."""
    return f"{os.path.abspath(prefix)}|quant={quant}"


class ExecutorCache:
    """``{artifact_key: [[sig_repr, bucket], ...]}`` with JSON round-trip."""

    def __init__(self, entries: Optional[Dict[str, list]] = None,
                 path: Optional[str] = None):
        self.entries: Dict[str, list] = {k: list(v)
                                         for k, v in (entries or {}).items()}
        self.path = path
        self._lock = threading.Lock()

    # -- io -----------------------------------------------------------------
    @classmethod
    def load(cls, path: Optional[str] = None) -> "ExecutorCache":
        """Missing or corrupt manifests yield an EMPTY cache (warn on
        corruption) — a broken warm set must never block serving."""
        path = path or default_cache_path()
        if not os.path.exists(path):
            return cls(path=path)
        try:
            with open(path) as f:
                raw = json.load(f)
            if not isinstance(raw, dict) or \
                    not isinstance(raw.get("entries", {}), dict):
                raise ValueError("not an executor cache object")
            return cls(raw.get("entries", {}), path=path)
        except (OSError, ValueError) as e:
            warnings.warn(f"executor cache {path!r} unreadable ({e}); "
                          "treating as empty", stacklevel=2)
            return cls(path=path)

    def save(self, path: Optional[str] = None):
        path = path or self.path
        if not path:
            raise ValueError("ExecutorCache.save: no path")
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                    exist_ok=True)
        with self._lock:
            payload = {"version": _VERSION, "entries": self.entries}
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=1, sort_keys=True)
            os.replace(tmp, path)

    # -- access -------------------------------------------------------------
    def record(self, key: str, sig, bucket: int) -> bool:
        """Record a first-seen shape; returns True when it was new."""
        row = [repr(sig), int(bucket)]
        with self._lock:
            rows = self.entries.setdefault(key, [])
            if row in rows:
                return False
            rows.append(row)
            return True

    def shapes(self, key: str) -> List[Tuple[tuple, int]]:
        """Recorded ``(signature, bucket)`` pairs for an artifact.
        Unparseable rows are skipped (forward/backward compatible)."""
        with self._lock:
            rows = list(self.entries.get(key, []))
        out = []
        for sig_repr, bucket in rows:
            try:
                out.append((ast.literal_eval(sig_repr), int(bucket)))
            except (ValueError, SyntaxError):
                continue
        return out

    def __len__(self):
        with self._lock:
            return sum(len(v) for v in self.entries.values())


def attach(server, key: str, cache: ExecutorCache,
           autosave: bool = True) -> None:
    """Record every first-seen shape the server compiles under ``key``."""

    def _observe(sig, bucket):
        if cache.record(key, sig, bucket) and autosave and cache.path:
            try:
                cache.save()
            except OSError:
                pass  # a read-only cache dir must not fail serving

    server.shape_observer = _observe


def _synth_batch(sig, bucket: int) -> List[np.ndarray]:
    """Zero arrays matching one recorded ``(sig, bucket)`` shape."""
    return [np.zeros((bucket,) + tuple(tail), dtype=np.dtype(dtype_str))
            for tail, dtype_str in sig]


def prime(server, key: str, cache: ExecutorCache,
          prime_fn: Optional[Callable] = None) -> int:
    """Compile the recorded shape set into ``server`` BEFORE it takes
    traffic, then seed ``warm_start`` so the shapes never count as
    recompiles. ``prime_fn(sig, bucket)`` overrides the synthesized
    zero-batch execution for executors with out-of-band state (decode).
    Returns the number of primed shapes."""
    pairs = cache.shapes(key)
    primed = []
    for sig, bucket in pairs:
        try:
            if prime_fn is not None:
                prime_fn(sig, bucket)
            else:
                arrays = _synth_batch(sig, bucket)
                for replica in server.replicas:
                    replica.fn(arrays)
        except Exception as e:  # noqa: BLE001 - stale entry, skip it
            warnings.warn(f"executor-cache prime skipped {sig!r} x "
                          f"{bucket}: {e!r}", stacklevel=2)
            continue
        primed.append((sig, bucket))
    server.warm_start(primed)
    return len(primed)
