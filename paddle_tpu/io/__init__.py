"""paddle_tpu.io (reference: python/paddle/io/__init__.py)."""
from .dataloader import DataLoader, default_collate_fn  # noqa: F401
from .dataset import (  # noqa: F401
    ChainDataset, ComposeDataset, ConcatDataset, Dataset, IterableDataset,
    Subset, TensorDataset, random_split)
from .sampler import (  # noqa: F401
    BatchSampler, DistributedBatchSampler, RandomSampler, Sampler,
    SequenceSampler, WeightedRandomSampler)
from .dataloader import get_worker_info  # noqa: F401
