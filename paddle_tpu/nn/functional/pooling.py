"""Pooling via lax.reduce_window (reference: python/paddle/nn/functional/pooling.py,
operators/pool_op.*)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .conv import _tuplize, _resolve_padding


def _window_dims(kernel, stride, pad, n, channel_last, x_ndim):
    kernel = _tuplize(kernel, n)
    stride = _tuplize(stride if stride is not None else kernel, n)
    pads = _resolve_padding(pad, n, stride, (1,) * n, kernel)
    if pads == "SAME":
        pads = [( (k - 1) // 2, k - 1 - (k - 1) // 2) for k in kernel]
    if channel_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        padding = [(0, 0)] + list(pads) + [(0, 0)]
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        padding = [(0, 0), (0, 0)] + list(pads)
    return window, strides, padding


def _max_pool(x, kernel, stride, padding, ceil_mode, n, data_format):
    channel_last = data_format[-1] == "C"
    window, strides, pads = _window_dims(kernel, stride, padding, n, channel_last, x.ndim)
    init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    return jax.lax.reduce_window(x, init, jax.lax.max, window, strides, pads)


def _avg_pool(x, kernel, stride, padding, ceil_mode, exclusive, n, data_format):
    channel_last = data_format[-1] == "C"
    window, strides, pads = _window_dims(kernel, stride, padding, n, channel_last, x.ndim)
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
    if exclusive and any(p != (0, 0) for p in pads):
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pads)
        return summed / counts
    return summed / float(np.prod(window))


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    return _max_pool(x, kernel_size, stride, padding, ceil_mode, 1,
                     "NLC" if data_format[-1] == "C" else "NCW")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _max_pool(x, kernel_size, stride, padding, ceil_mode, 2, data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _max_pool(x, kernel_size, stride, padding, ceil_mode, 3, data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _avg_pool(x, kernel_size, stride, padding, ceil_mode, exclusive, 1,
                     "NLC" if data_format[-1] == "C" else "NCW")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _avg_pool(x, kernel_size, stride, padding, ceil_mode, exclusive, 2, data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _avg_pool(x, kernel_size, stride, padding, ceil_mode, exclusive, 3, data_format)


def _adaptive_starts_ends(in_size, out_size):
    starts = (np.arange(out_size) * in_size) // out_size
    ends = -(-(np.arange(1, out_size + 1) * in_size) // out_size)
    return starts, ends


def _adaptive_pool(x, output_size, n, data_format, op):
    channel_last = data_format[-1] == "C"
    out_sizes = _tuplize(output_size, n)
    spatial_axes = tuple(range(1, 1 + n)) if channel_last else tuple(range(2, 2 + n))
    # Fast path: input divisible by output — single reshape+reduce (XLA-friendly).
    in_sizes = [x.shape[a] for a in spatial_axes]
    if all(i % o == 0 for i, o in zip(in_sizes, out_sizes)):
        shape = list(x.shape)
        for a, o in zip(reversed(spatial_axes), reversed(out_sizes)):
            i = shape[a]
            shape[a:a + 1] = [o, i // o]
        y = jnp.reshape(x, shape)
        reduce_axes = tuple(a + 1 + k for k, a in enumerate(sorted(spatial_axes)))
        return op(y, axis=reduce_axes)
    # General path: per-axis segment reduction.
    y = x
    for k, (a, o) in enumerate(zip(spatial_axes, out_sizes)):
        starts, ends = _adaptive_starts_ends(y.shape[a], o)
        pieces = [op(jax.lax.slice_in_dim(y, int(s), int(e), axis=a), axis=a, keepdims=True)
                  for s, e in zip(starts, ends)]
        y = jnp.concatenate(pieces, axis=a)
    return y


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "NCW", jnp.mean)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format, jnp.mean)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format, jnp.mean)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, "NCW", jnp.max)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, "NCHW", jnp.max)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, "NCDHW", jnp.max)
