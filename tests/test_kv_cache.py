"""Paged KV cache semantics (paddle_tpu/inference/kv_cache.py): page
accounting, copy-on-write on shared tails, ref-counted prefix sharing,
digest-collision safety, and LRU eviction that never touches a pinned
page. Pure numpy — no jax in this file.
"""
import numpy as np
import pytest

from paddle_tpu.inference.kv_cache import CacheOOM, PagedKVCache


def make_cache(num_pages=8, page_size=4, heads=2, dim=4, **kw):
    return PagedKVCache(num_pages, page_size, heads, dim, **kw)


def kv_for(tokens, heads=2, dim=4, layers=1):
    """Deterministic K/V rows derived from token ids, so a page's
    contents can be checked later by value."""
    t = np.asarray(tokens, np.float32).reshape(1, -1, 1, 1)
    k = np.broadcast_to(t, (layers, t.shape[1], heads, dim)).copy()
    return k, -k


def fill(cache, seq, tokens):
    k, v = kv_for(tokens, cache.k.shape[3], cache.k.shape[4],
                  cache.num_layers)
    cache.append(seq, tokens, k, v)


# -- basic paging -------------------------------------------------------------

def test_alloc_block_table_and_release():
    c = make_cache()
    s = c.create([])
    fill(c, s, list(range(10)))          # 2.5 pages
    assert s.length == 10 and len(s.pages) == 3
    assert c.used_pages() == 3
    bt = c.block_table(s, 5)
    assert bt.dtype == np.int32 and list(bt[:3]) == s.pages
    assert list(bt[3:]) == [0, 0]
    with pytest.raises(ValueError):
        c.block_table(s, 2)              # narrower than the sequence
    c.release(s)
    # 2 full pages registered for sharing (evictable), the partial tail
    # page was private and freed immediately
    st = c.stats()
    assert st["pages_used"] == 2 and st["registered"] == 2
    assert st["evictable"] == 2
    c.release(s)                         # idempotent
    with pytest.raises(ValueError):
        fill(c, s, [1])                  # released sequences are closed


def test_partial_tail_never_registered():
    c = make_cache(page_size=4)
    s = c.create([])
    fill(c, s, [1, 2, 3])                # < one page
    assert c.stats()["registered"] == 0
    assert c.match_prefix([1, 2, 3]) == (0, [])
    c.release(s)
    assert c.used_pages() == 0           # private page freed


def test_pages_needed_and_can_admit():
    c = make_cache(num_pages=4, page_size=4)
    assert c.pages_needed(0) == 0
    assert c.pages_needed(1) == 1
    assert c.pages_needed(4) == 1
    assert c.pages_needed(5) == 2
    assert c.can_admit(4) and not c.can_admit(5)
    s = c.create([])
    fill(c, s, list(range(8)))           # 2 pages pinned by s
    assert not c.can_admit(3)
    c.release(s)                         # both registered -> evictable
    assert c.can_admit(4)


# -- prefix sharing + refcounts ----------------------------------------------

def test_prefix_reuse_pins_pages_and_counts_hit_tokens():
    c = make_cache(page_size=4)
    a = c.create(list(range(8)))
    assert a.cached_tokens == 0          # cold cache
    fill(c, a, list(range(8)))
    b = c.create(list(range(8)))
    assert b.cached_tokens == 8 and b.pages == a.pages
    assert c.prefix_hit_tokens == 8
    for p in b.pages:
        # prefix table + a + b
        assert c.ref[p] == 3


def test_refcount_drop_never_frees_still_referenced_page():
    c = make_cache(page_size=4)
    a = c.create([])
    fill(c, a, list(range(8)))
    b = c.create(list(range(8)))         # pins a's registered pages
    shared = list(b.pages)
    c.release(a)
    # pages must survive: b still decodes through them
    assert c.free_pages() == c.num_pages - 2
    assert c.stats()["evictable"] == 0   # pinned by b -> not evictable
    for p in shared:
        assert c.ref[p] == 2             # prefix table + b
        np.testing.assert_array_equal(c.k[0, p, 0],
                                      c.k[0, p, 0])  # still addressable
    c.release(b)
    assert c.stats()["evictable"] == 2   # only the table holds them now
    assert c.trim(10) == 2
    assert c.used_pages() == 0


def test_cow_fork_on_write_to_shared_tail():
    c = make_cache(page_size=4)
    a = c.create([])
    fill(c, a, [1, 2, 3, 4, 5, 6])       # page0 full, tail has (5, 6)
    b = c.fork(a)
    tail = a.pages[-1]
    assert b.pages == a.pages and c.ref[tail] == 2
    fill(c, a, [7])                      # writes the SHARED tail -> COW
    assert a.pages[-1] != tail and b.pages[-1] == tail
    assert c.ref[tail] == 1 and c.ref[a.pages[-1]] == 1
    # the copied prefix of the tail (tokens 5, 6) rode along
    np.testing.assert_array_equal(c.k[0, a.pages[-1], :2],
                                  c.k[0, tail, :2])
    # and b's view is untouched by a's divergence
    fill(c, b, [8])
    assert float(c.k[0, a.pages[-1], 2, 0, 0]) == 7.0
    assert float(c.k[0, b.pages[-1], 2, 0, 0]) == 8.0
    assert a.length == b.length == 7


def test_fork_then_both_diverge_full_page_registration():
    c = make_cache(page_size=2)
    a = c.create([])
    fill(c, a, [1, 2, 3])                # page full + tail (3,)
    b = c.fork(a)
    fill(c, a, [4])                      # COW, fills a's page -> registers
    fill(c, b, [5])                      # COW, fills b's page -> registers
    assert c.match_prefix([1, 2, 3, 4])[0] == 4
    assert c.match_prefix([1, 2, 3, 5])[0] == 4
    assert c.match_prefix([1, 2, 9, 9])[0] == 2


# -- eviction -----------------------------------------------------------------

def test_eviction_refuses_pinned_pages():
    c = make_cache(num_pages=2, page_size=4)
    a = c.create([])
    fill(c, a, list(range(8)))           # both pages pinned + registered
    with pytest.raises(CacheOOM):
        b = c.create([])
        fill(c, b, [0])                  # nothing evictable -> OOM
    assert c.evictions == 0              # never evicted a pinned page
    c.release(a)
    b = c.create([])
    fill(c, b, [0])                      # now an LRU page gets evicted
    assert c.evictions == 1
    assert c.stats()["registered"] == 1


def test_lru_eviction_is_least_recently_matched_first():
    c = make_cache(num_pages=3, page_size=4)
    chains = {}
    for base in (0, 100, 200):
        s = c.create([])
        toks = list(range(base, base + 4))
        fill(c, s, toks)
        c.release(s)
        chains[base] = (toks, s)
    # touch chain 0 (create pins + LRU-touches; match_prefix is a pure
    # peek) so chain 100 becomes the LRU victim
    t = c.create(chains[0][0])
    assert t.cached_tokens == 4
    c.release(t)
    s = c.create([])
    fill(c, s, [999])                    # full pool -> one eviction
    assert c.evictions == 1
    assert c.match_prefix(chains[0][0])[0] == 4      # survived (touched)
    assert c.match_prefix(chains[100][0])[0] == 0    # evicted
    assert c.match_prefix(chains[200][0])[0] == 4    # survived


def test_trim_counts_and_stops_at_pinned():
    c = make_cache(num_pages=4, page_size=2)
    a = c.create([])
    fill(c, a, [1, 2, 3, 4])             # 2 registered pages
    b = c.create([1, 2])                 # pins the first one
    c.release(a)
    assert c.trim(10) == 1               # only the unpinned page goes
    assert c.stats()["evictable"] == 0
    c.release(b)


# -- digest safety ------------------------------------------------------------

def test_digest_collision_full_token_compare():
    c = make_cache(page_size=4,
                   digest_fn=lambda chain, chunk: "COLLIDE")
    a = c.create([])
    fill(c, a, [1, 2, 3, 4])
    # a different chunk hashes to the same digest; the full-token
    # compare must reject it — wrong KV is never served
    assert c.match_prefix([9, 9, 9, 9]) == (0, [])
    assert c.match_prefix([1, 2, 3, 4])[0] == 4
    s = c.create([9, 9, 9, 9, 5])
    assert s.cached_tokens == 0


def test_chained_digest_distinguishes_same_chunk_after_divergence():
    c = make_cache(page_size=2)
    a = c.create([])
    fill(c, a, [1, 2, 7, 8])             # chain: (1,2) -> (7,8)
    b = c.create([])
    fill(c, b, [3, 4, 7, 8])             # same 2nd chunk, different chain
    # matching (1,2,7,8) must NOT pick up b's (7,8) page
    n, pages = c.match_prefix([1, 2, 7, 8])
    assert n == 4 and pages == a.pages
    n, pages = c.match_prefix([3, 4, 7, 8])
    assert n == 4 and pages == b.pages
    assert a.pages[1] != b.pages[1]
