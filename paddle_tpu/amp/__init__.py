"""AMP: autocast + GradScaler (reference: python/paddle/amp/auto_cast.py:20,
grad_scaler.py:20; dygraph impl fluid/dygraph/amp/auto_cast.py:95 amp_guard,
loss_scaler.py:28 AmpScaler; op lists imperative/amp_auto_cast.cc).

TPU-native policy: default low-precision dtype is **bfloat16** (the MXU's
native input type) — no loss scaling needed, but the full dynamic-loss-scale
machinery is kept for float16 parity with the reference.

O1: white-listed ops (the matmul family) compute in bf16 — implemented by a
cast hook inside F.linear / F.conv* / paddle_tpu.matmul, mirroring how the
reference's Tracer consults the white/black lists per op (tracer.cc:177).
O2: decorate() casts the whole model's floating params to bf16, keeping
norms in fp32.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp

# Reference white/black lists (imperative/amp_auto_cast.cc): matmul-family
# in low precision; numerically-sensitive ops stay fp32.
WHITE_LIST = {"matmul", "conv1d", "conv2d", "conv3d", "linear", "einsum",
              "attention", "bmm", "mm"}
BLACK_LIST = {"softmax", "log_softmax", "cross_entropy", "layer_norm",
              "batch_norm", "exp", "log", "mean", "sum", "cumsum"}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.white = set(WHITE_LIST)
        self.black = set(BLACK_LIST)


_state = _AmpState()


def amp_state():
    return _state


def amp_dtype():
    return _state.dtype


def should_cast(op_name: str) -> bool:
    return _state.enabled and op_name in _state.white and op_name not in _state.black


def cast_if_amp(op_name, *xs):
    """Cast floating inputs to the amp dtype when the op is white-listed."""
    if not should_cast(op_name):
        return xs
    dt = _state.dtype
    return tuple(x.astype(dt) if hasattr(x, "dtype") and
                 jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != dt else x
                 for x in xs)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """reference: python/paddle/amp/auto_cast.py:20."""
    prev = (_state.enabled, _state.dtype, _state.level, _state.white, _state.black)
    _state.enabled = enable
    _state.dtype = jnp.float16 if dtype in ("float16", "fp16") else jnp.bfloat16
    _state.level = level
    if custom_white_list:
        _state.white = set(WHITE_LIST) | set(custom_white_list)
    if custom_black_list:
        _state.black = set(BLACK_LIST) | set(custom_black_list)
        _state.white = _state.white - _state.black
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level,
         _state.white, _state.black) = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model floating params to the amp dtype, keep norm layers fp32
    (reference: fluid/contrib/mixed_precision/decorator.py:446 and
    fp16_utils.py:322 cast_model_to_fp16 keep-list semantics)."""
    from ..nn.layers.norm import LayerNorm, _BatchNormBase, GroupNorm

    dt = jnp.float16 if dtype in ("float16", "fp16") else jnp.bfloat16
    model_list = models if isinstance(models, (list, tuple)) else [models]
    if level == "O2":
        for model in model_list:
            for layer in model.sublayers(include_self=True):
                if isinstance(layer, (LayerNorm, _BatchNormBase, GroupNorm)):
                    continue
                for p in layer._parameters.values():
                    if p is not None and jnp.issubdtype(p.dtype, jnp.floating):
                        p.value = p.value.astype(dt)
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """Dynamic loss scaling (reference: python/paddle/amp/grad_scaler.py;
    kernels amp/check_finite_and_unscale_op.cu, update_loss_scaling_op.cu).

    On bf16 TPU this is a near-no-op (scale=1 works), retained for fp16
    parity. Both the imperative API (scale/minimize) and a pure functional
    path (scale_loss / unscale_and_update for jitted steps) are provided.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._already_unscaled = False

    def is_enable(self):
        return self._enable

    def get_loss_scaling(self):
        return self._scale

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    @staticmethod
    def _unscale_dict(grads, inv):
        """Shared by unscale_ and unscale_and_update: multiply every grad
        by ``inv`` and report whether any was non-finite (traced bool) —
        ONE fused all-finite reduction (resilience.guard), the in-graph
        equivalent of check_finite_and_unscale_op's single kernel."""
        from ..resilience.guard import all_finite
        inv = jnp.asarray(inv, jnp.float32)
        unscaled = {k: None if g is None else g * inv.astype(g.dtype)
                    for k, g in grads.items()}
        return unscaled, ~all_finite(unscaled)

    def unscale_(self, grads_or_optimizer):
        """Unscale grads; detect non-finite. Accepts a dict of grads (returns
        (unscaled, found_inf)) or an optimizer (unscales Parameter.grad).

        The optimizer path checks all grads with ONE jitted stacked
        reduction and a single device sync — the per-parameter
        ``bool(jnp.all(jnp.isfinite(g)))`` loop it replaces paid one
        blocking sync per leaf."""
        if isinstance(grads_or_optimizer, dict):
            return self._unscale_dict(grads_or_optimizer, 1.0 / self._scale)
        opt = grads_or_optimizer
        if self._already_unscaled:
            return self._found_inf
        from ..resilience.guard import all_finite_value
        inv = 1.0 / self._scale
        unscaled = {}
        for i, p in enumerate(opt._parameter_list or []):
            if p.grad is not None:
                unscaled[i] = p.grad * inv
        found = not all_finite_value(unscaled)   # one host sync, total
        for i, p in enumerate(opt._parameter_list or []):
            if p.grad is not None:
                p.grad = unscaled[i]
        self._found_inf = found
        self._already_unscaled = True
        return found

    def update(self, found_inf=None):
        if not (self._enable and self._dynamic):
            return
        found = self._found_inf if found_inf is None else bool(found_inf)
        if found:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False
        self._already_unscaled = False

    # -- pure functional path (jitted steps) --------------------------------
    # The imperative update() above mutates Python floats and therefore
    # cannot move under jit (a traced step bakes self._scale at trace time
    # — the reference avoids this by putting update_loss_scaling INTO the
    # graph, fluid/contrib/mixed_precision/decorator.py:446 +
    # operators/amp/update_loss_scaling_op). These methods are the traced
    # equivalent: the scale and good/bad counters live in a state pytree
    # that the caller threads through the jitted step.

    def init_scale_state(self):
        """Loss-scale state pytree: {"scale", "good", "bad"} arrays."""
        return {"scale": jnp.asarray(self._scale, jnp.float32),
                "good": jnp.zeros((), jnp.int32),
                "bad": jnp.zeros((), jnp.int32)}

    def scale_loss(self, loss, scale_state):
        """Scale a loss by the live (traced) scale from the state pytree."""
        return loss * scale_state["scale"].astype(loss.dtype)

    def update_scale_state(self, scale_state, found_inf):
        """Pure incr/decr policy: (scale_state, traced found_inf bool) →
        new scale_state — the jnp.where translation of update(). Shared by
        unscale_and_update and the engine's in-step NaN guard (which feeds
        it the guard's own fused finite check)."""
        if not (self._enable and self._dynamic):  # same gate as update()
            return scale_state
        scale = scale_state["scale"]
        bad = jnp.where(found_inf, scale_state["bad"] + 1, 0)
        good = jnp.where(found_inf, 0, scale_state["good"] + 1)
        decr = bad >= self._decr_every
        incr = good >= self._incr_every
        new_scale = jnp.where(
            decr, jnp.maximum(scale * self._decr_ratio, 1.0),
            jnp.where(incr, scale * self._incr_ratio, scale))
        return {"scale": new_scale,
                "good": jnp.where(incr, 0, good),
                "bad": jnp.where(decr, 0, bad)}

    def unscale_and_update(self, grads, scale_state):
        """Pure: (grads dict, scale_state) → (unscaled, found_inf, new_state).

        All-traced: found_inf is a 0-d bool array, and the returned state
        applies the same incr/decr policy as update() with jnp.where so the
        scale actually moves across jitted steps.
        """
        unscaled, found = self._unscale_dict(grads, 1.0 / scale_state["scale"])
        return unscaled, found, self.update_scale_state(scale_state, found)

    def step(self, optimizer):
        found = self.unscale_(optimizer)
        if not found:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "incr_every": self._incr_every,
                "decr_every": self._decr_every, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, sd):
        self._scale = sd["scale"]
        self._good_steps = sd.get("good_steps", 0)
        self._bad_steps = sd.get("bad_steps", 0)

    set_state_dict = load_state_dict


AmpScaler = GradScaler
