"""HybridParallelOptimizer (reference:
fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:89 —
wraps the inner optimizer so grad clip norms span the WHOLE hybrid mesh, and
DP-axis grad averaging happens before the update).

TPU-native: installs a psum-over-axes hook on ClipGradByGlobalNorm and
averages grads over "data" (and "sharding") axes inside the jitted step.
"""
from __future__ import annotations

from jax import lax

from ...optimizer.clip import ClipGradByGlobalNorm
from ...optimizer.optimizer import Optimizer


def _bound_axes(axes):
    out = []
    for a in axes:
        try:
            lax.axis_index(a)
            out.append(a)
        except Exception:
            pass
    return out


class HybridParallelOptimizer:
    def __init__(self, inner_opt: Optimizer, hcg=None, strategy=None):
        self._inner_opt = inner_opt
        self._hcg = hcg
        self._strategy = strategy
        clip = inner_opt._grad_clip
        if isinstance(clip, ClipGradByGlobalNorm):
            # The squared-norm must be summed over model/pipe/sharding axes
            # (each rank holds only its shard of those params) — reference
            # HybridParallelClipGrad._dygraph_clip.
            def reduce_fn(total):
                for ax in _bound_axes(("model", "pipe", "sharding")):
                    total = lax.psum(total, ax)
                return total

            clip.norm_reduce_fn = reduce_fn

    # delegate the full Optimizer surface
    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    def sync_gradients(self, grads: dict) -> dict:
        axes = _bound_axes(("data",))
        if not axes:
            return grads
        return {k: None if g is None else lax.pmean(g, axes[0])
                for k, g in grads.items()}

    def apply_gradients(self, params, grads, state, lr=None, lr_scales=None):
        grads = self.sync_gradients(grads)
        return self._inner_opt.apply_gradients(params, grads, state, lr,
                                               lr_scales)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self):
        self._inner_opt.clear_grad()


class HybridParallelGradScaler:
    """reference: dygraph_optimizer/hybrid_parallel_gradscaler.py — the
    found-inf flag must be any-reduced across the mesh so all ranks skip the
    step together."""

    def __init__(self, scaler, hcg=None):
        self._scaler = scaler
        self._hcg = hcg

    def __getattr__(self, name):
        return getattr(self._scaler, name)

    def unscale_(self, grads):
        unscaled, found = self._scaler.unscale_(grads)
        for ax in _bound_axes(("data", "model", "pipe", "sharding")):
            found = lax.pmax(found.astype("int32"), ax) > 0
        return unscaled, found
