"""Tests for flags, regularizer, device, hub, utils, onnx export
(reference parity: platform/flags.cc, python/paddle/regularizer.py,
python/paddle/device.py, python/paddle/hub.py, python/paddle/utils/)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def test_flags_set_get_roundtrip():
    paddle.set_flags({"FLAGS_benchmark": True})
    assert paddle.get_flags("FLAGS_benchmark")["FLAGS_benchmark"] is True
    paddle.set_flags({"benchmark": False})
    assert paddle.get_flags(["benchmark"])["FLAGS_benchmark"] is False
    with pytest.raises(ValueError):
        paddle.set_flags({"FLAGS_no_such_flag": 1})


def test_check_nan_inf_flag_catches_bad_grads():
    from paddle_tpu.framework import flags
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with pytest.raises(FloatingPointError):
            flags.check_numerics({"g": np.array([1.0, np.nan])}, "t:")
        flags.check_numerics({"g": np.array([1.0, 2.0])}, "t:")  # no raise
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_l2decay_matches_manual_sgd():
    coeff = 0.1
    lr = 0.5
    w0 = np.array([2.0, -3.0], dtype=np.float32)
    g = np.array([0.5, 0.5], dtype=np.float32)

    p = paddle.nn.Parameter(w0.copy())
    opt = paddle.optimizer.SGD(lr, parameters=[p],
                               weight_decay=paddle.regularizer.L2Decay(coeff))
    params = {"w": p.value}
    state = opt.init_state(params)
    new_params, _ = opt.apply_gradients(params, {"w": g}, state, lr=lr)
    expect = w0 - lr * (g + coeff * w0)
    np.testing.assert_allclose(np.asarray(new_params["w"]), expect, rtol=1e-6)


def test_l1decay_adds_sign_term():
    coeff = 0.1
    lr = 1.0
    w0 = np.array([2.0, -3.0], dtype=np.float32)
    g = np.zeros(2, dtype=np.float32)
    p = paddle.nn.Parameter(w0.copy())
    opt = paddle.optimizer.SGD(lr, parameters=[p],
                               weight_decay=paddle.regularizer.L1Decay(coeff))
    params = {"w": p.value}
    state = opt.init_state(params)
    new_params, _ = opt.apply_gradients(params, {"w": g}, state, lr=lr)
    expect = w0 - lr * coeff * np.sign(w0)
    np.testing.assert_allclose(np.asarray(new_params["w"]), expect, rtol=1e-6)


def test_device_namespace():
    assert paddle.device.device_count() >= 1
    cpu = paddle.device.CPUPlace()
    assert cpu.get_device_id() == 0
    assert cpu.jax_device.platform == "cpu"
    assert isinstance(paddle.device.get_available_device(), list)
    paddle.device.synchronize()
    paddle.device.cuda.empty_cache()  # no-op shim


def test_nn_clip_alias():
    assert paddle.nn.ClipGradByGlobalNorm is \
        paddle.optimizer.clip.ClipGradByGlobalNorm


def test_hub_local(tmp_path):
    (tmp_path / "hubconf.py").write_text(
        "def tiny(n=3):\n    'docstring here'\n    return list(range(n))\n")
    assert paddle.hub.list(str(tmp_path)) == ["tiny"]
    assert "docstring" in paddle.hub.help(str(tmp_path), "tiny")
    assert paddle.hub.load(str(tmp_path), "tiny", n=2) == [0, 1]
    with pytest.raises(NotImplementedError):
        paddle.hub.load("user/repo", "tiny", source="github")


def test_deprecated_decorator_warns():
    @paddle.utils.deprecated(update_to="new_api", since="0.1")
    def old_api():
        return 42

    with pytest.warns(DeprecationWarning):
        assert old_api() == 42


def test_onnx_export_produces_stablehlo(tmp_path):
    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)

        def forward(self, x):
            return self.fc(x)

    m = M()
    from paddle_tpu.static import InputSpec
    out = paddle.onnx.export(
        m, str(tmp_path / "m.onnx"),
        input_spec=[InputSpec([2, 4], "float32")])
    # contract: export returns the .onnx path when conversion succeeds,
    # else the StableHLO artifact prefix; the StableHLO + params artifacts
    # are always written at the prefix either way.
    prefix = out[:-5] if out.endswith(".onnx") else out
    if out.endswith(".onnx"):
        assert os.path.exists(out)
    assert os.path.exists(prefix + ".stablehlo")
    assert os.path.exists(prefix + ".pdiparams")


def test_run_check_smoke(capsys):
    paddle.utils.run_check()
    out = capsys.readouterr().out
    assert "installed successfully" in out
