"""paddle.hub-style model loading from a local hubconf.py (the reference
tree routes hub entry through python/paddle/hapi + vision model zoo; the
hub protocol is: a repo dir contains ``hubconf.py`` whose public callables
are the entrypoints).

Zero-egress: only the ``source='local'`` path is supported; github sources
raise with a clear message.
"""
from __future__ import annotations

import importlib.util
import os
import sys


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no hubconf.py under {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def _check_source(source: str):
    if source != "local":
        raise NotImplementedError(
            "paddle_tpu.hub supports source='local' only (no egress); "
            "clone the repo and pass its path")


def list(repo_dir: str, source: str = "local"):  # noqa: A001
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir: str, model: str, source: str = "local"):  # noqa: A001
    _check_source(source)
    return getattr(_load_hubconf(repo_dir), model).__doc__


def load(repo_dir: str, model: str, source: str = "local", **kwargs):
    _check_source(source)
    return getattr(_load_hubconf(repo_dir), model)(**kwargs)
