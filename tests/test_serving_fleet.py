"""ISSUE 19: the self-healing serving fleet and its satellites.

- SLO rule action registry (``on_alert`` / ``on_clear``): registered
  actions replace the default flight dump, latch/unlatch drives them
  exactly once per episode, a raising action never breaks the poll.
- Persistent compiled-executor cache: round-trip, corrupt-file
  degradation, ``warm_start`` / ``prime`` closing the recompile set.
- Layer-cache generation pinning: a pinned entry survives the artifact
  being overwritten on disk (the hot-swap rollback guarantee), eviction
  skips pins, and a pinned key whose entry is gone fails loudly instead
  of silently serving the wrong bytes.
- ServingFleet: membership files, SLO-action + threshold autoscaling,
  canary rollback / promotion, the hot-swap poller, SIGTERM draining
  every member exactly once — all with fleet-wide closed accounting.

Fleet unit tests use plain-numpy executors (no jax compile) so the
whole file stays fast; the Predictor-backed end-to-end path is covered
by ``tools/chaos_smoke.py --scenario hot_swap`` and the bench fleet
phase (tests/test_bench_smoke.py).
"""
import json
import os
import signal
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import inference, nn
from paddle_tpu.inference import executor_cache as ec
from paddle_tpu.inference import fleet as fleet_mod
from paddle_tpu.inference.serving import InferenceServer, ServingConfig
from paddle_tpu.jit import InputSpec
from paddle_tpu.telemetry.metrics import Registry
from paddle_tpu.telemetry.slo import SloMonitor, SloRule


def _breach(reg, shed=10, total=20):
    reg.counter("serving_requests_total").inc(total)
    reg.counter("serving_requests_shed_total").inc(shed)


# -- SLO action registry ------------------------------------------------------

class TestSloActions:
    def _rule(self):
        return SloRule("shed_burn",
                       numerator="serving_requests_shed_total",
                       denominator="serving_requests_total",
                       threshold=0.3, window_s=5.0, min_denominator=10.0)

    def test_registered_actions_replace_default_dump(self, monkeypatch):
        dumps = []
        from paddle_tpu.telemetry import flight
        monkeypatch.setattr(flight, "dump",
                            lambda *a, **kw: dumps.append(a))
        reg = Registry()
        rule = self._rule()
        hits = []
        assert rule.on_alert(lambda r, burn: hits.append((r.name, burn))) \
            is not None  # decorator-friendly: returns the fn
        mon = SloMonitor([rule], registry=reg)
        mon.poll(now=0.0)
        _breach(reg)
        mon.poll(now=1.0)
        assert hits == [("shed_burn", pytest.approx(0.5))]
        assert dumps == []          # custom action replaced the dump
        assert rule.alerts == 1

    def test_default_alert_can_be_kept_alongside(self, monkeypatch):
        from paddle_tpu.telemetry import flight, slo
        dumps = []
        monkeypatch.setattr(flight, "dump",
                            lambda *a, **kw: dumps.append(a))
        reg = Registry()
        rule = self._rule()
        hits = []
        rule.on_alert(lambda r, b: hits.append(b))
        rule.on_alert(slo.default_alert)
        mon = SloMonitor([rule], registry=reg)
        mon.poll(now=0.0)
        _breach(reg)
        mon.poll(now=1.0)
        assert len(hits) == 1 and len(dumps) == 1

    def test_latch_unlatch_drives_alert_and_clear_once(self):
        reg = Registry()
        rule = self._rule()
        alerts, clears = [], []
        rule.on_alert(lambda r, b: alerts.append(b))
        rule.on_clear(lambda r, b: clears.append(b))
        mon = SloMonitor([rule], registry=reg)
        mon.poll(now=0.0)
        _breach(reg)
        mon.poll(now=1.0)
        assert len(alerts) == 1 and rule.latched
        # sustained breach: latched, no re-fire, no clear
        _breach(reg)
        mon.poll(now=2.0)
        assert len(alerts) == 1 and clears == []
        # recovery: burn collapses below threshold/2 -> ONE clear action
        reg.counter("serving_requests_total").inc(300)
        mon.poll(now=6.5)
        assert not rule.latched
        assert len(clears) == 1 and rule.clears == 1
        mon.poll(now=6.6)
        assert len(clears) == 1     # clearing is edge-triggered too
        # re-breach: a fresh episode re-fires the alert actions
        _breach(reg, shed=15, total=20)
        mon.poll(now=7.5)
        assert len(alerts) == 2 and rule.alerts == 2

    def test_raising_action_never_breaks_the_poll(self):
        reg = Registry()
        rule = self._rule()
        hits = []

        def bad_action(r, b):
            raise RuntimeError("action exploded")

        rule.on_alert(bad_action)
        rule.on_alert(lambda r, b: hits.append(b))
        mon = SloMonitor([rule], registry=reg)
        mon.poll(now=0.0)
        _breach(reg)
        mon.poll(now=1.0)           # must not raise
        assert len(hits) == 1       # later actions still ran


# -- executor cache -----------------------------------------------------------

class TestExecutorCache:
    def test_roundtrip(self, tmp_path):
        path = str(tmp_path / "cache.json")
        c = ec.ExecutorCache(path=path)
        sig = (((32,), "<f4"),)
        assert c.record("k", sig, 4) is True
        assert c.record("k", sig, 4) is False   # dedup
        c.save()
        c2 = ec.ExecutorCache.load(path)
        assert c2.shapes("k") == [(sig, 4)]
        assert len(c2) == 1

    def test_corrupt_file_degrades_to_empty(self, tmp_path):
        path = str(tmp_path / "cache.json")
        with open(path, "w") as f:
            f.write("{not json")
        with pytest.warns(UserWarning, match="unreadable"):
            c = ec.ExecutorCache.load(path)
        assert len(c) == 0
        # unparseable rows are skipped, parseable ones survive
        with open(path, "w") as f:
            json.dump({"version": 1, "entries":
                       {"k": [["(((32,), '<f4'),)", 2],
                              ["garbage(", 4]]}}, f)
        assert ec.ExecutorCache.load(path).shapes("k") == \
            [((((32,), "<f4"),), 2)]

    def test_attach_records_and_prime_closes_recompiles(self, tmp_path):
        path = str(tmp_path / "cache.json")
        cache = ec.ExecutorCache(path=path)
        calls = []

        def fn(arrays):
            calls.append(np.asarray(arrays[0]).shape)
            return [np.asarray(arrays[0]) * 2.0]

        # first server: attach observes its first-seen shapes
        s1 = InferenceServer([fn], config=ServingConfig(max_batch=4))
        ec.attach(s1, "art", cache)
        with s1:
            s1.submit([np.ones((1, 8), np.float32)],
                      deadline_s=5.0).result(timeout=10)
        assert s1.stats()["recompiles"] == 1
        assert cache.shapes("art"), "observer must have recorded"
        assert os.path.exists(path), "autosave on record"

        # second server: primed from the manifest BEFORE traffic
        s2 = InferenceServer([fn], config=ServingConfig(max_batch=4))
        n_calls = len(calls)
        assert ec.prime(s2, "art", cache) == len(cache.shapes("art"))
        assert len(calls) > n_calls     # compiles paid off-path
        with s2:
            s2.submit([np.ones((1, 8), np.float32)],
                      deadline_s=5.0).result(timeout=10)
        assert s2.stats()["recompiles"] == 0, "warm_start must close it"

    def test_prime_skips_broken_entries(self, tmp_path):
        cache = ec.ExecutorCache(path=str(tmp_path / "c.json"))
        cache.record("art", (((8,), "<f4"),), 1)
        cache.record("art", (((8,), "not-a-dtype"),), 1)

        def fn(arrays):
            return [np.asarray(arrays[0])]

        server = InferenceServer([fn], config=ServingConfig(max_batch=4))
        with pytest.warns(UserWarning, match="prime skipped"):
            n = ec.prime(server, "art", cache)
        assert n == 1


# -- layer-cache generation pinning -------------------------------------------

@pytest.fixture()
def saved_model(tmp_path):
    paddle.seed(7)
    net = nn.Linear(8, 4)
    net.eval()
    prefix = str(tmp_path / "model")
    paddle.jit.save(net, prefix,
                    input_spec=[InputSpec([None, 8], "float32")])
    return prefix


def _overwrite_params(prefix, factor=100.0):
    import pickle
    with open(prefix + ".pdiparams", "rb") as f:
        blob = pickle.load(f)
    blob["params"] = {k: np.asarray(v) * factor
                     for k, v in blob["params"].items()}
    with open(prefix + ".pdiparams", "wb") as f:
        pickle.dump(blob, f)


class TestLayerPinning:
    def test_pinned_layer_survives_artifact_overwrite(self, saved_model):
        prefix = saved_model
        inference.clear_layer_cache()
        try:
            key = inference.layer_cache_key(prefix)
            pred = inference.Predictor(inference.Config(prefix),
                                       layer_key=key)
            x = np.ones((1, 8), np.float32)
            before = np.asarray(pred.run([x])[0])
            inference.pin_layer(key)
            _overwrite_params(prefix)
            # eviction must skip the pinned generation
            assert inference.evict_stale_layers() == 0
            # a REBUILD at the pinned key (rollback, scale-up) serves the
            # incumbent weights, not the poisoned bytes now on disk
            pred2 = inference.Predictor(inference.Config(prefix),
                                        layer_key=key)
            np.testing.assert_allclose(np.asarray(pred2.run([x])[0]),
                                       before)
            # released: the stale entry is evictable and a fresh load
            # picks up the new artifact
            inference.unpin_layer(key)
            assert inference.evict_stale_layers() == 1
            pred3 = inference.Predictor(inference.Config(prefix))
            after = np.asarray(pred3.run([x])[0])
            assert not np.allclose(after, before)
        finally:
            inference.clear_layer_cache()

    def test_pinned_key_with_lost_entry_fails_loudly(self, saved_model):
        prefix = saved_model
        inference.clear_layer_cache()
        try:
            key = inference.layer_cache_key(prefix)
            _overwrite_params(prefix)   # on-disk no longer matches key
            with pytest.raises(KeyError, match="pinned layer generation"):
                inference._load_layer(prefix, key=key)
        finally:
            inference.clear_layer_cache()

    def test_pin_refcounting(self, saved_model):
        prefix = saved_model
        inference.clear_layer_cache()
        try:
            key = inference.layer_cache_key(prefix)
            inference.Predictor(inference.Config(prefix), layer_key=key)
            inference.pin_layer(key)
            inference.pin_layer(key)
            _overwrite_params(prefix)
            inference.unpin_layer(key)
            assert inference.evict_stale_layers() == 0   # still pinned
            inference.unpin_layer(key)
            assert inference.evict_stale_layers() == 1
        finally:
            inference.clear_layer_cache()


# -- ServingFleet -------------------------------------------------------------

def _np_gen(gen_id, scale=2.0, delay=0.0):
    """A ModelGeneration over a plain-numpy executor: scale == nan makes
    a generation the default sanity gate must reject."""

    def fn(arrays):
        if delay:
            time.sleep(delay)
        return [np.asarray(arrays[0]) * scale]

    def make_server():
        return InferenceServer([fn], config=ServingConfig(max_batch=4))

    return fleet_mod.ModelGeneration(gen_id, make_server)


def _pumped(fleet, stop, interval=0.005):
    def pump():
        while not stop.is_set():
            try:
                fleet.submit([np.ones((1, 4), np.float32)],
                             deadline_s=5.0)
            except RuntimeError:
                pass
            time.sleep(interval)

    th = threading.Thread(target=pump, daemon=True)
    th.start()
    return th


class TestServingFleet:
    def test_bootstrap_membership_and_shutdown(self, tmp_path):
        cfg = fleet_mod.FleetConfig(min_members=2, max_members=4)
        fleet = fleet_mod.ServingFleet(_np_gen(0), config=cfg,
                                       membership_root=str(tmp_path),
                                       fleet_id="t")
        fleet.start()
        assert fleet.stats()["members"] == 2
        assert len(fleet.live_members()) == 2
        mdir = os.path.join(str(tmp_path), "members", "t")
        assert len([f for f in os.listdir(mdir)
                    if f.endswith(".json")]) == 2
        out = fleet.submit([np.ones((1, 4), np.float32)],
                           deadline_s=5.0).result(timeout=10)
        np.testing.assert_allclose(np.asarray(out[0]), 2.0)
        fleet.shutdown(drain=True)
        assert fleet.accounted()
        assert [f for f in os.listdir(mdir) if f.endswith(".json")] == []
        # post-shutdown admission sheds as "draining" — never silently lost
        fleet.submit([np.ones((1, 4), np.float32)], deadline_s=5.0)
        assert fleet.stats()["shed_causes"].get("draining", 0) >= 1
        assert fleet.accounted()

    def test_stale_member_files_reaped(self, tmp_path):
        cfg = fleet_mod.FleetConfig(min_members=1,
                                    member_stale_after_s=0.05)
        fleet = fleet_mod.ServingFleet(_np_gen(0), config=cfg,
                                       membership_root=str(tmp_path),
                                       fleet_id="t")
        fleet.start()
        mdir = os.path.join(str(tmp_path), "members", "t")
        with open(os.path.join(mdir, "dead-host-m9.json"), "w") as f:
            json.dump({"host": "dead-host", "member": "m9", "t": 0}, f)
        old = time.time() - 60
        os.utime(os.path.join(mdir, "dead-host-m9.json"), (old, old))
        assert fleet.reap_stale_members() == 1
        assert {m["member"] for m in fleet.live_members()} == {"m0"} or \
            len(fleet.live_members()) == 1
        fleet.shutdown(drain=True)

    def test_autoscale_up_on_load_and_down_when_idle(self):
        cfg = fleet_mod.FleetConfig(
            min_members=1, max_members=2, cooldown_s=0.0,
            scale_up_wait_s=0.01, scale_up_queue_depth=2,
            scale_down_idle_s=5.0)
        fleet = fleet_mod.ServingFleet(_np_gen(0, delay=0.05), config=cfg)
        fleet.start()
        reqs = [fleet.submit([np.ones((1, 4), np.float32)],
                             deadline_s=30.0) for _ in range(12)]
        fleet.poll_once()
        st = fleet.stats()
        assert st["members"] == 2 and st["scale_ups"] == 1
        for r in reqs:
            r.result(timeout=30)
        # drain the queues, then present an idle fleet far in the future
        t = time.monotonic() + 100.0
        fleet.poll_once(now=t)              # idle episode starts
        fleet.poll_once(now=t + 6.0)        # > scale_down_idle_s later
        st = fleet.stats()
        assert st["members"] == 1 and st["scale_downs"] == 1
        fleet.shutdown(drain=True)
        assert fleet.accounted()

    def test_slo_action_scales_up(self):
        cfg = fleet_mod.FleetConfig(min_members=1, max_members=2,
                                    scale_up_wait_s=1e9,
                                    scale_up_queue_depth=10**9)
        fleet = fleet_mod.ServingFleet(_np_gen(0), config=cfg)
        fleet.start()
        reg = Registry()
        rule = SloRule("shed_burn",
                       numerator="serving_requests_shed_total",
                       denominator="serving_requests_total",
                       threshold=0.3, window_s=5.0, min_denominator=10.0)
        rule.on_alert(fleet.scale_up_action())
        mon = SloMonitor([rule], registry=reg)
        mon.poll(now=0.0)
        _breach(reg)
        mon.poll(now=1.0)
        st = fleet.stats()
        assert st["members"] == 2 and st["scale_ups"] == 1
        # at max_members the action is a safe no-op
        _breach(reg, shed=15, total=20)
        reg.counter("serving_requests_total").inc(300)
        mon.poll(now=6.5)                   # unlatch
        _breach(reg, shed=15, total=20)
        mon.poll(now=7.5)                   # re-alert at max size
        assert fleet.stats()["members"] == 2
        fleet.shutdown(drain=True)

    def test_hot_swap_bad_canary_rolls_back(self):
        cfg = fleet_mod.FleetConfig(
            min_members=2, max_members=4, canary_shadow_fraction=1.0,
            canary_min_shadow=3, canary_timeout_s=10.0)
        fleet = fleet_mod.ServingFleet(_np_gen(0), config=cfg)
        fleet.start()
        stop = threading.Event()
        th = _pumped(fleet, stop)
        try:
            assert fleet.hot_swap(_np_gen(1, scale=np.nan)) is False
        finally:
            stop.set()
            th.join(timeout=5)
        st = fleet.stats()
        assert st["rolled_back"] == 1 and st["promoted"] == 0
        assert st["generation"] == 0
        assert fleet.last_canary_checks["sanity"] is False
        # live traffic still healthy on the incumbent generation
        out = fleet.submit([np.ones((1, 4), np.float32)],
                           deadline_s=5.0).result(timeout=10)
        assert np.isfinite(np.asarray(out[0])).all()
        fleet.shutdown(drain=True)
        assert fleet.accounted()        # shadows included

    def test_hot_swap_good_canary_promotes_all_members(self):
        cfg = fleet_mod.FleetConfig(
            min_members=3, max_members=4, canary_shadow_fraction=1.0,
            canary_min_shadow=3, canary_timeout_s=10.0)
        fleet = fleet_mod.ServingFleet(_np_gen(0), config=cfg)
        fleet.start()
        stop = threading.Event()
        th = _pumped(fleet, stop)
        try:
            assert fleet.hot_swap(_np_gen(1, scale=3.0)) is True
        finally:
            stop.set()
            th.join(timeout=5)
        st = fleet.stats()
        assert st["promoted"] == 1 and st["generation"] == 1
        assert st["members"] == 3           # capacity preserved
        assert set(st["member_generations"]) == {1}
        out = fleet.submit([np.ones((1, 4), np.float32)],
                           deadline_s=5.0).result(timeout=10)
        np.testing.assert_allclose(np.asarray(out[0]), 3.0)
        fleet.shutdown(drain=True)
        assert fleet.accounted()

    def test_hot_swap_poller_publishes_and_remembers_rejections(self):
        published = []

        def watch():
            return 1

        def publish(step):
            published.append(step)
            return _np_gen(step, scale=np.nan)

        cfg = fleet_mod.FleetConfig(
            min_members=1, canary_shadow_fraction=1.0,
            canary_min_shadow=2, canary_timeout_s=10.0)
        fleet = fleet_mod.ServingFleet(_np_gen(0), config=cfg,
                                       watch_fn=watch, publish_fn=publish)
        fleet.start()
        stop = threading.Event()
        th = _pumped(fleet, stop)
        try:
            fleet.poll_once()
            assert published == [1]
            assert fleet.stats()["rolled_back"] == 1
            fleet.poll_once()       # rejected step is not retried
            assert published == [1]
        finally:
            stop.set()
            th.join(timeout=5)
        fleet.shutdown(drain=True)

    def test_hot_swap_poller_publish_failure_counts_as_rollback(self):
        def publish(step):
            raise OSError("artifact unreadable")

        fleet = fleet_mod.ServingFleet(
            _np_gen(0), config=fleet_mod.FleetConfig(min_members=1),
            watch_fn=lambda: 5, publish_fn=publish)
        fleet.start()
        fleet.poll_once()
        st = fleet.stats()
        assert st["rolled_back"] == 1 and st["generation"] == 0
        fleet.poll_once()           # remembered, not retried
        assert fleet.stats()["rolled_back"] == 1
        fleet.shutdown(drain=True)

    def test_sigterm_drains_every_member_exactly_once(self):
        """Satellite 4: SIGTERM -> one graceful fleet-wide drain; every
        member server drained exactly once even when SIGTERM repeats or
        shutdown is called again, with fleet-wide closed accounting —
        and the previous SIGTERM handler still chains."""
        chained = []
        prev = signal.getsignal(signal.SIGTERM)
        signal.signal(signal.SIGTERM,
                      lambda signum, frame: chained.append(signum))
        fleet = fleet_mod.ServingFleet(
            _np_gen(0), config=fleet_mod.FleetConfig(min_members=2))
        try:
            fleet.start()
            drains = {}
            with fleet._lock:
                members = list(fleet._members)
            for m in members:
                real = m.server.shutdown

                def counting(drain=True, timeout=30.0, _real=real,
                             _name=m.name):
                    drains[_name] = drains.get(_name, 0) + 1
                    return _real(drain=drain, timeout=timeout)

                m.server.shutdown = counting
            reqs = [fleet.submit([np.ones((1, 4), np.float32)],
                                 deadline_s=10.0) for _ in range(6)]
            fleet.install_sigterm_drain()
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.monotonic() + 10.0
            while not fleet._stopped and time.monotonic() < deadline:
                time.sleep(0.01)
            os.kill(os.getpid(), signal.SIGTERM)    # repeat SIGTERM
            time.sleep(0.1)
            fleet.shutdown(drain=True)              # and a manual call
            assert drains == {m.name: 1 for m in members}
            assert fleet._shutdowns == 1
            # graceful: in-flight work completed, nothing silently lost
            for r in reqs:
                assert r.done()
            assert fleet.accounted()
            assert len(chained) >= 2                # previous handler ran
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_context_manager_and_double_shutdown(self):
        with fleet_mod.ServingFleet(
                _np_gen(0),
                config=fleet_mod.FleetConfig(min_members=1)) as fleet:
            fleet.submit([np.ones((1, 4), np.float32)],
                         deadline_s=5.0).result(timeout=10)
        fleet.shutdown(drain=True)      # idempotent
        assert fleet._shutdowns == 1
        assert fleet.accounted()
