"""fleet.utils — recompute (activation checkpointing) + hybrid-parallel grad
helpers.

Capability map (reference):
- ``recompute``             ← fleet/utils/recompute.py:63 RecomputeFunction /
  :171 recompute — a PyLayer that saves RNG state, drops activations, and
  re-runs forward inside backward. Here it is jax.checkpoint: XLA
  rematerializes the wrapped computation in the backward pass. RNG
  determinism is free — randomness comes from explicit functional PRNG keys,
  so the recomputed forward sees the same keys (no CUDA RNG state
  save/restore dance needed).
- ``fused_allreduce_gradients`` ← fleet/utils/hybrid_parallel_util.py:117 —
  bucketed NCCL allreduce over the DP axis. Here one pmean per gradient
  tree: XLA fuses/schedules collectives itself (no manual bucketing).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
from jax import lax

__all__ = ["recompute", "checkpoint_policy", "fused_allreduce_gradients"]

_POLICIES = {
    None: None,
    "full": None,  # save nothing extra — recompute everything
    "dots_saveable": jax.checkpoint_policies.dots_saveable,
    "dots_with_no_batch_dims_saveable":
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
    "everything_saveable": jax.checkpoint_policies.everything_saveable,
}


def checkpoint_policy(name: Optional[str]):
    """Resolve a policy name to a jax.checkpoint_policies entry. Policies
    refine the memory/FLOPs trade (e.g. save matmul outputs, recompute
    elementwise) — the knob the reference lacks (it always recomputes the
    whole segment)."""
    try:
        return _POLICIES[name]
    except KeyError:
        raise ValueError(f"unknown checkpoint policy {name!r}; "
                         f"one of {sorted(k for k in _POLICIES if k)}") from None


def recompute(function: Callable, *args, preserve_rng_state: bool = True,
              policy: Optional[str] = None, **kwargs):
    """Run ``function(*args)`` with activation rematerialization: outputs are
    computed now, intermediates are NOT kept for backward — they are
    recomputed when gradients flow (reference: fleet/utils/recompute.py:171).

    ``function`` may be a Layer or any callable; closed-over parameters are
    treated as saved residuals (weights are live anyway), only the wrapped
    segment's intermediates are dropped.
    """
    pol = checkpoint_policy(policy) if isinstance(policy, (str, type(None))) \
        else policy
    wrapped = jax.checkpoint(lambda *a: function(*a, **kwargs), policy=pol)
    return wrapped(*args)


def fused_allreduce_gradients(grads, hcg=None, axes=("data", "sharding"),
                              grad_sync="fp32", block=None,
                              bucket_bytes: int = 4 << 20, residuals=None):
    """Average a gradient pytree over the data-parallel axes. Valid inside
    shard_map/pmap where the axes are bound; outside (single device or pure
    pjit/GSPMD, where XLA inserts the collectives itself) it is a no-op.

    Now genuinely "fused": the tree is flattened into dtype-bucketed flat
    segments and exchanged over ONE axis tuple via
    ``distributed/compressed.py`` — one collective per bucket instead of one
    per tensor (the reference hybrid_parallel_util.py:117 bucketing).
    ``grad_sync`` picks the wire format ("fp32" | "bf16" | "int8" | "int4",
    or a per-axis {axis: policy} mapping for DCN gating); the quantized
    policies take and return an error-feedback ``residuals`` pytree, in
    which case the return is ``(grads, new_residuals)``."""
    from ..compressed import compressed_tree_mean
    live = []
    for ax in axes:
        try:
            lax.axis_index(ax)
            live.append(ax)
        except Exception:
            pass
    if not live:
        return grads if residuals is None else (grads, residuals)
    grads, new_res = compressed_tree_mean(
        grads, tuple(live), policy=grad_sync, block=block,
        bucket_bytes=bucket_bytes, residuals=residuals)
    return grads if residuals is None else (grads, new_res)
