"""Mixture-of-Experts layer with expert parallelism over a mesh axis.

The reference has no MoE (SURVEY.md §2 EP row: absent; only
operators/collective/alltoall_op.cc exists as the building block). To meet
"same capabilities" the framework ships the capability class: top-k gated
MoE whose experts are sharded over the "model" (or a dedicated) mesh axis,
with lax.all_to_all dispatch/combine — the TPU-native version of what
alltoall_op.cc enables.

Design (static shapes, MXU-friendly): capacity-based dispatch. Each device
routes its tokens to per-expert buffers of fixed capacity C (drop+pad, like
GShard/Switch), all_to_all's them over the expert axis, applies its local
experts batched, and all_to_all's back.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.layers.common import Linear

EXPERT_AXIS = "model"


def _in_axis(axis):
    try:
        lax.axis_index(axis)
        return True
    except Exception:
        return False


def top2_gating(logits, capacity):
    """Top-2 gating with load-balancing aux loss (GShard-style).

    logits: (T, E). Returns (combine (T, E, C), dispatch bool (T, E, C),
    aux_loss scalar).
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    g1 = jnp.max(probs, axis=-1)
    e1 = jnp.argmax(probs, axis=-1)
    probs_wo1 = probs * (1.0 - jax.nn.one_hot(e1, E))
    g2 = jnp.max(probs_wo1, axis=-1)
    e2 = jnp.argmax(probs_wo1, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    # aux loss: mean prob per expert × fraction of tokens routed to it
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(e1, E), axis=0)
    aux = jnp.sum(me * ce) * E

    def positions(e_idx):
        onehot = jax.nn.one_hot(e_idx, E, dtype=jnp.int32)  # (T, E)
        pos = jnp.cumsum(onehot, axis=0) - 1                # position in expert
        return onehot, pos

    oh1, pos1 = positions(e1)
    # second choice queues behind first-choice tokens of the same expert
    oh2, pos2_raw = positions(e2)
    counts1 = jnp.sum(oh1, axis=0, keepdims=True)
    pos2 = pos2_raw + counts1

    def build(onehot, pos, gate):
        keep = (jnp.sum(onehot * pos, axis=-1) < capacity) & (gate > 0)
        slot = jnp.sum(onehot * pos, axis=-1)
        disp = (onehot.astype(bool) & keep[:, None])[..., None] & \
            (jax.nn.one_hot(slot, capacity, dtype=jnp.int32)[:, None, :] > 0)
        comb = disp.astype(jnp.float32) * gate[:, None, None]
        return comb, disp

    c1, d1 = build(oh1, pos1, g1)
    c2, d2 = build(oh2, pos2, g2)
    return c1 + c2, d1 | d2, aux


class ExpertFFN(Layer):
    def __init__(self, d_model, d_hidden):
        super().__init__()
        self.fc1 = Linear(d_model, d_hidden)
        self.fc2 = Linear(d_hidden, d_model)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x), approximate=True))


class MoELayer(Layer):
    """Top-2 MoE with expert parallelism.

    Expert weights are STACKED along a leading (E, ...) axis — one batched
    einsum applies all (local) experts, and sharding that axis over
    ``axis_name`` (e.g. NamedSharding(mesh, P("model"))) shards parameter
    memory E/n-per-device; inside shard_map the local slice is selected with
    one dynamic_slice, not an O(E) switch. num_experts must be divisible by
    the expert-axis size. Outside shard_map (single device) all experts run
    locally — same numerics.

    The load-balancing aux loss is written to the non-persistable buffer
    ``aux_loss`` so it flows out of jitted functional_call as a value (read
    it from new_buffers, or eagerly as ``moe.aux_loss``) instead of leaking
    a tracer through a Python attribute.
    """

    def __init__(self, d_model, d_hidden, num_experts, capacity_factor=2.0,
                 axis_name=EXPERT_AXIS, gate_weight_attr=None):
        super().__init__()
        from ..nn.initializer import XavierUniform
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.axis_name = axis_name
        self.gate = Linear(d_model, num_experts, bias_attr=False)
        E = num_experts
        self.w1 = self.create_parameter(
            (E, d_model, d_hidden),
            initializer=XavierUniform(fan_in=d_model, fan_out=d_hidden))
        self.b1 = self.create_parameter((E, d_hidden), is_bias=True)
        self.w2 = self.create_parameter(
            (E, d_hidden, d_model),
            initializer=XavierUniform(fan_in=d_hidden, fan_out=d_model))
        self.b2 = self.create_parameter((E, d_model), is_bias=True)
        self.register_buffer("aux_loss", jnp.zeros((), jnp.float32),
                             persistable=False)

    def _run_experts(self, buf, w1, b1, w2, b2):
        """buf: (e, C, D) through e stacked experts → (e, C, D)."""
        dt = buf.dtype
        h = jnp.einsum("ecd,edh->ech", buf, w1.astype(dt)) + \
            b1.astype(dt)[:, None, :]
        h = F.gelu(h, approximate=True)
        return jnp.einsum("ech,ehd->ecd", h, w2.astype(dt)) + \
            b2.astype(dt)[:, None, :]

    def forward(self, x):
        b, s, d = x.shape
        tokens = jnp.reshape(x, (b * s, d))
        T = tokens.shape[0]
        E = self.num_experts
        in_spmd = _in_axis(self.axis_name)
        n = lax.axis_size(self.axis_name) if in_spmd else 1
        cap = int(self.capacity_factor * T * 2 / E) or 1
        # round capacity to a lane-friendly size
        cap = max(8, ((cap + 7) // 8) * 8)

        logits = self.gate(tokens)
        combine, dispatch, aux = top2_gating(logits, cap)
        self.aux_loss = aux

        w1, b1 = self.w1.value, self.b1.value
        w2, b2 = self.w2.value, self.b2.value
        # dispatch: (T, E, C) x (T, D) → (E, C, D)
        expert_in = jnp.einsum("tec,td->ecd",
                               dispatch.astype(tokens.dtype), tokens)
        if in_spmd and n > 1:
            # (E, C, D) → all_to_all over expert axis: every device keeps its
            # E/n experts' buffers from ALL devices → (E/n, n*C, D)
            expert_in = lax.all_to_all(expert_in, self.axis_name,
                                       split_axis=0, concat_axis=1,
                                       tiled=True)
            local = E // n
            start = lax.axis_index(self.axis_name) * local
            expert_out = self._run_experts(
                expert_in,
                lax.dynamic_slice_in_dim(w1, start, local, 0),
                lax.dynamic_slice_in_dim(b1, start, local, 0),
                lax.dynamic_slice_in_dim(w2, start, local, 0),
                lax.dynamic_slice_in_dim(b2, start, local, 0))
            expert_out = lax.all_to_all(expert_out, self.axis_name,
                                        split_axis=1, concat_axis=0,
                                        tiled=True)  # (E, C, D)
        else:
            expert_out = self._run_experts(expert_in, w1, b1, w2, b2)

        out = jnp.einsum("tec,ecd->td", combine.astype(tokens.dtype),
                         expert_out)
        return jnp.reshape(out, (b, s, d))
