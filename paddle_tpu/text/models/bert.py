"""BERT/ERNIE model family — bidirectional encoder for BASELINE.md config 3
("ERNIE/BERT-base AMP pretrain").

The reference trains ERNIE (a BERT-architecture encoder with
knowledge-masking pretraining) through the same fleet hybrid-parallel stack
as GPT (SURVEY.md §2 C50 TP layers, C43 AMP). This implementation is
TPU-first, sharing the GPT building blocks:
- attention via F.scaled_dot_product_attention → Pallas flash attention
  (bidirectional, is_causal=False);
- QKV/MLP matmuls Column/RowParallelLinear on the "model" mesh axis;
- bf16 compute via amp.auto_cast, master-fp32 weights;
- MLM + NSP pretraining heads (the reference's ernie pretrain objective
  class), parallel (vocab-sharded) cross entropy for the MLM loss.
"""
from __future__ import annotations

import jax.numpy as jnp

from ... import nn
from ...nn import functional as F
from ...nn.initializer import Normal
from ...nn.layer import Layer
from ...distributed.meta_parallel.parallel_layers.mp_layers import (
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding)
from .gpt import GPTAttention, GPTMLP

__all__ = [
    "BertEmbeddings", "BertEncoderLayer", "BertModel", "BertPooler",
    "BertPretrainingHeads", "BertForPretraining",
    "BertForSequenceClassification", "ErnieModel", "ErnieForPretraining",
    "bert_base", "bert_large",
]


class BertEmbeddings(Layer):
    """word + position + token-type embeddings, LN, dropout (reference BERT
    embedding; ernie shares the layout)."""

    def __init__(self, vocab_size, hidden_size, max_position_embeddings=512,
                 type_vocab_size=2, hidden_dropout_prob=0.1,
                 layer_norm_epsilon=1e-12, tensor_parallel=True):
        super().__init__()
        emb_cls = VocabParallelEmbedding if tensor_parallel else nn.Embedding
        self.word_embeddings = emb_cls(vocab_size, hidden_size)
        self.position_embeddings = nn.Embedding(max_position_embeddings,
                                                hidden_size)
        self.token_type_embeddings = nn.Embedding(type_vocab_size, hidden_size)
        self.layer_norm = nn.LayerNorm(hidden_size,
                                       epsilon=layer_norm_epsilon)
        self.dropout = nn.Dropout(hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        s = input_ids.shape[-1]
        if position_ids is None:
            position_ids = jnp.arange(s, dtype=jnp.int32)[None, :]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class BertEncoderLayer(Layer):
    """Post-LN transformer encoder layer (BERT layout: residual→LN, unlike
    GPT's pre-LN). Attention is bidirectional."""

    def __init__(self, hidden_size, num_heads, intermediate_size=None,
                 attn_dropout=0.1, hidden_dropout=0.1,
                 layer_norm_epsilon=1e-12, tensor_parallel=True,
                 mp_degree=1):
        super().__init__()
        intermediate_size = intermediate_size or 4 * hidden_size
        self.attn = GPTAttention(hidden_size, num_heads, attn_dropout,
                                 hidden_dropout, tensor_parallel, mp_degree,
                                 causal=False)
        self.ln_1 = nn.LayerNorm(hidden_size, epsilon=layer_norm_epsilon)
        self.mlp = GPTMLP(hidden_size, intermediate_size, hidden_dropout,
                          tensor_parallel)
        self.ln_2 = nn.LayerNorm(hidden_size, epsilon=layer_norm_epsilon)

    def forward(self, x, attn_mask=None):
        x = self.ln_1(x + self.attn(x, attn_mask))
        x = self.ln_2(x + self.mlp(x))
        return x


class BertPooler(Layer):
    def __init__(self, hidden_size):
        super().__init__()
        self.dense = nn.Linear(hidden_size, hidden_size)

    def forward(self, hidden_states):
        return jnp.tanh(self.dense(hidden_states[:, 0]))


class BertModel(Layer):
    """Bidirectional encoder trunk + pooler."""

    def __init__(self, vocab_size=30522, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=None,
                 max_position_embeddings=512, type_vocab_size=2,
                 attn_dropout=0.1, hidden_dropout=0.1,
                 layer_norm_epsilon=1e-12, tensor_parallel=True,
                 mp_degree=1, with_pool=True):
        super().__init__()
        self.hidden_size = hidden_size
        self.embeddings = BertEmbeddings(
            vocab_size, hidden_size, max_position_embeddings,
            type_vocab_size, hidden_dropout, layer_norm_epsilon,
            tensor_parallel)
        self.encoder = nn.LayerList([
            BertEncoderLayer(hidden_size, num_heads, intermediate_size,
                             attn_dropout, hidden_dropout,
                             layer_norm_epsilon, tensor_parallel, mp_degree)
            for _ in range(num_layers)])
        self.pooler = BertPooler(hidden_size) if with_pool else None

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attn_mask=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        for layer in self.encoder:
            x = layer(x, attn_mask)
        pooled = self.pooler(x) if self.pooler is not None else None
        return x, pooled


class BertPretrainingHeads(Layer):
    """MLM transform + (tied) vocab projection and NSP binary head."""

    def __init__(self, hidden_size, vocab_size, embedding_weight=None,
                 layer_norm_epsilon=1e-12, tensor_parallel=True):
        super().__init__()
        self.transform = nn.Linear(hidden_size, hidden_size)
        self.layer_norm = nn.LayerNorm(hidden_size,
                                       epsilon=layer_norm_epsilon)
        if embedding_weight is not None:
            self.decoder_weight = embedding_weight  # tied (vocab, hidden)
            vocab_size = embedding_weight.shape[0]
        else:
            self.decoder_weight = self.create_parameter(
                (vocab_size, hidden_size), initializer=Normal(0.0, 0.02))
            if tensor_parallel:
                from jax.sharding import PartitionSpec as P
                self.decoder_weight.pspec = P("model", None)
        self.decoder_bias = self.create_parameter(
            (vocab_size,), is_bias=True)
        if tensor_parallel:
            # logits arrive vocab-sharded under shard_map TP — the bias must
            # shard the same way (cf. ColumnParallelLinear bias.pspec)
            from jax.sharding import PartitionSpec as P
            self.decoder_bias.pspec = P("model")
        self.seq_relationship = nn.Linear(hidden_size, 2)

    def forward(self, sequence_output, pooled_output):
        from ...distributed.meta_parallel.parallel_layers.mp_layers import (
            _in_shard_map, copy_to_model_parallel)
        h = self.layer_norm(F.gelu(self.transform(sequence_output),
                                   approximate=True))
        if _in_shard_map():
            h = copy_to_model_parallel(h)  # see GPTLMHead
        mlm_logits = jnp.matmul(
            h, jnp.swapaxes(self.decoder_weight.value, 0, 1)) \
            + self.decoder_bias.value
        nsp_logits = self.seq_relationship(pooled_output)
        return mlm_logits, nsp_logits


class BertForPretraining(Layer):
    """MLM + NSP pretraining objective (reference ernie pretrain task)."""

    def __init__(self, bert: BertModel = None, tensor_parallel=True,
                 **kwargs):
        super().__init__()
        self.bert = bert or BertModel(tensor_parallel=tensor_parallel,
                                      **kwargs)
        self.cls = BertPretrainingHeads(
            self.bert.hidden_size, 0,
            embedding_weight=self.bert.embeddings.word_embeddings.weight,
            tensor_parallel=tensor_parallel)
        self.parallel_loss = ParallelCrossEntropy()

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attn_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, position_ids,
                                attn_mask)
        return self.cls(seq, pooled)

    def loss(self, mlm_logits, nsp_logits, mlm_labels, nsp_labels,
             ignore_index=-100):
        """Masked-LM CE (ignoring unmasked positions) + NSP CE."""
        per_tok = self.parallel_loss(mlm_logits, jnp.maximum(mlm_labels, 0))
        mask = (mlm_labels != ignore_index).astype(per_tok.dtype)
        mlm = jnp.sum(per_tok * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        nsp = jnp.mean(F.cross_entropy(nsp_logits, nsp_labels,
                                       reduction="none"))
        return mlm + nsp


class BertForSequenceClassification(Layer):
    def __init__(self, bert: BertModel = None, num_classes=2, dropout=0.1,
                 tensor_parallel=False, **kwargs):
        super().__init__()
        self.bert = bert or BertModel(tensor_parallel=tensor_parallel,
                                      **kwargs)
        self.dropout = nn.Dropout(dropout)
        self.classifier = nn.Linear(self.bert.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attn_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, position_ids,
                              attn_mask)
        return self.classifier(self.dropout(pooled))


# ERNIE is the BERT architecture with knowledge-masked pretraining data; the
# network classes are shared (reference ernie uses the same encoder stack).
ErnieModel = BertModel
ErnieForPretraining = BertForPretraining


def bert_base(**kw):
    cfg = dict(vocab_size=30522, hidden_size=768, num_layers=12,
               num_heads=12, max_position_embeddings=512)
    cfg.update(kw)
    return cfg


def bert_large(**kw):
    cfg = dict(vocab_size=30522, hidden_size=1024, num_layers=24,
               num_heads=16, max_position_embeddings=512)
    cfg.update(kw)
    return cfg
