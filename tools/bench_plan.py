#!/usr/bin/env python
"""Bench the auto-parallel planner end to end: search, pick, run, drift.

Runs ``distributed/auto.plan_search`` over the bench-config GPT at the
host's device count, compares the pick's calibrated predicted step time
against the two baselines the planner must beat (the naive all-data-
parallel layout and ``auto.plan()``'s memory-ordered pick), then —
unless ``--plan-only`` — builds the chosen config for real via
``ParallelTrainer.from_plan`` and measures it, recording the
predicted/measured pair under the ``planner_step_time`` calibration key
so the drift between planned and actual step time lands in
``calibration_drift_ratio{key=planner_step_time}``.

The runnable search space here is the subspace the plain
``GPTForPretraining`` builder can realize (data x sharding
factorizations, grad_sync policy / dcn gating / buckets, remat; TP when
the hidden size supports it): pipe and sep need the model-side wrappers
(`PipelineParallel`, sep-aware attention) that this flat builder does
not construct, so ``--max-pipe/--max-sep`` default to 1. The FULL
five-axis space is exercised by ``plan_search``'s own tests.

Output: ONE JSON line on stdout (schema_version 2), like every bench
tool. ``--smoke`` shrinks shapes/steps for CI; ``--plan-only`` skips
building/measuring entirely (the two-process determinism test diffs the
ranked plan list of two such runs).
"""
import argparse
import json
import sys
import time


def _args():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes, 2 steps (CI)")
    ap.add_argument("--plan-only", action="store_true",
                    help="search + rank only; no staging, no measuring")
    ap.add_argument("--devices", type=int, default=8,
                    help="simulated host devices (XLA_FLAGS, default 8)")
    ap.add_argument("--top-k", type=int, default=8)
    ap.add_argument("--stage-top-k", type=int, default=2,
                    help="analytic top-k re-scored from their staged "
                         "step (0 = analytic only)")
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--max-pipe", type=int, default=1)
    ap.add_argument("--max-sep", type=int, default=1)
    ap.add_argument("--zero-stage", type=int, default=1)
    return ap.parse_args()


def _gpt_spec(smoke: bool):
    if smoke:
        return dict(vocab=256, h=64, layers=1, heads=2, seq=32,
                    batch_per_device=4)
    # the bench.py CPU gpt_base shape
    return dict(vocab=1024, h=128, layers=2, heads=4, seq=128,
                batch_per_device=4)


def make_gpt_builder(spec: dict, global_batch: int):
    """``builder(plan) -> (trainer, inputs, labels)`` over the plain
    bench GPT — used for plan_search's staged tier AND to build the
    winning config for measurement (same construction path both ways,
    so the staged score prices exactly what gets run)."""
    def build(plan):
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu import nn
        from paddle_tpu.distributed.engine import ParallelTrainer
        from paddle_tpu.text.models import GPTForPretraining

        paddle.seed(0)
        mesh = plan.build_mesh()
        model = GPTForPretraining(
            tensor_parallel=plan.degrees.get("model", 1) > 1,
            vocab_size=spec["vocab"], hidden_size=spec["h"],
            num_layers=spec["layers"], num_heads=spec["heads"],
            max_position_embeddings=spec["seq"], attn_dropout=0.0,
            hidden_dropout=0.0)
        model.bfloat16()
        opt = paddle.optimizer.AdamW(3e-4, parameters=model.parameters())
        trainer = ParallelTrainer.from_plan(
            plan, model, opt,
            lambda logits, lbl: nn.functional.cross_entropy(logits, lbl),
            mesh=mesh)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, spec["vocab"],
                          (global_batch, spec["seq"])).astype("int32")
        labels = rng.randint(0, spec["vocab"],
                             (global_batch, spec["seq"])).astype("int32")
        return trainer, ids, labels
    return build


def count_gpt_params(spec: dict) -> int:
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.text.models import GPTForPretraining

    paddle.seed(0)
    model = GPTForPretraining(
        tensor_parallel=False, vocab_size=spec["vocab"],
        hidden_size=spec["h"], num_layers=spec["layers"],
        num_heads=spec["heads"], max_position_embeddings=spec["seq"],
        attn_dropout=0.0, hidden_dropout=0.0)
    return sum(int(np.prod(p.shape)) for p in model.parameters())


def search(spec: dict, n_devices: int, *, top_k=8, stage_top_k=0,
           builder=None, max_pipe=1, max_sep=1, zero_stage=1,
           hbm_bytes=16e9):
    """plan_search over the bench GPT spec; returns (ranked, baselines).

    ``baselines`` prices the naive all-DP layout and ``auto.plan()``'s
    memory-ordered pick with the SAME analytic calibrated model, plus
    the strict-beat verdicts the acceptance criterion asks for —
    compared on the analytic tier so all three share one scale."""
    from paddle_tpu.distributed import auto

    n_params = count_gpt_params(spec)
    global_batch = spec["batch_per_device"] * n_devices
    kw = dict(layers=spec["layers"], hidden=spec["h"],
              seq_len=spec["seq"], global_batch=global_batch,
              batch_per_device=spec["batch_per_device"],
              hbm_bytes=hbm_bytes, param_bytes=2, zero_stage=zero_stage,
              max_pipe=max_pipe, max_sep=max_sep,
              micro_choices=(1,), top_k=top_k)
    ranked = auto.plan_search(n_params, n_devices, **kw)
    score_kw = dict(layers=spec["layers"], hidden=spec["h"],
                    seq_len=spec["seq"], global_batch=global_batch,
                    param_bytes=2)

    all_dp = auto.Plan(
        degrees={"data": n_devices, "sharding": 1, "model": 1,
                 "pipe": 1, "sep": 1},
        per_device=auto._estimate(
            n_params, {"data": n_devices, "sharding": 1, "model": 1,
                       "pipe": 1, "sep": 1},
            layers=spec["layers"], hidden=spec["h"], seq_len=spec["seq"],
            batch_per_device=spec["batch_per_device"], param_bytes=2,
            zero_stage=zero_stage, remat=False),
        hbm_bytes=hbm_bytes, zero_stage=zero_stage)
    auto.score_plan(all_dp, n_params, **score_kw)
    mem_pick = auto.plan(
        n_params, n_devices, layers=spec["layers"], hidden=spec["h"],
        seq_len=spec["seq"], batch_per_device=spec["batch_per_device"],
        hbm_bytes=hbm_bytes, param_bytes=2, zero_stage=zero_stage,
        max_model=max(1, spec["h"] // 128))
    auto.score_plan(mem_pick, n_params, **score_kw)

    pick_t = ranked[0].predicted.total
    baselines = {
        "pick_predicted_s": pick_t,
        "all_dp_predicted_s": all_dp.predicted.total,
        "memory_pick_predicted_s": mem_pick.predicted.total,
        "memory_pick_degrees": {k: mem_pick.degrees[k]
                                for k in sorted(mem_pick.degrees)},
        "pick_beats_all_dp": pick_t < all_dp.predicted.total,
        "pick_beats_memory_pick": pick_t < mem_pick.predicted.total,
    }
    if stage_top_k > 0 and builder is not None:
        ranked = auto.plan_search(n_params, n_devices, builder=builder,
                                  stage_top_k=stage_top_k, **kw)
    return ranked, baselines, n_params


def main():
    args = _args()
    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from _mesh_setup import ensure_repo_on_path, force_host_devices
    force_host_devices(args.devices)
    ensure_repo_on_path()
    import jax

    from paddle_tpu import telemetry

    t0 = time.perf_counter()
    n_devices = len(jax.devices())
    spec = _gpt_spec(args.smoke)
    global_batch = spec["batch_per_device"] * n_devices
    builder = make_gpt_builder(spec, global_batch)
    stage_k = 0 if args.plan_only else args.stage_top_k
    ranked, baselines, n_params = search(
        spec, n_devices, top_k=args.top_k, stage_top_k=stage_k,
        builder=builder, max_pipe=args.max_pipe, max_sep=args.max_sep,
        zero_stage=args.zero_stage)
    pick = ranked[0]
    predicted_s = pick.predicted.total

    out = {
        "schema_version": 2,
        "bench": "plan",
        "metric": "planner_step_time_ms",
        "unit": "ms",
        "value": round(predicted_s * 1e3, 6),
        "devices": n_devices,
        "params": n_params,
        "smoke": bool(args.smoke),
        "plan_only": bool(args.plan_only),
        "pick": pick.to_dict(),
        "plans": [p.to_dict() for p in ranked],
        "baselines": baselines,
        "calibration": None,
        "search_ms": round((time.perf_counter() - t0) * 1e3, 3),
    }
    if not args.plan_only:
        trainer, ids, labels = builder(pick)
        steps = max(1, 1 if args.smoke else args.steps)
        warmup = max(1, 1 if args.smoke else args.warmup)
        for _ in range(warmup):
            loss = trainer.train_step(ids, labels)
        float(loss)
        t1 = time.perf_counter()
        for _ in range(steps):
            loss = trainer.train_step(ids, labels)
        final_loss = float(loss)
        measured_s = (time.perf_counter() - t1) / steps
        telemetry.calibration.record("planner_step_time", predicted_s,
                                     measured_s)
        out["value"] = round(measured_s * 1e3, 6)
        out["predicted_ms"] = round(predicted_s * 1e3, 6)
        out["measured_ms"] = round(measured_s * 1e3, 6)
        out["final_loss"] = round(final_loss, 4)
        # predicted/measured/drift triple from the calibration registry
        out["calibration"] = telemetry.calibration.pair(
            "planner_step_time")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
