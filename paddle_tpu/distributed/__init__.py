"""paddle_tpu.distributed (reference: python/paddle/distributed/).

Collectives (all_reduce/all_gather/…) are lax.p* ops over named mesh axes —
see collective.py; topology/fleet in fleet/; parallel layers in meta_parallel/.
"""
from .env import ParallelEnv, get_rank, get_world_size, init_parallel_env  # noqa: F401
from .collective import (  # noqa: F401
    all_gather, all_reduce, alltoall, barrier, broadcast, get_group,
    new_group, recv, reduce, reduce_scatter, scatter, send, split,
    split_group, wait, ReduceOp)
from .entry import (CountFilterEntry, EntryAttr,  # noqa: F401
                    ProbabilityEntry)
from .ps.datafeed import InMemoryDataset, QueueDataset  # noqa: F401
from .parallel import DataParallel  # noqa: F401
from . import fleet  # noqa: F401
from .spawn import spawn  # noqa: F401
from . import checkpoint  # noqa: F401
from . import ps  # noqa: F401
from . import sharding  # noqa: F401,E402
from . import auto  # noqa: F401,E402
