"""A/B the device-resident hot embedding tier (HeterEmbedding) against
the host pure_callback-per-lookup PS path (DistributedEmbedding) on the
Wide&Deep CTR workload (BASELINE configs[4]).

Run: python tools/bench_heter_embedding.py   (SMOKE=1 for a tiny CPU
config). Prints samples/sec for both paths + the hot-tier hit rate.
Target (round-3 verdict item 2): device path >= 10x the host path on
chip. Only a host scalar fetch is a trustworthy sync through the device
tunnel — see bench.py `_timed_steps`.
"""
import os
import time

import numpy as np


def main():
    import paddle_tpu as paddle
    from paddle_tpu.distributed.engine import ParallelTrainer
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.rec import WideDeep
    import jax.numpy as jnp

    smoke = os.environ.get("SMOKE") == "1"
    if smoke:
        fields, batch, steps, warmup = [1000] * 8, 256, 4, 2
        hidden, cap = (64, 32), 4096
    else:
        fields, batch, steps, warmup = [100_000] * 26, 4096, 20, 8
        hidden, cap = (400, 400, 400), 1_000_000

    rng = np.random.RandomState(0)
    # zipf-ish skew: real CTR traffic is head-heavy, which is what a
    # cache tier exploits
    def draw_ids():
        u = rng.zipf(1.3, size=(batch, len(fields)))
        return (u % np.asarray(fields)[None, :]).astype("int64")

    batches = [(draw_ids(), rng.randn(batch, 13).astype("float32"),
                rng.randint(0, 2, batch).astype("float32"))
               for _ in range(steps + warmup)]

    def bce(logit, y):
        return jnp.mean(jnp.maximum(logit, 0) - logit * y
                        + jnp.log1p(jnp.exp(-jnp.abs(logit))))

    results = {}
    for mode in ("heter", True):
        paddle.seed(0)
        build_mesh({"data": 1})
        model = WideDeep(fields, dense_dim=13, embedding_dim=16,
                         hidden_sizes=hidden, sparse=mode,
                         heter_capacity=cap)
        opt = paddle.optimizer.Adagrad(0.05, epsilon=1e-8,
                                       parameters=model.parameters())
        tr = ParallelTrainer(model, opt, bce)

        def step(ids, dense, y):
            if mode == "heter":
                ids = model.prepare_batch(ids)
            return tr.train_step((ids, dense), y)

        for ids, dense, y in batches[:warmup]:
            loss = step(ids, dense, y)
        float(loss)
        t0 = time.perf_counter()
        for ids, dense, y in batches[warmup:]:
            loss = step(ids, dense, y)
        float(loss)
        dt = time.perf_counter() - t0
        name = "heter_device_tier" if mode == "heter" else "host_ps_tier"
        results[name] = batch * steps / dt
        line = f"{name:18s}: {results[name]:12,.1f} samples/sec"
        if mode == "heter":
            line += (f"  (hot hit rate {model.ctr_table.hit_rate:.3f}, "
                     f"evicts {model.ctr_table.stats['evicts']})")
        print(line)
    print(f"device/host speedup: "
          f"{results['heter_device_tier'] / results['host_ps_tier']:.1f}x")


if __name__ == "__main__":
    main()
