"""Speculative decoding on the paged KV cache (ISSUE 16).

Every generated token normally costs one full target-model step. A
*drafter* breaks that coupling: it proposes K cheap draft tokens, the
request's next chunk becomes ``[last_generated, d1..dK]`` — a K+1-token
"prefill" row against the shared prefix — and ONE target-model step
verifies the whole window by re-entering the same token-denominated
mixed prefill/decode batcher (:class:`~paddle_tpu.inference.serving.
DecodeServer`). The executor already returns per-slot next tokens, so
slot ``i`` of the chunk yields the greedy continuation after
``chunk[:i+1]``; draft ``d_i`` is accepted iff it equals slot ``i-1``'s
greedy token, and the accepted run always ends with one *bonus* token
the target model produced itself. Greedy acceptance makes the output
token stream EXACTLY the non-speculative greedy stream
(``decode_model.dense_generate`` is the oracle) — speculation changes
cost, never content.

Cache discipline: at draft time the sequence's ``CacheSeq`` is
COW-forked (:meth:`PagedKVCache.fork`) — the fork pins the shared
prefix pages for the in-flight verify window, so eviction pressure
cannot pull pages out from under a speculative step. On a full accept
the chunk's K/V is appended to the FORK (exercising copy-on-write off
the shared tail page) and the fork becomes the sequence; on a partial
or zero accept the fork is released first and only the verified prefix
of the chunk is appended to the original sequence. All of this happens
in ``_commit_chunk`` — after ``try_finish``, like every cache write in
the server — so a failover mid-verify re-runs the identical chunk
idempotently and a cancelled step never touched the cache.

Shape closure: a drafter must return EXACTLY ``k`` tokens or none, so
chunk lengths stay in ``{1, 1+k}`` and the executor's (T, R) bucket
set — now with the K+1-token verify rows bucketed like any prefill
chunk — remains closed. ``k = 0`` (or a drafter with nothing to say)
degrades to plain one-token decode.

Drafters:

- :class:`NGramDrafter` — self-speculative: match the longest recent
  n-gram of the history against its earlier occurrences and replay the
  continuation. Free (no model), surprisingly effective on repetitive
  or prefix-heavy workloads.
- :class:`DraftModelDrafter` — pluggable small-model hook; any
  ``fn(history_tokens, k) -> tokens``. ``from_decode_server`` routes
  drafting through another (smaller) :class:`DecodeServer`, so the
  draft model runs on the same serving machinery.

Observability: ``spec_draft_tokens_total`` / ``spec_accepted_tokens_
total`` counters, a ``spec_accept_rate`` histogram and
``spec_verify_steps_total`` land in the metrics registry; each verify
dispatch carries a ``spec_verify`` phase label on its per-re-entry
trace span plus a ``spec_verify`` event with drafted/accepted counts.
``stats()["spec_decode"]`` reports the aggregate accept rate and decode
tokens per target-model step — the quantity speculation multiplies.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np

from ..telemetry import tracing as _tracing
from .serving import DecodeServer, GenerationRequest

__all__ = ["NGramDrafter", "DraftModelDrafter", "SpeculativeDecodeServer"]


class NGramDrafter:
    """Self-speculative drafter: if the last ``n`` tokens of the history
    occurred before, propose the ``k`` tokens that followed that earlier
    occurrence (longest ``n`` wins, most recent occurrence wins). Short
    continuations are padded by repeating their last token — the
    contract is exactly ``k`` tokens or none."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1,
                 max_history: int = 512):
        if not 1 <= int(min_ngram) <= int(max_ngram):
            raise ValueError("need 1 <= min_ngram <= max_ngram")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        self.max_history = int(max_history)

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        hist = [int(t) for t in history][-self.max_history:]
        for n in range(min(self.max_ngram, len(hist) - 1),
                       self.min_ngram - 1, -1):
            tail = hist[-n:]
            for i in range(len(hist) - n - 1, -1, -1):
                if hist[i:i + n] == tail:
                    cont = hist[i + n:i + n + k]
                    if cont:
                        cont += [cont[-1]] * (k - len(cont))
                        return cont
        return []


class DraftModelDrafter:
    """Drafts from a small model: ``draft_fn(history_tokens, k)`` returns
    the proposed continuation (truncated / padded here to exactly ``k``;
    an empty or failed draft degrades to plain decode)."""

    def __init__(self, draft_fn: Callable[[List[int], int], Sequence[int]]):
        self.draft_fn = draft_fn

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        try:
            out = [int(t) for t in self.draft_fn(list(history), k)]
        except Exception:  # noqa: BLE001 - a failed draft is just "no draft"
            return []
        if not out:
            return []
        out = out[:k]
        return out + [out[-1]] * (k - len(out))

    @classmethod
    def from_decode_server(cls, server: DecodeServer,
                           timeout: Optional[float] = 30.0
                           ) -> "DraftModelDrafter":
        """Route drafting through another DecodeServer (the small draft
        model on the same serving machinery). Shed / failed / timed-out
        draft generations degrade to plain decode."""

        def fn(history: List[int], k: int) -> List[int]:
            req = server.submit_generate(history, k)
            return [int(t) for t in req.result(timeout=timeout)[0]]

        return cls(fn)


class SpeculativeDecodeServer(DecodeServer):
    """:class:`DecodeServer` whose decode steps are speculative.

    ``drafter`` proposes ``spec_k`` tokens per decode step (exactly
    ``spec_k`` or none); verify rides the normal batcher as a 1+K-token
    chunk, so prefill, mixed batches, admission, failover and drain are
    untouched. Exactness: output == plain greedy decode, token for
    token."""

    def __init__(self, step_fns, cache, drafter=None, spec_k: int = 4,
                 **kw):
        super().__init__(step_fns, cache, **kw)
        if drafter is None:
            drafter = NGramDrafter()
        self.drafter = drafter
        # 1 + k must fit the per-dispatch token budget
        self.spec_k = max(0, min(int(spec_k), self.cfg.max_batch - 1))

    # -- scheduling ----------------------------------------------------------

    def _assign_chunk(self, req: GenerationRequest):
        super()._assign_chunk(req)          # prefill walk / plain decode
        req.spec_draft = []
        if (self.spec_k < 1 or len(req.chunk) != 1
                or req.seq.length < len(req.prompt)
                or req.max_new - len(req.generated) < 2):
            return
        draft = self.drafter.propose(req.prompt + req.generated,
                                     self.spec_k)
        if not draft:
            return                          # K=0 fallback: plain decode
        if len(draft) != self.spec_k:
            raise ValueError(
                f"drafter returned {len(draft)} tokens, wants 0 or "
                f"{self.spec_k} (chunk lengths must stay bucketed)")
        # pin the shared prefix for the in-flight verify window; the
        # fork survives failover requeues (chunk re-runs identically)
        req.draft_fork = self.cache.fork(req.seq)
        req.spec_draft = [int(t) for t in draft]
        req.chunk = req.chunk + req.spec_draft
        req.rows = len(req.chunk)
        self._count("spec_draft_tokens_total", n=self.spec_k)
        self._count_only("spec_drafted", self.spec_k)

    def _phase_of(self, r) -> str:
        if getattr(r, "spec_draft", None):
            return "spec_verify"
        return super()._phase_of(r)

    # -- commit --------------------------------------------------------------

    def _commit_chunk(self, r: GenerationRequest, nxt: np.ndarray,
                      k_chunk: np.ndarray, v_chunk: np.ndarray):
        draft = getattr(r, "spec_draft", None)
        if not draft:
            before = len(r.generated)
            super()._commit_chunk(r, nxt, k_chunk, v_chunk)
            if len(r.generated) > before:
                self._count_only("target_steps")
            return
        k = len(draft)
        # slot i holds the greedy token AFTER chunk[:i+1]; draft d_i is
        # accepted iff it matches slot i-1's token (chained — a miss
        # invalidates everything behind it)
        j = 0
        while j < k and int(nxt[j]) == draft[j]:
            j += 1
        # the accepted run [nxt[0..j]] always includes the bonus token
        # the target model computed at the last matching position
        accepted = [int(t) for t in nxt[:j + 1]]
        room = r.max_new - len(r.generated)
        accepted = accepted[:room]
        if r.eos_token is not None and r.eos_token in accepted:
            accepted = accepted[:accepted.index(r.eos_token) + 1]
        # cache commit: chunk rows 0..j carry KV for tokens that are now
        # canonical (the step input + the j matched drafts), capped at
        # ``room`` so a max_new-truncating accept cannot push the
        # sequence past its admission-checked page budget. Full accept
        # adopts the fork (append COWs the shared tail page); otherwise
        # release the fork FIRST so the partial append doesn't COW
        # against our own speculative pin.
        n_kv = min(1 + j, room)
        fork = getattr(r, "draft_fork", None)
        r.draft_fork = None
        if j == k and fork is not None:
            self.cache.append(fork, r.chunk[:n_kv],
                              k_chunk[:, :n_kv], v_chunk[:, :n_kv])
            self.cache.release(r.seq)
            r.seq = fork
        else:
            if fork is not None:
                self.cache.release(fork)
            self.cache.append(r.seq, r.chunk[:n_kv],
                              k_chunk[:, :n_kv], v_chunk[:, :n_kv])
        r.generated.extend(accepted)
        r.spec_draft = []
        self._count("spec_accepted_tokens_total", n=j)
        self._count("spec_verify_steps_total")
        self._observe("spec_accept_rate", j / float(k))
        self._count_only("spec_accepted", j)
        self._count_only("spec_verify_steps")
        self._count_only("target_steps")
        self._count_only("decode_tokens", len(accepted))
        self._count("decode_tokens_total", n=len(accepted))
        _tracing.add_event("spec_verify", drafted=k, accepted=j,
                           tokens=len(accepted))

    # -- reporting -----------------------------------------------------------

    def stats(self):
        s = super().stats()
        with self._clock:
            drafted = self.counts.get("spec_drafted", 0)
            acc = self.counts.get("spec_accepted", 0)
            vsteps = self.counts.get("spec_verify_steps", 0)
            tsteps = self.counts.get("target_steps", 0)
            toks = self.counts.get("decode_tokens", 0)
        s["spec_decode"] = {
            "draft_tokens": drafted,
            "accepted_tokens": acc,
            "verify_steps": vsteps,
            "accept_rate": acc / drafted if drafted else 0.0,
            # decode tokens over EVERY target-model step that produced
            # any (plain + verify) — the quantity speculation
            # multiplies (1.0 == plain decode)
            "tokens_per_target_step":
                toks / tsteps if tsteps else 0.0,
        }
        return s
