"""Model summary + FLOPs (reference: python/paddle/hapi/model_summary.py,
dynamic_flops.py) — implemented via shape tracing with jax.eval_shape."""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def summary(net, input_size, dtypes=None):
    """Print a per-layer summary. input_size: tuple or list of tuples
    (batch dim may be None/-1 → treated as 1)."""
    if isinstance(input_size, tuple):
        input_sizes = [input_size]
    else:
        input_sizes = list(input_size)
    dtypes = dtypes or ["float32"] * len(input_sizes)
    inputs = []
    for shape, dt in zip(input_sizes, dtypes):
        shape = tuple(1 if s in (None, -1) else s for s in shape)
        inputs.append(jnp.zeros(shape, dtype=dt))

    rows = []
    hooks = []

    def make_hook(name, layer):
        def hook(l, inp, out):
            try:
                out_shape = list(out.shape) if hasattr(out, "shape") else "-"
            except Exception:
                out_shape = "-"
            n_params = sum(int(np.prod(p.shape))
                           for p in l._parameters.values() if p is not None)
            rows.append((f"{type(l).__name__}-{len(rows) + 1}", out_shape, n_params))
        return layer.register_forward_post_hook(hook)

    for name, layer in net.named_sublayers(include_self=False):
        if not layer._sub_layers:  # leaf layers only
            hooks.append(make_hook(name, layer))
    was_training = net.training
    net.eval()
    try:
        net(*inputs)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    total_params = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable_params = sum(int(np.prod(p.shape)) for p in net.parameters()
                           if p.trainable)
    header = f"{'Layer (type)':<30}{'Output Shape':<25}{'Param #':<12}"
    line = "-" * len(header)
    print(line)
    print(header)
    print("=" * len(header))
    for name, shape, n in rows:
        print(f"{name:<30}{str(shape):<25}{n:<12}")
    print("=" * len(header))
    print(f"Total params: {total_params:,}")
    print(f"Trainable params: {trainable_params:,}")
    print(f"Non-trainable params: {total_params - trainable_params:,}")
    print(line)
    return {"total_params": total_params, "trainable_params": trainable_params}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Rough FLOPs count via XLA cost analysis of the jitted forward."""
    from ..jit.functionalization import functional_call, state_of

    shape = tuple(1 if s in (None, -1) else s for s in input_size)
    x = jnp.zeros(shape, dtype="float32")
    params, buffers = state_of(net)

    def pure(p, b, xx):
        out, _ = functional_call(net, p, b, xx)
        return out

    try:
        lowered = jax.jit(pure).lower(params, buffers, x)
        compiled = lowered.compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        return int(ca.get("flops", 0))
    except Exception:
        return 0
