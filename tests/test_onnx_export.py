"""ONNX export: real wire-format emission for Sequential models, StableHLO
fallback otherwise (reference: python/paddle/onnx/export.py -> paddle2onnx).

The emitted bytes are validated with the dependency-free protobuf decoder in
paddle_tpu.onnx._pb (the `onnx` package is not in this image); when `onnx`
IS importable the checker test runs too.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.onnx import _pb
from paddle_tpu.static import InputSpec


def _decode_model(path):
    with open(path, "rb") as f:
        buf = f.read()
    model = _pb.decode(buf)
    assert model[1][0] == 8  # ir_version
    graph = _pb.decode(model[7][0])
    nodes = [_pb.decode(n) for n in graph.get(1, [])]
    inits = [_pb.decode(t) for t in graph.get(5, [])]
    return model, graph, nodes, inits


def _op_types(nodes):
    return [n[4][0].decode() for n in nodes]


def test_onnx_export_sequential_mlp(tmp_path):
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2),
                      nn.Softmax())
    m.eval()
    out = paddle.onnx.export(m, str(tmp_path / "mlp.onnx"),
                             input_spec=[InputSpec([None, 4], "float32")])
    assert out.endswith(".onnx") and os.path.exists(out)
    _, graph, nodes, inits = _decode_model(out)
    ops = _op_types(nodes)
    assert ops == ["Gemm", "Relu", "Gemm", "Softmax", "Identity"]
    # initializers: 2 weights + 2 biases, with correct dims
    dims = sorted(tuple(t.get(1, [])) for t in inits)
    assert ((4, 8) in dims) and ((8, 2) in dims)
    # weight payload round-trips bit-exact
    w0 = np.asarray(m[0].weight.value, dtype=np.float32)
    blobs = [np.frombuffer(t[9][0], dtype=np.float32) for t in inits]
    assert any(b.size == w0.size and
               np.array_equal(b.reshape(w0.shape), w0) for b in blobs)


def test_onnx_export_lenet(tmp_path):
    from paddle_tpu.vision.models import LeNet
    m = LeNet()
    m.eval()
    out = paddle.onnx.export(m, str(tmp_path / "lenet.onnx"),
                             input_spec=[InputSpec([None, 1, 28, 28],
                                                   "float32")])
    assert out.endswith(".onnx")
    _, graph, nodes, _ = _decode_model(out)
    ops = _op_types(nodes)
    assert ops.count("Conv") == 2 and ops.count("MaxPool") == 2
    assert ops.count("Gemm") == 3 and "Flatten" in ops
    # graph input/output value_info present
    vi_in = _pb.decode(graph[11][0])
    assert vi_in[1][0] == b"input"


def test_onnx_export_fallback_warns(tmp_path):
    class Residual(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            return x + self.fc(x)

    m = Residual()
    with pytest.warns(UserWarning, match="ONNX conversion not available"):
        prefix = paddle.onnx.export(
            m, str(tmp_path / "res.onnx"),
            input_spec=[InputSpec([2, 4], "float32")])
    assert not prefix.endswith(".onnx")
    assert os.path.exists(prefix + ".stablehlo")


def test_onnx_checker_if_available(tmp_path):
    onnx = pytest.importorskip("onnx")
    m = nn.Sequential(nn.Linear(4, 2))
    out = paddle.onnx.export(m, str(tmp_path / "chk.onnx"),
                             input_spec=[InputSpec([1, 4], "float32")])
    model = onnx.load(out)
    onnx.checker.check_model(model)
