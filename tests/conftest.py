"""Test config: force CPU backend with 8 virtual devices so distributed
(DP/TP/PP/sharding) logic is testable without TPUs — the SURVEY.md §4
translation of the reference's subprocess-on-localhost TestDistBase."""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags +
                               " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Numeric tests verify math, not precision policy: pin fp32-exact matmuls
# (prod default keeps the fast MXU path).
import jax  # noqa: E402

# The axon TPU plugin ignores the JAX_PLATFORMS env var — force via config.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")
