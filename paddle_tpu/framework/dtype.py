"""Dtype registry.

TPU-native equivalent of the reference's VarType dtype enum
(reference: paddle/fluid/framework/framework.proto:106) plus the float types in
platform/float16.h, bfloat16.h, complex.h. On TPU, dtypes are plain
``jnp.dtype`` objects; we expose paddle-style names and a default-dtype switch
(reference: python/paddle/framework/framework.py set_default_dtype).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Public dtype aliases (paddle.<name>)
bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_STR2DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
}

_default_dtype = jnp.float32


def set_default_dtype(d):
    """Set the default floating dtype used by layer parameter creation."""
    global _default_dtype
    _default_dtype = convert_dtype_to_jax(d)


def get_default_dtype():
    return _default_dtype


def convert_dtype_to_jax(dtype):
    """Normalize str/np/jnp dtype specs to a jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _STR2DTYPE:
            raise ValueError(f"Unknown dtype string: {dtype!r}")
        return _STR2DTYPE[dtype]
    return jnp.dtype(dtype).type if isinstance(dtype, np.dtype) else dtype


def is_floating(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.integer)
