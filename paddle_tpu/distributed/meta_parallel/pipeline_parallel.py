"""Pipeline-parallel engine (reference:
fleet/meta_parallel/pipeline_parallel.py:114 train_batch — micro-batch
forward :156 / backward :199 loops with p2p send/recv
(pp_utils/p2p_communication.py:84,:93); static 1F1B in
framework/section_worker.cc:139-183).

TPU-native schedule: the whole pipeline is ONE SPMD program under shard_map
over the "pipe" mesh axis. Activations move between stages with
lax.ppermute; the schedule is a lax.scan over M + S - 1 ticks (GPipe fill +
steady state). The *backward* pipeline is not hand-written: jax AD
differentiates through the scan, transposing every ppermute into the
reverse-direction hop — producing exactly the reversed communication pattern
that pipeline_parallel.py:199 implements manually. Per-microbatch activation
memory is bounded with jax.checkpoint (remat) over each stage application.

Parameter memory: the transformer body lives in _StackedStage parameters
(pp_layers.py) whose leading member dim is sharded over "pipe" — inside the
shard_map each device's slice is exactly its own stage's members, applied
with a lax.scan. First/last-stage layers (embedding, norm, head) are
replicated over pipe; their gradients are psum'd over "pipe" by the engine
so the replication is genuine (each stage contributes zeros for layers it
does not run).

Stage dispatch: when the layer plan decomposes as prologue -> uniform
stacked body -> epilogue (PipelineLayer.uniform_split — the canonical
transformer shape), every device executes the SAME pre/stack/post program
each tick with the heterogeneous parts masked by stage id. This is the
collective-safe form: collectives inside the layers (ring attention's
ppermute over "sep", TP psums) are issued by all devices in the same
order. The older dispatch — a lax.switch on the stage id — is kept as a
fallback for non-decomposable plans, but collectives under a per-device
switch branch are undefined behavior in SPMD (devices join different op
instances: ppermute deadlocks or silently exchanges the wrong tensors),
so the engine refuses that fallback when the mesh has a "sep" axis.
"""
from __future__ import annotations

from collections import OrderedDict

import jax
import jax.numpy as jnp
from jax import lax

from ...jit.functionalization import functional_call
from ...nn.layer import Layer

PIPE_AXIS = "pipe"


def _extract(state, prefix):
    """Sub-dict of a flat name->array dict under `prefix.`."""
    plen = len(prefix) + 1
    return {k[plen:]: v for k, v in state.items()
            if k.startswith(prefix + ".")}


class PipelineParallel(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        from .parallel_layers.pp_layers import PipelineLayer
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel requires a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self.accumulate_steps = 1
        self.schedule = "gpipe"
        if strategy is not None:
            self.accumulate_steps = int(
                strategy.pipeline_configs.get("accumulate_steps", 1))
            self.schedule = strategy.pipeline_configs.get(
                "schedule", self.schedule)
        self._compiled = None

    # -- single-device semantics (debug/eval) ------------------------------
    def forward(self, x):
        return self._layers(x)

    # -- uniform (collective-safe) building blocks --------------------------
    def _apply_plain_items(self, items, params, buffers, x, key):
        """Apply a run of non-stacked plan items functionally."""
        layers = self._layers
        for i, ent in items:
            kind = ent[0]
            if kind == "layer":
                mod = getattr(layers, f"mod{i}")
                x, _ = functional_call(
                    mod, _extract(params, f"mod{i}"),
                    _extract(buffers, f"mod{i}"), x,
                    rng=jax.random.fold_in(key, i))
            elif kind == "shared":
                _, owner_i, fw, attr = ent
                if fw is not None:
                    w = params[layers.owner_weight_key(owner_i, attr)]
                    x = fw(x, w)
                else:
                    owner = getattr(layers, f"mod{owner_i}")
                    x, _ = functional_call(
                        owner, _extract(params, f"mod{owner_i}"),
                        _extract(buffers, f"mod{owner_i}"), x,
                        rng=jax.random.fold_in(key, i))
            else:  # pragma: no cover - uniform_split guarantees no stacks
                raise AssertionError("stacked item in plain run")
        return x

    def _uniform_fns(self):
        """(pre_fn, stack_fn, post_fn) for the uniform schedules, or None.

        Each takes (params, buffers, x, key) and is executed by EVERY
        device every tick: pre/post touch only pipe-replicated params, so
        they compute identically everywhere (results masked by stage id
        at the call site); stack_fn applies this device's k local stacked
        members — structurally identical across stages, so any
        collectives inside line up."""
        split = self._layers.uniform_split()
        if split is None:
            return None
        pre_items, gid, post_items = split
        layers = self._layers
        stack = getattr(layers, f"stack{gid}")
        k = layers.groups[gid][2]
        a = layers.groups[gid][0]

        def pre_fn(params, buffers, x, key):
            return self._apply_plain_items(pre_items, params, buffers, x,
                                           key)

        def stack_fn(params, buffers, x, key):
            from .parallel_layers.pp_layers import _escape
            sp = _extract(params, f"stack{gid}")
            sb = _extract(buffers, f"stack{gid}")
            # rng folds with the GLOBAL member index (stage offset +
            # local j): folding with the local index alone would hand
            # every stage's j-th block the same dropout stream
            j0 = a + lax.axis_index(PIPE_AXIS) * k

            def blk(h_c, xs):
                pj, bj, j = xs
                pj = {n: pj[_escape(n)] for n in stack.param_names}
                bj = {n: bj[_escape(n)] for n in stack.buffer_names}
                out, _ = functional_call(
                    stack._template, pj, bj, h_c,
                    rng=jax.random.fold_in(key, j0 + j))
                return out, None

            x, _ = lax.scan(jax.checkpoint(blk), x,
                            (sp, sb, jnp.arange(k)))
            return x

        def post_fn(params, buffers, x, key):
            return self._apply_plain_items(post_items, params, buffers, x,
                                           key)

        return pre_fn, stack_fn, post_fn

    # -- per-stage functional forward (switch fallback) ---------------------
    def _stage_forward_fn(self, s):
        """Build fwd(params, buffers, h, key) applying stage `s`'s items.

        `params`/`buffers` are the FLAT model dicts as seen inside the
        active shard_map: _StackedStage entries hold the LOCAL (per-device)
        member slice — which on the device executing branch `s` is exactly
        stage s's members — while mod{i} entries are replicated.
        """
        layers = self._layers
        items = layers.stage_items(s)
        k_local = {gid: k for gid, (_, _, k) in enumerate(layers.groups)}

        def fwd(params, buffers, h, key):
            x = h
            idx = 0
            n = len(items)
            while idx < n:
                i, ent = items[idx]
                kind = ent[0]
                if kind == "stacked":
                    _, gid, m0 = ent
                    stack = getattr(layers, f"stack{gid}")
                    k = k_local[gid]
                    # contiguous run of this stack's members in this stage
                    run = 1
                    while idx + run < n and items[idx + run][1][0] == "stacked" \
                            and items[idx + run][1][1] == gid:
                        run += 1
                    assert run == k, (
                        f"stage {s}: stacked run {run} != per-stage k {k}")
                    sp = _extract(params, f"stack{gid}")
                    sb = _extract(buffers, f"stack{gid}")

                    def blk(h_c, xs, _stack=stack, _i0=i):
                        from .parallel_layers.pp_layers import _escape
                        pj, bj, j = xs
                        pj = {n: pj[_escape(n)] for n in _stack.param_names}
                        bj = {n: bj[_escape(n)] for n in _stack.buffer_names}
                        out, _ = functional_call(
                            _stack._template, pj, bj, h_c,
                            rng=jax.random.fold_in(key, _i0 + j))
                        return out, None

                    js = jnp.arange(k)
                    x, _ = lax.scan(jax.checkpoint(blk), x, (sp, sb, js))
                    idx += run
                    continue
                if kind == "layer":
                    mod = getattr(layers, f"mod{i}")
                    x, _ = functional_call(
                        mod, _extract(params, f"mod{i}"),
                        _extract(buffers, f"mod{i}"), x,
                        rng=jax.random.fold_in(key, i))
                elif kind == "shared":
                    _, owner_i, fw, attr = ent
                    if fw is not None:
                        w = params[layers.owner_weight_key(owner_i, attr)]
                        x = fw(x, w)
                    else:
                        owner = getattr(layers, f"mod{owner_i}")
                        x, _ = functional_call(
                            owner, _extract(params, f"mod{owner_i}"),
                            _extract(buffers, f"mod{owner_i}"), x,
                            rng=jax.random.fold_in(key, i))
                idx += 1
            return x

        return fwd

    # -- the SPMD pipelined loss -------------------------------------------
    def build_pipeline_loss_fn(self, loss_fn, micro_batches: int):
        """Return pure_loss(params, buffers, rng, inputs, labels) that runs
        the selected schedule inside an active shard_map over the pipe axis.

        inputs/labels are the FULL batch (replicated over pipe); they are
        re-split into `micro_batches` microbatches here (reference
        pipeline_parallel.py _load_micro_batch).
        """
        S = self.num_stages
        M = micro_batches
        uniform = self._uniform_fns()
        if uniform is not None:
            return self._uniform_pipeline_loss(loss_fn, M, uniform)
        return self._switch_pipeline_loss(loss_fn, M)

    def _uniform_pipeline_loss(self, loss_fn, M, uniform):
        """Collective-safe GPipe: every tick, every device runs the SAME
        pre -> stack -> post program; stage identity only selects inputs
        and masks outputs. jax AD transposes the scan into the reverse
        pipeline with the same uniformity."""
        S = self.num_stages
        pre_fn, stack_fn, post_fn = uniform

        def pure_loss(params, buffers, key, inputs, labels):
            sid = lax.axis_index(PIPE_AXIS)
            is_first = sid == 0
            is_last = sid == S - 1
            mb = inputs.shape[0] // M
            micro_in = inputs.reshape((M, mb) + inputs.shape[1:])
            micro_lb = labels.reshape((M, mb) + labels.shape[1:])

            probe = jax.eval_shape(
                lambda: stack_fn(params, buffers,
                                 pre_fn(params, buffers, micro_in[0],
                                        key), key))
            h_shape, h_dtype = probe.shape, probe.dtype
            zeros_h = jnp.zeros(h_shape, h_dtype)

            def compute(h_recv, m, k_t):
                x_pre = pre_fn(params, buffers, micro_in[m], k_t)
                x0 = jnp.where(is_first, x_pre.astype(h_dtype), h_recv)
                h_out = stack_fn(params, buffers, x0, k_t)
                # non-last stages feed ZEROS to the epilogue: the value
                # is discarded by the mask below, and zeros keep the
                # head numerics finite (no inf*0 NaNs in the transpose)
                x_post = jnp.where(is_last, h_out, zeros_h)
                out = post_fn(params, buffers, x_post, k_t)
                l = loss_fn(out, micro_lb[m])
                return h_out.astype(h_dtype), l

            def tick(carry, t):
                h_recv, loss_acc = carry
                m = jnp.clip(t - sid, 0, M - 1)
                valid = (t - sid >= 0) & (t - sid < M)
                k_t = jax.random.fold_in(key, t)
                h_out, l = jax.checkpoint(compute)(h_recv, m, k_t)
                loss_acc = loss_acc + jnp.where(valid & is_last, l, 0.0)
                h_next = lax.ppermute(
                    h_out, PIPE_AXIS, [(i, (i + 1) % S) for i in range(S)])
                return (h_next, loss_acc), None

            (h_last, loss_acc), _ = lax.scan(
                tick, (zeros_h, jnp.zeros((), jnp.float32)),
                jnp.arange(M + S - 1))
            from .parallel_layers.mp_layers import \
                reduce_from_parallel_region
            total = reduce_from_parallel_region(loss_acc, axis=PIPE_AXIS)
            return total / M

        return pure_loss

    def _switch_pipeline_loss(self, loss_fn, M):
        """lax.switch stage dispatch — fallback for plans that do not
        decompose into pre/stack/post. Only safe when stages contain no
        collectives (see module docstring)."""
        S = self.num_stages
        stage_fns = [self._stage_forward_fn(s) for s in range(S)]

        def pure_loss(params, buffers, key, inputs, labels):
            sid = lax.axis_index(PIPE_AXIS)
            mb = inputs.shape[0] // M
            micro_in = inputs.reshape((M, mb) + inputs.shape[1:])
            micro_lb = labels.reshape((M, mb) + labels.shape[1:])

            # probe the carry shape: trace stage0 on microbatch 0
            probe = jax.eval_shape(
                lambda: stage_fns[0](params, buffers, micro_in[0], key))
            h_shape, h_dtype = probe.shape, probe.dtype

            def apply_stage(s, m, key):
                """Branch for stage s; every branch returns (h, loss)."""
                def branch(h):
                    x0 = micro_in[m] if s == 0 else h
                    out = stage_fns[s](params, buffers, x0, key)
                    if s == S - 1:
                        l = loss_fn(out, micro_lb[m])
                        return (out.astype(h_dtype)
                                if out.shape == h_shape
                                else jnp.zeros(h_shape, h_dtype)), l
                    return out, jnp.zeros((), jnp.float32)
                return branch

            def tick(carry, t):
                h_recv, loss_acc = carry
                m = jnp.clip(t - sid, 0, M - 1)
                valid = (t - sid >= 0) & (t - sid < M)
                k_t = jax.random.fold_in(key, t)
                branches = [jax.checkpoint(apply_stage(s, m, k_t))
                            for s in range(S)]
                h_out, l = lax.switch(sid, branches, h_recv)
                l = jnp.where(valid, l, 0.0)
                loss_acc = loss_acc + l
                h_next = lax.ppermute(
                    h_out, PIPE_AXIS, [(i, (i + 1) % S) for i in range(S)])
                return (h_next, loss_acc), None

            h0 = jnp.zeros(h_shape, h_dtype)
            (h_last, loss_acc), _ = lax.scan(
                tick, (h0, jnp.zeros((), jnp.float32)), jnp.arange(M + S - 1))
            # only the last stage accumulated loss; broadcast it
            from .parallel_layers.mp_layers import \
                reduce_from_parallel_region
            total = reduce_from_parallel_region(loss_acc, axis=PIPE_AXIS)
            return total / M

        return pure_loss

    # -- 1F1B schedule (manual VJP) ----------------------------------------
    def build_pipeline_grads_fn(self, loss_fn, micro_batches: int):
        """Return pure_grads(params, buffers, rng, inputs, labels, wrt) ->
        (loss, grads) running the 1F1B schedule (reference:
        framework/section_worker.cc:139-183 — startup forwards, then
        alternating backward/forward in steady state).

        Unlike the GPipe scan (whose AD transpose stashes one activation
        per tick, O(M + S)), this schedule differentiates each stage
        locally with jax.vjp inside the tick and carries at most S stashed
        stage inputs plus one gradient accumulator — in-flight microbatches
        are bounded by num_stages, the 1F1B memory guarantee.

        Timing (stage s, microbatch m, S stages), just-in-time variant:
          forward:  t = s + 2f       (even t - s parity)
          backward: t = 2S - 1 - s + 2m   (odd parity — strict 1F1B
                    alternation; producers run exactly one tick before
                    consumers in both directions, so one ppermute carry
                    suffices, no inter-stage queues)
        Total ticks: 2(M + S - 1). Each backward recomputes its stage
        forward from the stashed input (remat semantics, like the GPipe
        path's jax.checkpoint), so a stash slot is one activation, not a
        residual set.
        """
        S = self.num_stages
        M = micro_batches
        uniform = self._uniform_fns()
        if uniform is not None:
            return self._uniform_pipeline_grads(loss_fn, M, uniform)
        return self._switch_pipeline_grads(loss_fn, M)

    def _uniform_pipeline_grads(self, loss_fn, M, uniform):
        """Collective-safe 1F1B: each tick every device runs the uniform
        forward body AND the uniform backward body (a jax.vjp of the same
        body), with stage identity only masking which results commit.
        In the steady state different stages genuinely do forward and
        backward work at the same tick — under the switch dispatch their
        collectives would pair across phases (the silent-corruption
        variant of the switch UB); here both phases' collective sequences
        are issued by every device in the same order."""
        S = self.num_stages
        pre_fn, stack_fn, post_fn = uniform

        def pure_grads(params, buffers, key, inputs, labels, wrt):
            sid = lax.axis_index(PIPE_AXIS)
            is_first = sid == 0
            is_last = sid == S - 1
            mb = inputs.shape[0] // M
            micro_in = inputs.reshape((M, mb) + inputs.shape[1:])
            micro_lb = labels.reshape((M, mb) + labels.shape[1:])
            wrt_params = {k: params[k] for k in wrt}
            rest = {k: v for k, v in params.items() if k not in wrt}

            probe = jax.eval_shape(
                lambda: stack_fn(params, buffers,
                                 pre_fn(params, buffers, micro_in[0],
                                        key), key))
            h_shape, h_dtype = probe.shape, probe.dtype
            zeros_h = jnp.zeros(h_shape, h_dtype)
            gzero = jax.tree_util.tree_map(
                lambda v: jnp.zeros(jnp.shape(v), jnp.float32), wrt_params)

            def body_fwd(wp, x0b, m, k_m):
                full = dict(rest)
                full.update(wp)
                x_pre = pre_fn(full, buffers, micro_in[m], k_m)
                x0 = jnp.where(is_first, x_pre.astype(h_dtype), x0b)
                return stack_fn(full, buffers, x0, k_m).astype(h_dtype)

            def body_full(wp, x0b, m, k_m):
                h = body_fwd(wp, x0b, m, k_m)
                full = dict(rest)
                full.update(wp)
                x_post = jnp.where(is_last, h, zeros_h)
                out = post_fn(full, buffers, x_post, k_m)
                return h, loss_fn(out, micro_lb[m])

            fwd_perm = [(i, (i + 1) % S) for i in range(S)]
            bwd_perm = [(i, (i - 1) % S) for i in range(S)]

            def tick(carry, t):
                h_recv, cot_recv, stash, gacc, loss_acc = carry
                # -- forward phase (t = s + 2f; see the switch variant's
                # timing notes) --
                td = t - sid
                f_raw = td // 2
                fwd_valid = (td >= 0) & (td % 2 == 0) & (f_raw < M)
                f_idx = jnp.clip(f_raw, 0, M - 1)
                h_out = body_fwd(wrt_params, h_recv,
                                 f_idx, jax.random.fold_in(key, f_idx))
                slot = f_idx % S
                stash = stash.at[slot].set(
                    jnp.where(fwd_valid, h_recv, stash[slot]))
                # -- backward phase (t = 2S - 1 - s + 2m) --
                bd = t - (2 * S - 1 - sid)
                m_num = bd // 2
                bwd_valid = (bd >= 0) & (bd % 2 == 0) & (m_num < M)
                m_idx = jnp.clip(m_num, 0, M - 1)
                k_b = jax.random.fold_in(key, m_idx)
                h_in = stash[m_idx % S]
                (h_b, l_m), vjp = jax.vjp(
                    lambda wp, x0b: body_full(wp, x0b, m_idx, k_b),
                    wrt_params, h_in)
                # last stage seeds the loss cotangent; others propagate
                # the received activation cotangent (their h feeds the
                # next stage, never the loss)
                cot_h = jnp.where(is_last, jnp.zeros_like(cot_recv),
                                  cot_recv)
                cot_l = jnp.where(is_last, jnp.float32(1.0 / M),
                                  jnp.float32(0.0))
                gw, gx = vjp((cot_h, cot_l.astype(l_m.dtype)))
                gacc = jax.tree_util.tree_map(
                    lambda a, g: a + jnp.where(bwd_valid, g, 0.0),
                    gacc, gw)
                loss_acc = loss_acc + jnp.where(bwd_valid & is_last,
                                                l_m, 0.0)
                # -- communicate --
                h_next = lax.ppermute(
                    jnp.where(fwd_valid, h_out, zeros_h), PIPE_AXIS,
                    fwd_perm)
                cot_next = lax.ppermute(
                    jnp.where(bwd_valid, gx.astype(h_dtype), zeros_h),
                    PIPE_AXIS, bwd_perm)
                return (h_next, cot_next, stash, gacc, loss_acc), None

            stash0 = jnp.zeros((S,) + h_shape, h_dtype)
            carry0 = (zeros_h, zeros_h, stash0, gzero,
                      jnp.zeros((), jnp.float32))
            (h_l, c_l, st_l, gacc, loss_acc), _ = lax.scan(
                tick, carry0, jnp.arange(2 * (M + S - 1)))
            from .parallel_layers.mp_layers import \
                reduce_from_parallel_region
            total = reduce_from_parallel_region(loss_acc, axis=PIPE_AXIS)
            return total / M, gacc

        return pure_grads

    def _switch_pipeline_grads(self, loss_fn, M):
        """lax.switch 1F1B — fallback for non-decomposable plans; same
        collective-safety caveat as _switch_pipeline_loss."""
        S = self.num_stages
        stage_fns = [self._stage_forward_fn(s) for s in range(S)]

        def pure_grads(params, buffers, key, inputs, labels, wrt):
            sid = lax.axis_index(PIPE_AXIS)
            mb = inputs.shape[0] // M
            micro_in = inputs.reshape((M, mb) + inputs.shape[1:])
            micro_lb = labels.reshape((M, mb) + labels.shape[1:])
            wrt_params = {k: params[k] for k in wrt}
            rest = {k: v for k, v in params.items() if k not in wrt}

            def run_stage(s, wp, x0, m):
                full = dict(rest)
                full.update(wp)
                return stage_fns[s](full, buffers, x0,
                                    jax.random.fold_in(key, m))

            probe = jax.eval_shape(
                lambda: run_stage(0, wrt_params, micro_in[0], 0))
            h_shape, h_dtype = probe.shape, probe.dtype
            zeros_h = jnp.zeros(h_shape, h_dtype)
            gzero = jax.tree_util.tree_map(
                lambda v: jnp.zeros(jnp.shape(v), jnp.float32), wrt_params)

            def fwd_branch(s):
                def go(ops):
                    h_recv, m = ops
                    if s == S - 1:
                        # last stage defers fwd to its backward's vjp
                        return zeros_h
                    x0 = micro_in[m] if s == 0 else h_recv
                    out = run_stage(s, wrt_params, x0, m)
                    return out.astype(h_dtype)
                return go

            def bwd_branch(s):
                def go(ops):
                    h_in, cot_in, m = ops
                    if s == S - 1:
                        if s == 0:
                            # single-stage pipeline: input comes from the
                            # microbatch, not the (never-written) stash
                            def f0(wp):
                                out = run_stage(0, wp, micro_in[m], m)
                                return loss_fn(out, micro_lb[m])
                            loss_m, vjp = jax.vjp(f0, wrt_params)
                            (gw,) = vjp(jnp.float32(1.0 / M))
                            return gw, zeros_h, loss_m

                        def f(wp, h):
                            out = run_stage(s, wp, h, m)
                            return loss_fn(out, micro_lb[m])
                        loss_m, vjp = jax.vjp(f, wrt_params, h_in)
                        gw, gh = vjp(jnp.float32(1.0 / M))
                        return gw, gh.astype(h_dtype), loss_m
                    if s == 0:
                        def f(wp):
                            return run_stage(0, wp, micro_in[m], m)
                        _, vjp = jax.vjp(f, wrt_params)
                        (gw,) = vjp(cot_in)
                        return gw, zeros_h, jnp.zeros((), jnp.float32)

                    def f(wp, h):
                        return run_stage(s, wp, h, m)
                    _, vjp = jax.vjp(f, wrt_params, h_in)
                    gw, gh = vjp(cot_in)
                    return gw, gh.astype(h_dtype), jnp.zeros((), jnp.float32)
                return go

            fwd_branches = [fwd_branch(s) for s in range(S)]
            bwd_branches = [bwd_branch(s) for s in range(S)]

            fwd_perm = [(i, (i + 1) % S) for i in range(S)]
            bwd_perm = [(i, (i - 1) % S) for i in range(S)]

            def tick(carry, t):
                h_recv, cot_recv, stash, gacc, loss_acc = carry
                # -- forward phase: t_f(s, f) = s + 2f (just-in-time 1F1B:
                # every producer runs exactly one tick before its consumer,
                # so the single ppermute carry needs no inter-stage queue;
                # forwards sit on even (t - s) parity, backwards on odd,
                # so a stage never does both in one tick) --
                td = t - sid
                f_idx_raw = td // 2
                fwd_valid = (td >= 0) & (td % 2 == 0) & (f_idx_raw < M)
                f_idx = jnp.clip(f_idx_raw, 0, M - 1)
                h_out = lax.switch(sid, fwd_branches, (h_recv, f_idx))
                # stash this stage's INPUT for its later backward (in-flight
                # <= S per stage, so the ring buffer never clobbers a live
                # slot; stage 0 re-reads micro_in at backward time instead)
                slot = f_idx % S
                stash = stash.at[slot].set(
                    jnp.where(fwd_valid & (sid > 0), h_recv, stash[slot]))
                # -- backward phase (t = 2S - 1 - s + 2m) --
                bd = t - (2 * S - 1 - sid)
                m_num = bd // 2
                bwd_valid = (bd >= 0) & (bd % 2 == 0) & (m_num < M)
                m_idx = jnp.clip(m_num, 0, M - 1)
                h_in = stash[m_idx % S]
                gw, gh, loss_m = lax.switch(
                    sid, bwd_branches, (h_in, cot_recv, m_idx))
                gacc = jax.tree_util.tree_map(
                    lambda a, g: a + jnp.where(bwd_valid, g, 0.0), gacc, gw)
                loss_acc = loss_acc + jnp.where(bwd_valid, loss_m, 0.0)
                # -- communicate --
                h_next = lax.ppermute(
                    jnp.where(fwd_valid, h_out, zeros_h), PIPE_AXIS, fwd_perm)
                cot_next = lax.ppermute(
                    jnp.where(bwd_valid, gh, zeros_h), PIPE_AXIS, bwd_perm)
                return (h_next, cot_next, stash, gacc, loss_acc), None

            stash0 = jnp.zeros((S,) + h_shape, h_dtype)
            carry0 = (zeros_h, zeros_h, stash0, gzero,
                      jnp.zeros((), jnp.float32))
            (h_l, c_l, st_l, gacc, loss_acc), _ = lax.scan(
                tick, carry0, jnp.arange(2 * (M + S - 1)))
            from .parallel_layers.mp_layers import \
                reduce_from_parallel_region
            total = reduce_from_parallel_region(loss_acc, axis=PIPE_AXIS)
            return total / M, gacc

        return pure_grads

    # passthrough
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, sd, *args, **kwargs):
        return self._layers.set_state_dict(sd, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def named_buffers(self, prefix="", include_sublayers=True):
        # delegate like named_parameters: buffer names must match the
        # mod{i}./stack{g}. prefixes the stage forward extracts
        return self._layers.named_buffers(prefix, include_sublayers)

    def named_buffer_pspecs(self):
        return self._layers.named_buffer_pspecs()
