"""Elastic multi-host runtime: coordinated restore barrier + remesh.

The fleet ``ElasticManager`` (file-KV membership, HOLD/RESTART/EXIT) only
*decides*; this module makes the decision safe to act on:

- ``FileCoordinator`` — allgather/barrier over the shared-filesystem KV
  (the loopback stand-in for the jax.distributed coordinator), so hosts
  can agree on anything without etcd.  Each collective round lives in a
  numbered generation directory; participants touch their entry while
  waiting, so an abandoned round (all writers stale) is skipped rather
  than reused.
- ``coordinated_restore`` — the restore barrier: every host reports its
  local ``CheckpointManager.latest_valid_step()``, the values are
  min-reduced to the newest step valid on *every* host, each host
  restores exactly that step, and a barrier holds everyone until all
  restores finished.  No host trains ahead on divergent state.
  Counted in ``elastic_restore_barrier_total`` /
  ``elastic_step_disagreements_total``.
- ``reshard_trainer`` / ``remap_comm_err`` — scale-up/scale-down remesh:
  params/opt/guard ride the sharded checkpoint (save on the old mesh,
  restore on the new one — orbax reshards), while the EQuARX
  error-feedback residuals (``state["comm_err"]``, replica-major with a
  mesh-dependent leading dimension) are remapped host-side: surviving
  rank rows are carried as a prefix, rows beyond the new rank count are
  dropped (their L2 norm counted in
  ``elastic_residual_dropped_norm_total``), new ranks start at zero.
- ``ElasticRuntime`` — binds manager + coordinator + remesh policy into
  the object ``run_resilient(elastic=...)`` re-enters through instead of
  exiting 75: drain → commit → stabilize membership → (bounded) remesh →
  coordinated restore barrier → continue.

Retention caveat: the min-reduce can only roll back as far as every
host's retention window (``CheckpointManager(max_to_keep=...)``) still
holds the common step; divergence deeper than the window raises rather
than silently training on mismatched state.
"""
from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Optional

import numpy as np

__all__ = ["CoordinatorTimeout", "FileCoordinator", "coordinated_restore",
           "remap_comm_err", "reshard_trainer", "ElasticRuntime",
           "data_parallel_remesh_fn"]

RESHARD_STATE_KEYS = ("params", "buffers", "opt", "guard")


class CoordinatorTimeout(RuntimeError):
    """An allgather/barrier round did not complete before its deadline
    (a participant died mid-round or never arrived)."""


class FileCoordinator:
    """Allgather/barrier over a shared directory — the loopback
    counterpart of the jax.distributed coordinator, usable by N processes
    (or threads) that share a filesystem.

    Protocol: each named collective is a sequence of *generation*
    directories ``<root>/<name>/<g>/``.  A participant joins the first
    generation that is neither complete (every expected host present) nor
    abandoned (incomplete with every entry stale), writes
    ``<host>.json`` atomically, then polls — touching its own entry so
    live rounds stay distinguishable from dead ones — until the expected
    host set (re-read from ``hosts_fn`` every poll, so membership loss
    mid-round shrinks the wait) is fully present.
    """

    def __init__(self, root: str, job_id: str = "job",
                 host: Optional[str] = None, stale_after: float = 10.0,
                 poll: float = 0.05):
        self.root = os.path.join(root, job_id + ".coord")
        self.host = host or f"pid-{os.getpid()}"
        self.stale_after = float(stale_after)
        self.poll = float(poll)
        os.makedirs(self.root, exist_ok=True)

    def _entries(self, gen_dir: str) -> Dict[str, tuple]:
        out = {}
        try:
            names = os.listdir(gen_dir)
        except OSError:
            return out
        for fn in names:
            if not fn.endswith(".json"):
                continue
            full = os.path.join(gen_dir, fn)
            try:
                mtime = os.path.getmtime(full)
                with open(full) as f:
                    payload = json.load(f)
            except (OSError, ValueError):
                continue  # mid-replace or vanished: next poll sees it
            out[fn[:-len(".json")]] = (payload["v"], mtime)
        return out

    def _pick_generation(self, name: str, expected: set) -> int:
        base = os.path.join(self.root, name)
        try:
            gens = sorted(int(g) for g in os.listdir(base) if g.isdigit())
        except OSError:
            gens = []
        for g in gens:
            entries = self._entries(os.path.join(base, str(g)))
            if expected <= set(entries):
                continue                      # completed round
            if not entries:
                # a peer ran makedirs but its entry hasn't landed yet —
                # empty means joinable, NOT abandoned (classifying it as
                # abandoned would split the round across two generations)
                return g
            now = time.time()
            if any(now - m <= self.stale_after
                   for _, m in entries.values()):
                return g                      # live incomplete round: join
            # incomplete with every writer stale: abandoned — skip
        return (gens[-1] + 1) if gens else 0

    def _write(self, gen_dir: str, value):
        tmp = os.path.join(gen_dir, f".{self.host}.tmp")
        with open(tmp, "w") as f:
            json.dump({"v": value}, f)
        os.replace(tmp, os.path.join(gen_dir, self.host + ".json"))

    def allgather(self, name: str, value, hosts_fn: Callable[[], List[str]],
                  timeout: float = 120.0) -> Dict[str, object]:
        """Contribute ``value`` under ``name`` and return every expected
        host's contribution as ``{host: value}``."""
        expected = set(hosts_fn()) | {self.host}
        gen_dir = os.path.join(
            self.root, name, str(self._pick_generation(name, expected)))
        os.makedirs(gen_dir, exist_ok=True)
        self._write(gen_dir, value)
        mine = os.path.join(gen_dir, self.host + ".json")
        deadline = time.time() + timeout
        while True:
            try:
                os.utime(mine)
            except OSError:
                self._write(gen_dir, value)
            expected = set(hosts_fn()) | {self.host}
            entries = self._entries(gen_dir)
            if expected <= set(entries):
                return {h: entries[h][0] for h in sorted(expected)}
            if time.time() > deadline:
                raise CoordinatorTimeout(
                    f"allgather {name!r}: waited {timeout:.0f}s for "
                    f"{sorted(expected - set(entries))} in {gen_dir}")
            time.sleep(self.poll)

    def barrier(self, name: str, hosts_fn: Callable[[], List[str]],
                timeout: float = 120.0):
        self.allgather(name, 1, hosts_fn, timeout=timeout)


def coordinated_restore(manager, template, coordinator: FileCoordinator,
                        hosts_fn: Callable[[], List[str]],
                        timeout: float = 120.0):
    """The restore barrier. Returns ``(restored, common_step)`` where
    ``restored`` is the checkpoint payload (None on a coordinated fresh
    start) and ``common_step`` the min-reduced step (-1 when any host has
    no valid checkpoint at all)."""
    from .. import telemetry
    from . import faults
    if manager is not None and getattr(manager, "async_commit", False):
        # an in-flight async commit must land (or be suppressed) before
        # this host reports: the barrier min-reduces COMMITTED steps only,
        # so no peer restores a step we haven't durably finished
        manager.flush()
    local = manager.latest_valid_step() if manager is not None else None
    local = -1 if local is None else int(local)
    if faults.fires("restore_divergence", site="restore_barrier"):
        # pretend our newest checkpoint is torn: report one step older
        local = max(local - 1, -1)
    steps = coordinator.allgather("restore_step", local, hosts_fn,
                                  timeout=timeout)
    values = [int(v) for v in steps.values()]
    common = min(values)
    tel = telemetry.enabled()
    if tel and len(set(values)) > 1:
        telemetry.counter(
            "elastic_step_disagreements_total",
            "restore barriers where hosts reported divergent steps").inc()
    restored = None
    if common >= 0:
        if common not in set(manager.all_steps() or []):
            raise RuntimeError(
                f"common step {common} not in local retention "
                f"{sorted(manager.all_steps() or [])}; divergence exceeds "
                f"the checkpoint retention window")
        restored = manager.restore(step=common, template=template)
    coordinator.barrier("restore_barrier", hosts_fn, timeout=timeout)
    if tel:
        telemetry.counter(
            "elastic_restore_barrier_total",
            "coordinated restore barriers completed").inc()
    return restored, common


def remap_comm_err(old_host_arrays: Dict[str, np.ndarray], trainer):
    """Remap replica-major error-feedback residuals onto the trainer's
    CURRENT rank layout. Surviving ranks keep their rows as a prefix
    (``min(R_old, R_new)``); dropped rows (scale-down, vanished keys,
    shape changes) are re-zeroed with their L2 norm counted in
    ``elastic_residual_dropped_norm_total``; new ranks start from zero.
    """
    import jax
    from jax.sharding import NamedSharding
    from .. import telemetry

    dropped_sq = 0.0
    new = {}
    current = trainer.state["comm_err"]
    for k, spec in trainer.comm_err_specs.items():
        fresh = current[k]
        old = old_host_arrays.get(k)
        if old is None:
            new[k] = fresh
            continue
        old = np.asarray(old)
        if old.shape[1:] != tuple(fresh.shape[1:]):
            dropped_sq += float((old.astype(np.float64) ** 2).sum())
            new[k] = fresh
            continue
        buf = np.zeros(tuple(fresh.shape), dtype=fresh.dtype)
        rows = min(old.shape[0], buf.shape[0])
        buf[:rows] = old[:rows]
        if old.shape[0] > buf.shape[0]:
            extra = old[buf.shape[0]:].astype(np.float64)
            dropped_sq += float((extra ** 2).sum())
        new[k] = jax.device_put(buf, NamedSharding(trainer.mesh, spec))
    for k, old in old_host_arrays.items():
        if k not in trainer.comm_err_specs:
            dropped_sq += float((np.asarray(old).astype(np.float64) ** 2)
                                .sum())
    if dropped_sq > 0.0 and telemetry.enabled():
        telemetry.counter(
            "elastic_residual_dropped_norm_total",
            "L2 norm of error-feedback residual rows dropped by remesh"
        ).inc(float(np.sqrt(dropped_sq)))
    trainer.state["comm_err"] = new
    return new


def reshard_trainer(trainer, new_mesh, reshard_dir: str):
    """Carry a live trainer onto ``new_mesh``: params/buffers/opt/guard go
    save-on-old-mesh → restore-on-new-mesh through the sharded checkpoint
    (works when the meshes disagree — orbax reshards to the template),
    comm_err residuals are remapped host-side (their leading replica
    dimension follows the mesh, so they cannot ride the checkpoint)."""
    import jax
    from .. import telemetry
    from ..distributed.checkpoint import load_checkpoint, save_checkpoint

    old_comm = {k: np.asarray(jax.device_get(v))
                for k, v in trainer.state["comm_err"].items()}
    payload = {k: trainer.state[k] for k in RESHARD_STATE_KEYS}
    save_checkpoint(reshard_dir, payload, overwrite=True, use_async=False)
    trainer.remesh(new_mesh)
    template = {k: trainer.state[k] for k in RESHARD_STATE_KEYS}
    restored = load_checkpoint(reshard_dir, template=template)
    for k in RESHARD_STATE_KEYS:
        trainer.state[k] = restored[k]
    remap_comm_err(old_comm, trainer)
    if telemetry.enabled():
        telemetry.counter("elastic_remesh_total",
                          "trainer remesh/reshard operations").inc()
    return trainer


def data_parallel_remesh_fn(reshard_dir: str,
                            degrees_fn: Optional[Callable] = None):
    """A ``remesh_fn`` for ElasticRuntime that rebuilds a data-parallel
    mesh sized to the healthy host set (``degrees_fn(hosts) -> degrees``
    overrides the default one-data-axis policy) and reshards through
    ``reshard_dir``."""
    def _remesh(trainer, hosts: List[str]):
        import jax
        from ..distributed.mesh import build_mesh
        if degrees_fn is not None:
            degrees = degrees_fn(hosts)
        else:
            degrees = {"data": max(1, min(len(jax.devices()), len(hosts)))}
        reshard_trainer(trainer, build_mesh(degrees), reshard_dir)
    return _remesh


class ElasticRuntime:
    """Manager + coordinator + remesh policy, consumed by
    ``run_resilient(elastic=...)``.  ``reenter=True`` tells the runner a
    RESTART is handled in place (drain → ``on_restart`` → ``enter``)
    instead of propagating exit 75; ``on_restart`` returning False (no
    stable membership, remesh budget exhausted, remesh failed) falls back
    to the relaunch path."""

    reenter = True

    def __init__(self, manager, coordinator: Optional[FileCoordinator] = None,
                 remesh_fn: Optional[Callable] = None, max_remeshes: int = 2,
                 poll: float = 0.25, stabilize_polls: int = 3,
                 stabilize_timeout: float = 60.0,
                 barrier_timeout: float = 120.0,
                 schedule_fingerprints=None):
        self.manager = manager
        self.coordinator = coordinator
        self.remesh_fn = remesh_fn
        self.max_remeshes = max_remeshes
        self.poll = poll
        self.stabilize_polls = stabilize_polls
        self.stabilize_timeout = stabilize_timeout
        self.barrier_timeout = barrier_timeout
        # {program: collective-schedule fingerprint} (or a zero-arg
        # callable producing it): cross-checked against every other
        # rank through the coordinator on each enter() — trainer start
        # AND every elastic remesh — aborting with a diff instead of
        # wedging into the collective hang the divergence would cause
        self.schedule_fingerprints = schedule_fingerprints
        self.remeshes = 0
        self.barrier_steps: List[int] = []   # common step of each entry
        self._adopted: Optional[set] = None  # host set training started on
        self._synthetic: List[str] = []      # host_join member files

    # -- simulated membership (the host_join fault hook) ---------------------
    def simulate_join(self) -> str:
        """Materialize a synthetic member in the KV (deterministic
        host_join fault); heartbeated by watch() until removed."""
        name = f"sim-join-{len(self._synthetic)}"
        path = self.manager._member_file(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write("synthetic")
        self._synthetic.append(path)
        return path

    def _synthetic_names(self) -> set:
        return {os.path.basename(p)[:-len(".alive")]
                for p in self._synthetic}

    def _heartbeat_synthetic(self):
        for p in list(self._synthetic):
            try:
                os.utime(p)
            except OSError:
                self._synthetic.remove(p)   # removed externally: it "left"

    # -- membership ----------------------------------------------------------
    def _coord_hosts(self) -> List[str]:
        """Barrier participants: live KV members that are real processes
        (synthetic host_join members cannot write barrier entries)."""
        out = set(self.manager.hosts()) - self._synthetic_names()
        out.add(self.coordinator.host if self.coordinator is not None
                else self.manager.host)
        return sorted(out)

    def _stable_hosts(self) -> Optional[List[str]]:
        """Wait for ``stabilize_polls`` consecutive identical host-set
        observations inside the np range; None on timeout."""
        deadline = time.time() + self.stabilize_timeout
        last, streak = None, 0
        while time.time() < deadline:
            self.manager.heartbeat()
            self._heartbeat_synthetic()
            cur = tuple(self.manager.hosts())
            streak = streak + 1 if cur == last else 1
            last = cur
            if (streak >= self.stabilize_polls
                    and self.manager.np_min <= len(cur)
                    <= self.manager.np_max):
                return list(cur)
            time.sleep(self.poll)
        return None

    def watch(self, proc_alive=lambda: True) -> str:
        """Manager watch, plus: a host-set change *within* the np range
        (which the manager reports as HOLD) is still a RESTART here — the
        mesh was built for the adopted set."""
        from ..distributed.fleet.elastic import ElasticStatus
        self._heartbeat_synthetic()
        st = self.manager.watch(proc_alive)
        if st == ElasticStatus.HOLD and self._adopted is not None:
            if set(self.manager.hosts()) != self._adopted:
                return ElasticStatus.RESTART
        return st

    # -- restart / entry -----------------------------------------------------
    def on_restart(self, trainer) -> bool:
        """Handle a RESTART in place: wait for stable membership, remesh
        if the healthy set changed (bounded by ``max_remeshes``). False
        means give up and let the relaunch path (exit 75) take over."""
        from .. import telemetry
        hosts = self._stable_hosts()
        if hosts is None:
            return False
        changed = (self._adopted is not None
                   and set(hosts) != self._adopted)
        if changed and self.remesh_fn is not None:
            if self.remeshes >= self.max_remeshes:
                return False
            try:
                self.remesh_fn(trainer, list(hosts))
            except Exception:
                if telemetry.enabled():
                    telemetry.counter(
                        "elastic_remesh_failed_total",
                        "remesh attempts that fell back to relaunch").inc()
                return False
            self.remeshes += 1
        self._adopted = set(hosts)
        return True

    def enter(self, ckpt_manager, template, timeout: Optional[float] = None):
        """(Re)enter training through the restore barrier; returns the
        restored payload (None = coordinated fresh start)."""
        from .. import telemetry
        timeout = self.barrier_timeout if timeout is None else timeout
        if self._adopted is None:
            hosts = self._stable_hosts()
            self._adopted = set(hosts if hosts is not None
                                else self.manager.hosts())
        if (self.schedule_fingerprints is not None
                and self.coordinator is not None):
            from ..analysis.schedule import crossrank_verify
            fps = self.schedule_fingerprints
            if callable(fps):
                fps = fps()
            # unique exchange name per entry: a remesh re-entry must not
            # read the previous generation's stale allgather files
            crossrank_verify(
                self.coordinator, fps, self._coord_hosts, timeout=timeout,
                name=f"schedule_fp_{len(self.barrier_steps)}")
        if self.coordinator is not None and ckpt_manager is not None:
            restored, common = coordinated_restore(
                ckpt_manager, template, self.coordinator,
                self._coord_hosts, timeout=timeout)
        else:
            restored = (ckpt_manager.restore(template=template)
                        if ckpt_manager is not None else None)
            common = getattr(ckpt_manager, "last_restored_step", None) \
                if restored is not None else None
            common = -1 if common is None else int(common)
            if telemetry.enabled():
                telemetry.counter(
                    "elastic_restore_barrier_total",
                    "coordinated restore barriers completed").inc()
        self.barrier_steps.append(common)
        return restored
