"""Python-facing sparse/dense PS tables over the native core.

Capability map (reference): distributed/table/common_sparse_table.cc (sharded
key->row store, server-side optimizer), common_dense_table.cc,
framework/fleet/fleet_wrapper.h:69 (pull/push entry points). These classes
are the in-process view; the RPC tier (reference brpc_ps_server/client) is
``service.py`` — PsServer/DistributedSparseTable over csrc/ps/ps_service.cc
— which hash-routes every key to its owning server via ``shard_keys``.
"""
from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from .native import lib

_OPTIMIZERS = {"sgd": 0, "adagrad": 1, "adam": 2}


def _as_f32(a):
    return np.ascontiguousarray(a, dtype=np.float32)


def _as_i64(a):
    return np.ascontiguousarray(a, dtype=np.int64)


def _fp(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _ip(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


class SparseTable:
    """Unbounded-vocabulary embedding table with host-side optimizer.

    Rows materialize on first touch (no [vocab, dim] dense alloc) — the
    trillion-parameter recsys pattern of the reference's PS tier.
    """

    def __init__(self, dim: int, optimizer: str = "adagrad", seed: int = 0,
                 init_range: float = 0.01, beta1: float = 0.9,
                 beta2: float = 0.999, eps: float = 1e-8):
        if optimizer not in _OPTIMIZERS:
            raise ValueError(f"optimizer must be one of {list(_OPTIMIZERS)}")
        self.dim = dim
        self.optimizer = optimizer
        self._lib = lib()
        self._h = self._lib.ps_sparse_create(
            dim, _OPTIMIZERS[optimizer], seed, init_range, beta1, beta2, eps)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.ps_sparse_destroy(self._h)
            self._h = None

    def __len__(self):
        return int(self._lib.ps_sparse_size(self._h))

    def pull(self, keys, create_missing: bool = True) -> np.ndarray:
        keys = _as_i64(keys)
        flat = keys.reshape(-1)
        out = np.empty((flat.size, self.dim), dtype=np.float32)
        self._lib.ps_sparse_pull(self._h, _ip(flat), flat.size, _fp(out),
                                 1 if create_missing else 0)
        return out.reshape(keys.shape + (self.dim,))

    def push(self, keys, grads, lr: float):
        keys = _as_i64(keys).reshape(-1)
        grads = _as_f32(grads).reshape(keys.size, self.dim)
        self._lib.ps_sparse_push(self._h, _ip(keys), keys.size, _fp(grads),
                                 lr)

    @property
    def row_width(self) -> int:
        """dim * (1 + optimizer slot columns) — the full-row stride used
        by the tier-exchange API."""
        return int(self._lib.ps_sparse_row_width(self._h))

    def export_rows(self, keys, create_missing: bool = True) -> np.ndarray:
        """Read FULL rows — (N, row_width): value columns then optimizer
        slot columns — for handing rows to a device-resident hot tier
        (HeterPS promote; reference heter_ps/heter_comm.h)."""
        keys = _as_i64(keys).reshape(-1)
        out = np.empty((keys.size, self.row_width), dtype=np.float32)
        self._lib.ps_sparse_export_rows(self._h, _ip(keys), keys.size,
                                        _fp(out),
                                        1 if create_missing else 0)
        return out

    def import_rows(self, keys, rows):
        """Write FULL rows back (HeterPS flush on eviction): inverse of
        export_rows, creating absent keys."""
        keys = _as_i64(keys).reshape(-1)
        rows = _as_f32(rows).reshape(keys.size, self.row_width)
        self._lib.ps_sparse_import_rows(self._h, _ip(keys), keys.size,
                                        _fp(rows))

    def spill(self, path: str, max_hot_rows: int):
        """Evict least-recently-touched rows beyond ``max_hot_rows`` to a
        disk file (reference table/ssd_sparse_table.cc cold tier); spilled
        rows are promoted back transparently on the next pull/push."""
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        if not self._lib.ps_sparse_spill(self._h, path.encode(),
                                         int(max_hot_rows)):
            raise IOError(f"failed to spill sparse table to {path}")

    @property
    def hot_rows(self) -> int:
        return int(self._lib.ps_sparse_hot_rows(self._h))

    def save(self, path: str):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        if not self._lib.ps_sparse_save(self._h, path.encode()):
            raise IOError(f"failed to save sparse table to {path}")

    def load(self, path: str):
        if not self._lib.ps_sparse_load(self._h, path.encode()):
            raise IOError(f"failed to load sparse table from {path} "
                          f"(missing file or dim/optimizer mismatch)")


class DenseTable:
    """Host-resident dense parameter block with host optimizer
    (reference: common_dense_table.cc)."""

    def __init__(self, size: int, optimizer: str = "sgd", beta1: float = 0.9,
                 beta2: float = 0.999, eps: float = 1e-8,
                 init: Optional[np.ndarray] = None):
        self.size = int(size)
        self._lib = lib()
        self._h = self._lib.ps_dense_create(
            self.size, _OPTIMIZERS[optimizer], beta1, beta2, eps)
        if init is not None:
            self.set(init)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.ps_dense_destroy(self._h)
            self._h = None

    def set(self, values):
        v = _as_f32(values).reshape(-1)
        assert v.size == self.size
        self._lib.ps_dense_set(self._h, _fp(v))

    def pull(self) -> np.ndarray:
        out = np.empty((self.size,), dtype=np.float32)
        self._lib.ps_dense_pull(self._h, _fp(out))
        return out

    def push(self, grad, lr: float):
        g = _as_f32(grad).reshape(-1)
        assert g.size == self.size
        self._lib.ps_dense_push(self._h, _fp(g), lr)


class GraphTable:
    """Graph store + neighbor sampling for graph-learning PS workloads
    (reference: distributed/table/common_graph_table.cc — adjacency store,
    random_sample_neighboors, node features). Multi-host sharding by node
    key hash happens above (``shard_keys``), like the sparse table."""

    def __init__(self, feat_dim: int = 0, seed: int = 0):
        self.feat_dim = int(feat_dim)
        self._lib = lib()
        self._h = self._lib.ps_graph_create(self.feat_dim, seed)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.ps_graph_destroy(self._h)
            self._h = None

    def add_edges(self, src, dst, weights=None):
        src = _as_i64(src).reshape(-1)
        dst = _as_i64(dst).reshape(-1)
        assert src.size == dst.size
        wp = _fp(_as_f32(weights).reshape(-1)) if weights is not None \
            else None
        self._lib.ps_graph_add_edges(self._h, _ip(src), _ip(dst), wp,
                                     src.size)

    def set_node_feature(self, keys, feats):
        keys = _as_i64(keys).reshape(-1)
        feats = _as_f32(feats).reshape(keys.size, self.feat_dim)
        self._lib.ps_graph_set_feature(self._h, _ip(keys), _fp(feats),
                                       keys.size)

    def node_feature(self, keys) -> np.ndarray:
        keys = _as_i64(keys).reshape(-1)
        out = np.empty((keys.size, self.feat_dim), dtype=np.float32)
        self._lib.ps_graph_get_feature(self._h, _ip(keys), _fp(out),
                                       keys.size)
        return out

    def degree(self, key: int) -> int:
        return int(self._lib.ps_graph_degree(self._h, int(key)))

    def sample_neighbors(self, keys, k: int, seed: int = 0,
                         weighted: bool = False):
        """Sample without replacement: returns (neighbors (N, k) with -1
        padding, counts (N,)). ``weighted=True`` draws edge-weight-
        proportional (Efraimidis-Spirakis); unweighted edges count 1.0."""
        keys = _as_i64(keys).reshape(-1)
        out = np.empty((keys.size, k), dtype=np.int64)
        counts = np.empty((keys.size,), dtype=np.int64)
        self._lib.ps_graph_sample_neighbors(self._h, _ip(keys), keys.size,
                                            int(k), int(seed), _ip(out),
                                            _ip(counts),
                                            1 if weighted else 0)
        return out, counts

    def __len__(self):
        return int(self._lib.ps_graph_num_nodes(self._h))


def shard_keys(keys: np.ndarray, num_shards: int) -> np.ndarray:
    """Hash-shard assignment for multi-host key routing (same mix as the
    native table's internal sharding)."""
    h = keys.astype(np.uint64).copy()
    h ^= h >> np.uint64(33)
    h *= np.uint64(0xFF51AFD7ED558CCD)
    h ^= h >> np.uint64(33)
    return (h % np.uint64(num_shards)).astype(np.int64)
