"""Loss layers (reference: python/paddle/nn/layer/loss.py)."""
from __future__ import annotations

from .. import functional as F
from ..layer import Layer


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax

    def forward(self, input, label):
        return F.cross_entropy(input, label, weight=self.weight,
                               ignore_index=self.ignore_index,
                               reduction=self.reduction,
                               soft_label=self.soft_label, axis=self.axis,
                               use_softmax=self.use_softmax)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self.weight, self.ignore_index, self.reduction = weight, ignore_index, reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index, self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight, self.reduction, self.pos_weight = weight, reduction, pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin, self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin, self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.margin, self.p, self.epsilon = margin, p, epsilon
        self.swap, self.reduction = swap, reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, self.margin,
                                     self.p, self.epsilon, self.swap, self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid loss layer (reference nn/layer/loss.py
    HSigmoidLoss). Holds the internal-node weight table (num_classes-1, D)
    and optional bias; see functional.hsigmoid_loss for the tree encoding."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        if not is_custom and num_classes < 2:
            raise ValueError("num_classes must be >= 2 for the default tree")
        self.num_classes = num_classes
        self.is_custom = is_custom
        rows = num_classes - 1 if not is_custom else num_classes
        self.weight = self.create_parameter((rows, feature_size),
                                            attr=weight_attr)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter((rows, 1), attr=bias_attr,
                                              is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        bias = self.bias.value.reshape(-1) if self.bias is not None else None
        return F.hsigmoid_loss(input, label, self.num_classes,
                               self.weight.value, bias,
                               path_table=path_table, path_code=path_code)
